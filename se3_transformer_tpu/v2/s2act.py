"""Separable S2 activation: the v2 replacement for NormSE3.

Two composed parts, both cheap and both degree-local ("separable"):

  1. an EXACTLY equivariant per-degree scalar gate — a Dense head on
     the invariant l=0 channel, sigmoid, multiplying each l>0 degree's
     channels (the only learned piece);
  2. a pointwise nonlinearity on a fixed S2 grid (optional,
     ``grid_nonlin``): each degree's channel c is synthesized to a
     function f(omega) = sum_m x_m Y_lm(omega) on a Gauss-Legendre x
     uniform-phi grid, gelu'd pointwise, and analyzed back onto the
     SAME degree-l harmonics. Rotation acts on f by composition and
     commutes with any pointwise map in the continuum, so the ONLY
     equivariance cost is quadrature aliasing of gelu(f)'s tail
     spectrum — with the default grid that measures ~1e-7 at degree 8
     (tests/test_v2.py gates it with the rest of the family at 1e-4).

The synthesis/analysis matrices are host-float64 constants built from
so3.spherical_harmonics (xp=np) with the analysis solved against the
grid Gram matrix, so analysis(synthesis(x)) == x to float64 regardless
of the SH normalization convention — this is what makes padded and
unpadded forwards agree exactly (zero features stay exactly zero
through the grid roundtrip: gelu(0) == 0).

NormSE3's norm-nonlinearity needs the safe_norm clip to keep grads
finite at zero features; the S2 path has no norm at all, so grads are
finite at degenerate geometry (frames.py pole-guard cases) for free.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Callable, Dict, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..ops.fiber import Fiber

Features = Dict[str, jnp.ndarray]


@lru_cache(maxsize=None)
def s2_grid_matrices(degree: int, n_theta: int, n_phi: int):
    """(synthesis [G, 2l+1], analysis [2l+1, G]) for one degree on the
    Gauss-Legendre(cos theta) x uniform(phi) grid, host float64.
    analysis @ synthesis == I to quadrature exactness (n_theta > l,
    n_phi > 2l): the Gram solve absorbs the SH normalization."""
    from ..so3.spherical_harmonics import (angles_to_xyz,
                                           real_spherical_harmonics)
    nodes, glw = np.polynomial.legendre.leggauss(n_theta)
    theta = np.arccos(nodes)                       # [n_theta]
    phi = 2.0 * np.pi * np.arange(n_phi) / n_phi   # [n_phi]
    tt, pp = np.meshgrid(theta, phi, indexing='ij')
    xyz = angles_to_xyz(tt.reshape(-1), pp.reshape(-1), xp=np)
    Y = np.asarray(real_spherical_harmonics(degree, xyz, xp=np),
                   dtype=np.float64)               # [G, 2l+1]
    w = np.repeat(glw, n_phi) * (2.0 * np.pi / n_phi)  # [G]
    Yw = Y.T * w[None, :]                          # [2l+1, G]
    gram = Yw @ Y                                  # [2l+1, 2l+1]
    A = np.linalg.solve(gram, Yw)
    return Y, A


def default_grid(degree: int, resolution: Optional[int] = None):
    """(n_theta, n_phi) for one degree. 2l+2 theta nodes already make
    the LINEAR roundtrip exact; the default oversamples ~2x beyond
    that so gelu's alias tail lands below ~1e-6 (measured: 4(l+1)
    nodes give ~5e-7 equivariance at l = 6 and 8 — see
    tests/test_v2.py). Per-degree grids keep low degrees cheap: only
    the top of the fiber pays for the fine grid."""
    n_theta = resolution if resolution is not None \
        else max(4 * (degree + 1), 8)
    assert n_theta >= degree + 1, \
        f's2 grid resolution {n_theta} cannot resolve degree {degree}'
    return n_theta, 2 * n_theta + 1


class SeparableS2Activation(nn.Module):
    """See module docstring. Drop-in for NormSE3 in the v2 blocks:
    Features -> Features, same fiber in and out."""
    fiber: Fiber
    nonlin: Callable = nn.gelu
    # the S2-grid pointwise nonlinearity on l>0 degrees; False leaves
    # the gate as the only l>0 transform (exactly equivariant mode)
    grid_nonlin: bool = True
    # theta nodes override (None -> default_grid)
    resolution: Optional[int] = None

    @nn.compact
    def __call__(self, features: Features) -> Features:
        x0 = features['0']                         # [..., C0, 1]
        scalars = x0[..., 0]

        out = {}
        for degree, channels in self.fiber:
            key = str(degree)
            x = features[key]
            if degree == 0:
                out[key] = self.nonlin(x)
                continue
            if self.grid_nonlin:
                n_theta, n_phi = default_grid(degree, self.resolution)
                Y, A = s2_grid_matrices(degree, n_theta, n_phi)
                synth = jnp.asarray(Y, x.dtype)
                analy = jnp.asarray(A, x.dtype)
                f = jnp.einsum('...cp,gp->...cg', x, synth)
                x = jnp.einsum('...cg,pg->...cp', self.nonlin(f), analy)
            gate = nn.sigmoid(nn.Dense(channels,
                                       name=f'gate{degree}')(scalars))
            out[key] = x * gate[..., None]
        return out
