"""Per-m radial convolution: the SE3TransformerV2 contraction layer.

The v1 so2 backend keeps the dense path's parameterization — a radial
trunk emitting [mid, C*F, O] blocks that couple every canonical-kernel
frequency to every output row — and gets its win purely from replacing
the basis contraction with the banded rotate-in/rotate-out reduction.
That still materializes a dense-basis-SHAPED radial output (the
``R = h @ w3`` intermediate is mid x C*F x O per edge), which caps the
measured speedup (ROADMAP item 2).

V2 goes the rest of the way (EquiformerV2, arXiv:2306.12059): the
radial trunk emits the per-+/-m banded weight blocks DIRECTLY.  For a
degree pair (d_in -> d_out) and each m <= min(d_in, d_out) the learned
per-edge kernel is the 2x2 rotation-like block

    [[a, b], [-b, a]]        acting on the (q = d_in - m, q = d_in + m)
                             component pair of the edge-frame features,

with (a, b) produced per (channel, output-channel) by
``R_m = h @ wm + bm`` — so R_m IS the banded block and nothing
dense-basis-shaped ever exists.  Exact equivariance is structural:
both the kernel block and the frame rotation's Dz blocks live in
span{I, [[0, 1], [-1, 0]]} on each +/-m pair (so2/frames._dz_apply's
index convention), hence commute; the m-truncation knob ``max_m``
(zeroing blocks with m > max_m, EquiformerV2's mmax) therefore costs
zero equivariance.

Spine reuse, per the family contract:

  * rotate-in / rotate-out come from so2/frames (hoisted once per
    input/output degree like ConvSE3's so2 branch);
  * the per-m apply is the existing ops.conv._radial_contract — the
    Pallas 'plain' kernel, QuantTensor fused dequant, conv_bf16 cast
    and node-axis streaming all serve v2 unchanged;
  * node-axis chunking consults the SAME 'so2' tuning kind
    (so2.contract._pick_so2_chunks), so scripts/tune_kernels.py owns
    the knob for both families;
  * the radial trunk is ops.conv.radial_hidden, so its Dense_0/Dense_1
    kernels keep the int8-safe quant class (invariant inputs).

No canonical-kernel table, no banded_z, no basis.get_basis — v2 never
imports them (tests/test_v2.py asserts this structurally by making
both raise during a v2 forward).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..ops.conv import _radial_contract, radial_hidden
from ..ops.core import LinearSE3, residual_se3
from ..ops.fiber import Fiber
from ..parallel.exchange import exchange_index_select
from ..quant.qtensor import concat_weights
from ..utils.helpers import masked_mean

Features = Dict[str, jnp.ndarray]
EdgeInfo = Tuple[Optional[jnp.ndarray], Optional[jnp.ndarray],
                 Optional[jnp.ndarray]]

# v2's compact default trunk width: the per-m blocks are [mid, 2C, O]
# instead of v1's [mid, C*F, O], so the trunk that feeds them can be
# narrow without starving the contraction (EquiformerV2 uses the same
# regime). This is the main measured lever behind the degree-6 win in
# V2_SWEEP.jsonl.
DEFAULT_V2_MID_DIM = 32


def v2_band_rows(d_in: int, d_out: int,
                 max_m: Optional[int] = None) -> int:
    """Band rows a (d_in -> d_out) pair contributes: 2 * M + 1 with
    M = min(d_in, d_out[, max_m]). The truncation is exactly
    equivariant (dropped blocks are identically zero weights)."""
    m = min(d_in, d_out)
    if max_m is not None:
        m = min(m, max_m)
    return 2 * m + 1


class V2ConvSE3(nn.Module):
    """Graph convolution over precomputed neighborhoods with per-m
    radial parameterization (module docstring). Same call contract as
    ConvSE3 except the basis dict is replaced by the edge ``frames``
    payload (v2 has exactly one backend — there is nothing dense to
    fall back to)."""
    fiber_in: Fiber
    fiber_out: Fiber
    self_interaction: bool = True
    pool: bool = True
    edge_dim: int = 0
    mid_dim: int = DEFAULT_V2_MID_DIM
    # EquiformerV2's mmax: truncate the per-m blocks at |m| <= max_m
    # (None = full band). Zero weights, not an approximation: exactly
    # equivariant at any setting.
    max_m: Optional[int] = None
    pallas: Optional[bool] = None
    pallas_interpret: bool = False
    edge_chunks: Optional[int] = None
    radial_bf16: bool = False
    conv_bf16: bool = False

    def _per_m_params(self, m: int, degree_in: int, degree_out: int,
                      mid: int, m_in: int, m_out: int):
        """The (wm, bm) block for one (m, d_in, d_out) triple: K = 2C
        columns (the [a | b] halves of the 2x2 block) for m > 0, C for
        the unpaired m = 0 row."""
        K = m_in if m == 0 else 2 * m_in
        wm = self.param(
            f'wm{m}_{degree_in}_{degree_out}',
            nn.initializers.variance_scaling(1.0, 'fan_in',
                                             'truncated_normal',
                                             in_axis=0, out_axis=(1, 2)),
            (mid, K, m_out), jnp.float32)
        bm = self.param(f'bm{m}_{degree_in}_{degree_out}',
                        nn.initializers.zeros, (K, m_out), jnp.float32)
        return wm, bm

    @nn.compact
    def __call__(self, inp: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, frames) -> Features:
        from ..so2.contract import _pick_so2_chunks
        from ..so2.frames import rotate_in, rotate_out

        neighbor_indices, neighbor_masks, edges = edge_info

        edge_features = rel_dist[..., None]                # [b, n, k, 1]
        if edges is not None:
            edge_features = jnp.concatenate((edge_features, edges),
                                            axis=-1)

        hidden = radial_hidden(
            edge_features, self.mid_dim,
            dtype=jnp.bfloat16 if self.radial_bf16 else None)

        # gather + rotate into the edge frame ONCE per input degree
        # (ConvSE3's so2 hoist — rotations are parameter-free)
        rotated = {}
        for degree_in, _ in self.fiber_in:
            g = exchange_index_select(inp[str(degree_in)],
                                      neighbor_indices, axis=1)
            rotated[str(degree_in)] = rotate_in(g, frames, degree_in)

        # node-axis streaming rides _radial_contract's edge_chunks and
        # shares the 'so2' tuning kind (one autotuner knob for both
        # families); the layer-level key mirrors so2_pair_contract's
        max_din = max(d for d, _ in self.fiber_in)
        max_dout = max(d for d, _ in self.fiber_out)
        chunks = self.edge_chunks
        if chunks is None:
            cmax = max(c for _, c in self.fiber_in)
            omax = max(c for _, c in self.fiber_out)
            shape = (int(rel_dist.shape[1]), cmax, omax,
                     max_din, max_dout,
                     -1 if self.max_m is None else int(self.max_m))
            chunks = _pick_so2_chunks(shape,
                                      np.dtype(rel_dist.dtype).name)
        if chunks is not None and chunks <= 1:
            chunks = None

        outputs = {}
        for degree_out, m_out in self.fiber_out:
            # band order M (the +/-m reach of this output degree)
            M = min(degree_out, max_din)
            if self.max_m is not None:
                M = min(M, self.max_m)
            neg_rows, pos_rows = [], []
            center = None
            for m in range(M + 1):
                # every input degree whose band reaches m contributes;
                # segments concatenate along the contracted K axis
                # exactly like the grouped so2 path's z segments
                segs, wms, bms = [], [], []
                for degree_in, m_in in self.fiber_in:
                    if min(degree_in, degree_out) < m:
                        continue
                    wm, bm = self._per_m_params(
                        m, degree_in, degree_out, hidden.shape[-1],
                        m_in, m_out)
                    wms.append(wm)
                    bms.append(bm)
                    xr = rotated[str(degree_in)]   # [..., C, 2di+1]
                    if m == 0:
                        segs.append((xr[..., degree_in][..., None, :],))
                    else:
                        xneg = xr[..., degree_in - m]      # [..., C]
                        xpos = xr[..., degree_in + m]
                        row_neg = jnp.concatenate((xneg, xpos), axis=-1)
                        row_pos = jnp.concatenate((xpos, -xneg), axis=-1)
                        segs.append((row_neg[..., None, :],
                                     row_pos[..., None, :]))
                # v2_m [..., rows, K]: rows = (−m, +m) for m > 0
                rows = len(segs[0])
                v2_m = jnp.concatenate(
                    [jnp.concatenate([s[r] for s in segs], axis=-1)
                     for r in range(rows)], axis=-2)
                out_m = _radial_contract(
                    hidden, concat_weights(wms, axis=1),
                    jnp.concatenate(bms, axis=0), v2_m,
                    pallas=self.pallas,
                    pallas_interpret=self.pallas_interpret,
                    edge_chunks=chunks,
                    conv_bf16=self.conv_bf16)      # [..., rows, O]
                if m == 0:
                    center = out_m[..., 0, :]
                else:
                    neg_rows.append(out_m[..., 0, :])
                    pos_rows.append(out_m[..., 1, :])
            # assemble the P axis: rows d_out-M .. d_out+M carry the
            # band, everything beyond (including m > max_m when
            # truncating) is structurally zero
            band = jnp.stack(
                neg_rows[::-1] + [center] + pos_rows,
                axis=-2)                           # [..., 2M+1, O]
            if degree_out > M:
                pad = [(0, 0)] * band.ndim
                pad[-2] = (degree_out - M, degree_out - M)
                band = jnp.pad(band, pad)
            acc = rotate_out(jnp.swapaxes(band, -1, -2), frames,
                             degree_out)           # [..., O, P]

            if self.pool:
                acc = masked_mean(acc, neighbor_masks, axis=2) \
                    if neighbor_masks is not None else acc.mean(axis=2)
            outputs[str(degree_out)] = acc

        if self.self_interaction:
            assert self.pool, \
                'must pool edges if followed with self interaction'
            self_out = LinearSE3(self.fiber_in, self.fiber_out,
                                 name='self_interact')(inp)
            outputs = residual_se3(outputs, self_out)

        # same remat tag as ConvSE3: under save_only_these_names the
        # trunk's backward replay fetches these instead of re-running
        # the per-m contractions
        from jax.ad_checkpoint import checkpoint_name
        outputs = {k: checkpoint_name(v, 'conv_out')
                   for k, v in outputs.items()}
        return outputs
