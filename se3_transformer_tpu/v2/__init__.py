"""SE3TransformerV2: eSCN-direct model family (per-m radial blocks +
separable S2 activations). See v2/model.py for the family contract."""
from .conv import DEFAULT_V2_MID_DIM, V2ConvSE3, v2_band_rows
from .model import SE3TransformerV2, SE3TransformerV2Module
from .s2act import SeparableS2Activation, s2_grid_matrices

__all__ = [
    'DEFAULT_V2_MID_DIM', 'V2ConvSE3', 'v2_band_rows',
    'SE3TransformerV2', 'SE3TransformerV2Module',
    'SeparableS2Activation', 's2_grid_matrices',
]
