"""SE3TransformerV2: the eSCN-direct model family.

A sibling of models/se3_transformer.py — deliberately NOT checkpoint
compatible with v1 (the radial parameterization is per-m banded blocks,
see v2/conv.py; CheckpointManager's family guard makes cross-loading
fail loud instead of with a flax key error). The USER contract is
identical to v1's:

    module.apply({'params': p}, feats, coors, mask=mask,
                 adj_mat=adj, return_type=1)

with the same feats normalization (tokens -> Embed, arrays -> {'0'}),
the same cartesian<->irrep degree-1 permutation, the same
``output_degrees == 1 -> return_type = 0`` and '0'-squeeze output
conventions and the same return_pooled masked mean — so the
InferenceEngine AOT buckets, the trainer and the serving stack all
plug in unchanged. ``adj_mat`` is accepted and unused, matching the
v1 default path's semantics (it only matters under v1's
attend_sparse_neighbors machinery, which v2 does not grow).

Architecture: conv_in -> depth x (SeparableS2Activation -> V2ConvSE3
+ residual) -> SeparableS2Activation -> conv_out, all on the per-m
radial path with the edge-frames payload as the only geometry — no
basis tensors anywhere.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..observability import named_scope
from ..ops.core import LinearSE3, residual_se3
from ..ops.fiber import Fiber
from ..ops.neighbors import exclude_self_indices, remove_self, \
    select_neighbors
from ..utils.helpers import masked_mean
from .conv import DEFAULT_V2_MID_DIM, V2ConvSE3
from .s2act import SeparableS2Activation

# cartesian <-> irrep component permutations for degree-1 features —
# same convention as v1 (models/se3_transformer.py)
_CART_TO_IRREP = (1, 2, 0)
_IRREP_TO_CART = (2, 0, 1)


def _permute_degree1(features, perm):
    if '1' not in features:
        return features
    return {**features,
            '1': features['1'][..., jnp.asarray(perm)]}


class SE3TransformerV2Module(nn.Module):
    """flax module for the v2 family (see module docstring; the eager
    wrapper below mirrors v1's SE3Transformer call style)."""
    dim: int
    depth: int = 2
    num_degrees: int = 4
    output_degrees: int = 1
    input_degrees: int = 1
    dim_in: Optional[int] = None
    dim_out: Optional[int] = None
    num_tokens: Optional[int] = None
    num_neighbors: int = 12
    valid_radius: float = 1e5
    reduce_dim_out: bool = False
    edge_dim: int = 0
    # v2 knobs (v2/conv.py, v2/s2act.py)
    mid_dim: int = DEFAULT_V2_MID_DIM
    max_m: Optional[int] = None
    s2_grid_nonlin: bool = True
    s2_resolution: Optional[int] = None
    # spine passthroughs, same meaning as v1
    differentiable_coors: bool = False
    matmul_precision: Optional[str] = 'highest'
    pallas: Optional[bool] = None
    pallas_interpret: bool = False
    edge_chunks: Optional[int] = None
    radial_bf16: bool = False
    conv_bf16: bool = False

    # the checkpoint/capability family stamp (training/checkpoint.py
    # guards restores on it; serving surfaces it)
    model_family = 'se3_v2'

    @nn.compact
    def __call__(self, feats, coors, mask=None, adj_mat=None, edges=None,
                 return_type=None, return_pooled=False,
                 neighbor_mask=None):
        if self.matmul_precision is not None:
            with jax.default_matmul_precision(self.matmul_precision):
                return self._forward(feats, coors, mask, edges,
                                     return_type, return_pooled,
                                     neighbor_mask)
        return self._forward(feats, coors, mask, edges, return_type,
                             return_pooled, neighbor_mask)

    def _forward(self, feats, coors, mask, edges, return_type,
                 return_pooled, neighbor_mask):
        assert self.input_degrees == 1, \
            'v2 takes scalar (degree-0) inputs'
        dim_in = self.dim_in if self.dim_in is not None else self.dim
        dim_out = self.dim_out if self.dim_out is not None else self.dim
        fiber_in = Fiber.create(1, dim_in)
        fiber_hidden = Fiber.create(self.num_degrees, self.dim)
        fiber_out = Fiber.create(self.output_degrees, dim_out)

        if self.output_degrees == 1:
            return_type = 0

        if self.num_tokens is not None:
            feats = nn.Embed(self.num_tokens, dim_in,
                             name='token_emb')(feats)
        if not isinstance(feats, dict):
            feats = {'0': feats[..., None]}
        feats = _permute_degree1(feats, _CART_TO_IRREP)

        b, n = feats['0'].shape[0], feats['0'].shape[1]
        assert feats['0'].shape[2] == dim_in, \
            f"feature dim {feats['0'].shape[2]} != configured {dim_in}"

        num_neighbors = int(min(self.num_neighbors, n - 1))
        assert num_neighbors > 0, 'must fetch at least 1 neighbor'

        # fixed-K neighbor selection, self-excluded — the v1 dense path
        self_excl = exclude_self_indices(n)
        rel_pos_full = coors[:, :, None, :] - coors[:, None, :, :]
        rel_pos = remove_self(rel_pos_full, self_excl)
        indices = jnp.broadcast_to(self_excl[None], (b, n, n - 1))
        pair_mask = None
        if mask is not None:
            pm = mask[:, :, None] & mask[:, None, :]
            pair_mask = remove_self(pm, self_excl)
        if edges is not None:
            edges = remove_self(edges, self_excl)
        if neighbor_mask is not None:
            neighbor_mask = remove_self(neighbor_mask, self_excl)

        with named_scope('neighbors'):
            hood, nearest = select_neighbors(
                rel_pos, indices, num_neighbors, self.valid_radius,
                pair_mask=pair_mask, neighbor_mask=neighbor_mask)
        if edges is not None:
            from ..utils.helpers import batched_index_select
            edges = batched_index_select(edges, nearest, axis=2)

        # the ONLY geometry payload: edge frames (so2/frames.py) — v2
        # has no basis tensors at any degree
        with named_scope('frames'):
            from ..so2.frames import edge_frames
            frames = edge_frames(hood.rel_pos, self.num_degrees - 1,
                                 differentiable=self.differentiable_coors)

        edge_info = (hood.indices, hood.mask, edges)
        conv_kwargs = dict(
            mid_dim=self.mid_dim, max_m=self.max_m,
            edge_dim=(edges.shape[-1] if edges is not None else 0),
            pallas=self.pallas, pallas_interpret=self.pallas_interpret,
            edge_chunks=self.edge_chunks, radial_bf16=self.radial_bf16,
            conv_bf16=self.conv_bf16)

        with named_scope('conv_in'):
            x = V2ConvSE3(fiber_in, fiber_hidden, name='conv_in',
                          **conv_kwargs)(feats, edge_info,
                                         hood.rel_dist, frames)
        for i in range(self.depth):
            y = SeparableS2Activation(
                fiber_hidden, grid_nonlin=self.s2_grid_nonlin,
                resolution=self.s2_resolution, name=f'act{i}')(x)
            y = V2ConvSE3(fiber_hidden, fiber_hidden, name=f'block{i}',
                          **conv_kwargs)(y, edge_info, hood.rel_dist,
                                         frames)
            x = residual_se3(y, x)
        x = SeparableS2Activation(
            fiber_hidden, grid_nonlin=self.s2_grid_nonlin,
            resolution=self.s2_resolution, name='act_out')(x)
        with named_scope('conv_out'):
            x = V2ConvSE3(fiber_hidden, fiber_out, name='conv_out',
                          **conv_kwargs)(x, edge_info, hood.rel_dist,
                                         frames)

        if self.reduce_dim_out:
            x = LinearSE3(fiber_out, fiber_out.to(1),
                          name='linear_out')(x)
            x = {k: v[..., 0, :] for k, v in x.items()}

        x = _permute_degree1(x, _IRREP_TO_CART)

        if return_pooled:
            pool = (lambda t: masked_mean(t, mask, axis=1)) \
                if mask is not None else (lambda t: t.mean(axis=1))
            x = {k: pool(v) for k, v in x.items()}
        if '0' in x:
            x = {**x, '0': x['0'][..., 0]}
        if return_type is not None:
            return x[str(return_type)]
        return x


class SE3TransformerV2:
    """Eager convenience wrapper mirroring v1's SE3Transformer:

        model = SE3TransformerV2(dim=8, depth=1, num_degrees=7)
        out = model(feats, coors, mask, return_type=1)

    Parameters initialize lazily on first call (seeded)."""

    model_family = 'se3_v2'

    def __init__(self, *, seed: int = 0, **kwargs):
        self.module = SE3TransformerV2Module(**kwargs)
        self.seed = seed
        self.params = None
        self._apply = jax.jit(
            self.module.apply,
            static_argnames=('return_type', 'return_pooled'))

    def init(self, rng, *args, **kwargs):
        self.params = self.module.init(rng, *args, **kwargs)['params']
        return self.params

    def __call__(self, feats, coors, mask=None, adj_mat=None, edges=None,
                 return_type=None, return_pooled=False,
                 neighbor_mask=None):
        kwargs = dict(mask=mask, edges=edges, return_type=return_type,
                      return_pooled=return_pooled,
                      neighbor_mask=neighbor_mask)
        if self.params is None:
            init_fn = jax.jit(
                self.module.init,
                static_argnames=('return_type', 'return_pooled'))
            self.params = init_fn(jax.random.PRNGKey(self.seed), feats,
                                  coors, **kwargs)['params']
        return self._apply({'params': self.params}, feats, coors,
                           **kwargs)
