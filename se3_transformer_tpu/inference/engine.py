"""AOT-precompiled bucketed inference engine.

Training amortizes one compile over thousands of steps; serving cannot —
a request that triggers a fresh XLA compile pays seconds-to-minutes of
latency, which on a tail percentile is an outage. The engine therefore
moves ALL compilation to startup:

  * `jax.jit(fn).lower(...).compile()` once per shape bucket, giving a
    dict of AOT executables keyed `(bucket_len, batch_size, dtype)`.
    AOT executables cannot retrace — an off-contract shape is a loud
    TypeError at the engine boundary, never a silent compile (the
    `RetraceWatchdog`'s compile-event counter doubles as the proof:
    zero post-warmup events on a healthy engine).
  * the bucket's chain adjacency is baked into each executable as a
    trace-time constant (one fewer transfer per call), matching the
    shapes `PointCloudDataset.batches` produces for training.
  * `donate_buffers=True` (default off-CPU) donates the coords buffer —
    the largest per-call input — back to XLA for output reuse.
  * `activation_dtype=jnp.bfloat16` casts coords on the way in and the
    output back to float32: the bf16 serving path, same equivariance
    budget as the training-side `conv_bf16` option.

Params stay a call argument (not baked), so a checkpoint refresh is
`engine.params = mgr.restore_params()` — no recompile as long as shapes
match. The persistent compilation cache (`utils.compilation_cache`)
makes even the startup compiles warm across process restarts.

Quantized serving (ROADMAP item 3): `precision='int8_mix'` (or any
quant.rules mix / explicit rule list) quantizes the params INSIDE the
params setter — restore-time, on host — so the AOT buckets compile
against the quantized abstract tree and the fp32 degree-0 weights
never materialize on device. Weight swaps re-quantize at the engine's
own mix (zero recompiles — shapes/dtypes are unchanged), and every
bucket's cost record carries the mix + the before/after param bytes.

Sharded serving (ROADMAP item 3): pass `mesh` (+ optionally
`partition_rules`, a `parallel.rules` rule set name or rule list —
default 'tp') and the engine becomes mesh-aware end to end: params are
restored/placed directly into their `NamedSharding`s via the partition-
rule engine (the SAME rules training's `shard_params` uses — serving
and training shardings cannot drift), every bucket executable is
AOT-compiled against the SHARDED abstract params (so one large model
spans chips while DP replicas multiply throughput), and request arrays
are committed replicated onto the mesh at `run()`. The params-only
orbax restore path and the per-bucket cost ledger are unchanged.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..native.loader import chain_adjacency, pad_to_bucket
from ..observability import PhaseTimer
from .admission import fit_bucket, oversize_error


def bucket_phase(bucket: int) -> str:
    """The PhaseTimer phase name for a bucket's execute latency."""
    return f'bucket_{bucket}'


class InferenceEngine:
    """Restore params, precompile per bucket, answer fixed-shape batches.

        module = DenoiseConfig(...).build_module()
        engine = InferenceEngine.from_checkpoint(
            module, '/ckpts/run1', buckets=(64, 128), batch_size=8)
        out = engine.predict(tokens, coords)          # one request
        out = engine.run(128, tokens, coords, mask)   # a padded batch

    `run` is the `MicroBatcher` runner; `predict` is the convenience
    single-request path (pads to the smallest fitting bucket). Both
    block until the result is ready so the per-bucket PhaseTimer
    percentiles are honest device latencies.
    """

    def __init__(self, module, params, *,
                 buckets: Sequence[int] = (64, 128, 256, 512),
                 batch_size: int = 1,
                 return_type: int = 1,
                 activation_dtype: Optional[jnp.dtype] = None,
                 with_chain_adjacency: bool = True,
                 donate_buffers: Optional[bool] = None,
                 apply_kwargs: Optional[dict] = None,
                 timer: Optional[PhaseTimer] = None,
                 mesh: Optional[Mesh] = None,
                 partition_rules=None,
                 precision=None,
                 precompile: bool = True,
                 fault_injector=None):
        self.module = module
        # the family capability signal (replica snapshots / HostServer
        # stats surface it for family-aware fleet placement; v1 modules
        # stamp 'se3_v1', v2 'se3_v2')
        self.model_family = getattr(module, 'model_family', 'se3_v1')
        self.mesh = mesh
        # chaos-harness hook (faults.FaultInjector): fires at the top
        # of run() so injected engine failures/latency walk the real
        # execution path; None in production costs nothing
        self.fault_injector = fault_injector
        # rule set name ('replicated'/'tp'/'fsdp') or explicit rule
        # list (parallel.rules); only consulted when a mesh is given
        self.partition_rules = ('tp' if partition_rules is None
                                else partition_rules)
        # weight-precision mix (quant.rules): a shipped mix name
        # ('int8_mix' / 'bf16' / 'fp8_mix') or explicit (regex,
        # precision) rules. The params SETTER quantizes — restore-time,
        # on host, BEFORE the device_put — so the fp32 degree-0 weights
        # never materialize on device (test-pinned); None/'fp32' is the
        # bit-identical passthrough. Orthogonal to activation_dtype
        # (weight storage vs activation compute).
        self.precision = None if precision in (None, 'fp32') \
            else precision
        self.precision_name = 'fp32'
        self.quant_report = None
        if self.precision is not None:
            from ..quant import mix_name, resolve_mix
            resolve_mix(self.precision)   # fail fast on a bad mix name
            self.precision_name = mix_name(self.precision)
        self.param_specs = None      # filled by the params setter
        self.params = params         # property setter device_puts once
        self.buckets = tuple(sorted(int(b) for b in buckets))
        assert self.buckets, 'no buckets'
        self.batch_size = int(batch_size)
        self.return_type = return_type
        self.activation_dtype = activation_dtype
        self.with_chain_adjacency = with_chain_adjacency
        if donate_buffers is None:
            # donation is a no-op-with-warning on CPU; auto-enable only
            # where the backend implements it
            donate_buffers = jax.default_backend() != 'cpu'
        self.donate_buffers = bool(donate_buffers)
        self.apply_kwargs = dict(apply_kwargs or {})
        self.timer = timer if timer is not None else PhaseTimer()
        self._executables: Dict[Tuple[int, int, str], Callable] = {}
        self.compile_seconds: Dict[Tuple[int, int, str], float] = {}
        # per-bucket schema'd `cost` record bodies (observability.costs)
        # — serving capacity planning reads memory-per-bucket off these;
        # ServeTelemetry.arm() emits them into the telemetry stream
        self.cost_payloads: Dict[Tuple[int, int, str], dict] = {}
        self.tuning_consults: list = []  # filled by warmup()
        self.batches_served: Dict[int, int] = {b: 0 for b in self.buckets}
        self.rows_served: Dict[int, int] = {b: 0 for b in self.buckets}
        if precompile:
            self.warmup()

    # ------------------------------------------------------------------ #
    @classmethod
    def from_checkpoint(cls, module, checkpoint_dir: str,
                        step: Optional[int] = None, **kwargs
                        ) -> 'InferenceEngine':
        """Params-only restore (`CheckpointManager.restore_params`) —
        optimizer state never materializes on the serving host. The
        module's `model_family` stamp rides into the manager, so
        loading a v1 checkpoint into a v2 module (or vice versa) fails
        with the structured ModelFamilyMismatch, not a flax key
        error."""
        from ..training.checkpoint import CheckpointManager
        params = CheckpointManager(
            checkpoint_dir,
            model_family=getattr(module, 'model_family', None),
        ).restore_params(step)
        return cls(module, params, **kwargs)

    # ------------------------------------------------------------------ #
    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, value):
        # device_put ONCE per (re)load — restore_params hands back numpy
        # leaves, and re-transferring the whole parameter set host-to-
        # device on every run() call would dominate per-batch latency
        # off-CPU. A setter so the checkpoint-refresh recipe
        # `engine.params = mgr.restore_params()` stays fast too. With a
        # mesh, every leaf goes straight into the NamedSharding its
        # partition rule names (host arrays shard on the way in — the
        # full tensor is never replicated across the mesh first), and a
        # weight swap re-places into the SAME specs so the AOT
        # executables keep matching without a recompile.
        #
        # With a precision mix, quantization happens HERE, on host,
        # before any device placement: the quantized pytree (int8/fp8
        # QuantTensors + scales, bf16 casts) is what lands in HBM — the
        # fp32 tree never does. The same setter is the rolling-swap
        # re-quantization contract: `swap_weights(raw_fp32_params)`
        # re-quantizes at THIS engine's mix (each replica may run its
        # own), shapes/dtypes are unchanged, so the AOT executables
        # keep matching — zero drops, zero recompiles. A tree that is
        # already quantized (e.g. handed between engines) passes
        # through untouched.
        if self.precision is not None:
            from ..quant import is_quantized, quantize_params
            if not is_quantized(value):
                value, self.quant_report = quantize_params(
                    value, self.precision)
        if self.mesh is None:
            self._params = jax.device_put(value)
            return
        from ..parallel.rules import place_with_rules
        self._params, self.param_specs = place_with_rules(
            value, self.mesh, self.partition_rules)

    @property
    def dtype_name(self) -> str:
        return (jnp.dtype(self.activation_dtype).name
                if self.activation_dtype is not None else 'float32')

    def _key(self, bucket: int) -> Tuple[int, int, str]:
        # the precision mix folds into the key's dtype slot: an int8
        # engine's executables must never collide with an fp32 one's
        # in caches keyed on these tuples (the bucket stays slot 0 —
        # telemetry reads key[0])
        dt = self.dtype_name
        if self.precision is not None:
            dt = f'{dt}+{self.precision_name}'
        return (int(bucket), self.batch_size, dt)

    @property
    def executables(self) -> Dict[Tuple[int, int, str], Callable]:
        return dict(self._executables)

    def _make_fn(self, bucket: int) -> Callable:
        adj = (jnp.asarray(chain_adjacency(bucket))
               if self.with_chain_adjacency else None)
        act = self.activation_dtype
        module, return_type, extra = (self.module, self.return_type,
                                      self.apply_kwargs)

        def fn(params, tokens, coords, mask):
            if act is not None:
                coords = coords.astype(act)
            out = module.apply({'params': params}, tokens, coords,
                               mask=mask, adj_mat=adj,
                               return_type=return_type, **extra)
            if act is not None:
                out = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), out)
            return out

        return fn

    @property
    def _replicated(self) -> Optional[NamedSharding]:
        return (NamedSharding(self.mesh, P())
                if self.mesh is not None else None)

    def _abstract_batch(self, bucket: int):
        B, L = self.batch_size, bucket
        repl = self._replicated

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

        return (sds((B, L), jnp.int32), sds((B, L, 3), jnp.float32),
                sds((B, L), jnp.bool_))

    def _abstract_params(self):
        """ShapeDtypeStructs of the placed params; on a mesh they carry
        the rule engine's NamedShardings, so the AOT compile partitions
        the whole program around sharded weights."""
        mesh = self.mesh

        def abstract(a, spec=None):
            sharding = (NamedSharding(mesh, spec)
                        if mesh is not None else None)
            return jax.ShapeDtypeStruct(
                np.shape(a), getattr(a, 'dtype', np.dtype(type(a))),
                sharding=sharding)

        if mesh is None:
            return jax.tree_util.tree_map(abstract, self.params)
        return jax.tree_util.tree_map(abstract, self.params,
                                      self.param_specs)

    def compile_bucket(self, bucket: int) -> Callable:
        """AOT lower+compile one bucket's executable (idempotent)."""
        key = self._key(bucket)
        if key in self._executables:
            return self._executables[key]
        assert bucket in self.buckets, f'{bucket} is not a configured bucket'
        abstract_params = self._abstract_params()
        tokens, coords, mask = self._abstract_batch(bucket)
        donate = (2,) if self.donate_buffers else ()  # coords buffer
        t0 = time.perf_counter()
        executable = (jax.jit(self._make_fn(bucket), donate_argnums=donate)
                      .lower(abstract_params, tokens, coords, mask)
                      .compile())
        self.compile_seconds[key] = round(time.perf_counter() - t0, 3)
        self._executables[key] = executable
        try:
            # one cost ledger entry per bucket executable: peak HBM
            # split + flops, the capacity-planning surface (guarded —
            # introspection must never fail a compile that succeeded)
            from ..observability.costs import cost_payload
            body = cost_payload(
                executable,
                label=f'bucket_{bucket},b={self.batch_size},'
                      f'dtype={self.dtype_name},'
                      f'precision={self.precision_name}')
            # the precision mix + the restore-time before/after param
            # bytes ride every bucket's cost record — the per-replica
            # memory claim is a ledger field, not prose (extra fields
            # are schema-legal on cost records)
            body['precision_mix'] = self.precision_name
            if self.quant_report is not None:
                body['quant'] = dict(self.quant_report)
            self.cost_payloads[key] = body
        except Exception as e:  # noqa: BLE001
            import sys
            print(f'engine: cost introspection failed for bucket '
                  f'{bucket} ({type(e).__name__}: {e})', file=sys.stderr)
        return executable

    def warmup(self) -> Dict[Tuple[int, int, str], float]:
        """Compile every bucket; returns per-executable compile seconds.
        Call before arming a RetraceWatchdog — afterwards a healthy
        engine produces ZERO compile events. Each compile also ledgers
        its executable into `cost_payloads` (one schema'd `cost` body
        per bucket — ServeTelemetry.arm() streams them out).

        Also records which kernel block picks the AOT compiles resolved
        from the measured tuning table vs the heuristic
        (kernels/tuning.py): `tuning_consults` / stats()['kernel_tuning']
        — a serving deployment benchmarked under a tuned entry must be
        distinguishable from a heuristic one in its telemetry."""
        from ..kernels import tuning
        # drop the kernel jit caches first: picks resolve at trace time,
        # so a kernel traced earlier in-process (training, a prior
        # engine) would compile these buckets without recording a single
        # consult (the masquerading failure bench.py also guards)
        if any(self._key(b) not in self._executables
               for b in self.buckets):
            tuning.clear_kernel_caches()
        snap = tuning.snapshot()
        for b in self.buckets:
            self.compile_bucket(b)
        consults = tuning.consults_since(snap)
        if consults or not self.tuning_consults:
            # a re-warmup with every bucket already compiled records an
            # (accurate) empty delta — it must not wipe the consults of
            # the warmup that actually built the executables
            self.tuning_consults = consults
        adopted = [c for c in self.tuning_consults
                   if c['source'] != 'heuristic']
        if adopted:
            import sys
            print('engine warmup: tuned kernel table entries in effect: '
                  + '; '.join(
                      f"{c['kernel']}{tuple(c['shape'])}->"
                      f"{tuple(c['blocks'])} ({c['source']})"
                      for c in adopted), file=sys.stderr)
        return dict(self.compile_seconds)

    # ------------------------------------------------------------------ #
    def bucket_for(self, length: int) -> Optional[int]:
        return fit_bucket(self.buckets, length)

    @property
    def max_len(self) -> int:
        return self.buckets[-1]

    def run(self, bucket: int, tokens, coords, mask):
        """Execute one padded fixed-shape batch on the bucket's AOT
        executable; blocks until the result is ready (honest latency)."""
        if self.fault_injector is not None:
            self.fault_injector.fire('engine_run', bucket=int(bucket))
        executable = self._executables.get(self._key(bucket))
        if executable is None:
            executable = self.compile_bucket(bucket)
        tokens = jnp.asarray(tokens, jnp.int32)
        coords = jnp.asarray(coords, jnp.float32)
        mask = jnp.asarray(mask, jnp.bool_)
        if self.mesh is not None:
            # AOT executables are strict about input placement: commit
            # the request arrays replicated onto the mesh (the compiled
            # program was lowered with exactly these shardings)
            repl = self._replicated
            tokens, coords, mask = (jax.device_put(x, repl)
                                    for x in (tokens, coords, mask))
        with self.timer.phase(bucket_phase(bucket)):
            out = executable(self.params, tokens, coords, mask)
            out = jax.block_until_ready(out)
        self.batches_served[bucket] += 1
        self.rows_served[bucket] += int(np.asarray(mask).any(-1).sum())
        return out

    def predict(self, tokens, coords) -> np.ndarray:
        """One request end to end: pad to the smallest fitting bucket,
        run, return only the real (unpadded) rows."""
        tokens = np.asarray(tokens)
        length = len(tokens)
        bucket = self.bucket_for(length)
        if bucket is None:
            raise oversize_error(length, self.max_len)
        t, c, m = pad_to_bucket([tokens], [coords], bucket,
                                batch_size=self.batch_size)
        out = np.asarray(self.run(bucket, t, c, m))
        return out[0, :length]

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        """Engine-side counters for the serve telemetry record."""
        sharding = None
        if self.mesh is not None:
            n_sharded = sum(
                1 for s in jax.tree_util.tree_leaves(
                    self.param_specs,
                    is_leaf=lambda x: isinstance(x, P))
                if any(a is not None for a in s))
            sharding = dict(
                mesh={a: int(s) for a, s in
                      zip(self.mesh.axis_names, self.mesh.devices.shape)},
                rules=(self.partition_rules
                       if isinstance(self.partition_rules, str)
                       else 'custom'),
                sharded_params=n_sharded)
        return dict(
            buckets=list(self.buckets), batch_size=self.batch_size,
            dtype=self.dtype_name, sharding=sharding,
            precision=self.precision_name,
            model_family=self.model_family,
            quant=(dict(self.quant_report)
                   if self.quant_report is not None else None),
            executables=[list(k) for k in self._executables],
            compile_seconds={str(k[0]): v
                             for k, v in self.compile_seconds.items()},
            batches_served={str(b): n
                            for b, n in self.batches_served.items() if n},
            rows_served={str(b): n
                         for b, n in self.rows_served.items() if n},
            # memory-per-bucket off the ledger (peak = arg+out+temp,
            # XLA's static estimate; full bodies in cost_payloads)
            peak_hbm_by_bucket={str(k[0]): v['peak_bytes']
                                for k, v in self.cost_payloads.items()},
            kernel_tuning=list(self.tuning_consults))
