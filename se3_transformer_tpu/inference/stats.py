"""Bounded running aggregates shared by batching and telemetry.

A serve loop runs for days, so every retained statistic must be O(1):
these fold samples into exact running {count, sum, min, max} (one dict,
never a growing list). One implementation — `MicroBatcher` (batch fill)
and `ServeTelemetry` (request latency) both use it.
"""
from __future__ import annotations

import numpy as np


def agg_zero() -> dict:
    return dict(count=0, sum=0.0, min=None, max=None)


def agg_update(agg: dict, values) -> dict:
    """Fold a window of samples into the exact running aggregate."""
    for v in values:
        v = float(v)
        agg['count'] += 1
        agg['sum'] += v
        agg['min'] = v if agg['min'] is None else min(agg['min'], v)
        agg['max'] = v if agg['max'] is None else max(agg['max'], v)
    return agg


def agg_stats(agg: dict) -> dict:
    """The window-shaped {count, mean, min, max} view of an aggregate."""
    if not agg['count']:
        return dict(count=0, mean=None, min=None, max=None)
    return dict(count=agg['count'],
                mean=round(agg['sum'] / agg['count'], 4),
                min=round(agg['min'], 4), max=round(agg['max'], 4))


def window_stats(values) -> dict:
    """One-shot {count, mean, min, max} over a (bounded) sample window."""
    a = np.asarray(list(values), dtype=float)
    if a.size == 0:
        return dict(count=0, mean=None, min=None, max=None)
    return dict(count=int(a.size), mean=round(float(a.mean()), 4),
                min=round(float(a.min()), 4), max=round(float(a.max()), 4))
