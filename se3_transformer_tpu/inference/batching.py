"""Micro-batching: variable-length requests -> fixed-shape bucket batches.

Single requests waste a fixed-shape executable (a batch of 8 runs one
request at 8x the per-request cost), but waiting forever for a full
batch destroys tail latency. The `MicroBatcher` trades between them with
exactly two knobs:

  * flush-on-full — the moment a bucket's queue holds `batch_size`
    requests, the batch dispatches (throughput bound);
  * flush-on-deadline — `pump()` dispatches any bucket whose OLDEST
    request has waited `max_wait_ms`, padding the short batch with
    all-masked dummy rows (latency bound).

Padding to the bucket reuses `native.loader.pad_to_bucket` — the same
implementation the training dataset uses, so serving shapes cannot drift
from the shapes the model was trained (and the engine compiled) on.

The batcher is deliberately synchronous and single-threaded: `submit()`
enqueues and returns a `PendingResult`, the serve loop calls `pump()`
between accepts (and `drain()` at the end). That keeps it trivially
testable (inject `clock`) and keeps all jax dispatch on one thread; an
async front-end can wrap `submit`/`pump` without the core changing.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..native.loader import pad_to_bucket
from .admission import AdmissionController, fit_bucket, oversize_error
from .stats import agg_update, agg_zero


class PendingResult:
    """Future-lite: filled in by the flush that dispatches the request.
    `done=True` with `error` set means the request terminally failed —
    its batch's runner raised (and any retry budget is spent), or its
    deadline expired while queued (`ok` distinguishes; the error is
    structured: the runner's exception or a `RequestFailed`).

    `deadline` (absolute, same clock as `submitted_at`; None = no
    timeout) propagates the caller's `timeout_s` through every queue
    and redispatch; `attempts` counts dispatches that FAILED under this
    request (the router's bounded-retry budget). `trace` is the
    request-tracing context (observability.tracing) — a dict carrying
    the trace id and the parent span id the next tier hangs its spans
    under; None (the default) keeps every tracing site zero-cost."""

    __slots__ = ('request_id', 'length', 'bucket', 'result', 'done',
                 'error', 'submitted_at', 'completed_at', 'deadline',
                 'attempts', 'trace')

    def __init__(self, request_id, length: int, bucket: int,
                 submitted_at: float, deadline: Optional[float] = None,
                 trace: Optional[dict] = None):
        self.request_id = request_id
        self.length = length
        self.bucket = bucket
        self.result = None
        self.done = False
        self.error: Optional[BaseException] = None
        self.submitted_at = submitted_at
        self.completed_at: Optional[float] = None
        self.deadline = deadline
        self.attempts = 0
        self.trace = trace

    @property
    def ok(self) -> bool:
        return self.done and self.error is None

    @property
    def latency_s(self) -> Optional[float]:
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


def dispatch_batch(runner, bucket: int, batch_size: int, tokens, coords,
                   pending: List[PendingResult],
                   completed: List[PendingResult],
                   completed_capacity: int,
                   clock: Callable[[], float],
                   on_success: Optional[Callable[[int], None]] = None,
                   on_failure: Optional[Callable] = None,
                   tracer=None) -> None:
    """THE dispatch body — pad, run, resolve — shared by `MicroBatcher`
    (deadline micro-batching) and `serving.ContinuousBatcher`
    (in-flight slots), so the pad/slice/error contract cannot drift
    between them. Pads with `native.loader.pad_to_bucket` (the training
    dataset's padder), slices each result back to its request's true
    rows, and on a raising runner resolves EVERY request of the batch
    done-with-error (no submitter hangs forever) before re-raising.

    `on_success(rows)` / `on_failure(bucket, tokens, coords, pending,
    exc) -> bool` are the fault-domain hooks (serving.Router wires
    them): success feeds the replica's health breaker; a failure
    handler that returns True TAKES OWNERSHIP of the batch's requests
    (the router's retry queue will redispatch or structurally fail
    each one) — dispatch_batch then neither resolves nor re-raises.
    The hooks receive the ORIGINAL per-request arrays, not the padded
    batch, so a redispatch re-pads for its new bucket slot.

    `tracer` (observability.tracing.Tracer, optional) records
    queue_wait / dispatch / device_run spans for every request that
    carries a trace context (`p.trace`); None keeps dispatch span-free.
    """
    raw_tokens, raw_coords = list(tokens), list(coords)
    t_start = clock()
    tokens, coords, mask = pad_to_bucket(tokens, coords, bucket,
                                         batch_size=batch_size)
    t_run = clock()
    try:
        out = np.asarray(runner(bucket, tokens, coords, mask))
    except Exception as e:
        t_done = clock()
        _trace_batch(tracer, bucket, pending, t_start, t_run, t_done,
                     error=type(e).__name__)
        if on_failure is not None and \
                on_failure(bucket, raw_tokens, raw_coords, pending, e):
            return      # requests taken over by the retry path
        now = clock()
        for p in pending:
            p.error = e
            p.done = True
            p.completed_at = now
            completed.append(p)
        if len(completed) > completed_capacity:
            del completed[:-completed_capacity]
        raise
    t_done = now = clock()
    _trace_batch(tracer, bucket, pending, t_start, t_run, t_done)
    for row, p in enumerate(pending):
        # copy: a view would pin the whole [B, L, ...] batch output
        # alive for as long as any single request's result is held
        p.result = np.array(out[row, :p.length])
        p.done = True
        p.completed_at = now
        completed.append(p)
    if len(completed) > completed_capacity:
        del completed[:-completed_capacity]
    if on_success is not None:
        on_success(len(pending))


def _trace_batch(tracer, bucket, pending, t_start, t_run, t_done,
                 error=None):
    """Record queue_wait / dispatch / device_run spans for each traced
    request of one dispatched batch. The device_run span nests under
    the dispatch span (exclusive dispatch time = pad + resolve
    overhead); a failing runner stamps the error class on the dispatch
    span so retried attempts are tellable apart in the tree."""
    if tracer is None:
        return
    for p in pending:
        tr = getattr(p, 'trace', None)
        if not tr:
            continue
        tracer.add(tr['ctx'], 'queue_wait', parent_id=tr['parent'],
                   ts=p.submitted_at,
                   dur_ms=(t_start - p.submitted_at) * 1e3)
        meta = dict(bucket=int(bucket), fill=len(pending))
        if error is not None:
            meta['error'] = error
        d = tracer.add(tr['ctx'], 'dispatch', parent_id=tr['parent'],
                       ts=t_start, dur_ms=(t_done - t_start) * 1e3,
                       **meta)
        tracer.add(tr['ctx'], 'device_run', parent_id=d['span'],
                   ts=t_run, dur_ms=(t_done - t_run) * 1e3)


class _BucketQueue:
    __slots__ = ('bucket', 'tokens', 'coords', 'pending')

    def __init__(self, bucket: int):
        self.bucket = bucket
        self.tokens: List[np.ndarray] = []
        self.coords: List[np.ndarray] = []
        self.pending: List[PendingResult] = []

    def __len__(self):
        return len(self.pending)


class MicroBatcher:
    """Queue requests per length bucket; flush on batch-full or deadline.

        batcher = MicroBatcher(engine.run, buckets=engine.buckets,
                               batch_size=engine.batch_size,
                               max_wait_ms=5.0, admission=ctl)
        pending = batcher.submit(tokens, coords)   # may raise
        batcher.pump()                             # deadline flushes
        ...
        batcher.drain()                            # end of stream

    `runner(bucket, tokens, coords, mask) -> out [B, L, ...]` is the
    engine's compiled entry; results are sliced back to each request's
    true (unpadded) rows before resolving its `PendingResult`.
    """

    def __init__(self, runner: Callable, buckets: Sequence[int],
                 batch_size: int, max_wait_ms: float = 10.0,
                 admission: Optional[AdmissionController] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.runner = runner
        self.buckets = tuple(sorted(int(b) for b in buckets))
        assert self.buckets, 'no buckets'
        self.batch_size = int(batch_size)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.admission = admission
        self.clock = clock
        self._queues = {b: _BucketQueue(b) for b in self.buckets}
        self._next_id = 0
        # request ids are monotonic ints PER BATCHER — merged record
        # streams from several replicas/hosts would collide, so owners
        # (Router/HostServer) set id_prefix to a host/replica component
        # and ids become globally unique strings like 'h0-r1-42'
        self.id_prefix: Optional[str] = None
        self.tracer = None             # observability.tracing.Tracer
        self.batches_dispatched = 0
        self.rows_dispatched = 0       # real (non-dummy) rows
        # real rows per dispatched batch: exact running stats forever,
        # raw samples capped (a serve loop runs for days — every
        # retention here must be bounded)
        self.fill_stats = agg_zero()
        self.fill_history: List[int] = []
        self._fill_capacity = 4096
        # completed results queue: DRAINED by the caller/telemetry via
        # pop_completed(); bounded so an unobserved queue cannot grow
        # without limit (oldest entries are dropped once over capacity —
        # each request's submitter still holds its own PendingResult)
        self.completed: List[PendingResult] = []
        self._completed_capacity = 65536

    # ------------------------------------------------------------------ #
    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def bucket_for(self, length: int) -> Optional[int]:
        return fit_bucket(self.buckets, length)

    def submit(self, tokens, coords) -> PendingResult:
        """Admit + enqueue one request; flushes its bucket if now full.

        Raises RequestRejected (oversize / overloaded) WITHOUT touching
        any compiled code path — rejection must never cost a compile.
        The bucket fit is checked BEFORE admission accounting, so a
        request no bucket can serve is counted rejected (never admitted)
        even when the admission controller's max_len is looser than the
        configured buckets.
        """
        tokens = np.asarray(tokens)
        length = len(tokens)
        bucket = self.bucket_for(length)
        if bucket is None:
            if self.admission is not None:
                self.admission.reject_oversize(length, self.buckets[-1])
            raise oversize_error(length, self.buckets[-1])
        if self.admission is not None:
            self.admission.admit(length, queue_depth=self.queue_depth)
        q = self._queues[bucket]
        rid = (self._next_id if self.id_prefix is None
               else f'{self.id_prefix}-{self._next_id}')
        pending = PendingResult(rid, length, bucket, self.clock())
        self._next_id += 1
        q.tokens.append(tokens)
        q.coords.append(np.asarray(coords, np.float32).reshape(-1, 3))
        q.pending.append(pending)
        if len(q) >= self.batch_size:
            self._flush(q)
        return pending

    def pump(self, now: Optional[float] = None) -> int:
        """Flush every bucket whose oldest request has hit the deadline.
        Returns the number of batches dispatched."""
        now = self.clock() if now is None else now
        n = 0
        for q in self._queues.values():
            if q.pending and now - q.pending[0].submitted_at >= self.max_wait_s:
                self._flush(q)
                n += 1
        return n

    def drain(self) -> int:
        """Flush every non-empty bucket regardless of deadline (end of a
        request stream / shutdown). Returns batches dispatched."""
        n = 0
        for q in self._queues.values():
            if q.pending:
                self._flush(q)
                n += 1
        return n

    def next_deadline(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the earliest pending deadline (sleep hint for a
        serve loop); None when idle."""
        oldest = [q.pending[0].submitted_at for q in self._queues.values()
                  if q.pending]
        if not oldest:
            return None
        now = self.clock() if now is None else now
        return max(0.0, min(oldest) + self.max_wait_s - now)

    def pop_completed(self) -> List[PendingResult]:
        """Drain the completed-results queue (telemetry's latency feed)."""
        done, self.completed = self.completed, []
        return done

    # ------------------------------------------------------------------ #
    def _flush(self, q: _BucketQueue):
        # the queue is cleared BEFORE dispatch: on a raising runner the
        # requests resolve done-with-error (never silently requeued)
        tokens, coords, pending = q.tokens, q.coords, q.pending
        q.tokens, q.coords, q.pending = [], [], []
        dispatch_batch(self.runner, q.bucket, self.batch_size, tokens,
                       coords, pending, self.completed,
                       self._completed_capacity, self.clock,
                       tracer=self.tracer)
        self.batches_dispatched += 1
        self.rows_dispatched += len(pending)
        agg_update(self.fill_stats, [len(pending)])
        if len(self.fill_history) < self._fill_capacity:
            self.fill_history.append(len(pending))
