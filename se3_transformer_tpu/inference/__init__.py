"""Inference/serving subsystem: AOT-precompiled bucketed engine with
micro-batching, admission control, and SLO telemetry.

The serving layer of the stack — it composes what training already
built instead of duplicating it:

  * `engine`    — `InferenceEngine`: params-only checkpoint restore
    (`CheckpointManager.restore_params`), one AOT executable per shape
    bucket (`jit(...).lower(...).compile()` at startup, cached per
    `(bucket_len, batch_size, dtype)`), donated coords buffers off-CPU,
    optional bf16 activation path.
  * `batching`  — `MicroBatcher`: variable-length requests queued per
    bucket, padded by the SAME `native.loader.pad_to_bucket` the
    training dataset uses, flushed on batch-full or `max_wait_ms`.
  * `admission` — `AdmissionController` + `RequestRejected`: oversize
    requests (longer than the largest bucket) and overload (queue depth
    at the shed threshold) are rejected with a structured error before
    they can touch — let alone compile — anything.
  * `telemetry` — `ServeTelemetry`: per-bucket latency p50/p95/p99 via
    the engine's `PhaseTimer`, schema'd `serve` JSONL records, and the
    RetraceWatchdog compile-event proof that a mixed-length request
    stream causes zero post-warmup compiles.

Entry point: `scripts/serve.py` (warmup -> serve loop -> summary
report); smoke gate: `make serve-smoke`.
"""
from .admission import (  # noqa: F401
    AdmissionController, OVERLOADED, OVERSIZE, RequestRejected,
)
from .batching import MicroBatcher, PendingResult  # noqa: F401
from .engine import InferenceEngine, bucket_phase  # noqa: F401
from .telemetry import ServeTelemetry  # noqa: F401
