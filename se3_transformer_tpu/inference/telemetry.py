"""Serving telemetry: per-bucket SLO percentiles, schema'd `serve`
records, and the zero-post-warmup-compile proof.

Composes the observability primitives rather than inventing new ones:

  * the engine's `PhaseTimer` already holds one `bucket_<L>` phase per
    executable — `flush()` turns its window percentiles (p50/p95/p99)
    into the `buckets` section of a `serve` record;
  * a `RetraceWatchdog` rides along for its process-wide compile-event
    counter: AOT executables cannot retrace, so after `arm()` ANY
    compile event is a contract violation. `post_warmup_compiles`
    accumulates the deltas — `scripts/serve.py` (and `make serve-smoke`)
    gate on it being exactly zero;
  * request latencies (queue wait + execute, from `MicroBatcher`'s
    completed results) and batch fill fold into window-shaped metrics
    for the end-of-run `summary` record.
"""
from __future__ import annotations

from typing import Optional

from ..observability import MetricLogger, RetraceWatchdog
from .admission import AdmissionController
from .batching import MicroBatcher
from .engine import InferenceEngine, bucket_phase


from .stats import agg_stats, agg_update, agg_zero, window_stats


class ServeTelemetry:
    """Wire an engine + batcher + admission controller into the JSONL
    telemetry stream.

        tele = ServeTelemetry(engine, batcher, admission, logger)
        engine.warmup()
        tele.arm()              # baseline AFTER the startup compiles
        ... serve ...
        tele.flush()            # one `serve` record per interval
        tele.close()            # cumulative `summary` record
        assert tele.post_warmup_compiles == 0
    """

    def __init__(self, engine: InferenceEngine,
                 batcher: Optional[MicroBatcher] = None,
                 admission: Optional[AdmissionController] = None,
                 logger: Optional[MetricLogger] = None,
                 watchdog: Optional[RetraceWatchdog] = None):
        self.engine = engine
        self.batcher = batcher
        self.admission = admission
        self.logger = logger
        self.watchdog = watchdog if watchdog is not None else \
            RetraceWatchdog()
        for key, executable in engine.executables.items():
            self.watchdog.track(f'bucket_{key[0]}', executable)
        self.post_warmup_compiles = 0
        self._armed = False
        self._latency_agg = agg_zero()
        self.flush_count = 0

    # ------------------------------------------------------------------ #
    def arm(self, emit_cost_records: bool = True):
        """Baseline the compile counter after warmup: every compile event
        from here on counts against the zero-post-warmup contract.

        Also streams the engine's per-bucket `cost` ledger (one
        schema'd record per warmed-up executable — peak HBM split,
        flops, collective bytes) so serving capacity planning reads
        memory-per-bucket off the record stream, not a debugger."""
        self.watchdog.check()        # first check arms the watchdog
        self._armed = True
        if emit_cost_records and self.logger is not None:
            for key in sorted(self.engine.cost_payloads):
                self.logger.log_record('cost', mirror=False,
                                       **self.engine.cost_payloads[key])

    def _drain_latencies(self):
        if self.batcher is None:
            return []
        ms = [p.latency_s * 1e3 for p in self.batcher.pop_completed()
              if p.latency_s is not None]
        agg_update(self._latency_agg, ms)
        return ms

    def flush(self) -> dict:
        """One schema'd `serve` record: per-bucket window percentiles,
        request counters, queue depth, watchdog snapshot."""
        timing = self.engine.timer.window_summary()
        buckets = {str(b): timing[bucket_phase(b)]
                   for b in self.engine.buckets
                   if bucket_phase(b) in timing}
        runtime = self.watchdog.check()
        if self._armed:
            self.post_warmup_compiles += runtime['compile_events_delta']
        requests = dict(
            served=sum(self.engine.rows_served.values()),
            rejected=(self.admission.snapshot()['rejected']
                      if self.admission else {}),
        )
        if self.admission is not None:
            requests['admitted'] = self.admission.admitted
        fields = dict(
            requests=requests,
            buckets=buckets,
            queue_depth=(self.batcher.queue_depth
                         if self.batcher is not None else 0),
            runtime=runtime,
            post_warmup_compiles=self.post_warmup_compiles,
        )
        latencies = self._drain_latencies()
        if latencies:
            fields['request_latency_ms'] = window_stats(latencies)
        self.flush_count += 1
        if self.logger is not None:
            return self.logger.log_record('serve', **fields)
        return fields

    def close(self) -> dict:
        """Cumulative `summary` record: total batches, request-latency /
        batch-fill metric windows, per-bucket cumulative timing, the
        engine's compile/serve counters, and the compile-event verdict."""
        # a FINAL watchdog check: compile events between the last flush
        # and close (e.g. a straggler drain) must not escape the verdict
        runtime = self.watchdog.check()
        if self._armed:
            self.post_warmup_compiles += runtime['compile_events_delta']
        self._drain_latencies()
        metrics = dict(request_latency_ms=agg_stats(self._latency_agg))
        if self.batcher is not None:
            metrics['batch_fill'] = agg_stats(self.batcher.fill_stats)
        fields = dict(
            steps=(self.batcher.batches_dispatched
                   if self.batcher is not None
                   else sum(self.engine.batches_served.values())),
            metrics=metrics,
            timing=self.engine.timer.cumulative_summary(),
            engine=self.engine.stats(),
            post_warmup_compiles=self.post_warmup_compiles,
            retrace_warnings_total=self.watchdog.warnings_total,
        )
        if self.admission is not None:
            fields['requests'] = self.admission.snapshot()
        if self.logger is not None:
            return self.logger.log_record('summary', **fields)
        return fields
