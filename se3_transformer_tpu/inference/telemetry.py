"""Serving telemetry: per-bucket SLO percentiles, schema'd `serve`
records, and the zero-post-warmup-compile proof.

Composes the observability primitives rather than inventing new ones:

  * the engine's `PhaseTimer` already holds one `bucket_<L>` phase per
    executable — `flush()` turns its window percentiles (p50/p95/p99)
    into the `buckets` section of a `serve` record;
  * a `RetraceWatchdog` rides along for its process-wide compile-event
    counter: AOT executables cannot retrace, so after `arm()` ANY
    compile event is a contract violation. `post_warmup_compiles`
    accumulates the deltas — `scripts/serve.py` (and `make serve-smoke`)
    gate on it being exactly zero;
  * request latencies (queue wait + execute, from `MicroBatcher`'s
    completed results) and batch fill fold into window-shaped metrics
    for the end-of-run `summary` record.

`ServeTelemetryBase` is the shared record-assembly plumbing: the
single-engine `ServeTelemetry` here and the multi-replica
`serving.RouterTelemetry` both build their `serve` records from the
same helpers, so the record shape cannot drift between the one-replica
and N-replica paths.
"""
from __future__ import annotations

from typing import Optional

from ..observability import MetricLogger, PhaseTimer, RetraceWatchdog
from ..observability.slo import LatencyHistogram
from .admission import AdmissionController
from .batching import MicroBatcher
from .engine import InferenceEngine, bucket_phase
from .stats import agg_stats, agg_update, agg_zero, window_stats


class ServeTelemetryBase:
    """Shared serve-record plumbing over (timer, watchdog, admission,
    logger): compile-delta accumulation against the armed baseline,
    per-bucket window assembly, the requests section, and the
    request-latency drain. Subclasses provide `_pop_completed()` (their
    source of resolved PendingResults) and `_emit_cost_records()`
    (their per-executable cost ledger)."""

    def __init__(self, timer: PhaseTimer,
                 admission: Optional[AdmissionController] = None,
                 logger: Optional[MetricLogger] = None,
                 watchdog: Optional[RetraceWatchdog] = None):
        self.timer = timer
        self.admission = admission
        self.logger = logger
        self.watchdog = watchdog if watchdog is not None else \
            RetraceWatchdog()
        self.post_warmup_compiles = 0
        self._armed = False
        self._latency_agg = agg_zero()
        self.flush_count = 0
        # mergeable per-bucket latency histograms (observability.slo):
        # fixed boundaries shared fleet-wide, so the FleetRouter's
        # aggregator can add counts across hosts and read EXACT merged
        # percentiles — plus the cumulative answered/failed counters
        # the fleet availability computation needs
        self.latency_hist: dict = {}
        self.answered_total = 0
        self.failed_total = 0
        self._window_ms: list = []

    # hooks ------------------------------------------------------------- #
    def _pop_completed(self):
        return []

    def _emit_cost_records(self):
        pass

    # shared assembly ---------------------------------------------------- #
    def arm(self, emit_cost_records: bool = True):
        """Baseline the compile counter after warmup: every compile
        event from here on counts against the zero-post-warmup
        contract. Also streams the per-executable `cost` ledger (one
        schema'd record per warmed-up bucket) so serving capacity
        planning reads memory-per-bucket off the record stream, not a
        debugger."""
        self.watchdog.check()        # first check arms the watchdog
        self._armed = True
        if emit_cost_records and self.logger is not None:
            self._emit_cost_records()

    def _check_runtime(self) -> dict:
        """Watchdog snapshot + armed compile-delta accumulation (shared
        by flush AND close so a straggler drain cannot escape the
        verdict)."""
        runtime = self.watchdog.check()
        if self._armed:
            self.post_warmup_compiles += runtime['compile_events_delta']
        return runtime

    def _bucket_windows(self, buckets) -> dict:
        """The serve record's `buckets` section off the shared timer's
        window percentiles (resets the window)."""
        timing = self.timer.window_summary()
        return {str(b): timing[bucket_phase(b)]
                for b in buckets if bucket_phase(b) in timing}

    def _requests_section(self, served: int) -> dict:
        requests = dict(
            served=served,
            rejected=(self.admission.snapshot()['rejected']
                      if self.admission else {}),
        )
        if self.admission is not None:
            requests['admitted'] = self.admission.admitted
        return requests

    def _drain_latencies(self):
        ms = []
        for p in self._pop_completed():
            if p.latency_s is not None:
                lat = p.latency_s * 1e3
                ms.append(lat)
                if p.ok:
                    # only ANSWERED latencies feed the SLO histograms —
                    # a timeout's latency is the deadline, not service
                    self.latency_hist.setdefault(
                        str(p.bucket), LatencyHistogram()).observe(lat)
            if p.ok:
                self.answered_total += 1
            elif p.done and p.error is not None:
                self.failed_total += 1
        agg_update(self._latency_agg, ms)
        self._window_ms.extend(ms)
        return ms

    def _latency_sections(self) -> dict:
        """The serve record's latency fields — ONE implementation for
        the single-engine and router emitters (the window accumulates
        across drains, so the `request_latency_ms` shape stays exactly
        what it was before histograms existed)."""
        self._drain_latencies()
        window, self._window_ms = self._window_ms, []
        fields = {}
        if window:
            fields['request_latency_ms'] = window_stats(window)
        if self.latency_hist:
            fields['latency_hist'] = {
                b: h.snapshot()
                for b, h in sorted(self.latency_hist.items())}
        return fields

    def slo_snapshot(self) -> dict:
        """Cumulative availability counters + mergeable histograms —
        the host's contribution to the fleet `slo` record (shipped in
        the stats RPC)."""
        self._drain_latencies()
        return dict(
            answered=self.answered_total,
            failed=self.failed_total,
            latency_hist={b: h.snapshot()
                          for b, h in sorted(self.latency_hist.items())})

    def _emit(self, kind: str, fields: dict) -> dict:
        if kind == 'serve':
            self.flush_count += 1
        if self.logger is not None:
            return self.logger.log_record(kind, **fields)
        return fields


class ServeTelemetry(ServeTelemetryBase):
    """Wire an engine + batcher + admission controller into the JSONL
    telemetry stream.

        tele = ServeTelemetry(engine, batcher, admission, logger)
        engine.warmup()
        tele.arm()              # baseline AFTER the startup compiles
        ... serve ...
        tele.flush()            # one `serve` record per interval
        tele.close()            # cumulative `summary` record
        assert tele.post_warmup_compiles == 0
    """

    def __init__(self, engine: InferenceEngine,
                 batcher: Optional[MicroBatcher] = None,
                 admission: Optional[AdmissionController] = None,
                 logger: Optional[MetricLogger] = None,
                 watchdog: Optional[RetraceWatchdog] = None):
        super().__init__(engine.timer, admission, logger, watchdog)
        self.engine = engine
        self.batcher = batcher
        for key, executable in engine.executables.items():
            self.watchdog.track(f'bucket_{key[0]}', executable)

    def _pop_completed(self):
        return self.batcher.pop_completed() if self.batcher is not None \
            else []

    def _emit_cost_records(self):
        for key in sorted(self.engine.cost_payloads):
            self.logger.log_record('cost', mirror=False,
                                   **self.engine.cost_payloads[key])

    def flush(self) -> dict:
        """One schema'd `serve` record: per-bucket window percentiles,
        request counters, queue depth, watchdog snapshot."""
        runtime = self._check_runtime()
        fields = dict(
            requests=self._requests_section(
                sum(self.engine.rows_served.values())),
            buckets=self._bucket_windows(self.engine.buckets),
            queue_depth=(self.batcher.queue_depth
                         if self.batcher is not None else 0),
            runtime=runtime,
            post_warmup_compiles=self.post_warmup_compiles,
        )
        fields.update(self._latency_sections())
        return self._emit('serve', fields)

    def close(self) -> dict:
        """Cumulative `summary` record: total batches, request-latency /
        batch-fill metric windows, per-bucket cumulative timing, the
        engine's compile/serve counters, and the compile-event verdict."""
        # a FINAL watchdog check: compile events between the last flush
        # and close (e.g. a straggler drain) must not escape the verdict
        self._check_runtime()
        self._drain_latencies()
        metrics = dict(request_latency_ms=agg_stats(self._latency_agg))
        if self.batcher is not None:
            metrics['batch_fill'] = agg_stats(self.batcher.fill_stats)
        fields = dict(
            steps=(self.batcher.batches_dispatched
                   if self.batcher is not None
                   else sum(self.engine.batches_served.values())),
            metrics=metrics,
            timing=self.timer.cumulative_summary(),
            engine=self.engine.stats(),
            post_warmup_compiles=self.post_warmup_compiles,
            retrace_warnings_total=self.watchdog.warnings_total,
        )
        if self.admission is not None:
            fields['requests'] = self.admission.snapshot()
        return self._emit('summary', fields)
