"""Admission control: reject early, degrade gracefully.

Two failure modes a bucketed AOT engine must never hit:

  * an **oversize request** — a sequence longer than the largest compiled
    bucket. Under `jax.jit` this would silently trigger a fresh multi-
    second XLA compile (the classic serving cliff); with AOT executables
    it would be a shape error deep in the engine. Either way the right
    answer is a structured rejection at the front door.
  * **queue collapse** — once the backlog exceeds what the engine can
    drain within the deadline budget, every queued request's latency
    grows without bound. Shedding load at a depth threshold keeps the
    p99 of *admitted* requests flat instead of letting everyone time out.

`RequestRejected` is an exception AND a record: `to_record()` returns the
JSON-safe payload that rides the `serve` telemetry stream, so rejections
are observable, not just raised. An overload shed additionally carries a
machine-readable `retry_after_s` hint (when the controller was built
with a `retry_hint`, e.g. the router's queue-depth x per-bucket-p50
estimate) — "retry with backoff" as a number a client can act on, not
prose.

`RequestFailed` is the TERMINAL sibling for requests that were admitted
but could not be answered — retry budget exhausted, or deadline expired
while queued. It resolves a `PendingResult` done-with-structured-error;
the zero-lost-requests contract (`make chaos-smoke`) is exactly that
every submit ends answered or `RequestRejected`/`RequestFailed`, never
silence.
"""
from __future__ import annotations

from typing import Callable, Optional

OVERSIZE = 'oversize'
OVERLOADED = 'overloaded'
# RequestFailed codes
RETRIES_EXHAUSTED = 'retries_exhausted'
DEADLINE = 'deadline'


def fit_bucket(buckets, length: int):
    """Smallest bucket that fits `length`, or None. THE bucket-fit rule —
    engine and batcher both route through it."""
    for b in buckets:
        if length <= b:
            return b
    return None


def oversize_error(length: int, max_len: int) -> 'RequestRejected':
    """THE oversize rejection payload (one constructor, three raisers).

    `max_bucket` duplicates `max_len` under the name clients reason in:
    a 30k-atom submitter reads the largest configured bucket straight
    off the structured detail (actionable — split the assembly or ask
    for a bigger deployment) instead of parsing the prose."""
    return RequestRejected(
        OVERSIZE,
        f'request length {length} exceeds the largest compiled bucket '
        f'({max_len}); recompile the engine with a larger bucket to '
        f'serve it',
        length=int(length), max_len=int(max_len),
        max_bucket=int(max_len))


class RequestRejected(Exception):
    """Structured rejection: `code` ('oversize' | 'overloaded') plus a
    machine-readable `detail` dict (max_len / queue depth / limits)."""

    def __init__(self, code: str, message: str, **detail):
        super().__init__(message)
        self.code = code
        self.detail = dict(detail)

    def to_record(self) -> dict:
        return dict(code=self.code, message=str(self), **self.detail)


class RequestFailed(Exception):
    """Structured TERMINAL failure of an admitted request: `code`
    ('retries_exhausted' | 'deadline') plus a machine-readable `detail`
    dict (attempts / deadline / the last underlying error). Set as a
    `PendingResult.error` — the submitter always gets an answer-shaped
    object, never a silently dropped request."""

    def __init__(self, code: str, message: str, **detail):
        super().__init__(message)
        self.code = code
        self.detail = dict(detail)

    def to_record(self) -> dict:
        return dict(code=self.code, message=str(self), **self.detail)


def retries_exhausted_error(attempts: int,
                            cause: Optional[BaseException] = None,
                            retry_after_s: Optional[float] = None
                            ) -> RequestFailed:
    """`retry_after_s` is the same machine-readable backoff hint an
    overload `RequestRejected` carries (the Router's `_fail_request`
    stamps its queue-depth estimate when the caller has none) — a
    terminal failure without it invites the client to hot-loop the
    struggling fleet it just fell out of."""
    detail = dict(
        attempts=int(attempts),
        cause=f'{type(cause).__name__}: {cause}' if cause is not None
        else None)
    if retry_after_s is not None:
        detail['retry_after_s'] = round(max(0.0, float(retry_after_s)), 4)
    return RequestFailed(
        RETRIES_EXHAUSTED,
        f'request failed on every replica it was dispatched to '
        f'({attempts} attempt{"s" if attempts != 1 else ""}); the retry '
        f'budget is spent',
        **detail)


def deadline_error(waited_s: float, timeout_s: float,
                   attempts: int = 0,
                   retry_after_s: Optional[float] = None) -> RequestFailed:
    detail = dict(
        waited_s=round(float(waited_s), 4),
        timeout_s=round(float(timeout_s), 4),
        attempts=int(attempts))
    if retry_after_s is not None:
        detail['retry_after_s'] = round(max(0.0, float(retry_after_s)), 4)
    return RequestFailed(
        DEADLINE,
        f'request deadline expired after {waited_s:.3f}s '
        f'(timeout {timeout_s:.3f}s) before a dispatch could answer it',
        **detail)


class AdmissionController:
    """Gate requests on length and backlog before they touch the engine.

        ctl = AdmissionController(max_len=512, max_queue_depth=256)
        ctl.admit(length=700, queue_depth=0)   # raises RequestRejected

    Counters (`admitted`, `rejected`) feed the `serve` telemetry record
    via `snapshot()`. `retry_hint(queue_depth) -> seconds` (optional —
    the Router wires its queue-depth x per-bucket-p50 estimate in)
    turns an overload shed's "retry with backoff" into a structured
    `retry_after_s` the client can actually schedule against.
    """

    def __init__(self, max_len: int,
                 max_queue_depth: Optional[int] = None,
                 retry_hint: Optional[Callable[[int], float]] = None):
        assert max_len > 0, 'max_len must be positive'
        self.max_len = int(max_len)
        self.max_queue_depth = (int(max_queue_depth)
                                if max_queue_depth is not None else None)
        self.retry_hint = retry_hint
        self.admitted = 0
        self.rejected = {OVERSIZE: 0, OVERLOADED: 0}

    def reject_oversize(self, length: int,
                        max_len: Optional[int] = None) -> None:
        """Count and raise an oversize rejection (callers that discover
        the overflow themselves — e.g. the batcher's bucket fit — route
        it through here so the counters stay truthful)."""
        self.rejected[OVERSIZE] += 1
        raise oversize_error(length, self.max_len if max_len is None
                             else max_len)

    def admit(self, length: int, queue_depth: int = 0) -> None:
        """Raise RequestRejected if the request must not enter the queue;
        otherwise count it admitted and return."""
        if length > self.max_len:
            self.reject_oversize(length)
        if (self.max_queue_depth is not None
                and queue_depth >= self.max_queue_depth):
            self.rejected[OVERLOADED] += 1
            detail = dict(queue_depth=int(queue_depth),
                          max_queue_depth=self.max_queue_depth)
            hint = ''
            if self.retry_hint is not None:
                retry_after = max(0.0, float(self.retry_hint(queue_depth)))
                detail['retry_after_s'] = round(retry_after, 4)
                hint = f' (retry_after_s={detail["retry_after_s"]})'
            raise RequestRejected(
                OVERLOADED,
                f'queue depth {queue_depth} at the shed threshold '
                f'({self.max_queue_depth}); retry with backoff{hint}',
                **detail)
        self.admitted += 1

    def snapshot(self) -> dict:
        """Cumulative counters for the serve record."""
        return dict(admitted=self.admitted, rejected=dict(self.rejected))
