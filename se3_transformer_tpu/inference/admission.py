"""Admission control: reject early, degrade gracefully.

Two failure modes a bucketed AOT engine must never hit:

  * an **oversize request** — a sequence longer than the largest compiled
    bucket. Under `jax.jit` this would silently trigger a fresh multi-
    second XLA compile (the classic serving cliff); with AOT executables
    it would be a shape error deep in the engine. Either way the right
    answer is a structured rejection at the front door.
  * **queue collapse** — once the backlog exceeds what the engine can
    drain within the deadline budget, every queued request's latency
    grows without bound. Shedding load at a depth threshold keeps the
    p99 of *admitted* requests flat instead of letting everyone time out.

`RequestRejected` is an exception AND a record: `to_record()` returns the
JSON-safe payload that rides the `serve` telemetry stream, so rejections
are observable, not just raised.
"""
from __future__ import annotations

from typing import Optional

OVERSIZE = 'oversize'
OVERLOADED = 'overloaded'


def fit_bucket(buckets, length: int):
    """Smallest bucket that fits `length`, or None. THE bucket-fit rule —
    engine and batcher both route through it."""
    for b in buckets:
        if length <= b:
            return b
    return None


def oversize_error(length: int, max_len: int) -> 'RequestRejected':
    """THE oversize rejection payload (one constructor, three raisers)."""
    return RequestRejected(
        OVERSIZE,
        f'request length {length} exceeds the largest compiled bucket '
        f'({max_len}); recompile the engine with a larger bucket to '
        f'serve it',
        length=int(length), max_len=int(max_len))


class RequestRejected(Exception):
    """Structured rejection: `code` ('oversize' | 'overloaded') plus a
    machine-readable `detail` dict (max_len / queue depth / limits)."""

    def __init__(self, code: str, message: str, **detail):
        super().__init__(message)
        self.code = code
        self.detail = dict(detail)

    def to_record(self) -> dict:
        return dict(code=self.code, message=str(self), **self.detail)


class AdmissionController:
    """Gate requests on length and backlog before they touch the engine.

        ctl = AdmissionController(max_len=512, max_queue_depth=256)
        ctl.admit(length=700, queue_depth=0)   # raises RequestRejected

    Counters (`admitted`, `rejected`) feed the `serve` telemetry record
    via `snapshot()`.
    """

    def __init__(self, max_len: int,
                 max_queue_depth: Optional[int] = None):
        assert max_len > 0, 'max_len must be positive'
        self.max_len = int(max_len)
        self.max_queue_depth = (int(max_queue_depth)
                                if max_queue_depth is not None else None)
        self.admitted = 0
        self.rejected = {OVERSIZE: 0, OVERLOADED: 0}

    def reject_oversize(self, length: int,
                        max_len: Optional[int] = None) -> None:
        """Count and raise an oversize rejection (callers that discover
        the overflow themselves — e.g. the batcher's bucket fit — route
        it through here so the counters stay truthful)."""
        self.rejected[OVERSIZE] += 1
        raise oversize_error(length, self.max_len if max_len is None
                             else max_len)

    def admit(self, length: int, queue_depth: int = 0) -> None:
        """Raise RequestRejected if the request must not enter the queue;
        otherwise count it admitted and return."""
        if length > self.max_len:
            self.reject_oversize(length)
        if (self.max_queue_depth is not None
                and queue_depth >= self.max_queue_depth):
            self.rejected[OVERLOADED] += 1
            raise RequestRejected(
                OVERLOADED,
                f'queue depth {queue_depth} at the shed threshold '
                f'({self.max_queue_depth}); retry with backoff',
                queue_depth=int(queue_depth),
                max_queue_depth=self.max_queue_depth)
        self.admitted += 1

    def snapshot(self) -> dict:
        """Cumulative counters for the serve record."""
        return dict(admitted=self.admitted, rejected=dict(self.rejected))
