"""Deterministic fault injection for the chaos harness.

Robustness claims that were never exercised are fiction: "a crashed
replica quarantines and recovers", "a torn checkpoint falls back" are
only true if something actually crashes a replica and tears a
checkpoint, on demand, reproducibly. `FaultInjector` is that something
— a seeded, plan-driven injector wired into three sites:

  * `replica_dispatch`  — `ReplicaWorker(fault_injector=...)` fires it
    before every batch execution (ctx: replica, bucket);
  * `engine_run`        — `InferenceEngine(fault_injector=...)` fires it
    inside `run()` (ctx: bucket) — one level deeper, under the timer;
  * `checkpoint_write` / `checkpoint_written` — `CheckpointManager(
    fault_injector=...)` fires before/after the durable write (ctx:
    step, and path on the post-write site, where a `corrupt` plan
    tears the just-written checkpoint — the preemption-mid-write
    scenario `restore`'s integrity fallback exists for);
  * `transport`         — `serving.transport` fires it before every RPC
    a fleet front-end issues (ctx: method, host): `latency` plans model
    a slow link, `exception` plans a reset connection, and the
    cooperative `drop` kind models a PARTITION — the transport sees
    'drop' and raises `TransportError` without ever sending, so the
    fleet-chaos smoke's RPC flakiness is seeded and deterministic, not
    emergent from process timing;
  * training sites (training.guardian / training.pipeline):
    `step_dispatch` fires before every guarded optimizer step (ctx:
    step — exception plans walk the rollback path a real device fault
    walks), `step_batch` fires at batch build (a `nan` plan poisons
    that step's coords, driving a genuine non-finite loss through the
    jitted step), `batch_source` fires before every producer-thread
    pull (`BatchProducer(fault_injector=...)` — exception plans
    exercise the transient-retry/poison-skip path), and
    `emergency_save` fires on the preemption handler's save path (a
    dying emergency writer must still exit resumable).

Fault kinds:

  * `exception` — raise `InjectedFault` (walks the exact path a real
    runner/engine/writer failure walks: dispatch_batch error contract,
    retry-with-redispatch, health accounting, async-write barriers);
  * `latency`   — sleep `latency_s` (a slow replica / slow writer);
  * `corrupt`   — truncate the file (or every file under the dir) named
    by ctx['path'] to `frac` of its bytes: a torn checkpoint on disk;
  * `nan`       — COOPERATIVE: record the firing and return 'nan' from
    `fire()`; the call site poisons its own data (the training
    guardian multiplies the step's batch coords by NaN, so a genuine
    non-finite loss walks the real jitted step — the injector cannot
    reach into a compiled program, so the site cooperates);
  * `drop`      — COOPERATIVE: record the firing and return 'drop'; the
    call site discards its own message (a transport raises
    `TransportError` without sending — a network partition looks like
    silence at the caller, not a raised exception inside it).

`fire()` returns the kind that acted ('exception' never returns — it
raises) or None when no plan triggered; only cooperative kinds need
the caller to look at it.

Plans are DETERMINISTIC: each plan keeps its own call counter over the
fires that match its site + ctx filters and triggers on explicit call
indices (`at=(3, 4)`), a period (`every=5`), or a seeded coin
(`p=0.1`, from the injector's private `random.Random(seed)` — same
seed, same faults). Every firing is appended to `injector.injected`
(JSON-safe), which is the `injections` payload of the schema'd `fault`
record — the evidence stream `make chaos-smoke` gates on.

    inj = FaultInjector(seed=0)
    inj.plan('replica_dispatch', 'exception', match=dict(replica=0),
             at=(2, 3, 4))                  # crash r0's dispatches 2-4
    inj.plan('engine_run', 'latency', every=7, latency_s=0.05)
    inj.plan('checkpoint_written', 'corrupt', at=(2,))   # tear ckpt 2
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, List, Optional, Sequence

__all__ = ['FaultInjector', 'InjectedFault']

FAULT_KINDS = ('exception', 'latency', 'corrupt', 'nan', 'drop')


class InjectedFault(RuntimeError):
    """A deliberately injected failure (site + plan provenance in the
    message). Semantically a RuntimeError: consumers must treat it the
    way they treat a real one — that is the point."""

    def __init__(self, site: str, message: str, **ctx):
        super().__init__(f'injected fault at {site}: {message}')
        self.site = site
        self.ctx = dict(ctx)


class _Plan:
    __slots__ = ('site', 'kind', 'at', 'every', 'p', 'match',
                 'latency_s', 'frac', 'max_fires', 'calls', 'fires')

    def __init__(self, site: str, kind: str, *,
                 at: Optional[Sequence[int]] = None,
                 every: Optional[int] = None,
                 p: Optional[float] = None,
                 match: Optional[dict] = None,
                 latency_s: float = 0.05,
                 frac: float = 0.5,
                 max_fires: Optional[int] = None):
        assert kind in FAULT_KINDS, f'unknown fault kind {kind!r}'
        assert sum(x is not None for x in (at, every, p)) == 1, \
            'exactly one of at= / every= / p= selects when a plan fires'
        self.site = site
        self.kind = kind
        self.at = tuple(int(i) for i in at) if at is not None else None
        self.every = int(every) if every is not None else None
        self.p = float(p) if p is not None else None
        self.match = dict(match or {})
        self.latency_s = float(latency_s)
        self.frac = float(frac)
        self.max_fires = max_fires
        self.calls = 0    # matching fire() calls seen (1-based index)
        self.fires = 0

    def wants(self, rng: random.Random) -> bool:
        """Called once per MATCHING fire(); decides and counts."""
        self.calls += 1
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.at is not None:
            return self.calls in self.at
        if self.every is not None:
            return self.calls % self.every == 0
        return rng.random() < self.p


def _truncate(path: str, frac: float):
    size = os.path.getsize(path)
    with open(path, 'r+b') as f:
        f.truncate(max(0, int(size * frac)))


def corrupt_path(path: str, frac: float = 0.5) -> List[str]:
    """Tear a checkpoint on disk: truncate the file — or, for an orbax
    step directory, every regular file under it — to `frac` of its
    bytes. Returns the torn paths (for the injection record)."""
    torn = []
    if os.path.isdir(path):
        for root, _, files in os.walk(path):
            for name in files:
                p = os.path.join(root, name)
                _truncate(p, frac)
                torn.append(p)
    else:
        _truncate(path, frac)
        torn.append(path)
    return torn


class FaultInjector:
    """Seeded, plan-driven fault injector (module docstring has the
    full contract). `fire(site, **ctx)` is the instrumentation hook —
    a no-plan site costs one dict lookup, so leaving the hooks wired in
    production code is free."""

    def __init__(self, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self.rng = random.Random(seed)
        self.seed = int(seed)
        self.sleep = sleep
        self._plans: List[_Plan] = []
        self.injected: List[dict] = []   # JSON-safe firing log
        # fire() is called concurrently at the `transport` site (the
        # fleet's dispatch/heartbeat/probe pool threads share one
        # injector): plan selection, counters, and the rng must stay
        # serialized or at=/every= firings drift run-to-run and the
        # "same seed, same faults" determinism claim is false
        self._lock = threading.Lock()

    def plan(self, site: str, kind: str = 'exception', **kw) -> '_Plan':
        p = _Plan(site, kind, **kw)
        self._plans.append(p)
        return p

    # ------------------------------------------------------------------ #
    def fire(self, site: str, **ctx):
        """Instrumentation hook: evaluate every plan for `site` whose
        ctx filters match; act on the first that triggers (raise /
        sleep / corrupt / return 'nan'). Recording happens BEFORE the
        action, so an injected exception is in the log even though it
        unwinds. Returns the kind that acted (None when no plan
        triggered) — cooperative kinds ('nan') rely on the caller
        reading it."""
        # decide + record under the lock (counters/rng/log serialized —
        # concurrent transport-site fires must not make an at=(5,) plan
        # double-fire or skip); ACT outside it (a latency sleep held
        # under the lock would serialize every concurrent RPC behind
        # the injected one, distorting the very timing being tested)
        fired = None
        with self._lock:
            for plan in self._plans:
                if plan.site != site:
                    continue
                if any(ctx.get(k) != v for k, v in plan.match.items()):
                    continue
                if not plan.wants(self.rng):
                    continue
                plan.fires += 1
                event = dict(site=site, kind=plan.kind, call=plan.calls,
                             **{k: v for k, v in ctx.items()
                                if isinstance(v, (str, int, float, bool))})
                self.injected.append(event)
                # one action per fire: later plans for this site keep
                # their counters (they were not consulted) and may
                # trigger on a future call — without this, stacked
                # latency plans would sleep twice and a
                # latency+exception pair would do both on one call,
                # violating the documented contract
                fired = (plan, event)
                break
        if fired is None:
            return None
        plan, event = fired
        if plan.kind == 'latency':
            event['latency_s'] = plan.latency_s
            self.sleep(plan.latency_s)
        elif plan.kind == 'corrupt':
            path = ctx.get('path')
            assert path, f'corrupt plan at {site} needs ctx path='
            event['torn'] = corrupt_path(path, plan.frac)
        elif plan.kind in ('nan', 'drop'):
            pass         # cooperative: the caller acts on the kind
        else:
            raise InjectedFault(
                site, f'{plan.kind} (call {event["call"]})', **ctx)
        return plan.kind

    # ------------------------------------------------------------------ #
    @property
    def injections_total(self) -> int:
        return len(self.injected)

    def snapshot(self) -> dict:
        """The `fault` record's injection payload."""
        by_site: dict = {}
        for e in self.injected:
            key = f"{e['site']}:{e['kind']}"
            by_site[key] = by_site.get(key, 0) + 1
        return dict(seed=self.seed, injections=list(self.injected),
                    injections_total=self.injections_total,
                    by_site=by_site)
