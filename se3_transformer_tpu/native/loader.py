"""ctypes loader for the native host-side graph/data pipeline.

Compiles graph_builder.cpp on first use (cached as a shared library next to
the source; rebuilt when the source is newer). Every entry point has a
NumPy fallback, so the framework works even without a toolchain — the
native path just keeps the TPU from waiting on host-side batch prep.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'graph_builder.cpp')
_LIB = os.path.join(_HERE, 'libse3graph.so')
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ['g++', '-O3', '-shared', '-fPIC', _SRC, '-o', _LIB + '.tmp']
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LIB + '.tmp', _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            needs_build = (not os.path.exists(_LIB)
                           or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
            if needs_build and not _build():
                return None
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None

        i8p = np.ctypeslib.ndpointer(np.uint8, flags='C_CONTIGUOUS')
        i32p = np.ctypeslib.ndpointer(np.int32, flags='C_CONTIGUOUS')
        f32p = np.ctypeslib.ndpointer(np.float32, flags='C_CONTIGUOUS')
        i32 = ctypes.c_int32

        lib.chain_adjacency.argtypes = [i32, i8p]
        lib.expand_adjacency.argtypes = [i32, i32, i8p, i32p]
        lib.knn_graph.argtypes = [f32p, i32, i32, i32, ctypes.c_float,
                                  i32p, f32p, i8p]
        lib.pad_token_batch.argtypes = [i32p, i32p, i32, i32, i32, i32p, i8p]
        lib.pad_coord_batch.argtypes = [f32p, i32p, i32, i32, f32p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def chain_adjacency(n: int) -> np.ndarray:
    lib = get_lib()
    out = np.zeros((n, n), np.uint8)
    if lib is not None:
        lib.chain_adjacency(n, out)
    else:
        i = np.arange(n)
        out = (np.abs(i[:, None] - i[None, :]) == 1).astype(np.uint8)
    return out.astype(bool)


def expand_adjacency(adj: np.ndarray, num_degrees: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Expanded adjacency + hop-count ring labels (host-side counterpart of
    ops.neighbors.expand_adjacency)."""
    n = adj.shape[-1]
    lib = get_lib()
    if lib is not None and adj.ndim == 2:
        # explicit copy: the C function expands its argument in place, and
        # ascontiguousarray would alias an already-uint8 caller array
        a = np.array(adj, dtype=np.uint8, copy=True, order='C')
        labels = np.zeros((n, n), np.int32)
        lib.expand_adjacency(n, num_degrees, a, labels)
        return a.astype(bool), labels
    # numpy fallback (also the batched path)
    a = adj.astype(bool)
    labels = a.astype(np.int32)
    cur = a
    for d in range(2, num_degrees + 1):
        nxt = (cur.astype(np.float32) @ cur.astype(np.float32)) > 0
        labels = np.where(nxt & ~cur & (labels == 0), d, labels)
        cur = nxt
    return cur, labels


def knn_graph(coords: np.ndarray, k: int, radius: float = np.inf
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Exact batched kNN excluding self. coords [b, n, 3] float32 ->
    (idx [b,n,k] i32, dist [b,n,k] f32, mask [b,n,k] bool)."""
    coords = np.ascontiguousarray(coords, np.float32)
    b, n, _ = coords.shape
    k = int(min(k, n - 1)) if n > 1 else 0
    lib = get_lib()
    idx = np.zeros((b, n, k), np.int32)
    dist = np.zeros((b, n, k), np.float32)
    mask = np.zeros((b, n, k), np.uint8)
    if k == 0:
        return idx, dist, mask.astype(bool)
    if lib is not None:
        r = np.float32(radius if np.isfinite(radius) else np.finfo(np.float32).max)
        lib.knn_graph(coords, b, n, k, r, idx, dist, mask)
        return idx, dist, mask.astype(bool)
    # numpy fallback
    d2 = ((coords[:, :, None, :] - coords[:, None, :, :]) ** 2).sum(-1)
    ii = np.arange(n)
    d2[:, ii, ii] = np.inf
    idx = np.argsort(d2, axis=-1)[..., :k].astype(np.int32)
    dist = np.sqrt(np.take_along_axis(d2, idx, axis=-1)).astype(np.float32)
    return idx, dist, dist <= radius


def pad_to_bucket(token_seqs, coord_seqs, bucket_len: int,
                  batch_size: Optional[int] = None, pad_value: int = 0):
    """THE pad-to-bucket implementation, shared by training
    (`training/dataset.py:batches`) and serving
    (`inference/batching.py:MicroBatcher`) so the two sides cannot drift:
    a sequence padded for a serving bucket is bit-identical to the same
    sequence padded for the training bucket.

    Truncates each ragged sequence to `bucket_len`, pads to
    tokens [B, bucket_len] / coords [B, bucket_len, 3] / mask
    [B, bucket_len], and — when `batch_size` exceeds the number of
    sequences — appends all-padding rows (mask False everywhere) so the
    batch matches a fixed-shape compiled executable.
    """
    assert batch_size is None or len(token_seqs) <= batch_size, (
        f'{len(token_seqs)} sequences do not fit a batch of {batch_size}')
    toks = [np.asarray(t)[:bucket_len] for t in token_seqs]
    crds = [np.asarray(c, np.float32).reshape(-1, 3)[:bucket_len]
            for c in coord_seqs]
    tokens, coords, mask = pad_batch(toks, crds, max_len=bucket_len,
                                     pad_value=pad_value)
    if batch_size is not None and tokens.shape[0] < batch_size:
        extra = batch_size - tokens.shape[0]
        tokens = np.concatenate(
            [tokens, np.full((extra, bucket_len), pad_value, np.int32)])
        coords = np.concatenate(
            [coords, np.zeros((extra, bucket_len, 3), np.float32)])
        mask = np.concatenate(
            [mask, np.zeros((extra, bucket_len), bool)])
    return tokens, coords, mask


def pad_batch(token_seqs, coord_seqs, max_len: Optional[int] = None,
              pad_value: int = 0):
    """Ragged (tokens, coords) sequences -> padded [b, L] / [b, L, 3] batch
    with mask. Host-side equivalent of the reference's per-sequence
    truncation loop (denoise.py:57-68)."""
    b = len(token_seqs)
    lengths = np.asarray([len(t) for t in token_seqs], np.int32)
    L = int(max_len if max_len is not None else lengths.max())
    lib = get_lib()
    tokens_out = np.full((b, L), pad_value, np.int32)
    mask = np.zeros((b, L), np.uint8)
    coords_out = np.zeros((b, L, 3), np.float32)
    if lib is not None:
        flat_t = np.ascontiguousarray(
            np.concatenate([np.asarray(t, np.int32) for t in token_seqs]))
        flat_c = np.ascontiguousarray(
            np.concatenate([np.asarray(c, np.float32).reshape(-1, 3)
                            for c in coord_seqs]))
        lib.pad_token_batch(flat_t, lengths, b, L, pad_value, tokens_out,
                            mask)
        lib.pad_coord_batch(flat_c, lengths, b, L, coords_out)
    else:
        for i, (t, c) in enumerate(zip(token_seqs, coord_seqs)):
            Li = min(len(t), L)
            tokens_out[i, :Li] = np.asarray(t[:Li], np.int32)
            coords_out[i, :Li] = np.asarray(c[:Li], np.float32)
            mask[i, :Li] = 1
    return tokens_out, coords_out, mask.astype(bool)
