from .loader import (
    native_available, chain_adjacency, expand_adjacency, knn_graph,
    pad_batch, pad_to_bucket, get_lib,
)
