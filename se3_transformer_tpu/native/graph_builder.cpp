// Host-side graph/data pipeline kernels (C, exported for ctypes).
//
// The reference's host pipeline is Python-side sidechainnet slicing
// (/root/reference/denoise.py:54-76). On TPU the accelerator must never
// wait on the host, so the batch-preparation path (adjacency construction,
// kNN candidate graphs for dataset filtering/bucketing, padded batch
// assembly) is native code. Compiled at import by native/loader.py; every
// entry point has a NumPy fallback.
//
// Build: g++ -O3 -march=native -shared -fPIC graph_builder.cpp -o libse3graph.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Chain adjacency: nodes i, i+1 bonded. out is [n*n] row-major uint8.
void chain_adjacency(int32_t n, uint8_t* out) {
    std::memset(out, 0, (size_t)n * n);
    for (int32_t i = 0; i + 1 < n; ++i) {
        out[(size_t)i * n + i + 1] = 1;
        out[(size_t)(i + 1) * n + i] = 1;
    }
}

// N-hop expansion with ring labels (reference se3_transformer_pytorch.py
// :1177-1190 semantics): labels[i,j] = smallest hop count <= num_degrees
// reachable via repeated boolean squaring, 0 if unreachable. adj and
// labels are [n*n]; adj is modified in place to the expanded matrix.
void expand_adjacency(int32_t n, int32_t num_degrees, uint8_t* adj,
                      int32_t* labels) {
    std::vector<uint8_t> cur(adj, adj + (size_t)n * n);
    for (size_t ij = 0; ij < (size_t)n * n; ++ij)
        labels[ij] = adj[ij] ? 1 : 0;
    std::vector<uint8_t> next((size_t)n * n);
    for (int32_t d = 2; d <= num_degrees; ++d) {
        // next = (cur @ cur) > 0
        for (int32_t i = 0; i < n; ++i) {
            const uint8_t* row = &cur[(size_t)i * n];
            uint8_t* nrow = &next[(size_t)i * n];
            std::memset(nrow, 0, n);
            for (int32_t k = 0; k < n; ++k) {
                if (!row[k]) continue;
                const uint8_t* krow = &cur[(size_t)k * n];
                for (int32_t j = 0; j < n; ++j) nrow[j] |= krow[j];
            }
        }
        for (size_t ij = 0; ij < (size_t)n * n; ++ij) {
            if (next[ij] && !cur[ij] && labels[ij] == 0) labels[ij] = d;
        }
        cur = next;
    }
    std::memcpy(adj, cur.data(), (size_t)n * n);
}

// Exact kNN (excluding self) per batch of point clouds.
// coords [b, n, 3] float32. Outputs idx [b, n, k] int32, dist [b, n, k]
// float32, mask [b, n, k] uint8 (dist <= radius). Selection by partial
// sort; ties broken by index (stable), matching fixed-K top-k semantics.
void knn_graph(const float* coords, int32_t b, int32_t n, int32_t k,
               float radius, int32_t* idx, float* dist, uint8_t* mask) {
    std::vector<std::pair<float, int32_t>> cand;
    for (int32_t bi = 0; bi < b; ++bi) {
        const float* C = coords + (size_t)bi * n * 3;
        for (int32_t i = 0; i < n; ++i) {
            cand.clear();
            cand.reserve(n - 1);
            const float xi = C[i * 3], yi = C[i * 3 + 1], zi = C[i * 3 + 2];
            for (int32_t j = 0; j < n; ++j) {
                if (j == i) continue;
                const float dx = xi - C[j * 3], dy = yi - C[j * 3 + 1],
                            dz = zi - C[j * 3 + 2];
                cand.emplace_back(dx * dx + dy * dy + dz * dz, j);
            }
            const int32_t kk = std::min<int32_t>(k, (int32_t)cand.size());
            std::partial_sort(cand.begin(), cand.begin() + kk, cand.end());
            size_t base = ((size_t)bi * n + i) * k;
            for (int32_t t = 0; t < k; ++t) {
                if (t < kk) {
                    float d = std::sqrt(cand[t].first);
                    idx[base + t] = cand[t].second;
                    dist[base + t] = d;
                    mask[base + t] = d <= radius ? 1 : 0;
                } else {
                    idx[base + t] = 0;
                    dist[base + t] = 0.f;
                    mask[base + t] = 0;
                }
            }
        }
    }
}

// Pad a ragged set of sequences into one [b, max_len] int32 batch plus
// mask. lengths [b], flat concatenated tokens.
void pad_token_batch(const int32_t* flat, const int32_t* lengths, int32_t b,
                     int32_t max_len, int32_t pad_value, int32_t* out,
                     uint8_t* mask) {
    size_t off = 0;
    for (int32_t bi = 0; bi < b; ++bi) {
        int32_t L = lengths[bi];
        for (int32_t t = 0; t < max_len; ++t) {
            out[(size_t)bi * max_len + t] = t < L ? flat[off + t] : pad_value;
            mask[(size_t)bi * max_len + t] = t < L ? 1 : 0;
        }
        off += L;
    }
}

// Same for float coordinate triples.
void pad_coord_batch(const float* flat, const int32_t* lengths, int32_t b,
                     int32_t max_len, float* out) {
    size_t off = 0;
    for (int32_t bi = 0; bi < b; ++bi) {
        int32_t L = lengths[bi];
        for (int32_t t = 0; t < max_len; ++t) {
            for (int32_t c = 0; c < 3; ++c)
                out[((size_t)bi * max_len + t) * 3 + c] =
                    t < L ? flat[(off + t) * 3 + c] : 0.f;
        }
        off += L;
    }
}

}  // extern "C"
