"""Pallas TPU kernel for the fused pairwise TFN convolution.

This is THE compute hot spot of the model (SURVEY.md §3.3): per edge e and
degree pair (d_in, d_out), the reference computes a radial profile
R[e, o, i, f] with a per-pair MLP, multiplies by the angular basis
B[e, P, Q, f] (P = 2*d_out+1, Q = 2*d_in+1) and contracts with gathered
neighbor features x[e, i, Q]. The XLA path materializes R in HBM —
2*E*o*i*f floats of traffic that dwarf the FLOPs (bandwidth-bound ~6x).

This kernel fuses the final radial matmul with the contraction so R only
ever exists as VMEM tiles:

    inputs  H  [E, mid+1]      radial-MLP hidden (with folded-bias 1s col)
            W3 [mid+1, IF, O]  final radial weight, (i, f) flattened
            V2 [E, P, IF]      = sum_Q B[e,P,Q,f] x[e,i,Q]  (cheap, XLA)
    per (if-chunk, e-block) program:
            R   = H_blk @ W3_chunk            # MXU, shared weights
            out += V2_chunk  @b R             # MXU, per-edge batched
    output  out [E, P, O]

Grid order is (n_if, n_e) with the output block revisited across the outer
if-axis (accumulate), so W3 streams through VMEM once per if-chunk and the
huge R tensor never touches HBM. The P axis rides the sublane dimension
(P <= 7 pads to 8 — cheap), O rides lanes.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(h_ref, w3_ref, v2_ref, o_ref):
    # R chunk: [E_b, IF_b, O] — exists only in VMEM
    r = jax.lax.dot_general(
        h_ref[:], w3_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # per-edge batched contraction: [E_b, P, IF_b] x [E_b, IF_b, O].
    # Each (f, e) program owns its own output block (partial sums over the
    # if-axis are reduced outside the kernel): output blocks are never
    # revisited, which keeps the TPU revisit rules trivially satisfied and
    # W3 streaming to exactly one pass.
    o_ref[0] = jax.lax.dot_general(
        v2_ref[:], r,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pick_blocks(E: int, IF: int, O: int, mid: int,
                 vmem_budget: int = 10 * 2 ** 20,
                 bwd: bool = False):
    """Choose (block_e, block_if) so the kernel working set fits in VMEM.

    The backward kernel's working set is roughly double the forward's
    (extra dR chunk, g input block, and dW3/dV2/dH output blocks), so it
    gets its own accounting."""
    block_if = min(IF, 128)
    while True:
        for block_e in (256, 128, 64, 32, 16, 8):
            w3 = mid * block_if * O * 4
            r = block_e * block_if * O * 4
            v2 = block_e * 8 * block_if * 4
            out = block_e * 8 * O * 4
            h = block_e * mid * 4
            total = w3 + 2 * r + v2 + out + h
            if bwd:
                # + dR chunk, g block, dW3 (w3-sized), dV2 (v2-sized),
                # dH (h-sized) blocks
                total += r + out + w3 + v2 + h
            if total <= vmem_budget:
                return block_e, block_if
        if block_if <= 8:
            return 8, block_if
        block_if //= 2


@functools.partial(jax.jit, static_argnames=('interpret',))
def fused_pairwise_conv(h: jnp.ndarray, w3: jnp.ndarray, v2: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """h [E, mid], w3 [mid, IF, O], v2 [E, P, IF] -> out [E, P, O] (f32).

    Fold the radial bias by appending a ones column to h and the bias row
    to w3 before calling (see PairwiseConvSE3).
    """
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]

    block_e, block_if = _pick_blocks(E, IF, O, mid)

    Ep = _round_up(E, block_e)
    IFp = _round_up(IF, block_if)
    if Ep != E:
        h = jnp.pad(h, ((0, Ep - E), (0, 0)))
        v2 = jnp.pad(v2, ((0, Ep - E), (0, 0), (0, 0)))
    if IFp != IF:
        w3 = jnp.pad(w3, ((0, 0), (0, IFp - IF), (0, 0)))
        v2 = jnp.pad(v2, ((0, 0), (0, 0), (0, IFp - IF)))

    n_if = IFp // block_if
    n_e = Ep // block_e

    out = pl.pallas_call(
        _kernel,
        grid=(n_if, n_e),
        in_specs=[
            pl.BlockSpec((block_e, mid), lambda f, e: (e, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mid, block_if, O), lambda f, e: (0, f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_e, P, block_if), lambda f, e: (e, 0, f),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_e, P, O), lambda f, e: (f, e, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_if, Ep, P, O), jnp.float32),
        interpret=interpret,
    )(h, w3, v2)

    # reduce the per-if-chunk partial sums (n_if <= 7 for IF <= 896; XLA
    # fuses this into a cheap elementwise pass)
    return out.sum(axis=0)[:E]


def pallas_available() -> bool:
    return jax.default_backend() == 'tpu'


# --------------------------------------------------------------------- #
# fused backward
# --------------------------------------------------------------------- #
# Cotangents of out[e,P,o] = sum_{if} V2[e,P,if] (H W3)[e,if,o]:
#   dV2[e,P,if] = sum_o  g[e,P,o]  R[e,if,o]
#   dR [e,if,o] = sum_P  V2[e,P,if] g[e,P,o]
#   dH [e,m]    = sum_{if,o} dR[e,if,o] W3[m,if,o]     (shared matmul)
#   dW3[m,if,o] = sum_e  H[e,m] dR[e,if,o]             (shared matmul)
# R and dR exist only as VMEM chunks. Accumulations that would revisit
# output blocks non-consecutively (dH over the outer if-axis) are written
# as per-chunk partials and reduced outside; dW3 accumulates over the
# minormost (e) axis, which is the legal consecutive-revisit pattern.


def _bwd_kernel(h_ref, w3_ref, v2_ref, g_ref,
                dv2_ref, dh_ref, dw3_ref):
    e = pl.program_id(1)

    # R chunk for dV2
    r = jax.lax.dot_general(
        h_ref[:], w3_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [E_b, IF_b, O]
    g = g_ref[:]                                         # [E_b, P, O]
    dv2_ref[0] = jax.lax.dot_general(
        g, r, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv2_ref.dtype)

    # dR chunk: per-edge [IF_b, P] @ [P, O]
    dr = jax.lax.dot_general(
        v2_ref[:], g, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)              # [E_b, IF_b, O]

    # dH partial for this if-chunk: [E_b, IF_b*O] @ [IF_b*O, mid]
    dh_ref[0] = jax.lax.dot_general(
        dr, w3_ref[:],
        dimension_numbers=(((1, 2), (1, 2)), ((), ())),
        preferred_element_type=jnp.float32).astype(dh_ref.dtype)

    # dW3 chunk accumulated over the inner e-axis (consecutive revisits)
    upd = jax.lax.dot_general(
        h_ref[:], dr, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [mid, IF_b, O]

    @pl.when(e == 0)
    def _():
        dw3_ref[:] = upd.astype(dw3_ref.dtype)

    @pl.when(e > 0)
    def _():
        dw3_ref[:] = dw3_ref[:] + upd.astype(dw3_ref.dtype)


@functools.partial(jax.jit, static_argnames=('interpret',))
def fused_pairwise_conv_bwd(h: jnp.ndarray, w3: jnp.ndarray,
                            v2: jnp.ndarray, g: jnp.ndarray,
                            interpret: bool = False):
    """Backward of fused_pairwise_conv: returns (dh, dw3, dv2), all f32.

    h [E, mid], w3 [mid, IF, O], v2 [E, P, IF], g [E, P, O].
    """
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]

    block_e, block_if = _pick_blocks(E, IF, O, mid, bwd=True)
    Ep = _round_up(E, block_e)
    IFp = _round_up(IF, block_if)
    if Ep != E:
        h = jnp.pad(h, ((0, Ep - E), (0, 0)))
        v2 = jnp.pad(v2, ((0, Ep - E), (0, 0), (0, 0)))
        g = jnp.pad(g, ((0, Ep - E), (0, 0), (0, 0)))
    if IFp != IF:
        w3 = jnp.pad(w3, ((0, 0), (0, IFp - IF), (0, 0)))
        v2 = jnp.pad(v2, ((0, 0), (0, 0), (0, IFp - IF)))

    n_if = IFp // block_if
    n_e = Ep // block_e

    dv2, dh_partial, dw3 = pl.pallas_call(
        _bwd_kernel,
        grid=(n_if, n_e),
        in_specs=[
            pl.BlockSpec((block_e, mid), lambda f, e: (e, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mid, block_if, O), lambda f, e: (0, f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_e, P, block_if), lambda f, e: (e, 0, f),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_e, P, O), lambda f, e: (e, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_e, P, block_if),
                         lambda f, e: (f, e, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_e, mid), lambda f, e: (f, e, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mid, block_if, O), lambda f, e: (0, f, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_if, Ep, P, block_if), jnp.float32),
            jax.ShapeDtypeStruct((n_if, Ep, mid), jnp.float32),
            jax.ShapeDtypeStruct((mid, IFp, O), jnp.float32),
        ],
        interpret=interpret,
    )(h, w3, v2, g)

    # dv2 partial blocks [n_if, Ep, P, block_if] -> [Ep, P, IFp]
    dv2 = dv2.transpose(1, 2, 0, 3).reshape(Ep, P, IFp)
    dh = dh_partial.sum(axis=0)
    return dh[:E], dw3[:, :IF], dv2[:E, :, :IF]
