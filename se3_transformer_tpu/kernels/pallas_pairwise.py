"""Pallas TPU kernel for the fused pairwise TFN convolution.

This is THE compute hot spot of the model (SURVEY.md §3.3): per edge e and
degree pair (d_in, d_out), the reference computes a radial profile
R[e, o, i, f] with a per-pair MLP, multiplies by the angular basis
B[e, P, Q, f] (P = 2*d_out+1, Q = 2*d_in+1) and contracts with gathered
neighbor features x[e, i, Q] (reference se3_transformer_pytorch.py:336-338).
The XLA path materializes R in HBM — 2*E*IF*O floats of traffic that dwarf
the FLOPs (bandwidth-bound ~6x). This kernel fuses the final radial matmul
with the contraction so R only ever exists as VMEM tiles.

Mosaic-lowering ground rules (learned on-chip: `infer-vector-layout:
unsupported shape cast` / `lhs contracting dims must be of size 1`):
every in-kernel tensor op must be a 2D matmul with single contracting
dims, a static sublane (row) slice, a [1, E] x [O, E] sublane broadcast,
or a sublane reduction. All reshapes/transposes happen OUTSIDE the kernel
in XLA, where they are free relayouts. The layout that makes that
possible puts the EDGE axis on lanes:

    hT  [mid, E]        radial-MLP hidden, transposed
    w3T [IF*O, mid]     final radial weight, (if, o) flattened if-major
    b3T [IF*O, 1]       radial bias column, same row order as w3T
    v2T [P, IF, E]      = sum_Q B[e,P,Q,f] x[e,i,Q], edge-last
    per (e-block, if-chunk) program:
        rT   = w3T_chunk @ hT_blk + b3T_chunk   # one 2D MXU matmul + a
                                                # [S,1]-over-lanes broadcast
        out[pO+o, e] += v2T[p, i, e] * rT[iO+o, e]   # P*bif sublane FMAs
    outT [P*O, E] -> transpose/reshape outside -> out [E, P, O]

    The bias rides as its own [S, 1] operand rather than folded into the
    matmul (a ones column on h / bias row on w3, the pre-round-4 design):
    folding made the contraction dim mid+1 = 129, and the MXU contracts
    in 128-chunks — the dominant dot (~95% of ALL flagship FLOPs, see
    utils/flops.py) paid a second, 1/129-useful pass, a structural ~2x
    tax on every path. mid stays exactly 128 now.

The grid is (n_e, n_if) with the out block revisited across the inner
if-axis (consecutive revisits — the legal TPU accumulation pattern), so
the huge R tensor never touches HBM and w3 streams through VMEM.

The backward runs as TWO kernels because its two accumulated cotangents
want different inner grid axes: dW3 accumulates over edges (grid
(n_if, n_e), e inner) while dH accumulates over if-chunks (grid
(n_e, n_if), f inner). dV2 falls out of kernel A for free. dR exists only
as per-(i) VMEM blocks in both.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _block_overrides(*names):
    """Forward block-size env overrides — the highest-priority escape
    hatch, above the measured table (kernels.tuning) and the heuristic:
    SE3_TPU_BLOCK_E paired with SE3_TPU_BLOCK_IF (plain) /
    SE3_TPU_BLOCK_CB (bx). BOTH variables of a pair must be set — a
    lone one warns and is ignored. Read per call (the jit cache keys on
    shapes/statics, not env — clear the entry-point caches after
    flipping them, see tuning.clear_kernel_caches). Backward kernels
    never use overrides (their working set is ~2x the forward's)."""
    import os
    vals = [os.environ.get(n, '') for n in names]
    if all(vals):
        try:
            return tuple(int(v) for v in vals)
        except ValueError:
            import warnings
            warnings.warn(f'block override ignored: {names} must be '
                          f'integers (got {vals})', stacklevel=2)
            return None
    if any(vals):
        import warnings
        warnings.warn(f'block override ignored: {names} must ALL be set '
                      f'(got {vals})', stacklevel=2)
    return None


def _validate_override(block_e, second, second_name, full_second,
                       vmem_estimate, vmem_budget):
    """Check an env override against the Mosaic tile-quantum rules
    (block_e multiple of 128; the pair's second member a multiple of 8 or
    the full axis) and the VMEM model. Quantum violations warn AND are
    ignored (a bad value would otherwise surface as an opaque Mosaic
    compile error, ADVICE r3 #4); an over-budget but tile-legal override
    warns and is HONORED — sweeps probe the budget edge on purpose."""
    import warnings
    if block_e <= 0 or block_e % 128 != 0:
        warnings.warn(
            f'block override ignored: SE3_TPU_BLOCK_E={block_e} must be a '
            f'positive multiple of 128 (Mosaic lane tiling)', stacklevel=3)
        return False
    if second <= 0 or (second % 8 != 0 and second < full_second):
        warnings.warn(
            f'block override ignored: {second_name}={second} must be a '
            f'positive multiple of 8 or cover the full axis '
            f'({full_second}) — Mosaic sublane tiling', stacklevel=3)
        return False
    est = vmem_estimate(block_e, min(second, full_second))
    if est > vmem_budget:
        warnings.warn(
            f'block override working set ~{est / 2**20:.1f} MiB exceeds '
            f'the {vmem_budget / 2**20:.0f} MiB VMEM model (honored '
            f'anyway — expect a Mosaic VMEM error if the model is right)',
            stacklevel=3)
    return True


def _vmem_plain(be: int, bif: int, IF: int, O: int, P: int, mid: int,
                bwd: bool = False) -> int:
    """Working-set bytes of the plain kernel at (be, bif) — the model
    _pick_blocks budgets against and tuning.admissible_candidates
    admits with. bif*O*128: the [S, 1] bias column tile-pads its lane
    dim to 128."""
    total = 4 * (mid * be + bif * O * mid + bif * O * 128
                 + 2 * bif * O * be + P * bif * be + P * O * be)
    if bwd:
        # kernel A additionally holds h_p (be*mid), the gT block
        # (= out-sized), the dv2 block (= v2-sized), the dw3 block
        # (= w3-sized) and the db3 block (= b3-sized)
        total += 4 * (be * mid + P * O * be + P * bif * be
                      + bif * O * mid + bif * O * 128)
    return total


def _vmem_bx(be: int, cb: int, O: int, P: int, Q: int, F: int,
             mid: int) -> int:
    """Working-set bytes of the basis-fused kernel at (be, cb)."""
    return 4 * (mid * be + cb * F * O * mid + cb * F * O * 128
                + 2 * cb * F * O * be
                + P * F * Q * be + cb * Q * be + P * O * be)


def _consult_table(kind, shape, dtype, heuristic_fn):
    """Measured-config table consult (kernels.tuning), between the env
    override and the heuristic: forced tuner candidates and promoted
    cache entries steer the pick; a cache entry failing the tile-quantum
    / VMEM admission model degrades to the heuristic with a warning.
    Every resolution is recorded for telemetry (bench record / serving
    warmup / run report)."""
    from . import tuning
    hit = tuning.lookup(kind, shape, dtype=dtype)
    if hit is not None:
        blocks, source = hit
        # forced candidates were admitted by the tuner's own enumeration;
        # re-validating them here would just duplicate warnings
        if source == 'forced' or tuning.validate_entry(kind, shape,
                                                       blocks):
            tuning.record_consult(kind, shape, dtype, source, blocks)
            return blocks
    blocks = heuristic_fn()
    tuning.record_consult(kind, shape, dtype, 'heuristic', blocks)
    return blocks


def _pick_blocks(E: int, IF: int, O: int, P: int, mid: int,
                 vmem_budget: Optional[int] = None,
                 max_unroll: int = 256, bwd: bool = False,
                 dtype: str = 'float32'):
    """Choose (block_e, block_if) so the working set fits in VMEM (with
    headroom for double buffering) and the in-kernel unrolled loop count
    P*block_if stays bounded (Mosaic compile time).

    Resolution order (forward only — the backward always runs this
    heuristic against its own 6 MiB model): SE3_TPU_BLOCK_E/IF env
    overrides, then the measured shape-keyed table (kernels.tuning:
    tuner-forced candidates, then promoted cache entries), then the
    VMEM-model heuristic below. With no overrides and an empty table the
    pick is bit-identical to the heuristic (regression-pinned in
    tests/test_kernel_tuning.py).

    Budget: 7 MiB forward / 6 MiB backward. The forward bump is an
    END-TO-END measured adoption (the only kind this picker accepts —
    see the warning below): it moves the flagship plain pick from
    (512, 8) to (512, 16), which benched 336.21 vs 296.26
    nodes·steps/s (+13.5%) on the conservative flagship, direction
    confirmed across alternating A/B pairs under tunnel-latency noise
    (04:0xZ pair: 300.77 vs 131.01; BENCH_SESSION.jsonl + round-4
    STATUS). block_if is non-monotonic end-to-end: 8 → 296, 16 → 336,
    32 → 107 — the budget admits exactly the measured-best middle. The
    backward keeps 6 MiB: its ~2x working set was never measured past
    it, and the A/B's backward ran the unchanged heuristic. NOTE
    (ADVICE r4 #4): non-flagship shapes inherit the 7 MiB forward
    budget unvalidated — given the measured end-to-end non-monotonicity
    of block_if, re-A/B before trusting a changed pick at a new shape.

    Mosaic block-shape rule: every blocked dim must either cover the full
    array or be divisible by its tile quantum — so block_if is the full IF
    (n_if == 1) or a multiple of 8, and block_e a multiple of 128.

    A MEASURED WARNING about re-tuning this from standalone sweeps: the
    round-4 KERNEL_TUNE sweep timed the STANDALONE plain kernel at the
    unchunked flagship shape (E=32768/IF=1024/O=7*... on a v5e) and
    ranked (256, 32) 18x faster than this picker's (512, 8) — but
    flipping the picker to prefer block_if (commit d0cd10d) made the
    REAL conservative flagship — the same contraction at E=4096 per
    chunk under lax.map+remat — 2.7x SLOWER end-to-end (294.97 ->
    107.51 nodes*steps/s, BENCH_SESSION.jsonl 00:47Z vs 01:39Z, same
    chip, kernel_smoke green both times). The standalone-vs-production
    rankings are OPPOSITE: inside the chunked/remat program the large
    w3/R tiles of a wide block_if evict the lax.map body's working set
    and the e-grid shortens 8x, while standalone the tiny block_if=8
    tiles are DMA-bound. The picker therefore keeps the
    production-validated preference (block_e first); use the
    SE3_TPU_BLOCK_E/IF overrides to experiment, and only re-rank from
    END-TO-END bench numbers, never from standalone kernel timings."""
    if vmem_budget is None:
        vmem_budget = (6 if bwd else 7) * 2 ** 20  # see docstring

    def _vmem(be, bif):
        return _vmem_plain(be, bif, IF, O, P, mid)

    def _heuristic():
        e_cap = _round_up(E, 128)
        for block_e in (512, 256, 128):
            if block_e > e_cap:
                continue
            block_if = min(IF, max(1, max_unroll // max(P, 1)))
            if block_if < IF:
                block_if = max(8, block_if // 8 * 8)
            while True:
                if _vmem_plain(block_e, block_if, IF, O, P, mid,
                               bwd=bwd) <= vmem_budget:
                    return block_e, block_if
                if block_if <= 8:
                    break
                block_if = max(8, block_if // 2 // 8 * 8)
        return 128, min(IF, 8)

    if bwd:
        # the backward never takes overrides or table entries (its ~2x
        # working set was only ever validated under this model's picks)
        return _heuristic()
    ov = _block_overrides('SE3_TPU_BLOCK_E', 'SE3_TPU_BLOCK_IF')
    if ov and _validate_override(ov[0], ov[1], 'SE3_TPU_BLOCK_IF', IF,
                                 _vmem, vmem_budget):
        from . import tuning
        blocks = ov[0], min(IF, ov[1])
        tuning.record_consult('plain', (E, IF, O, P, mid), dtype, 'env',
                              blocks)
        return blocks
    return _consult_table('plain', (E, IF, O, P, mid), dtype, _heuristic)


def _fwd_kernel(ht_ref, w3t_ref, b3t_ref, *rest, P, O, bif,
                precision, scaled=False):
    if scaled:
        st_ref, v2t_ref, o_ref = rest
    else:
        st_ref, (v2t_ref, o_ref) = None, rest
    f = pl.program_id(1)
    w = w3t_ref[:]
    hb = ht_ref[:]
    if w.dtype != hb.dtype:
        # quantized storage (int8/fp8 serving mixes): dequant INSIDE
        # the tile — upcast the VMEM block for the dot, then fold the
        # per-(if,o)-channel scale column in below. The fp32 weight
        # never exists outside this tile.
        w = w.astype(hb.dtype if hb.dtype == jnp.bfloat16
                     else jnp.float32)
    # R chunk, transposed: [bif*O, E_b] — exists only in VMEM. The bias
    # column broadcasts over lanes ([S, 1] + [S, E], the row-stat pattern
    # flash-attention kernels lower every day); the quant scale column
    # rides the same way ([S, 1] * [S, E]).
    rt = jax.lax.dot_general(
        w, hb,
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32)
    if scaled:
        rt = rt * st_ref[:]
    rt = rt + b3t_ref[:]
    for p in range(P):
        acc = None
        for i in range(bif):
            vrow = v2t_ref[p, i:i + 1, :]            # [1, E_b]
            if vrow.dtype != jnp.float32:
                # conv_bf16: V2 is STORED bf16 (half the dominant HBM/VMEM
                # stream) but the apply math stays f32-on-quantized-values
                vrow = vrow.astype(jnp.float32)
            term = vrow * rt[i * O:(i + 1) * O, :]   # [O, E_b]
            acc = term if acc is None else acc + term
        sl = slice(p * O, (p + 1) * O)

        @pl.when(f == 0)
        def _(acc=acc, sl=sl):
            o_ref[sl, :] = acc.astype(o_ref.dtype)

        @pl.when(f > 0)
        def _(acc=acc, sl=sl):
            o_ref[sl, :] = o_ref[sl, :] + acc.astype(o_ref.dtype)


def _to_lanes(h, w3, v2, g=None):
    """XLA-side relayouts (free) into the edge-on-lanes kernel layouts."""
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]
    ht = h.T                                        # [mid, E]
    w3t = w3.reshape(mid, IF * O).T                 # [(if,o), mid]
    v2t = v2.transpose(1, 2, 0)                     # [P, IF, E]
    gt = None if g is None else g.transpose(1, 2, 0).reshape(P * O, E)
    return ht, w3t, v2t, gt


def _bias_column(b3, IF, O, IFp):
    """[IF, O] bias -> [IFp*O, 1] kernel operand in w3T row order
    ((if, o) if-major), zero rows for the padded if's."""
    b3t = b3.astype(jnp.float32).reshape(IF * O, 1)
    if IFp != IF:
        b3t = jnp.pad(b3t, ((0, (IFp - IF) * O), (0, 0)))
    return b3t


def _fused_pairwise_conv_impl(h, w3, b3, v2, interpret, precision,
                              w3_scale=None):
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]
    # table key dtype: the dominant-stream storage dtype (conv_bf16
    # halves the V2 traffic, so its measured winner may differ from the
    # f32 one) — captured BEFORE the interpret-mode upcasts below
    key_dtype = jnp.dtype(v2.dtype).name

    # bf16 radial operands (radial_bf16): run the rt dot MXU-native with
    # f32 accumulation. Must be an EXPLICIT DEFAULT: None inherits the
    # caller's jax.default_matmul_precision context, and fp32 contract
    # precision on bf16 operands is rejected by Mosaic ("Bad lhs type")
    if h.dtype == jnp.bfloat16:
        precision = jax.lax.Precision.DEFAULT
        if interpret:  # CPU interpret can't dispatch BF16xBF16=F32 dots;
            # the upcast is exact and accumulation is f32 either way
            h = h.astype(jnp.float32)
            if w3_scale is None:
                w3 = w3.astype(jnp.float32)
            # quantized w3 keeps its storage dtype — the kernel body's
            # dtype-mismatch upcast is the dequant-in-tile
    if v2.dtype == jnp.bfloat16 and interpret:
        # conv_bf16 under interpret: the kernel body upcasts bf16 rows to
        # f32 right after the (Mosaic-only) VMEM load, so pre-upcasting
        # here is bit-identical — quantize-then-f32 either way
        v2 = v2.astype(jnp.float32)

    block_e, block_if = _pick_blocks(E, IF, O, P, mid, dtype=key_dtype)
    Ep, IFp = _round_up(E, block_e), _round_up(IF, block_if)

    ht, w3t, v2t, _ = _to_lanes(h, w3, v2)
    b3t = _bias_column(b3, IF, O, IFp)
    if Ep != E:
        ht = jnp.pad(ht, ((0, 0), (0, Ep - E)))
        v2t = jnp.pad(v2t, ((0, 0), (0, 0), (0, Ep - E)))
    if IFp != IF:
        w3t = jnp.pad(w3t, ((0, (IFp - IF) * O), (0, 0)))
        v2t = jnp.pad(v2t, ((0, 0), (0, IFp - IF), (0, 0)))

    n_e, n_if = Ep // block_e, IFp // block_if

    scaled = w3_scale is not None
    in_specs = [
        pl.BlockSpec((mid, block_e), lambda e, f: (0, e),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_if * O, mid), lambda e, f: (f, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_if * O, 1), lambda e, f: (f, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [ht, w3t, b3t]
    if scaled:
        # per-(if,o)-channel dequant scales in the w3T row order — the
        # same [S, 1] column layout (and zero-row padding) as the bias
        st = _bias_column(jnp.asarray(w3_scale, jnp.float32).reshape(
            IF, O), IF, O, IFp)
        in_specs.append(pl.BlockSpec((block_if * O, 1),
                                     lambda e, f: (f, 0),
                                     memory_space=pltpu.VMEM))
        args.append(st)
    in_specs.append(pl.BlockSpec((P, block_if, block_e),
                                 lambda e, f: (0, f, e),
                                 memory_space=pltpu.VMEM))
    args.append(v2t)

    outt = pl.pallas_call(
        functools.partial(_fwd_kernel, P=P, O=O, bif=block_if,
                          precision=precision, scaled=scaled),
        grid=(n_e, n_if),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((P * O, block_e), lambda e, f: (0, e),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P * O, Ep), jnp.float32),
        interpret=interpret,
    )(*args)

    return outt.reshape(P, O, Ep).transpose(2, 0, 1)[:E]


# --------------------------------------------------------------------- #
# SPMD partitioning rules
# --------------------------------------------------------------------- #
# The kernels are embarrassingly parallel over the edge axis (e) and the
# output-channel axis (o); only mid (m) and the contracted IF axis (k)
# must be replicated. Without these rules GSPMD treats the Mosaic custom
# call as opaque and would all-gather the sharded edge tensors onto every
# device. With them, a dp/sp-sharded model runs each device's kernel on
# its local edges, and tp-sharded radial weights (param_partition_specs
# shards w3 on o) keep the conv output o-sharded. The backward psums dW3
# over the edge-sharded axes and dH/dV2 over o-sharded axes inside the
# partition body — Shardy sees the results as fully reduced.


def _spec_axes(sharding, dim):
    spec = sharding.spec
    return spec[dim] if len(spec) > dim else None


def _axis_tuple(axes):
    if axes is None:
        return ()
    return axes if isinstance(axes, tuple) else (axes,)


def _factor_positions(rule, factor):
    """(operand_idx, dim) pairs where `factor` appears on the lhs of a
    'e m, m k o, ... -> ...' sharding rule."""
    lhs = rule.split('->')[0]
    return [(i, j) for i, op in enumerate(lhs.split(','))
            for j, f in enumerate(op.split()) if f == factor]


def _edge_o_axes(arg_shapes, e_pos, o_pos):
    """Resolve the (edge, output-channel) sharding axes by scanning EVERY
    operand that carries the factor (positions parsed from the rule
    string) — resolving e from h alone would silently drop the edge
    sharding when h arrives replicated but v2/basis/x/g carry it, and
    GSPMD would then all-gather the edge tensors (ADVICE r2 #1). A mesh
    axis can't shard both factors — on collision the edge sharding wins
    and the o-carrying operands get resharded by the partitioner."""
    def first(positions):
        for i, j in positions:
            ax = _spec_axes(arg_shapes[i].sharding, j)
            if ax is not None:
                return ax
        return None

    e, o = first(e_pos), first(o_pos)
    if set(_axis_tuple(e)) & set(_axis_tuple(o)):
        o = None
    return e, o


def _make_partitioned(impl, rule, need_repl, arg_specs, result_specs,
                      psum_fn=None):
    """Build a custom_partitioning wrapper around `impl`.

    arg_specs/result_specs: callables (P_, e, o) -> tuple of
    PartitionSpec (one per operand / result; a single-result entry point
    passes a 1-tuple and unwraps). psum_fn(outs, e, o): reduce partial
    sums inside the partition body (backward only)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P_

    single = psum_fn is None and len(result_specs(P_, None, None)) == 1
    e_pos, o_pos = _factor_positions(rule, 'e'), _factor_positions(rule, 'o')

    @custom_partitioning
    def f(*args):
        return impl(*args)

    def _shardings(mesh, specs):
        return tuple(NamedSharding(mesh, s) for s in specs)

    def partition(mesh, arg_shapes, result_shape):
        e, o = _edge_o_axes(arg_shapes, e_pos, o_pos)
        arg_sh = _shardings(mesh, arg_specs(P_, e, o))
        res_sh = _shardings(mesh, result_specs(P_, e, o))

        def lower_fn(*args):
            outs = impl(*args)
            return psum_fn(outs, e, o) if psum_fn else outs

        return (mesh, lower_fn, res_sh[0] if single else res_sh, arg_sh)

    def infer(mesh, arg_shapes, shape):
        e, o = _edge_o_axes(arg_shapes, e_pos, o_pos)
        m = arg_shapes[0].sharding.mesh
        res = _shardings(m, result_specs(P_, e, o))
        return res[0] if single else res

    _def_partition_compat(f, partition=partition,
                          infer_sharding_from_operands=infer,
                          sharding_rule=rule,
                          need_replication_factors=need_repl)
    return f


def _def_partition_compat(f, **kwargs):
    """def_partition across jax generations: the Shardy-era kwargs
    (sharding_rule / need_replication_factors) don't exist on GSPMD-era
    jax (<= 0.4.x) — there the partition/infer callbacks alone carry the
    semantics and the rule string is advisory, so dropping the two
    kwargs loses nothing. Without this fallback EVERY kernel entry point
    (including interpret mode on CPU) raises at trace time on older
    installs."""
    try:
        f.def_partition(**kwargs)
    except TypeError:
        kwargs = {k: v for k, v in kwargs.items()
                  if k not in ('sharding_rule',
                               'need_replication_factors')}
        f.def_partition(**kwargs)


@functools.lru_cache(maxsize=None)
def _fwd_partitioned(interpret, precision):
    return _make_partitioned(
        lambda h, w3, b3, v2: _fused_pairwise_conv_impl(h, w3, b3, v2,
                                                        interpret,
                                                        precision),
        rule='e m, m k o, k o, e p k -> e p o', need_repl=('m', 'k'),
        arg_specs=lambda P_, e, o: (P_(e, None), P_(None, None, o),
                                    P_(None, o), P_(e, None, None)),
        result_specs=lambda P_, e, o: (P_(e, None, o),))


@functools.partial(jax.jit, static_argnames=('interpret', 'precision'))
def fused_pairwise_conv(h: jnp.ndarray, w3: jnp.ndarray, v2: jnp.ndarray,
                        b3: jnp.ndarray = None,
                        interpret: bool = False,
                        precision=None,
                        w3_scale: jnp.ndarray = None) -> jnp.ndarray:
    """h [E, mid], w3 [mid, IF, O], v2 [E, P, IF], b3 [IF, O] (optional,
    zeros when None) -> out [E, P, O] (f32): out = v2 . (h@w3 + b3).

    The bias is a separate [S, 1] kernel operand, NOT folded into the
    contraction — folding made mid 129 and cost a structural ~2x on the
    dominant dot (module docstring). `precision` feeds the in-kernel MXU
    dots (captured from jax.default_matmul_precision by the caller — the
    kernel body traces outside that context). Partitions over sharded
    edge/output-channel axes (see the SPMD rules above).

    `w3_scale` [1, IF, O] switches on the quantized-serving epilogue:
    `w3` is then int8/fp8 STORAGE, dequantized inside the tile (upcast
    of the VMEM block + a per-(if,o)-channel scale column riding like
    the bias operand) so the fp32 radial weight never exists in HBM —
    out = v2 . ((h@w3) * scale + b3). Single-program only: the SPMD
    partition rules describe the 4-operand fp path, and quantized
    serving replicates params (quant + tp sharding is follow-up work).
    """
    if b3 is None:
        b3 = jnp.zeros(w3.shape[1:], jnp.float32)
    if w3_scale is not None:
        return _fused_pairwise_conv_impl(h, w3, b3, v2, interpret,
                                         precision, w3_scale=w3_scale)
    return _fwd_partitioned(interpret, precision)(h, w3, b3, v2)


def pallas_available() -> bool:
    from ..utils.helpers import is_tpu_backend
    return is_tpu_backend()


# --------------------------------------------------------------------- #
# basis-fused forward (V2 never touches HBM)
# --------------------------------------------------------------------- #
# The plain kernel above takes V2[e, P, IF] = sum_Q B[e,P,Q,F] x[e,c,Q]
# precomputed by an XLA einsum — which materializes V2 in HBM (write +
# read of E*P*IF floats, ~4-10x the traffic of B and x themselves at
# trunk widths). This variant moves that contraction into the kernel:
# per (e-block, c-chunk) program it reconstructs each V2 row [1, E] from
# a [Q, E] elementwise product + sublane reduction, so V2 only ever
# exists rows-at-a-time in VMEM. One kernel per (d_in, d_out) pair
# (the group concat of conv.py needs a uniform IF chunk axis, which
# heterogeneous (Q, F) segments don't give).
#
# Layouts (edge-on-lanes, as above):
#   bt [P*F*Q, E]   B rows, (p, f, q) flattened p-major — the (p, f)
#                   row-pairs the kernel reduces over are contiguous
#   xt [C*Q, E]     gathered features, (c, q) flattened c-major,
#                   C padded to a multiple of the c-chunk
#   w3t [(IF)*O, mid]  (i=(c,f), o) flattened i-major, rows padded with
#                   zeros for the padded c's (their contributions vanish)
# Grid (n_e, n_c) with the out block accumulated over the inner c axis.


def _fwd_bx_kernel(ht_ref, w3t_ref, b3t_ref, bt_ref, xt_ref, o_ref, *,
                   P, O, Q, F, cb, precision):
    c0 = pl.program_id(1)
    rt = jax.lax.dot_general(
        w3t_ref[:], ht_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32) + b3t_ref[:]  # [cb*F*O, E_b]
    for p in range(P):
        acc = None
        for il in range(cb * F):
            c_l, f_l = divmod(il, F)
            b_sl = (p * F + f_l) * Q
            # V2 row for (p, i=(c, f)): one [Q, E] product + reduction.
            # conv_bf16 stores B/x bf16 in HBM/VMEM (halving the biggest
            # streams); rows upcast at use so the math stays f32
            brows = bt_ref[b_sl:b_sl + Q, :]
            xrows = xt_ref[c_l * Q:(c_l + 1) * Q, :]
            if brows.dtype != jnp.float32:
                brows = brows.astype(jnp.float32)
            if xrows.dtype != jnp.float32:
                xrows = xrows.astype(jnp.float32)
            v2row = jnp.sum(brows * xrows,
                            axis=0, keepdims=True)   # [1, E_b]
            term = v2row * rt[il * O:(il + 1) * O, :]
            acc = term if acc is None else acc + term
        sl = slice(p * O, (p + 1) * O)

        @pl.when(c0 == 0)
        def _(acc=acc, sl=sl):
            o_ref[sl, :] = acc.astype(o_ref.dtype)

        @pl.when(c0 > 0)
        def _(acc=acc, sl=sl):
            o_ref[sl, :] = o_ref[sl, :] + acc.astype(o_ref.dtype)


def _pick_blocks_bx(E: int, C: int, O: int, P: int, Q: int, F: int,
                    mid: int, vmem_budget: int = 6 * 2 ** 20,
                    max_unroll: int = 512, kind: str = 'bx',
                    dtype: str = 'float32'):
    """(block_e, cb) for the basis-fused kernel. cb is the c-chunk: a
    multiple of 8 (so the xt row-block cb*Q and w3t row-block cb*F*O are
    tile-aligned for any odd Q/F) or the full (padded) C.

    Resolution order mirrors _pick_blocks: SE3_TPU_BLOCK_E/CB env
    overrides, then the measured shape-keyed table (kernels.tuning —
    'bx' and 'bxf' are distinct kinds: same contraction, different HBM
    basis operand), then the heuristic below.

    The round-4 KERNEL_TUNE standalone sweep at the flagship bxf shape
    measured the default (128, 8) within 2% of the best override
    (7.896 vs 7.723 ms at (512, 8)) — and the plain picker's cautionary
    tale applies (see _pick_blocks: a standalone-sweep-derived
    "improvement" cost the production conservative path 2.7x), so the
    budget and ordering stay as production-validated; the overrides and
    the end-to-end tuner (scripts/tune_kernels.py) are the
    experimentation paths."""
    def _vmem(be, cb):
        return _vmem_bx(be, cb, O, P, Q, F, mid)

    def _heuristic():
        for block_e in (512, 256, 128):
            if block_e > _round_up(E, 128):
                continue
            cb = min(_round_up(C, 8), max(8, max_unroll // max(P * F, 1)
                                          // 8 * 8))
            while True:
                if _vmem_bx(block_e, cb, O, P, Q, F, mid) <= vmem_budget:
                    return block_e, cb
                if cb <= 8:
                    break
                cb = max(8, cb // 2 // 8 * 8)
        # even the smallest block exceeds the model budget: the estimate
        # mirrors the loop's accounting at (128, 8). The flagship bxf
        # shape (P=7, Q=7, F=7, O=64, mid=128) lands here at ~7.5 MiB and
        # is PRODUCTION-VALIDATED on the v5e (round-4 kernel_smoke +
        # bench at record throughput) — the model is conservative, so
        # estimates within a margin of that validated point stay SILENT
        # (ADVICE r4 #3: a warning that fires on every healthy flagship
        # run trains users to ignore it). Only genuinely larger shapes
        # get the heads-up that pre-explains a real Mosaic VMEM failure.
        total = _vmem(128, 8)
        validated_silence = 9 * 2 ** 20  # flagship 7.5 MiB + margin
        if total > validated_silence:
            import warnings
            warnings.warn(
                f'fused bx kernel working-set model ~{total / 2**20:.1f} '
                f'MiB exceeds the {vmem_budget / 2**20:.0f} MiB budget '
                f'even at the smallest block (P={P}, Q={Q}, F={F}, O={O}, '
                f'mid={mid}) and is beyond the production-validated '
                f'~7.5 MiB flagship point; using (128, 8) — a Mosaic '
                f'VMEM error here means: use the unfused path',
                stacklevel=4)
        return 128, 8

    shape = (E, C, O, P, Q, F, mid)
    ov = _block_overrides('SE3_TPU_BLOCK_E', 'SE3_TPU_BLOCK_CB')
    if ov and _validate_override(ov[0], ov[1], 'SE3_TPU_BLOCK_CB',
                                 _round_up(C, 8), _vmem, vmem_budget):
        from . import tuning
        tuning.record_consult(kind, shape, dtype, 'env', ov)
        return ov
    return _consult_table(kind, shape, dtype, _heuristic)


def _fused_pairwise_conv_bx_impl(h, w3, b3, basis, x, interpret, precision,
                                 pqf=None):
    """basis is [E, P, Q, F] (structured), or — when `pqf`=(P, Q, F) is
    given — [E, P*F*Q] pre-flattened in (p, f, q) order (the layout
    get_basis(layout='pfq_flat') produces): the kernel operand
    bt [P*F*Q, E] is then a plain 2D transpose instead of a 6D
    relayout reading a ~60x tile-padded HBM buffer."""
    E, mid = h.shape
    if pqf is None:
        _, P, Q, F = basis.shape
    else:
        P, Q, F = pqf
        assert basis.shape == (E, P * F * Q), (basis.shape, pqf)
    C = x.shape[1]
    O = w3.shape[-1]
    assert w3.shape[1] == C * F, (w3.shape, C, F)
    # table key dtype: basis/x storage width (conv_bf16), captured
    # before the interpret-mode upcasts below
    key_dtype = jnp.dtype(basis.dtype).name
    if h.dtype == jnp.bfloat16:  # see fused_pairwise_conv (explicit
        # DEFAULT — None would inherit a possibly-fp32 context precision,
        # which Mosaic rejects on bf16 operands)
        precision = jax.lax.Precision.DEFAULT
        if interpret:
            h, w3 = h.astype(jnp.float32), w3.astype(jnp.float32)
    if interpret:
        # conv_bf16 under interpret: bit-identical to the kernel's
        # load-then-upcast (quantize-then-f32 either way)
        if basis.dtype == jnp.bfloat16:
            basis = basis.astype(jnp.float32)
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)

    block_e, cb = _pick_blocks_bx(E, C, O, P, Q, F, mid,
                                  kind='bxf' if pqf is not None else 'bx',
                                  dtype=key_dtype)
    Cp = _round_up(C, cb)
    Ep = _round_up(E, block_e)

    ht = h.T                                          # [mid, E]
    bt = basis.T if pqf is not None \
        else basis.transpose(1, 3, 2, 0).reshape(P * F * Q, E)
    xt = x.transpose(1, 2, 0).reshape(C * Q, E)
    w3t = w3.reshape(mid, C * F * O).T                # [(c,f,o), mid]
    b3t = _bias_column(b3, C * F, O, Cp * F)
    if Cp != C:
        xt = jnp.pad(xt, ((0, (Cp - C) * Q), (0, 0)))
        w3t = jnp.pad(w3t, ((0, (Cp - C) * F * O), (0, 0)))
    if Ep != E:
        ht = jnp.pad(ht, ((0, 0), (0, Ep - E)))
        bt = jnp.pad(bt, ((0, 0), (0, Ep - E)))
        xt = jnp.pad(xt, ((0, 0), (0, Ep - E)))

    n_e, n_c = Ep // block_e, Cp // cb

    outt = pl.pallas_call(
        functools.partial(_fwd_bx_kernel, P=P, O=O, Q=Q, F=F, cb=cb,
                          precision=precision),
        grid=(n_e, n_c),
        in_specs=[
            pl.BlockSpec((mid, block_e), lambda e, c: (0, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cb * F * O, mid), lambda e, c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cb * F * O, 1), lambda e, c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P * F * Q, block_e), lambda e, c: (0, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cb * Q, block_e), lambda e, c: (c, e),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((P * O, block_e), lambda e, c: (0, e),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P * O, Ep), jnp.float32),
        interpret=interpret,
    )(ht, w3t, b3t, bt, xt)

    return outt.reshape(P, O, Ep).transpose(2, 0, 1)[:E]


@functools.lru_cache(maxsize=None)
def _bx_partitioned(interpret, precision):
    return _make_partitioned(
        lambda h, w3, b3, basis, x: _fused_pairwise_conv_bx_impl(
            h, w3, b3, basis, x, interpret, precision),
        rule='e m, m i o, i o, e p q f, e c q -> e p o',
        need_repl=('m', 'i', 'q', 'f', 'c'),
        arg_specs=lambda P_, e, o: (P_(e, None), P_(None, None, o),
                                    P_(None, o),
                                    P_(e, None, None, None),
                                    P_(e, None, None)),
        result_specs=lambda P_, e, o: (P_(e, None, o),))


@functools.partial(jax.jit, static_argnames=('interpret', 'precision'))
def fused_pairwise_conv_bx(h: jnp.ndarray, w3: jnp.ndarray,
                           basis: jnp.ndarray, x: jnp.ndarray,
                           b3: jnp.ndarray = None,
                           interpret: bool = False,
                           precision=None) -> jnp.ndarray:
    """Basis-fused forward: h [E, mid], w3 [mid, C*F, O] (i=(c,f)
    c-major), basis [E, P, Q, F], x [E, C, Q], b3 [C*F, O] (optional,
    zeros when None) -> out [E, P, O] (f32).

    Equals fused_pairwise_conv(h, w3, einsum('epqf,ecq->e p (c f)', ...),
    b3) without ever materializing that V2 tensor in HBM. Partitions over
    sharded edge/output-channel axes (see the SPMD rules above).
    """
    if b3 is None:
        b3 = jnp.zeros(w3.shape[1:], jnp.float32)
    return _bx_partitioned(interpret, precision)(h, w3, b3, basis, x)


@functools.lru_cache(maxsize=None)
def _bxf_partitioned(pqf, interpret, precision):
    return _make_partitioned(
        lambda h, w3, b3, basis, x: _fused_pairwise_conv_bx_impl(
            h, w3, b3, basis, x, interpret, precision, pqf=pqf),
        rule='e m, m i o, i o, e z, e c q -> e p o',
        need_repl=('m', 'i', 'z', 'c', 'q'),
        arg_specs=lambda P_, e, o: (P_(e, None), P_(None, None, o),
                                    P_(None, o),
                                    P_(e, None), P_(e, None, None)),
        result_specs=lambda P_, e, o: (P_(e, None, o),))


@functools.partial(jax.jit,
                   static_argnames=('pqf', 'interpret', 'precision'))
def fused_pairwise_conv_bxf(h: jnp.ndarray, w3: jnp.ndarray,
                            basis_flat: jnp.ndarray, x: jnp.ndarray,
                            pqf: tuple, b3: jnp.ndarray = None,
                            interpret: bool = False,
                            precision=None) -> jnp.ndarray:
    """fused_pairwise_conv_bx with the basis pre-flattened per edge to
    [E, P*F*Q] in (p, f, q) order (get_basis layout='pfq_flat'). Same
    math, but the HBM basis buffer is ~60x smaller at num_degrees=4: the
    structured [.., P, Q, F] form tile-pads its two small odd minor axes
    to (8, 128), the flat form pads one axis to the next 128 multiple.
    pqf = (P, Q, F) static ints."""
    if b3 is None:
        b3 = jnp.zeros(w3.shape[1:], jnp.float32)
    return _bxf_partitioned(tuple(pqf), interpret, precision)(
        h, w3, b3, basis_flat, x)


# --------------------------------------------------------------------- #
# fused backward
# --------------------------------------------------------------------- #
# Cotangents of out[e,P,o] = sum_{if} V2[e,P,if] R[e,if,o],
# R = H W3 + B3:
#   dV2[e,P,if] = sum_o  g[e,P,o]  R[e,if,o]
#   dR [e,if,o] = sum_P  V2[e,P,if] g[e,P,o]
#   dH [e,m]    = sum_{if,o} dR[e,if,o] W3[m,if,o]
#   dW3[m,if,o] = sum_e  H[e,m] dR[e,if,o]
#   dB3[if,o]   = sum_e  dR[e,if,o]
# Kernel A (grid (n_if, n_e), e inner): rT matmul (+bias) -> dV2 rows
# (sublane reduce), dR blocks -> dW3 (matmul) and dB3 (lane reduce),
# both accumulated over the inner edge axis.
# Kernel B (grid (n_e, n_if), f inner): dR blocks (no matmul needed)
# -> dH accumulated over the inner if axis.


def _bwd_a_kernel(ht_ref, h_ref, w3t_ref, b3t_ref, v2t_ref, gt_ref,
                  dv2_ref, dw3_ref, db3_ref, *, P, O, bif, precision):
    e = pl.program_id(1)
    # R must include the bias here: dV2 = g . R
    rt = jax.lax.dot_general(
        w3t_ref[:], ht_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32) + b3t_ref[:]  # [bif*O, E_b]
    g = gt_ref[:]                                    # [P*O, E_b]
    for i in range(bif):
        r_i = rt[i * O:(i + 1) * O, :]               # [O, E_b]
        dr_i = None
        for p in range(P):
            gp = g[p * O:(p + 1) * O, :]             # [O, E_b]
            # dV2[(p, i)] = sum_o g[p,o,:] * r[i,o,:]
            dv2_ref[p, i:i + 1, :] = jnp.sum(
                gp * r_i, axis=0, keepdims=True).astype(dv2_ref.dtype)
            vrow = v2t_ref[p, i:i + 1, :]            # [1, E_b]
            if vrow.dtype != jnp.float32:
                vrow = vrow.astype(jnp.float32)      # conv_bf16 storage
            term = vrow * gp                         # [O, E_b]
            dr_i = term if dr_i is None else dr_i + term
        # dW3 rows for this i: [O, E_b] @ [E_b, mid], accumulated over the
        # inner edge grid axis (consecutive revisits)
        upd = jax.lax.dot_general(
            dr_i, h_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=precision,
            preferred_element_type=jnp.float32)      # [O, mid]
        # dB3 rows: sum dR over edges (lane reduction), same revisit
        # accumulation. Padded edge lanes contribute zeros (v2/g padded).
        db3_upd = jnp.sum(dr_i, axis=1, keepdims=True)   # [O, 1]
        sl = slice(i * O, (i + 1) * O)

        @pl.when(e == 0)
        def _(upd=upd, db3_upd=db3_upd, sl=sl):
            dw3_ref[sl, :] = upd.astype(dw3_ref.dtype)
            db3_ref[sl, :] = db3_upd.astype(db3_ref.dtype)

        @pl.when(e > 0)
        def _(upd=upd, db3_upd=db3_upd, sl=sl):
            dw3_ref[sl, :] = dw3_ref[sl, :] + upd.astype(dw3_ref.dtype)
            db3_ref[sl, :] = db3_ref[sl, :] + db3_upd.astype(db3_ref.dtype)


def _bwd_b_kernel(w3f_ref, v2t_ref, gt_ref, dh_ref, *, P, O, bif,
                  precision):
    f = pl.program_id(1)
    g = gt_ref[:]                                    # [P*O, E_b]
    w3f = w3f_ref[0]                                 # [mid, bif*O]
    acc = None
    for i in range(bif):
        dr_i = None
        for p in range(P):
            vrow = v2t_ref[p, i:i + 1, :]
            if vrow.dtype != jnp.float32:
                vrow = vrow.astype(jnp.float32)      # conv_bf16 storage
            term = vrow * g[p * O:(p + 1) * O, :]
            dr_i = term if dr_i is None else dr_i + term
        # dH partial: [mid, O] @ [O, E_b]
        upd = jax.lax.dot_general(
            w3f[:, i * O:(i + 1) * O], dr_i,
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=precision,
            preferred_element_type=jnp.float32)      # [mid, E_b]
        acc = upd if acc is None else acc + upd

    @pl.when(f == 0)
    def _():
        dh_ref[:] = acc.astype(dh_ref.dtype)

    @pl.when(f > 0)
    def _():
        dh_ref[:] = dh_ref[:] + acc.astype(dh_ref.dtype)


def _fused_pairwise_conv_bwd_impl(h, w3, b3, v2, g, interpret, precision):
    # f32 gradient math: bf16 radial operands (radial_bf16) upcast
    # exactly. A bf16 V2 (conv_bf16) STAYS bf16 through HBM — the
    # backward kernels upcast rows in VMEM like the forward does, so the
    # half-width saving on the dominant stream holds for the backward
    # too (upcasting here would write a full f32 copy back to HBM first)
    h, w3 = h.astype(jnp.float32), w3.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if v2.dtype == jnp.bfloat16 and interpret:
        # interpret can't mix dtypes the way Mosaic lowers them; the
        # pre-upcast is bit-identical to the kernels' row upcasts
        v2 = v2.astype(jnp.float32)
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]

    block_e, block_if = _pick_blocks(E, IF, O, P, mid, bwd=True)
    Ep, IFp = _round_up(E, block_e), _round_up(IF, block_if)

    ht, w3t, v2t, gt = _to_lanes(h, w3, v2, g)
    b3t = _bias_column(b3, IF, O, IFp)
    h_p, w3f = h, w3.reshape(mid, IF * O)
    if Ep != E:
        ht = jnp.pad(ht, ((0, 0), (0, Ep - E)))
        h_p = jnp.pad(h_p, ((0, Ep - E), (0, 0)))
        v2t = jnp.pad(v2t, ((0, 0), (0, 0), (0, Ep - E)))
        gt = jnp.pad(gt, ((0, 0), (0, Ep - E)))
    if IFp != IF:
        w3t = jnp.pad(w3t, ((0, (IFp - IF) * O), (0, 0)))
        w3f = jnp.pad(w3f, ((0, 0), (0, (IFp - IF) * O)))
        v2t = jnp.pad(v2t, ((0, 0), (0, IFp - IF), (0, 0)))

    n_e, n_if = Ep // block_e, IFp // block_if

    # kernel A: dV2 + dW3 + dB3 (accumulate over inner e axis)
    dv2t, dw3t, db3t = pl.pallas_call(
        functools.partial(_bwd_a_kernel, P=P, O=O, bif=block_if,
                          precision=precision),
        grid=(n_if, n_e),
        in_specs=[
            pl.BlockSpec((mid, block_e), lambda f, e: (0, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_e, mid), lambda f, e: (e, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_if * O, mid), lambda f, e: (f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_if * O, 1), lambda f, e: (f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, block_if, block_e), lambda f, e: (0, f, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P * O, block_e), lambda f, e: (0, e),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((P, block_if, block_e), lambda f, e: (0, f, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_if * O, mid), lambda f, e: (f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_if * O, 1), lambda f, e: (f, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((P, IFp, Ep), jnp.float32),
            jax.ShapeDtypeStruct((IFp * O, mid), jnp.float32),
            jax.ShapeDtypeStruct((IFp * O, 1), jnp.float32),
        ],
        interpret=interpret,
    )(ht, h_p, w3t, b3t, v2t, gt)

    # kernel B: dH (accumulate over inner if axis; no matmul with w3T
    # needed — dR comes straight from v2/g). The if-chunk axis of w3 rides
    # a leading block-1 dim so the (mid, bif*O) tail covers its full array
    # dims (Mosaic block-shape rule).
    w3f3 = w3f.reshape(mid, n_if, block_if * O).transpose(1, 0, 2)
    dht = pl.pallas_call(
        functools.partial(_bwd_b_kernel, P=P, O=O, bif=block_if,
                          precision=precision),
        grid=(n_e, n_if),
        in_specs=[
            pl.BlockSpec((1, mid, block_if * O), lambda e, f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, block_if, block_e), lambda e, f: (0, f, e),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P * O, block_e), lambda e, f: (0, e),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((mid, block_e), lambda e, f: (0, e),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mid, Ep), jnp.float32),
        interpret=interpret,
    )(w3f3, v2t, gt)

    dh = dht.T[:E]
    dw3 = dw3t.reshape(IFp, O, mid).transpose(2, 0, 1)[:, :IF]
    dv2 = dv2t.transpose(2, 0, 1)[:E, :, :IF]
    db3 = db3t.reshape(IFp, O)[:IF]
    return dh, dw3, dv2, db3


def _bwd_psums(outs, e, o):
    dh, dw3, dv2, db3 = outs
    # dW3/dB3 sum over edges (sharded e axes); dH/dV2 sum over the output
    # channels (sharded o axes under tensor parallelism)
    if _axis_tuple(e):
        dw3 = jax.lax.psum(dw3, _axis_tuple(e))
        db3 = jax.lax.psum(db3, _axis_tuple(e))
    if _axis_tuple(o):
        dh = jax.lax.psum(dh, _axis_tuple(o))
        dv2 = jax.lax.psum(dv2, _axis_tuple(o))
    return dh, dw3, dv2, db3


@functools.lru_cache(maxsize=None)
def _bwd_partitioned(interpret, precision):
    return _make_partitioned(
        lambda h, w3, b3, v2, g: _fused_pairwise_conv_bwd_impl(
            h, w3, b3, v2, g, interpret, precision),
        rule='e m, m k o, k o, e p k, e p o -> e m, m k o, e p k, k o',
        need_repl=('m', 'k'),
        arg_specs=lambda P_, e, o: (P_(e, None), P_(None, None, o),
                                    P_(None, o),
                                    P_(e, None, None), P_(e, None, o)),
        result_specs=lambda P_, e, o: (P_(e, None), P_(None, None, o),
                                       P_(e, None, None), P_(None, o)),
        psum_fn=_bwd_psums)


@functools.partial(jax.jit, static_argnames=('interpret', 'precision'))
def fused_pairwise_conv_bwd(h: jnp.ndarray, w3: jnp.ndarray,
                            v2: jnp.ndarray, g: jnp.ndarray,
                            b3: jnp.ndarray = None,
                            interpret: bool = False, precision=None):
    """Backward of fused_pairwise_conv: returns (dh, dw3, dv2, db3),
    all f32.

    h [E, mid], w3 [mid, IF, O], v2 [E, P, IF], g [E, P, O], b3 [IF, O]
    (optional, zeros when None — b3 feeds dV2 = g . R with R including
    the bias; db3 itself is bias-independent: sum_e dR).
    bf16 radial operands are upcast (exactly) and the backward runs in
    f32 — gradients stay at the policy precision under radial_bf16.
    Partitions over sharded edge/output-channel axes with the dW3/dB3
    (and, under tp, dH/dV2) partial sums reduced in the partition body.
    """
    if b3 is None:
        b3 = jnp.zeros(w3.shape[1:], jnp.float32)
    return _bwd_partitioned(interpret, precision)(h, w3, b3, v2, g)
