"""Pallas TPU kernel for the fused pairwise TFN convolution.

This is THE compute hot spot of the model (SURVEY.md §3.3): per edge e and
degree pair (d_in, d_out), the reference computes a radial profile
R[e, o, i, f] with a per-pair MLP, multiplies by the angular basis
B[e, P, Q, f] (P = 2*d_out+1, Q = 2*d_in+1) and contracts with gathered
neighbor features x[e, i, Q]. The XLA path materializes R in HBM —
2*E*o*i*f floats of traffic that dwarf the FLOPs (bandwidth-bound ~6x).

This kernel fuses the final radial matmul with the contraction so R only
ever exists as VMEM tiles:

    inputs  H  [E, mid+1]      radial-MLP hidden (with folded-bias 1s col)
            W3 [mid+1, IF, O]  final radial weight, (i, f) flattened
            V2 [E, P, IF]      = sum_Q B[e,P,Q,f] x[e,i,Q]  (cheap, XLA)
    per (if-chunk, e-block) program:
            R   = H_blk @ W3_chunk            # MXU, shared weights
            out += V2_chunk  @b R             # MXU, per-edge batched
    output  out [E, P, O]

Grid order is (n_if, n_e) with the output block revisited across the outer
if-axis (accumulate), so W3 streams through VMEM once per if-chunk and the
huge R tensor never touches HBM. The P axis rides the sublane dimension
(P <= 7 pads to 8 — cheap), O rides lanes.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(h_ref, w3_ref, v2_ref, o_ref):
    # R chunk: [E_b, IF_b, O] — exists only in VMEM
    r = jax.lax.dot_general(
        h_ref[:], w3_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # per-edge batched contraction: [E_b, P, IF_b] x [E_b, IF_b, O].
    # Each (f, e) program owns its own output block (partial sums over the
    # if-axis are reduced outside the kernel): output blocks are never
    # revisited, which keeps the TPU revisit rules trivially satisfied and
    # W3 streaming to exactly one pass.
    o_ref[0] = jax.lax.dot_general(
        v2_ref[:], r,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pick_blocks(E: int, IF: int, O: int, mid: int,
                 vmem_budget: int = 10 * 2 ** 20):
    """Choose (block_e, block_if) so W3 chunk + R chunk + V2 fit in VMEM."""
    block_if = min(IF, 128)
    while True:
        # W3 chunk + double-buffered R + H + V2 + out (f32 accounting)
        for block_e in (256, 128, 64, 32, 16, 8):
            w3 = mid * block_if * O * 4
            r = block_e * block_if * O * 4
            v2 = block_e * 8 * block_if * 4
            out = block_e * 8 * O * 4
            h = block_e * mid * 4
            if w3 + 2 * r + v2 + out + h <= vmem_budget:
                return block_e, block_if
        if block_if <= 8:
            return 8, block_if
        block_if //= 2


@functools.partial(jax.jit, static_argnames=('interpret',))
def fused_pairwise_conv(h: jnp.ndarray, w3: jnp.ndarray, v2: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """h [E, mid], w3 [mid, IF, O], v2 [E, P, IF] -> out [E, P, O] (f32).

    Fold the radial bias by appending a ones column to h and the bias row
    to w3 before calling (see PairwiseConvSE3).
    """
    E, mid = h.shape
    _, IF, O = w3.shape
    P = v2.shape[1]

    block_e, block_if = _pick_blocks(E, IF, O, mid)

    Ep = _round_up(E, block_e)
    IFp = _round_up(IF, block_if)
    if Ep != E:
        h = jnp.pad(h, ((0, Ep - E), (0, 0)))
        v2 = jnp.pad(v2, ((0, Ep - E), (0, 0), (0, 0)))
    if IFp != IF:
        w3 = jnp.pad(w3, ((0, 0), (0, IFp - IF), (0, 0)))
        v2 = jnp.pad(v2, ((0, 0), (0, 0), (0, IFp - IF)))

    n_if = IFp // block_if
    n_e = Ep // block_e

    out = pl.pallas_call(
        _kernel,
        grid=(n_if, n_e),
        in_specs=[
            pl.BlockSpec((block_e, mid), lambda f, e: (e, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mid, block_if, O), lambda f, e: (0, f, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_e, P, block_if), lambda f, e: (e, 0, f),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_e, P, O), lambda f, e: (f, e, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_if, Ep, P, O), jnp.float32),
        interpret=interpret,
    )(h, w3, v2)

    # reduce the per-if-chunk partial sums (n_if <= 7 for IF <= 896; XLA
    # fuses this into a cheap elementwise pass)
    return out.sum(axis=0)[:E]


def pallas_available() -> bool:
    return jax.default_backend() == 'tpu'
