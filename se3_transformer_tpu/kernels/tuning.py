"""Shape-keyed kernel block-config autotuner table.

The fused pairwise-conv and attention Pallas kernels carry the flagship
head-to-head win (docs/PERF.md), but their block sizes historically came
from a static VMEM-budget heuristic validated only at the flagship shape
— `_pick_blocks` itself warns that non-flagship shapes inherit the 7 MiB
forward budget unvalidated, and that standalone-sweep rankings were
measured OPPOSITE to end-to-end rankings (the d0cd10d regression:
294.97 -> 107.51 nodes*steps/s). This module gives every pick function a
measured-config table consulted BEFORE the heuristic:

    precedence:  env override  >  forced candidate  >  cache  >  heuristic

  * env overrides (SE3_TPU_BLOCK_E/IF/CB) stay the highest-priority
    escape hatch — checked by the pick functions before this module is
    consulted at all;
  * `force(kind, blocks)` is the tuner's in-process candidate mechanism
    (scripts/tune_kernels.py): a pending table entry under measurement,
    without env-string round-trips or a subprocess per setting;
  * the cache is a versioned on-disk JSON table (same durability pattern
    as the Q_J `.npz` cache in basis.py: atomic rename, corrupt file =
    miss, version bump = invalidation) keyed on
    (kernel kind, shape tuple, dtype, device_kind, cache version), with
    per-entry provenance (code_rev, benched nodes*steps/s, timestamp);
  * with an empty cache and no overrides every pick is bit-identical to
    the heuristic (regression-pinned in tests/test_kernel_tuning.py).

Entries enter the cache ONLY through `promote()`, and the supported
promoter (scripts/tune_kernels.py) measures candidates END-TO-END
through the real bench step — never the standalone kernel — and
requires a win over the incumbent across alternating A/B pairs. Every
consult (cache hit, env/forced override, or heuristic fallback) is
recorded in an in-process log that bench.py, the serving engine's AOT
warmup, and the run report surface, so an adopted pick is always
distinguishable from a heuristic one in telemetry.

Unlike basis.CACHE_PATH (frozen at import), the cache directory env var
is read per call: tests and the tuner retarget `SE3_TPU_CACHE_PATH`
without re-importing the package.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

CACHE_VERSION = 1

# kernel kinds with tunable picks. 'plain'/'bx'/'bxf' are the pairwise
# forward kernels (the backward ALWAYS runs its own bwd-model heuristic
# — overrides and table entries never reach it, see _pick_blocks);
# 'attention' is the fused attention forward block_n and
# 'attention_bwd' the fused attention BACKWARD block_n (its working set
# is ~2x the forward's, so it keys its own measured entries —
# previously the bwd ran the heuristic only and the tuner could never
# promote a measured bwd block); 'so2' is the banded SO(2)
# contraction's node-axis streaming chunk count
# (so2/contract.py::_pick_so2_chunks — blocks = (chunks,), 1 =
# unchunked); 'flash' is the streaming equivariant-attention kernel's
# (block_n, block_j) tile pair (kernels/pallas_flash.py),
# 'flash_stream' its XLA fallback's node-axis chunk count
# (blocks = (chunks,), 1 = unchunked), and 'flash_global' the same
# chunk-count pick for the graph-free global variant — its own kind
# because its per-chunk working set is O(rows * n) not O(rows * K),
# so a small-n kNN-calibrated entry must never steer an assembly-n
# global step (the Pallas block pick stays kind 'flash': global
# shapes key K=0 there).
KINDS = ('plain', 'bx', 'bxf', 'attention', 'attention_bwd', 'so2',
         'flash', 'flash_stream', 'flash_global')

# Mosaic's scoped-vmem stack limit is ~16 MiB; 12 MiB leaves slack for
# compiler temporaries (same constant, same hard-won reason, as
# pallas_attention._VMEM_LIMIT). Used as the admission ceiling for
# candidates whose kind has no stricter production budget.
MOSAIC_SCOPED_VMEM = 12 * 2 ** 20

_lock = threading.Lock()
# kind -> (shape-or-None, dtype-or-None, blocks): None wildcards match
# every pick of the kind (test convenience); the tuner always pins the
# target shape+dtype so a candidate under measurement cannot leak into
# the OTHER same-kind picks of the traced program (whose admissible
# sets differ — and whose picks revert to the heuristic at deployment,
# which would invalidate the end-to-end promotion evidence)
_forced: Dict[str, Tuple[Optional[Tuple[int, ...]], Optional[str],
                         Tuple[int, ...]]] = {}
# consult log: (kind, shape, dtype, source, blocks) -> count. Bounded by
# construction (picks happen at trace time; distinct keys are few).
_consults: Dict[Tuple, int] = {}
# file memo: path -> ((mtime_ns, size), entries)
_loaded: Dict[str, Tuple[Tuple[int, int], dict]] = {}


# --------------------------------------------------------------------- #
# cache file
# --------------------------------------------------------------------- #

def cache_dir() -> str:
    """Read per call (NOT frozen at import like basis.CACHE_PATH) so the
    tuner and tests can retarget without re-importing."""
    return os.environ.get(
        'SE3_TPU_CACHE_PATH',
        os.path.expanduser('~/.cache/se3_transformer_tpu'))


def cache_file() -> str:
    # version in the NAME: a bump orphans the old file instead of
    # migrating it (same invalidation mechanism as basis._qj_cache_file)
    return os.path.join(cache_dir(), f'kernel_blocks_v{CACHE_VERSION}.json')


def _key(kind: str, shape: Sequence[int], dtype: str,
         device_kind: str) -> str:
    return f'{kind}|{",".join(str(int(s)) for s in shape)}' \
           f'|{dtype}|{device_kind}'


def current_device_kind() -> str:
    """Device identity for the cache key: a v5e's measured winner must
    not silently steer a v4 (or the CPU interpret tests)."""
    try:
        import jax
        if jax.default_backend() == 'cpu':
            return 'cpu'
        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - identity is best-effort metadata
        return 'unknown'


def _load_entries(path: str) -> dict:
    """Parse the table; ANY failure (missing, truncated, corrupt JSON,
    wrong in-file version) is a plain cache miss, never an error."""
    try:
        st = os.stat(path)
    except OSError:
        return {}
    sig = (st.st_mtime_ns, st.st_size)
    with _lock:
        cached = _loaded.get(path)
        if cached is not None and cached[0] == sig:
            return cached[1]
    entries: dict = {}
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and data.get('version') == CACHE_VERSION \
                and isinstance(data.get('entries'), dict):
            entries = data['entries']
    except Exception:  # noqa: BLE001 - corrupt/truncated file: miss
        entries = {}
    with _lock:
        _loaded[path] = (sig, entries)
    return entries


def entries() -> dict:
    """The raw on-disk table ({key: {blocks, provenance}})."""
    return dict(_load_entries(cache_file()))


def lookup(kind: str, shape: Sequence[int], *, dtype: str = 'float32',
           device_kind: Optional[str] = None
           ) -> Optional[Tuple[Tuple[int, ...], str]]:
    """Measured blocks for (kind, shape, dtype, device) or None.

    Returns (blocks, source) with source 'forced' (a tune_kernels
    candidate under measurement) or 'cache'. The caller (the pick
    function) still validates tile legality and the VMEM model before
    adopting — a hand-edited or stale entry must degrade to the
    heuristic with a warning, not to an opaque Mosaic compile error.
    """
    with _lock:
        forced = _forced.get(kind)
    if forced is not None:
        fshape, fdtype, fblocks = forced
        if (fshape is None
                or fshape == tuple(int(s) for s in shape)) \
                and (fdtype is None or fdtype == dtype):
            return tuple(fblocks), 'forced'
    ents = _load_entries(cache_file())
    if not ents:
        return None
    if device_kind is None:
        device_kind = current_device_kind()
    ent = ents.get(_key(kind, shape, dtype, device_kind))
    if not isinstance(ent, dict):
        return None
    blocks = ent.get('blocks')
    if (not isinstance(blocks, (list, tuple)) or not blocks
            or not all(isinstance(b, int) for b in blocks)):
        return None  # malformed entry: miss
    return tuple(blocks), 'cache'


def promote(kind: str, shape: Sequence[int], blocks: Sequence[int], *,
            dtype: str = 'float32', device_kind: Optional[str] = None,
            provenance: Optional[dict] = None) -> dict:
    """Write a measured winner into the table (read-modify-write under a
    file lock, atomic rename — the basis.py Q_J pattern). Returns the
    stored entry. Callers other than scripts/tune_kernels.py should have
    an equally end-to-end justification for what they write."""
    assert kind in KINDS, f'unknown kernel kind {kind!r} (known: {KINDS})'
    if device_kind is None:
        device_kind = current_device_kind()
    prov = dict(provenance or {})
    prov.setdefault('time_utc',
                    time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()))
    if 'code_rev' not in prov:
        try:
            from ..observability.metrics import _code_rev
            prov['code_rev'] = _code_rev()
        except Exception:  # noqa: BLE001 - provenance is best-effort
            prov['code_rev'] = None
    entry = dict(blocks=[int(b) for b in blocks], provenance=prov)
    path = cache_file()
    os.makedirs(cache_dir(), exist_ok=True)
    lock_path = os.path.join(cache_dir(), 'kernel_blocks.lock')
    with open(lock_path, 'w') as lock_fh:
        try:
            import fcntl
            fcntl.flock(lock_fh, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # best-effort mutex, like the Q_J cache
        existing = _read_raw_entries(path)
        existing[_key(kind, shape, dtype, device_kind)] = entry
        tmp = f'{path}.{os.getpid()}.tmp'
        with open(tmp, 'w') as f:
            json.dump(dict(version=CACHE_VERSION, entries=existing), f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)
    with _lock:
        _loaded.pop(path, None)  # next lookup re-reads
    return entry


def _read_raw_entries(path: str) -> dict:
    """Re-read inside the write lock (the memo could be stale against a
    concurrent writer). Corrupt file: rebuild from scratch."""
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and data.get('version') == CACHE_VERSION \
                and isinstance(data.get('entries'), dict):
            return dict(data['entries'])
    except Exception:  # noqa: BLE001
        pass
    return {}


@contextlib.contextmanager
def force(kind: str, blocks: Sequence[int], *,
          shape: Optional[Sequence[int]] = None,
          dtype: Optional[str] = None):
    """Pin a candidate for one kind — the tuner's in-process measurement
    path (precedence: below env overrides, above the cache). Pass the
    target `shape` (and `dtype`) so ONLY that pick takes the candidate:
    a same-kind pick at another shape was never admitted for these
    blocks and must keep resolving cache/heuristic, or the measured A/B
    would not be the program that deploys. shape=None applies to every
    pick of the kind. Clears the kernel jit caches on entry AND exit:
    the pick runs at trace time, so a stale traced kernel would silently
    measure the wrong program (the lesson the old subprocess sweep
    learned the hard way)."""
    assert kind in KINDS, f'unknown kernel kind {kind!r}'
    with _lock:
        prior = _forced.get(kind)
        _forced[kind] = (
            None if shape is None else tuple(int(s) for s in shape),
            dtype, tuple(int(b) for b in blocks))
    clear_kernel_caches()
    try:
        yield
    finally:
        with _lock:
            if prior is None:
                _forced.pop(kind, None)
            else:
                _forced[kind] = prior
        clear_kernel_caches()


def clear_kernel_caches() -> int:
    """Drop every kernel jit/trace cache whose pick this table steers.
    Returns the number of caches cleared; raises if NOTHING was cleared
    (a silent no-op would let an A/B measure the same program twice —
    the invalid-pair failure mode of the retired env-var sweep)."""
    cleared = 0
    from . import pallas_attention as pa, pallas_flash as pf, \
        pallas_pairwise as pp
    for mod, names in (
            (pp, ('fused_pairwise_conv', 'fused_pairwise_conv_bx',
                  'fused_pairwise_conv_bxf', 'fused_pairwise_conv_bwd')),
            (pa, ('_fused_attention_fwd_impl',
                  '_fused_attention_bwd_impl')),
            (pf, ('_flash_fwd_impl',))):
        for nm in names:
            f = getattr(mod, nm, None)
            if f is not None and hasattr(f, 'clear_cache'):
                f.clear_cache()
                cleared += 1
    for mod, names in (
            (pp, ('_fwd_partitioned', '_bx_partitioned',
                  '_bxf_partitioned', '_bwd_partitioned')),
            (pa, ('_att_partitioned',))):
        for nm in names:
            f = getattr(mod, nm, None)
            if f is not None and hasattr(f, 'cache_clear'):
                f.cache_clear()
                cleared += 1
    if cleared == 0:
        raise RuntimeError(
            'clear_kernel_caches cleared nothing — kernel jit wrapper '
            'cache API changed; block A/Bs would be invalid')
    return cleared


# --------------------------------------------------------------------- #
# consult telemetry
# --------------------------------------------------------------------- #

def record_consult(kind: str, shape: Sequence[int], dtype: str,
                   source: str, blocks: Sequence[int]) -> None:
    """Called by the pick functions on every resolution. source is one
    of 'env' / 'forced' / 'cache' / 'heuristic'."""
    key = (kind, tuple(int(s) for s in shape), dtype, source,
           tuple(int(b) for b in blocks))
    with _lock:
        _consults[key] = _consults.get(key, 0) + 1


def reset_consults() -> None:
    with _lock:
        _consults.clear()


def consults() -> List[dict]:
    """Every distinct pick resolution since the last reset, as dicts
    ({kernel, shape, dtype, source, blocks, count}) — the payload
    bench.py and the serving warmup attach to their records."""
    with _lock:
        items = sorted(_consults.items())
    return [dict(kernel=k, shape=list(s), dtype=d, source=src,
                 blocks=list(b), count=n)
            for (k, s, d, src, b), n in items]


def snapshot() -> Dict[Tuple, int]:
    """Opaque marker for consults_since — lets concurrent consumers
    (bench record, serving warmup) report their own deltas without
    resetting the shared log out from under each other."""
    with _lock:
        return dict(_consults)


def consults_since(snap: Dict[Tuple, int]) -> List[dict]:
    """The consults recorded after `snap = snapshot()`."""
    with _lock:
        items = sorted(_consults.items())
    out = []
    for key, n in items:
        d = n - snap.get(key, 0)
        if d > 0:
            k, s, dt, src, b = key
            out.append(dict(kernel=k, shape=list(s), dtype=dt, source=src,
                            blocks=list(b), count=d))
    return out


def consult_summary(consult_list: Optional[List[dict]] = None) -> dict:
    """Compact adopted-vs-heuristic view for records: total counts per
    source plus the non-heuristic resolutions spelled out."""
    cs = consults() if consult_list is None else consult_list
    by_source: Dict[str, int] = {}
    for c in cs:
        by_source[c['source']] = by_source.get(c['source'], 0) + c['count']
    adopted = [c for c in cs if c['source'] != 'heuristic']
    return dict(by_source=by_source, adopted=adopted,
                cache_entries=len(entries()))


# --------------------------------------------------------------------- #
# candidate admission (the tuner's enumeration)
# --------------------------------------------------------------------- #

def admissible_candidates(kind: str, shape: Sequence[int]
                          ) -> List[Tuple[int, ...]]:
    """Tile-legal, VMEM-model-admissible candidate blocks for a shape —
    what scripts/tune_kernels.py is allowed to measure. Admission is
    model-based and conservative ON PURPOSE: the env-override path
    honors over-budget settings ("sweeps probe the budget edge"), and
    the round-4 sweep paid for that with Mosaic VMEM compile failures at
    bx/bxf (512, 16) and bx (256, 16) (KERNEL_TUNE.jsonl) — those
    configs are excluded here up front.

    Per kind:
      * 'plain': forward working set within the production 7 MiB budget
        (the same model `_pick_blocks` enforces). bwd-awareness is
        structural: the backward NEVER runs candidate blocks — it keeps
        its own 6 MiB bwd-model heuristic pick — so a forward candidate
        cannot regress the backward's VMEM fit.
      * 'bx'/'bxf': forward model within MOSAIC_SCOPED_VMEM (12 MiB) —
        the model already sits above the 6 MiB paper budget at the
        production-validated flagship default (~7.5 MiB), so the real
        ceiling with slack is the admission line. Same backward note.
      * 'attention': block_n ladder admitted against the BACKWARD row
        model (`_block_row_bytes(J, D, bwd=True)`): training
        differentiates attention with the same block size family, so a
        forward-only fit would still OOM end-to-end.
      * 'attention_bwd': the backward's own block_n ladder, admitted
        against the same bwd row model (the bwd IS the bwd program —
        forward entries never steer it and vice versa).
      * 'flash': (block_n, block_j) for the streaming
        equivariant-attention kernel, admitted against its bwd-aware
        VMEM row model (pallas_flash._flash_vmem_bytes).
      * 'flash_stream': node-axis chunk count for the kernel's XLA
        streaming fallback (1 = unchunked), the so2-kind pattern.
    """
    out: List[Tuple[int, ...]] = []
    if kind == 'plain':
        from .pallas_pairwise import _round_up, _vmem_plain
        E, IF, O, P, mid = (int(s) for s in shape)
        budget = 7 * 2 ** 20
        for be in (128, 256, 512):
            if be > _round_up(E, 128):
                continue
            for bif in _second_axis_candidates(IF):
                # same in-kernel unroll (Mosaic compile time) bound as
                # _pick_blocks' max_unroll: the in-process tuner has no
                # per-candidate timeout, so admitting a pathological
                # unroll would wedge the single-client tunnel compiling
                if P * bif > 256:
                    continue
                if _vmem_plain(be, min(bif, IF), IF, O, P, mid) <= budget:
                    out.append((be, bif))
    elif kind in ('bx', 'bxf'):
        from .pallas_pairwise import _round_up, _vmem_bx
        E, C, O, P, Q, F, mid = (int(s) for s in shape)
        for be in (128, 256, 512):
            if be > _round_up(E, 128):
                continue
            for cb in _second_axis_candidates(_round_up(C, 8)):
                if P * F * cb > 512:  # _pick_blocks_bx's max_unroll —
                    # see the plain-kind note above
                    continue
                if _vmem_bx(be, cb, O, P, Q, F, mid) \
                        <= MOSAIC_SCOPED_VMEM:
                    out.append((be, cb))
    elif kind in ('attention', 'attention_bwd'):
        from .pallas_attention import (
            _VMEM_LIMIT, _block_row_bytes, _round_up,
        )
        n, J, D = (int(s) for s in shape)
        # both kinds admit against the BACKWARD row model: a forward
        # entry still has to coexist with the bwd program end-to-end,
        # and the bwd kind's working set IS the bwd model
        row_bwd = _block_row_bytes(J, D, bwd=True)
        cap = max(8, _round_up(n, 8))
        for bn in (512, 256, 128, 64, 32, 16, 8):
            if bn <= cap and bn * row_bwd <= _VMEM_LIMIT:
                out.append((bn,))
    elif kind == 'flash':
        from .pallas_flash import flash_admissible_blocks
        out = flash_admissible_blocks(shape)
    elif kind == 'flash_stream':
        # node-axis chunk count for the XLA streaming fallback
        # (pallas_flash._flash_stream). The ladder must cover the
        # heuristic's own operating region (n // 16 — e.g. 64 chunks at
        # flagship n=1024), or the tuner could never measure it and
        # validate_entry would reject larger measured entries as corrupt
        n = int(shape[0])
        out = [(c,) for c in (1, 2, 4, 8, 16, 32, 64, 128) if c <= n]
    elif kind == 'flash_global':
        # the global variant's chunk count: same mechanism, ladder
        # extended through the assembly regime (n // 16 is 2048 chunks
        # at n=32768 — the heuristic's operating point must stay
        # admissible or validate_entry rejects measured entries there)
        n = int(shape[0])
        out = [(c,) for c in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                              1024, 2048) if c <= n]
    elif kind == 'so2':
        # node-axis streaming chunk count for the banded SO(2)
        # contraction (so2/contract.py): 1 = unchunked (the heuristic
        # default — its working set is small), higher counts trade
        # overhead for a lax.map memory ceiling. Always legal when the
        # count does not exceed the node axis.
        n = int(shape[0])
        out = [(c,) for c in (1, 2, 4, 8) if c <= n]
    else:
        raise ValueError(f'unknown kernel kind {kind!r} (known: {KINDS})')
    return out


def _second_axis_candidates(full: int) -> List[int]:
    """Sublane-quantum-legal sizes for the if/c chunk axis: multiples of
    8 below the full axis, plus the full axis itself."""
    sizes = [s for s in (8, 16, 32, 64, 128) if s < full and s % 8 == 0]
    sizes.append(full)
    return sizes


def validate_entry(kind: str, shape: Sequence[int],
                   blocks: Sequence[int]) -> bool:
    """Tile-quantum + VMEM-model gate applied by the pick functions to a
    table hit before adopting it. Stricter than the env-override path
    (which honors over-budget settings): a cache entry exists to be
    trusted silently, so anything the admission model rejects is treated
    as corrupt — warn and fall back to the heuristic."""
    ok = tuple(int(b) for b in blocks) in \
        set(admissible_candidates(kind, shape))
    if not ok:
        warnings.warn(
            f'kernel tuning table entry {kind}{tuple(shape)} -> '
            f'{tuple(blocks)} is not tile-legal/VMEM-admissible; '
            f'ignoring it (heuristic pick used). Re-run '
            f'scripts/tune_kernels.py or delete {cache_file()}',
            stacklevel=3)
    return ok
