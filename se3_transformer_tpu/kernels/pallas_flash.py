"""Streaming (flash-style) equivariant attention with on-the-fly
pairwise contraction.

The trunk's unfused attention path materializes, per layer and per
degree, three per-edge HBM tensors before a single score is computed:

  * the pairwise kernel basis  [b, n, k, P, Q, F]   (get_basis),
  * the keyed features k/v     [b, kv_h, n, J, D]   (ConvSE3 pool=False
    on exchange_index_select-gathered neighbors),
  * the score tensor           [b, h, n, J].

This module computes all three INSIDE the attention kernel, per
(node-block, kv-slot-block) tile, with an online softmax carried across
slot blocks — the flash-attention formulation of E2Former-V2
(arXiv:2601.16622) / the Clebsch-Gordan Transformer (arXiv:2509.24093)
specialized to the TFN contraction. Per tile the kernel:

  1. gathers the slot block's neighbor features from the NODE-level
     feature tensors (jnp.take on the in-VMEM [n, C, Q] operand — the
     [b, n, k, C, Q] gathered tensor never exists in HBM);
  2. runs the pluggable pairwise contraction in VMEM:
       'dense' arm — rebuilds the basis block from the per-edge
         spherical-harmonics stack Y [.., S] and the static Q_J
         constants (S = (2*max_J+1)^2 floats/edge versus the basis's
         P*Q*F *per degree pair*), contracts with the gathered block,
         and applies the radial matmul;
       'so2' arm — fuses PR 10's rotate-in -> banded-z -> radial ->
         rotate-out chain (previously pure XLA — the named residue) on
         the block, using the same factored Wigner application and
         canonical banded blocks as so2/contract.py;
  3. folds the block's scores into an online-softmax state (m, l, acc)
     held in VMEM scratch across the slot-block grid axis.

The always-valid prefix slots ([global, null, self] — the unfused
path's left-padded concat order) ride as a tiny [b, n, S0, kv_h*D]
tensor folded into the state at slot-block 0; neighbor masks keep the
unfused semantics exactly (finite NEG_INF fill, so a fully-masked row
degrades to the same uniform average the XLA softmax produces).

Dispatch: the Pallas kernel runs on TPU (or under `interpret=True` for
the CPU tests); everywhere else `_flash_stream` computes the identical
function by streaming REMAT'D NODE CHUNKS through XLA (lax.map +
jax.checkpoint), which is also what the `custom_vjp` backward replays —
recompute-in-backward, so the only saved residuals are the kernel's
inputs and the whole path composes with the reversible trunk for
near-O(1) activation memory.

A graph-free GLOBAL variant (`flash_global_attention`) drops the kNN
truncation entirely: per (i-block, j-block) tile it computes rel_pos /
rel_dist from the coordinates, the radial hidden through an inlined
Dense-LN-GELU trunk, and the harmonics/frames payload on the fly — NO
per-edge tensor of any kind touches HBM, so activation memory is O(n)
at O(n^2) compute. This is the large-assembly scenario where kNN
truncation is the accuracy bottleneck.

Block sizes are tuning kinds 'flash' ((block_n, block_j), admitted
against the VMEM row model below) and 'flash_stream' (the XLA
fallback's node-chunk count); every resolution is consulted through
kernels/tuning.py like the other kernels.
"""
from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# Mosaic scoped-vmem budget, same hard-won constant as pallas_attention
_VMEM_LIMIT = 12 * 2 ** 20

_FRAME_KEYS = ('cos_a', 'sin_a', 'cos_b', 'sin_b')

ARMS = ('dense', 'so2')


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


class FlashConfig(NamedTuple):
    """Static configuration of one flash-attention call (hashable —
    rides as the custom_vjp/jit static argument)."""
    pairs: Tuple[Tuple[int, int], ...]  # (d_in, channels) per input degree
    d_out: int
    heads: int
    kv_heads: int
    scale: float
    arm_v: str = 'dense'
    arm_k: str = 'dense'
    tie: bool = False            # keys ARE values (tie_key_values)
    prefix: int = 0              # always-valid leading kv slots
    has_mask: bool = False
    mode: str = 'knn'            # 'knn' | 'global'
    exclude_self: bool = False   # global mode: mask the j == i slot
    use_pallas: bool = False
    interpret: bool = False


# --------------------------------------------------------------------- #
# pairwise-contraction arms (pure jnp: shared by the kernel body, the
# XLA streaming fallback, and the recompute-in-backward replay)
# --------------------------------------------------------------------- #

@lru_cache(maxsize=None)
def _pair_cg(d_in: int, d_out: int) -> np.ndarray:
    """Static contraction constants turning the per-edge SH stack into
    the pairwise basis: T[s, p, q, f] with s indexing the flattened
    Y stack (degree J occupies rows J^2..(J+1)^2, offset by lo^2), so
    basis[.., p, q, f] = sum_s Y[.., lo^2 + s] T[s, p, q, f] equals
    get_basis's Q_J contraction exactly."""
    from ..basis import basis_transformation_Q_J
    lo, hi = abs(d_in - d_out), d_in + d_out
    P, Q = 2 * d_out + 1, 2 * d_in + 1
    F = 2 * min(d_in, d_out) + 1
    T = np.zeros(((hi + 1) ** 2 - lo ** 2, P, Q, F))
    for fi, J in enumerate(range(lo, hi + 1)):
        QJ = basis_transformation_Q_J(J, d_in, d_out)  # [(P*Q), 2J+1]
        T[J * J - lo * lo:(J + 1) * (J + 1) - lo * lo, :, :, fi] = \
            QJ.reshape(P, Q, 2 * J + 1).transpose(2, 0, 1)
    return T


def flash_sh_payload(rel_pos: jnp.ndarray, max_degree: int,
                     differentiable: bool = False) -> jnp.ndarray:
    """The dense arm's per-edge payload: real spherical harmonics
    J = 0..2*max_degree stacked to [..., (2*max_degree + 1)^2] —
    O(S) floats per edge versus the materialized basis's O(P*Q*F) per
    degree pair. Same normalization/stop_gradient contract as
    get_basis."""
    from ..basis import safe_normalize
    from ..so3.spherical_harmonics import real_spherical_harmonics_all
    rhat, _ = safe_normalize(rel_pos)
    Ys = real_spherical_harmonics_all(2 * max_degree, rhat, xp=jnp)
    out = jnp.concatenate([Ys[J] for J in range(2 * max_degree + 1)],
                          axis=-1)
    if not differentiable:
        out = jax.lax.stop_gradient(out)
    return out


def pack_frames(frames) -> jnp.ndarray:
    """so2 frames dict -> one [..., 4 * L1] array (kernel ref layout)."""
    return jnp.concatenate([frames[k] for k in _FRAME_KEYS], axis=-1)


def unpack_frames(packed: jnp.ndarray) -> dict:
    L1 = packed.shape[-1] // 4
    return {k: packed[..., i * L1:(i + 1) * L1]
            for i, k in enumerate(_FRAME_KEYS)}


@lru_cache(maxsize=None)
def _so2_pair_consts(d_in: int, d_out: int):
    """The canonical banded 2x2 blocks for one pair (so2/canonical.py)."""
    from ..so2.canonical import canonical_blocks
    a, b = canonical_blocks(d_in, d_out)
    return np.asarray(a), np.asarray(b)


@lru_cache(maxsize=None)
def _rot_consts(l: int):
    """Gather-free constants for the factored Wigner application at
    degree l: SEL [l+1, 2l+1] one-hot mapping harmonics m = 0..l onto
    the |m_q| positions (replaces so2.frames._dz_apply's constant-index
    gather — Pallas kernels cannot capture constant arrays, so every
    constant rides as an input ref), SGN [1, 2l+1] the +/-m block
    signs, and J_l the involution matrix."""
    from ..so2.frames import j_matrix
    m_abs = np.abs(np.arange(-l, l + 1))
    sel = np.zeros((l + 1, 2 * l + 1))
    sel[m_abs, np.arange(2 * l + 1)] = 1.0
    sgn = np.sign(-np.arange(-l, l + 1)).astype(np.float64)[None]
    return sel, sgn, j_matrix(l)


def _arm_consts(cfg: 'FlashConfig') -> dict:
    """Every constant array the contraction arms need, as numpy — the
    Pallas path passes them as kernel inputs, the XLA path converts
    them in place."""
    arms = {cfg.arm_v} | ({cfg.arm_k} if not cfg.tie else set())
    out = {}
    if 'dense' in arms:
        for i, (d_in, _) in enumerate(cfg.pairs):
            out[f'cg{i}'] = _pair_cg(d_in, cfg.d_out)
    if 'so2' in arms:
        for i, (d_in, _) in enumerate(cfg.pairs):
            a, b = _so2_pair_consts(d_in, cfg.d_out)
            out[f'so2a{i}'], out[f'so2b{i}'] = a, b
        for l in sorted({d for d, _ in cfg.pairs} | {cfg.d_out}):
            if l > 0:
                sel, sgn, J = _rot_consts(l)
                out[f'sel{l}'], out[f'sgn{l}'], out[f'J{l}'] = sel, sgn, J
    return out


def _dz_apply_c(x, cos_m, sin_m, sign, sel, sgn):
    """so2.frames._dz_apply with the constant-index gather replaced by a
    one-hot contraction (sel/sgn from _rot_consts) — bit-identical
    values, kernel-legal form."""
    cv = jnp.einsum('...m,mp->...p', cos_m, sel)
    sv = sign * jnp.einsum('...m,mp->...p', sin_m, sel) * sgn[0]
    while cv.ndim < x.ndim:
        cv, sv = cv[..., None, :], sv[..., None, :]
    return cv * x + sv * x[..., ::-1]


def _rotate_in_c(x, fr, l, consts):
    if l == 0:
        return x
    sel = consts[f'sel{l}']
    sgn = consts[f'sgn{l}']
    J = consts[f'J{l}']
    t = _dz_apply_c(x, fr['cos_a'][..., :l + 1], fr['sin_a'][..., :l + 1],
                    -1.0, sel, sgn)
    t = jnp.einsum('qp,...q->...p', J, t)       # J^T contraction
    t = _dz_apply_c(t, fr['cos_b'][..., :l + 1], fr['sin_b'][..., :l + 1],
                    -1.0, sel, sgn)
    return jnp.einsum('pq,...q->...p', J, t)


def _rotate_out_c(y, fr, l, consts):
    if l == 0:
        return y
    sel = consts[f'sel{l}']
    sgn = consts[f'sgn{l}']
    J = consts[f'J{l}']
    t = jnp.einsum('qp,...q->...p', J, y)       # J^T contraction
    t = _dz_apply_c(t, fr['cos_b'][..., :l + 1], fr['sin_b'][..., :l + 1],
                    1.0, sel, sgn)
    t = jnp.einsum('pq,...q->...p', J, t)
    return _dz_apply_c(t, fr['cos_a'][..., :l + 1], fr['sin_a'][..., :l + 1],
                       1.0, sel, sgn)


def _banded_z_c(xr, d_in: int, d_out: int, a, b):
    """so2.contract.banded_z (pad_rows=True) with the +/-m pair gathers
    rewritten as slices — same values, kernel-legal form."""
    mmin = min(d_in, d_out)
    xneg = xr[..., d_in - mmin:d_in + 1][..., ::-1][..., None, :]
    xpos = xr[..., d_in:d_in + mmin + 1][..., None, :]
    zneg = a * xneg + b * xpos                  # [..., C, F, M+1]
    zpos = a * xpos - b * xneg
    band = jnp.concatenate(
        (zneg[..., :0:-1], zneg[..., :1], zpos[..., 1:]), axis=-1)
    band = jnp.moveaxis(band, -1, -3)           # [..., band, C, F]
    if d_out > mmin:
        pad = [(0, 0)] * band.ndim
        pad[-3] = (d_out - mmin, d_out - mmin)
        band = jnp.pad(band, pad)
    C = xr.shape[-2]
    return band.reshape(*band.shape[:-2], C * band.shape[-1])


def _kv_block(arm: str, pairs, d_out: int, xg, h, sh, fr, w3, b3,
              consts, w3_scale=None):
    """One slot block's keyed features, entirely in registers/VMEM:
    xg tuple of [..., C, Q] gathered features (one per input degree),
    h [..., mid] radial hidden, sh [..., S] SH stack (dense arm),
    fr frames dict (so2 arm), w3 [mid, IF, O] / b3 [IF, O] grouped
    radial params, consts from _arm_consts -> [..., O, P]. Matches
    ConvSE3's grouped shared-radial contraction segment-for-segment
    (same params, same concat order), so the fused path is
    checkpoint-compatible.

    `w3_scale` [1, IF, O] is the quantized-serving epilogue: `w3` is
    then int8/fp8 storage riding as a kernel input ref, upcast in-tile
    for the radial dot, the per-channel scale folded into R — the fp32
    grouped weight never exists in HBM (quant.rules / the
    _radial_contract epilogue, kernel-side)."""
    segs = []
    for i, ((d_in, _), x) in enumerate(zip(pairs, xg)):
        if arm == 'dense':
            lo, hi = abs(d_in - d_out), d_in + d_out
            T = consts[f'cg{i}'].astype(x.dtype)
            y = sh[..., lo * lo:(hi + 1) * (hi + 1)]
            # HIGHEST precision like get_basis's Q_J contraction, so the
            # rebuilt basis block matches the materialized one bit-close
            basis = jnp.einsum('...s,spqf->...pqf', y, T,
                               precision=jax.lax.Precision.HIGHEST)
            v2 = jnp.einsum('...pqf,...cq->...pcf', basis, x)
            segs.append(v2.reshape(*v2.shape[:-2], -1))
        elif arm == 'so2':
            xr = _rotate_in_c(x, fr, d_in, consts)
            segs.append(_banded_z_c(xr, d_in, d_out,
                                    consts[f'so2a{i}'].astype(x.dtype),
                                    consts[f'so2b{i}'].astype(x.dtype)))
        else:
            raise ValueError(f'unknown contraction arm {arm!r} '
                             f'(known: {ARMS})')
    z = jnp.concatenate(segs, axis=-1) if len(segs) > 1 else segs[0]
    if w3_scale is not None:
        R = jnp.einsum('...m,mio->...io', h,
                       w3.astype(jnp.float32),
                       preferred_element_type=jnp.float32) \
            * w3_scale[0] + b3
    else:
        R = jnp.einsum('...m,mio->...io', h, w3,
                       preferred_element_type=jnp.float32) + b3
    out = jnp.einsum('...pi,...io->...po', z, R)
    out = jnp.swapaxes(out, -1, -2)                     # [..., O, P]
    if arm == 'so2':
        out = _rotate_out_c(out, fr, d_out, consts)
    return out


def _radial_apply(x: jnp.ndarray, rp: Tuple[jnp.ndarray, ...]
                  ) -> jnp.ndarray:
    """Inlined radial trunk (Dense -> LN -> GELU, twice) for the global
    kernel, where the per-edge hidden never exists in HBM. rp is the
    8-tuple (w1, b1, ln1_scale, ln1_bias, w2, b2, ln2_scale, ln2_bias)
    with every 1-D param reshaped [1, mid] (TPU refs want >= 2D)."""
    w1, b1, s1, o1, w2, b2, s2, o2 = rp

    def ln(t, s, o):
        mu = t.mean(-1, keepdims=True)
        var = ((t - mu) ** 2).mean(-1, keepdims=True)
        return (t - mu) * jax.lax.rsqrt(var + 1e-6) * s + o

    t = jnp.einsum('...e,em->...m', x, w1) + b1
    t = jax.nn.gelu(ln(t, s1, o1))
    t = jnp.einsum('...e,em->...m', t, w2) + b2
    return jax.nn.gelu(ln(t, s2, o2))


def _safe_dist(rel: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    return jnp.sqrt(jnp.maximum(jnp.sum(rel ** 2, axis=-1), eps ** 2))


def _global_edge_payload(cfg: 'FlashConfig', rel, rp_v, rp_k=None):
    """Everything the global (graph-free) tile computes on the fly from
    a [..., 3] rel_pos block: the radial hiddens through the inlined
    Dense-LN-GELU trunk and the harmonics/frames payload the active
    arms need. Shared by the XLA stream's chunk body, the Pallas kernel
    body, and the ring-sharded fold so the three dispatches stay one
    function by construction."""
    ef = _safe_dist(rel)[..., None]
    h_v = _radial_apply(ef, rp_v)
    h_k = _radial_apply(ef, rp_k) if rp_k is not None else h_v
    sh = flash_sh_payload(rel, _sh_degree(cfg), differentiable=True) \
        if 'dense' in (cfg.arm_v, cfg.arm_k) else None
    fr = None
    if 'so2' in (cfg.arm_v, cfg.arm_k):
        from ..so2.frames import edge_frames
        fr = edge_frames(rel, _frame_degree(cfg), differentiable=True)
    return h_v, h_k, sh, fr


# --------------------------------------------------------------------- #
# online softmax
# --------------------------------------------------------------------- #

def _attend_block(qr, kblk, vblk, maskblk, m, l, acc, scale,
                  inbounds=None):
    """Fold one kv slot block into the running online-softmax state.
    qr [..., kv, g, D]; k/v [..., j, kv, D]; maskblk [..., j] or None;
    m/l [..., kv, g]; acc [..., kv, g, D].

    `maskblk` keeps the UNFUSED semantics (finite NEG_INF fill — a
    fully-masked row degrades to the uniform average, exactly like the
    XLA softmax). `inbounds` [j] marks slots that exist only because
    the slot axis padded to the block quantum: their probability is
    HARD-zeroed after the exp, so padding never changes any row's
    result — including fully-masked rows."""
    sim = jnp.einsum('...kgd,...jkd->...kgj', qr, kblk) * scale
    if maskblk is not None:
        sim = jnp.where(maskblk[..., None, None, :], sim, NEG_INF)
    if inbounds is not None:
        sim = jnp.where(inbounds, sim, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(sim, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(sim - m_new[..., None])
    if inbounds is not None:
        p = p * inbounds.astype(p.dtype)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + \
        jnp.einsum('...kgj,...jkd->...kgd', p, vblk)
    return m_new, l_new, acc_new


def _init_state(qr, prefix_k, prefix_v, scale, Dh):
    """State after the always-valid prefix slots ([global, null, self]
    left of the neighbors, all True in the unfused path's left-padded
    mask); NEG_INF/0/0 when there is no prefix."""
    lead = qr.shape[:-1]
    if prefix_k is None:
        m0 = jnp.full(lead, NEG_INF, jnp.float32)
        l0 = jnp.zeros(lead, jnp.float32)
        acc0 = jnp.zeros((*lead, Dh), jnp.float32)
        return m0, l0, acc0
    m0 = jnp.full(lead, NEG_INF, jnp.float32)
    l0 = jnp.zeros(lead, jnp.float32)
    acc0 = jnp.zeros((*lead, Dh), jnp.float32)
    return _attend_block(qr, prefix_k, prefix_v, None, m0, l0, acc0,
                         scale)


# --------------------------------------------------------------------- #
# block-size resolution (tuning kinds 'flash' / 'flash_stream')
# --------------------------------------------------------------------- #

# allowance for the contraction constant tables (Q_J / canonical-band /
# Wigner-factor refs — cfg-dependent, largest for dense high-degree
# pairs; 1 MiB covers every pair set <= degree 6 with tile pads)
_CONST_VMEM_ALLOWANCE = 1 * 2 ** 20


def _flash_vmem_bytes(bn: int, bj: int, S0: int, heads: int, kv_h: int,
                      Dh: int, mid: int, IF: int, P: int,
                      n: int = 0, xres: int = 0) -> int:
    """Coarse per-program VMEM model with the TPU tile pads (minor dim
    -> 128, second-minor -> 8), double-buffered in/out blocks plus the
    dominant in-kernel temporaries (the rebuilt basis block, the
    per-edge radial matrix R, and the kv block). `xres` is the
    node-feature row footprint sum_i roundup(C_i * Q_i, 128) — in kNN
    mode those operands are VMEM-RESIDENT at FULL n (the in-tile gather
    reads them whole), an n-scaled term NO block size can shrink; in
    global mode (n=0 here) they are bj-blocked instead."""
    Dhp = _round_up(Dh, 128)
    midp = _round_up(mid, 128)
    bj8 = _round_up(bj, 8)
    blocks = (bn * heads * Dhp            # q
              + bn * heads * Dhp          # out
              + 2 * bn * bj8 * midp       # h_v, h_k
              + bn * bj8 * 128            # idx / mask / payload minors
              + bn * _round_up(max(S0, 1), 8) * _round_up(kv_h * Dh, 128))
    scratch = bn * heads * Dhp + 2 * bn * _round_up(heads, 128)
    temps = (2 * bn * bj8 * kv_h * Dhp            # kv blocks (k and v)
             + bn * bj8 * P * _round_up(IF, 128)  # z / basis block
             + bn * bj8 * IF * 128)               # R [.., IF, O] minor pad
    resident = _round_up(max(n, bj8), 8) * xres   # node features (see above)
    return 4 * (2 * blocks + scratch + temps + resident) \
        + _CONST_VMEM_ALLOWANCE


def flash_admissible_blocks(shape) -> list:
    """Tile-legal, VMEM-admissible (block_n, block_j) candidates for a
    'flash' shape tuple (n, K, S0, heads, kv_h, Dh, mid, IF, P, xres)
    — what scripts/tune_kernels.py may measure. In kNN mode (K > 0)
    the node-feature residency is n-scaled and block-independent: a
    shape whose resident set alone busts the budget admits NOTHING
    (the caller must fall back to the XLA stream), rather than
    admitting blocks that Mosaic would refuse to compile."""
    n, K, S0, heads, kv_h, Dh, mid, IF, P, xres = \
        (int(s) for s in tuple(shape) + (0,) * (10 - len(tuple(shape))))
    out = []
    slot = K if K > 0 else n
    res_n = n if K > 0 else 0
    for bn in (128, 64, 32, 16, 8):
        if bn > _round_up(n, 8):
            continue
        for bj in (8, 16, 32, 64, 128):
            if bj > _round_up(slot, 8):
                continue
            if _flash_vmem_bytes(bn, bj, S0, heads, kv_h, Dh, mid, IF,
                                 P, n=res_n, xres=xres) <= _VMEM_LIMIT:
                out.append((bn, bj))
    return out


def _pick_flash_blocks(shape, dtype: str) -> Tuple[int, int]:
    """(block_n, block_j) resolution: env override > measured table
    (kind 'flash') > VMEM-ladder heuristic; every resolution recorded."""
    from . import tuning
    env = os.environ.get('SE3_TPU_FLASH_BLOCKS', '')
    if env:
        bn, bj = (int(x) for x in env.split(','))
        tuning.record_consult('flash', shape, dtype, 'env', (bn, bj))
        return bn, bj
    hit = tuning.lookup('flash', shape, dtype=dtype)
    if hit is not None:
        blocks, source = hit
        if len(blocks) == 2 and (
                source == 'forced'
                or tuning.validate_entry('flash', shape, blocks)):
            tuning.record_consult('flash', shape, dtype, source,
                                  tuple(blocks))
            return int(blocks[0]), int(blocks[1])
    n, K, S0, heads, kv_h, Dh, mid, IF, P, xres = (int(s) for s in shape)
    slot = K if K > 0 else n
    # prefer a slot block covering the (small) kNN slot axis; the pick
    # must come FROM the admissible set — a blind fallback here would
    # hand Mosaic a config _dispatch just confirmed exists some
    # admissible alternative for (the scoped-VMEM error class the
    # fallback guard exists to prevent)
    bj_pref = min(_round_up(slot, 8), 32)
    cands = flash_admissible_blocks(shape)
    if cands:
        bn = max(c[0] for c in cands)
        row = [c[1] for c in cands if c[0] == bn]
        below = [b for b in row if b <= bj_pref]
        bj = max(below) if below else min(row)
    else:
        # nothing fits at any block size: _dispatch routes to the XLA
        # stream and this pick is never compiled
        bn, bj = 8, bj_pref
    tuning.record_consult('flash', shape, dtype, 'heuristic', (bn, bj))
    return bn, bj


def _pick_stream_chunks(shape, dtype: str,
                        kind: str = 'flash_stream') -> int:
    """Node-chunk count for the XLA streaming path (and the backward's
    recompute replay). Heuristic: ~16-node chunks — measured best on
    the CPU toy A/B sweep (SE3_TPU_FLASH_CHUNKS 1/2/4/8/16: 8 chunks
    at n=128 beat 4 on BOTH step time and peak bytes; 1 = unchunked
    loses the memory win entirely), small enough that the per-chunk
    edge tensors stay cache-sized.

    `kind` keys the measured table: 'flash_stream' for the kNN stream,
    'flash_global' for the graph-free variant, whose per-chunk working
    set is O(rows * n) rather than O(rows * K) — at assembly n the
    small-n-calibrated n // 16 hard-code is exactly what the measured
    table exists to override (its candidate ladder extends to 2048
    chunks, tuning.admissible_candidates)."""
    from . import tuning
    env = os.environ.get('SE3_TPU_FLASH_CHUNKS', '')
    if env:
        chunks = max(1, int(env))
        tuning.record_consult(kind, shape, dtype, 'env', (chunks,))
        return chunks
    hit = tuning.lookup(kind, shape, dtype=dtype)
    if hit is not None:
        blocks, source = hit
        if source == 'forced' or tuning.validate_entry(
                kind, shape, blocks):
            tuning.record_consult(kind, shape, dtype, source, blocks)
            return int(blocks[0])
    n = int(shape[0])
    chunks = max(1, n // 16)
    tuning.record_consult(kind, shape, dtype, 'heuristic', (chunks,))
    return chunks


def _stream_kind(cfg: 'FlashConfig') -> str:
    return 'flash_global' if cfg.mode == 'global' else 'flash_stream'


def _shape_key(cfg: FlashConfig, ops) -> Tuple[int, ...]:
    q = ops['q']
    n = int(q.shape[1])
    K = int(ops['idx'].shape[-1]) if cfg.mode == 'knn' else 0
    Dh = int(q.shape[-1])
    mid = int(ops['h_v'].shape[-1]) if 'h_v' in ops \
        else int(ops['rp_v'][4].shape[0])
    IF = int(ops['wv'].shape[1])
    # node-feature row footprint (tile-padded): n-RESIDENT in kNN mode,
    # so the VMEM admission model must see it (no block shrinks it)
    xres = sum(_round_up(c * (2 * d + 1), 128) for d, c in cfg.pairs)
    return (n, K, cfg.prefix, cfg.heads, cfg.kv_heads, Dh, mid, IF,
            2 * cfg.d_out + 1, xres)


# --------------------------------------------------------------------- #
# XLA streaming path (CPU/GPU forward AND the recompute backward)
# --------------------------------------------------------------------- #

def _gather_nodes(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """x [B, n, ...], idx [B, nc, K] -> [B, nc, K, ...]."""
    return jax.vmap(lambda xb, ib: xb[ib])(x, idx)


def _row_attention(cfg: FlashConfig, q, kf, vf, mask_full):
    """Full-row attention for one node chunk (q [..., h, D];
    kf/vf [..., J, kv, D]; mask [..., J] or None) — mathematically the
    online-softmax limit with one block, and bit-compatible with the
    unfused einsum+softmax path."""
    group = cfg.heads // cfg.kv_heads
    qr = q.reshape(*q.shape[:-2], cfg.kv_heads, group, q.shape[-1])
    sim = jnp.einsum('...kgd,...jkd->...kgj', qr, kf) * cfg.scale
    if mask_full is not None:
        sim = jnp.where(mask_full[..., None, None, :], sim, NEG_INF)
    attn = jax.nn.softmax(sim, axis=-1)
    out = jnp.einsum('...kgj,...jkd->...kgd', attn, vf)
    return out.reshape(*q.shape)


def _chunk_body(cfg: FlashConfig, chunk, full):
    """One node chunk of the streaming computation. `chunk` holds the
    per-node operands sliced along the node axis; `full` the node-level
    feature tensors and parameters (closed over by lax.map)."""
    q = chunk['q']                              # [B, nc, h, Dh]
    Dh = q.shape[-1]
    kv_h = cfg.kv_heads
    if cfg.mode == 'knn':
        idx = chunk['idx']
        xg = tuple(_gather_nodes(x, idx) for x in full['xs'])
        h_v, h_k = chunk['h_v'], chunk.get('h_k', chunk['h_v'])
        sh = chunk.get('sh')
        fr = unpack_frames(chunk['fr']) if 'fr' in chunk else None
        nmask = chunk.get('nmask')
    else:
        ci = chunk['coords']                    # [B, nc, 3]
        cj = full['coords']                     # [B, n, 3]
        rel = ci[:, :, None, :] - cj[:, None, :, :]
        h_v, h_k, sh, fr = _global_edge_payload(
            cfg, rel, full['rp_v'], full.get('rp_k'))
        xg = tuple(jnp.broadcast_to(x[:, None], (x.shape[0], q.shape[1],
                                                 *x.shape[1:]))
                   for x in full['xs'])
        nmask = None
        if 'nodemask' in full:
            nmask = jnp.broadcast_to(full['nodemask'][:, None, :],
                                     rel.shape[:-1])
        if cfg.exclude_self:
            rows = chunk['row_id'][..., None]       # [B, nc, 1]
            cols = jnp.arange(cj.shape[1])[None, None, :]
            notself = rows != cols
            nmask = notself if nmask is None else (nmask & notself)

    consts = full['consts']
    kv_v = _kv_block(cfg.arm_v, cfg.pairs, cfg.d_out, xg, h_v, sh, fr,
                     full['wv'], full['bv'], consts,
                     w3_scale=full.get('wv_scale'))
    kv_v = kv_v.reshape(*kv_v.shape[:-2], kv_h, Dh)
    if cfg.tie:
        kv_k = kv_v
    else:
        kv_k = _kv_block(cfg.arm_k, cfg.pairs, cfg.d_out, xg, h_k, sh,
                         fr, full['wk'], full['bk'], consts,
                         w3_scale=full.get('wk_scale'))
        kv_k = kv_k.reshape(*kv_k.shape[:-2], kv_h, Dh)

    if cfg.prefix:
        S0 = cfg.prefix
        pk = chunk['prefix_k'].reshape(*q.shape[:-2], S0, kv_h, Dh)
        pv = chunk['prefix_v'].reshape(*q.shape[:-2], S0, kv_h, Dh)
        kv_k = jnp.concatenate((pk, kv_k), axis=-3)
        kv_v = jnp.concatenate((pv, kv_v), axis=-3)
        if nmask is not None:
            ones = jnp.ones((*nmask.shape[:-1], S0), bool)
            nmask = jnp.concatenate((ones, nmask), axis=-1)
    return _row_attention(cfg, q, kv_k, kv_v, nmask)


def _sh_degree(cfg: FlashConfig) -> int:
    """SH stack degree covering every pair's J range: ceil(max_J / 2)
    since flash_sh_payload stacks J = 0..2*max_degree."""
    max_j = max(d_in + cfg.d_out for d_in, _ in cfg.pairs)
    return (max_j + 1) // 2

def _frame_degree(cfg: FlashConfig) -> int:
    return max([cfg.d_out] + [d for d, _ in cfg.pairs])


_CHUNKED_KEYS = ('q', 'idx', 'nmask', 'h_v', 'h_k', 'sh', 'fr',
                 'prefix_k', 'prefix_v', 'coords', 'row_id')


def _flash_stream(cfg: FlashConfig, ops: dict, chunks: int
                  ) -> jnp.ndarray:
    """The XLA streaming path: lax.map over remat'd node chunks — the
    per-edge working set exists only one chunk at a time, both forward
    and (via jax.checkpoint) in the backward replay."""
    chunked = {k: v for k, v in ops.items()
               if k in _CHUNKED_KEYS and v is not None}
    if cfg.mode == 'global':
        chunked['coords'] = ops['coords']
        B, n = ops['q'].shape[:2]
        chunked['row_id'] = jnp.broadcast_to(jnp.arange(n)[None], (B, n))
    full = {k: v for k, v in ops.items() if k not in chunked}
    if cfg.mode == 'global':
        full['coords'] = ops['coords']
    full['consts'] = {k: jnp.asarray(v, jnp.float32)
                      for k, v in _arm_consts(cfg).items()}

    body = partial(_chunk_body, cfg)
    n = ops['q'].shape[1]
    c = max(1, min(chunks, n))
    if c == 1:
        return body(chunked, full)
    n_pad = -(-n // c) * c

    def split(a):
        if n_pad != n:
            pad = [(0, 0)] * a.ndim
            pad[1] = (0, n_pad - n)
            a = jnp.pad(a, pad)
        a = a.reshape(a.shape[0], c, n_pad // c, *a.shape[2:])
        return jnp.swapaxes(a, 0, 1)

    out = jax.lax.map(jax.checkpoint(lambda t: body(t, full)),
                      jax.tree_util.tree_map(split, chunked))
    out = jnp.swapaxes(out, 0, 1)
    out = out.reshape(out.shape[0], n_pad, *out.shape[3:])
    return out[:, :n] if n_pad != n else out


# --------------------------------------------------------------------- #
# Pallas kernel
# --------------------------------------------------------------------- #

def _flash_kernel_body(cfg: FlashConfig, spec, dims, *refs):
    (bn, bj, jcount, S0, L1) = (dims['bn'], dims['bj'], dims['jcount'],
                                dims['S0'], dims['L1'])
    named = dict(zip(spec, refs[:len(spec)]))
    out_ref = refs[len(spec)]
    m_scr, l_scr, acc_scr = refs[len(spec) + 1:]
    j = pl.program_id(2)
    heads, kv_h = cfg.heads, cfg.kv_heads
    group = heads // kv_h
    q = named['q'][0].astype(jnp.float32)          # [bn, h, Dh]
    Dh = q.shape[-1]
    qr = q.reshape(bn, kv_h, group, Dh)

    @pl.when(j == 0)
    def _init():
        if cfg.prefix:
            pk = named['prefix_k'][0].reshape(bn, S0, kv_h, Dh)
            pv = named['prefix_v'][0].reshape(bn, S0, kv_h, Dh)
        else:
            pk = pv = None
        m0, l0, acc0 = _init_state(qr, pk, pv, cfg.scale, Dh)
        m_scr[...] = m0.reshape(bn, heads)
        l_scr[...] = l0.reshape(bn, heads)
        acc_scr[...] = acc0.reshape(bn, heads, Dh)

    # ---- the slot block's keyed features, built in VMEM ---- #
    # node features ride as flat [n, C*Q] refs (ONE minor-dim tile pad
    # per degree instead of Q -> 128 per channel row); unflatten after
    # the gather
    if cfg.mode == 'knn':
        idxb = named['idx'][0]                     # [bn, bj] int32
        xg = tuple(
            jnp.take(named[f'x{i}'][0], idxb,
                     axis=0).reshape(bn, bj, c, 2 * d + 1)
            for i, (d, c) in enumerate(cfg.pairs))
        h_v = named['h_v'][0]
        h_k = named['h_k'][0] if 'h_k' in named else h_v
        sh = named['sh'][0] if 'sh' in named else None
        fr = unpack_frames(named['fr'][0]) if 'fr' in named else None
        maskb = named['nmask'][0] if cfg.has_mask else None
    else:
        ci = named['coords_i'][0]                  # [bn, 3]
        cj = named['coords_j'][0]                  # [bj, 3]
        rel = ci[:, None, :] - cj[None, :, :]
        rp_v = tuple(named[f'rpv{i}'][...] for i in range(8))
        rp_k = tuple(named[f'rpk{i}'][...] for i in range(8)) \
            if 'rpk0' in named else None
        h_v, h_k, sh, fr = _global_edge_payload(cfg, rel, rp_v, rp_k)
        xg = tuple(
            jnp.broadcast_to(
                named[f'x{i}'][0].reshape(bj, c, 2 * d + 1)[None],
                (bn, bj, c, 2 * d + 1))
            for i, (d, c) in enumerate(cfg.pairs))
        maskb = None
        if cfg.has_mask:
            maskb = jnp.broadcast_to(named['nodemask'][0][None, :],
                                     (bn, bj))
        if cfg.exclude_self:
            rows = pl.program_id(1) * bn + \
                jax.lax.broadcasted_iota(jnp.int32, (bn, bj), 0)
            cols = j * bj + \
                jax.lax.broadcasted_iota(jnp.int32, (bn, bj), 1)
            notself = rows != cols
            maskb = notself if maskb is None else (maskb & notself)

    consts = {k[2:]: named[k][...] for k in spec if k.startswith('c_')}
    kv_v = _kv_block(cfg.arm_v, cfg.pairs, cfg.d_out, xg, h_v, sh, fr,
                     named['wv'][...], named['bv'][...], consts,
                     w3_scale=(named['wv_scale'][...]
                               if 'wv_scale' in named else None))
    kv_v = kv_v.reshape(bn, bj, kv_h, Dh)
    if cfg.tie:
        kv_k = kv_v
    else:
        kv_k = _kv_block(cfg.arm_k, cfg.pairs, cfg.d_out, xg, h_k, sh,
                         fr, named['wk'][...], named['bk'][...], consts,
                         w3_scale=(named['wk_scale'][...]
                                   if 'wk_scale' in named else None))
        kv_k = kv_k.reshape(bn, bj, kv_h, Dh)

    # slots past the true axis length exist only because of the block
    # quantum — hard-zeroed so padding never changes a row's result
    inb = None
    if dims['slots'] % bj != 0:
        inb = (j * bj + jax.lax.iota(jnp.int32, bj)) < dims['slots']

    m = m_scr[...].reshape(bn, kv_h, group)
    l = l_scr[...].reshape(bn, kv_h, group)
    acc = acc_scr[...].reshape(bn, kv_h, group, Dh)
    m, l, acc = _attend_block(qr, kv_k, kv_v, maskb, m, l, acc,
                              cfg.scale, inbounds=inb)
    m_scr[...] = m.reshape(bn, heads)
    l_scr[...] = l.reshape(bn, heads)
    acc_scr[...] = acc.reshape(bn, heads, Dh)

    @pl.when(j == jcount - 1)
    def _finalize():
        out_ref[0] = (acc / l[..., None]).reshape(
            bn, heads, Dh).astype(out_ref.dtype)


@partial(jax.jit, static_argnames=('cfg',))
def _flash_fwd_impl(cfg: FlashConfig, ops: dict) -> jnp.ndarray:
    """The Pallas forward: grid (B, node blocks, slot blocks) with the
    slot axis INNERMOST so the online-softmax scratch state is carried
    sequentially; out written at the last slot block."""
    q = ops['q']
    B, n, heads, Dh = q.shape
    kv_h = cfg.kv_heads
    shape = _shape_key(cfg, ops)
    bn, bj = _pick_flash_blocks(shape, jnp.dtype(q.dtype).name)
    bn = min(bn, _round_up(n, 8))

    def pad_nodes(a, fill=0):
        if a is None:
            return None
        n_pad = _round_up(n, bn)
        if n_pad == n:
            return a
        pad = [(0, 0)] * a.ndim
        pad[1] = (0, n_pad - n)
        return jnp.pad(a, pad, constant_values=fill)

    n_p = _round_up(n, bn)
    spec_names, in_specs, args = [], [], []

    def add(name, arr, block, index_map):
        spec_names.append(name)
        in_specs.append(pl.BlockSpec(block, index_map,
                                     memory_space=pltpu.VMEM))
        args.append(arr)

    add('q', pad_nodes(q), (1, bn, heads, Dh),
        lambda b, i, j: (b, i, 0, 0))

    if cfg.mode == 'knn':
        K = ops['idx'].shape[-1]
        K_p = _round_up(K, min(bj, _round_up(K, 8)))
        bj = min(bj, K_p)
        jcount = K_p // bj
        slots = K

        def pad_slots(a, fill=0):
            if a is None or a.shape[2] == K_p:
                return a
            pad = [(0, 0)] * a.ndim
            pad[2] = (0, K_p - a.shape[2])
            return jnp.pad(a, pad, constant_values=fill)

        # padded slots are hard-zeroed by the `inbounds` vector in the
        # kernel body, so no mask is needed for them
        add('idx', pad_slots(pad_nodes(ops['idx'])), (1, bn, bj),
            lambda b, i, j: (b, i, j))
        if cfg.has_mask:
            add('nmask', pad_slots(pad_nodes(ops['nmask'], False), False),
                (1, bn, bj), lambda b, i, j: (b, i, j))
        mid = ops['h_v'].shape[-1]
        add('h_v', pad_slots(pad_nodes(ops['h_v'])), (1, bn, bj, mid),
            lambda b, i, j: (b, i, j, 0))
        if not cfg.tie and 'h_k' in ops:
            add('h_k', pad_slots(pad_nodes(ops['h_k'])),
                (1, bn, bj, mid), lambda b, i, j: (b, i, j, 0))
        if 'sh' in ops:
            S = ops['sh'].shape[-1]
            add('sh', pad_slots(pad_nodes(ops['sh'])), (1, bn, bj, S),
                lambda b, i, j: (b, i, j, 0))
        if 'fr' in ops:
            FL = ops['fr'].shape[-1]
            add('fr', pad_slots(pad_nodes(ops['fr'])), (1, bn, bj, FL),
                lambda b, i, j: (b, i, j, 0))
        for i, x in enumerate(ops['xs']):
            x2 = x.reshape(x.shape[0], x.shape[1], -1)   # [B, n, C*Q]
            add(f'x{i}', x2, (1,) + x2.shape[1:],
                lambda b, i_, j: (b, 0, 0))
        L1 = (ops['fr'].shape[-1] // 4) if 'fr' in ops else 0
    else:
        bj = min(bj, _round_up(n, 8))
        n_pj = _round_up(n, bj)
        jcount = n_pj // bj
        slots = n

        def pad_cols(a, axis, fill=0):
            if a.shape[axis] == n_pj:
                return a
            pad = [(0, 0)] * a.ndim
            pad[axis] = (0, n_pj - a.shape[axis])
            return jnp.pad(a, pad, constant_values=fill)

        add('coords_i', pad_nodes(ops['coords']), (1, bn, 3),
            lambda b, i, j: (b, i, 0))
        add('coords_j', pad_cols(ops['coords'], 1), (1, bj, 3),
            lambda b, i, j: (b, j, 0))
        if cfg.has_mask:
            add('nodemask', pad_cols(ops['nodemask'], 1, False), (1, bj),
                lambda b, i, j: (b, j))
        for i, x in enumerate(ops['xs']):
            xp = pad_cols(x.reshape(x.shape[0], x.shape[1], -1), 1)
            add(f'x{i}', xp, (1, bj, xp.shape[-1]),
                lambda b, i_, j: (b, j, 0))
        for i, p in enumerate(ops['rp_v']):
            add(f'rpv{i}', p, p.shape, lambda b, i_, j: (0, 0))
        if 'rp_k' in ops:
            for i, p in enumerate(ops['rp_k']):
                add(f'rpk{i}', p, p.shape, lambda b, i_, j: (0, 0))
        L1 = 0

    add('wv', ops['wv'], ops['wv'].shape, lambda b, i, j: (0, 0, 0))
    add('bv', ops['bv'], ops['bv'].shape, lambda b, i, j: (0, 0))
    if 'wv_scale' in ops:
        # quantized grouped radial weights: the per-channel dequant
        # scales ride as their own [1, IF, O] input ref, like PR 11's
        # contraction constants
        add('wv_scale', ops['wv_scale'], ops['wv_scale'].shape,
            lambda b, i, j: (0, 0, 0))
    if not cfg.tie:
        add('wk', ops['wk'], ops['wk'].shape, lambda b, i, j: (0, 0, 0))
        add('bk', ops['bk'], ops['bk'].shape, lambda b, i, j: (0, 0))
        if 'wk_scale' in ops:
            add('wk_scale', ops['wk_scale'], ops['wk_scale'].shape,
                lambda b, i, j: (0, 0, 0))
    if cfg.prefix:
        S0 = cfg.prefix
        KD = kv_h * Dh
        add('prefix_k', pad_nodes(ops['prefix_k']), (1, bn, S0, KD),
            lambda b, i, j: (b, i, 0, 0))
        add('prefix_v', pad_nodes(ops['prefix_v']), (1, bn, S0, KD),
            lambda b, i, j: (b, i, 0, 0))
    # contraction constants (Q_J / canonical-band / Wigner-factor
    # tables): Pallas kernels cannot capture constant arrays, so every
    # one rides as a VMEM input ref
    for name, arr in sorted(_arm_consts(cfg).items()):
        carr = jnp.asarray(arr, jnp.float32)
        zeros = (0,) * carr.ndim
        add(f'c_{name}', carr, carr.shape,
            lambda b, i, j, _z=zeros: _z)

    dims = dict(bn=bn, bj=bj, jcount=jcount, S0=cfg.prefix, L1=L1,
                slots=slots)
    kernel = partial(_flash_kernel_body, cfg, tuple(spec_names), dims)
    out = pl.pallas_call(
        kernel,
        grid=(B, n_p // bn, jcount),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bn, heads, Dh),
                               lambda b, i, j: (b, i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, n_p, heads, Dh), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bn, heads), jnp.float32),
            pltpu.VMEM((bn, heads), jnp.float32),
            pltpu.VMEM((bn, heads, Dh), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(*args)
    return out[:, :n]


# --------------------------------------------------------------------- #
# dispatch + recompute-in-backward custom_vjp
# --------------------------------------------------------------------- #

def _dispatch(cfg: FlashConfig, ops: dict) -> jnp.ndarray:
    shape = _shape_key(cfg, ops)
    if cfg.use_pallas or cfg.interpret:
        # kNN mode holds the node-feature operands VMEM-resident at
        # full n — a shape whose resident set busts the scoped budget
        # at EVERY block size must fall back to the XLA stream, not
        # surface a Mosaic VMEM error (the fused_attention_fits idiom)
        if cfg.interpret or flash_admissible_blocks(shape):
            return _flash_fwd_impl(cfg, ops)
        import warnings
        warnings.warn(
            f'flash kernel working set (shape {shape}) exceeds the '
            f'scoped-VMEM budget at every block size; using the XLA '
            f'streaming path', stacklevel=2)
    chunks = _pick_stream_chunks(shape, jnp.dtype(ops['q'].dtype).name,
                                 kind=_stream_kind(cfg))
    return _flash_stream(cfg, ops, chunks)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_core(cfg: FlashConfig, ops: dict) -> jnp.ndarray:
    return _dispatch(cfg, ops)


def _flash_core_fwd(cfg, ops):
    # residuals are the INPUTS only — no basis, keyed features, or
    # scores survive the forward
    return _dispatch(cfg, ops), ops


def _flash_core_bwd(cfg, ops, g):
    # recompute-in-backward: replay the chunked XLA streaming path under
    # jax.vjp — activations exist one node chunk at a time, composing
    # with the reversible trunk's outer remat for near-O(1) memory
    shape = _shape_key(cfg, ops)
    chunks = _pick_stream_chunks(shape, jnp.dtype(ops['q'].dtype).name,
                                 kind=_stream_kind(cfg))
    _, vjp = jax.vjp(lambda o: _flash_stream(cfg, o, chunks), ops)
    (dops,) = vjp(g)
    return (dops,)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _resolve_pallas(pallas: Optional[bool], interpret: bool) -> bool:
    if interpret:
        return True
    if pallas is None:
        from ..utils.helpers import is_tpu_backend
        return is_tpu_backend()
    return pallas


# --------------------------------------------------------------------- #
# public entry points
# --------------------------------------------------------------------- #

def flash_attention(q, xs, idx, nmask, h_v, wv, bv, *,
                    pairs, d_out, heads, kv_heads, scale,
                    arm_v='dense', arm_k=None, h_k=None, wk=None,
                    bk=None, sh=None, frames=None, prefix_k=None,
                    prefix_v=None, wv_scale=None, wk_scale=None,
                    pallas=None, interpret=False
                    ) -> jnp.ndarray:
    """Streaming kNN equivariant attention for ONE output degree.

    q [B, n, h, Dh] (Dh = dim_head * (2*d_out+1), (dim_head, m)-major);
    xs tuple of node features [B, n, C_i, Q_i] per input degree (pairs
    order); idx [B, n, K] neighbor ids; nmask [B, n, K] bool or None;
    h_v/h_k [B, n, K, mid] radial hiddens; wv/bv (wk/bk) the grouped
    radial params [mid, IF, O] / [IF, O] with O = kv_heads * dim_head;
    sh the flash_sh_payload stack (dense arm); frames the so2 edge
    frames dict (so2 arm); prefix_k/v [B, n, S0, kv_heads * Dh] the
    always-valid [global, null, self] slots. tie keys to values by
    omitting wk. Returns [B, n, h, Dh] float32.
    """
    tie = wk is None
    arm_k = arm_v if arm_k is None else arm_k
    cfg = FlashConfig(
        pairs=tuple((int(d), int(c)) for d, c in pairs),
        d_out=int(d_out), heads=int(heads), kv_heads=int(kv_heads),
        scale=float(scale), arm_v=arm_v, arm_k=arm_k, tie=tie,
        prefix=int(prefix_k.shape[2]) if prefix_k is not None else 0,
        has_mask=nmask is not None, mode='knn',
        use_pallas=_resolve_pallas(pallas, interpret),
        interpret=interpret)
    ops = dict(q=q, xs=tuple(xs), idx=idx, h_v=h_v, wv=wv, bv=bv)
    if wv_scale is not None:
        # quantized grouped radial weights (quant.QuantTensor split by
        # the caller): wv is int8/fp8 storage, the scale dequants
        # in-tile as an epilogue on the radial dot
        ops['wv_scale'] = jnp.asarray(wv_scale, jnp.float32)
    if nmask is not None:
        ops['nmask'] = nmask
    if not tie:
        ops.update(wk=wk, bk=bk)
        if wk_scale is not None:
            ops['wk_scale'] = jnp.asarray(wk_scale, jnp.float32)
        if h_k is not None:
            ops['h_k'] = h_k
    if 'dense' in (arm_v, arm_k if not tie else arm_v):
        assert sh is not None, 'dense arm needs the sh payload'
        ops['sh'] = sh
    if 'so2' in (arm_v, arm_k if not tie else arm_v):
        assert frames is not None, 'so2 arm needs the edge frames'
        ops['fr'] = pack_frames(frames)
    if prefix_k is not None:
        ops.update(prefix_k=prefix_k, prefix_v=prefix_v)
    with jax.named_scope('flash_attention'):
        return _flash_core(cfg, ops)


def flash_global_attention(q, xs, coords, rp_v, wv, bv, *,
                           pairs, d_out, heads, kv_heads, scale,
                           arm='dense', rp_k=None, wk=None, bk=None,
                           node_mask=None, prefix_k=None, prefix_v=None,
                           exclude_self=True, pallas=None,
                           interpret=False,
                           materialize=False) -> jnp.ndarray:
    """Graph-free global equivariant attention (no kNN truncation): every
    node attends to every other node, with rel_pos/rel_dist, the radial
    hidden (rp_* = the 8-tuple Dense-LN-GELU trunk params, 1-D leaves
    reshaped [1, mid]) and the harmonics/frames payload computed on the
    fly per tile — no per-edge tensor ever exists in HBM, activation
    memory is O(n) at O(n^2) compute. The large-assembly scenario.

    `materialize=True` is the CONTROL arm: the identical function run as
    one unchunked pass (every [B, n, n, ...] per-edge tensor in HBM,
    plain autodiff — no custom_vjp, no recompute). Same params, same
    math; only the memory story differs. The assembly smoke and
    bench --assembly A/B the two arms for parity and the peak-HBM
    ledger claim."""
    tie = wk is None
    cfg = FlashConfig(
        pairs=tuple((int(d), int(c)) for d, c in pairs),
        d_out=int(d_out), heads=int(heads), kv_heads=int(kv_heads),
        scale=float(scale), arm_v=arm, arm_k=arm, tie=tie,
        prefix=int(prefix_k.shape[2]) if prefix_k is not None else 0,
        has_mask=node_mask is not None, mode='global',
        exclude_self=bool(exclude_self),
        use_pallas=(False if materialize
                    else _resolve_pallas(pallas, interpret)),
        interpret=interpret)
    rp_v = tuple(p.reshape(1, -1) if p.ndim == 1 else p for p in rp_v)
    ops = dict(q=q, xs=tuple(xs), coords=coords, rp_v=rp_v, wv=wv, bv=bv)
    if node_mask is not None:
        ops['nodemask'] = node_mask
    if not tie:
        assert rp_k is not None, 'untied keys need their radial params'
        ops.update(rp_k=tuple(p.reshape(1, -1) if p.ndim == 1 else p
                              for p in rp_k), wk=wk, bk=bk)
    if prefix_k is not None:
        ops.update(prefix_k=prefix_k, prefix_v=prefix_v)
    if materialize:
        # one chunk == the fully-materialized all-pairs computation,
        # differentiated by plain autodiff (no recompute-in-backward):
        # the O(n^2)-memory reference the streaming arm is judged against
        with jax.named_scope('global_attention_materialized'):
            return _flash_stream(cfg, ops, 1)
    with jax.named_scope('flash_global_attention'):
        return _flash_core(cfg, ops)


def flash_global_attention_sharded(q, xs, coords, rp_v, wv, bv, *,
                                   mesh, pairs, d_out, heads, kv_heads,
                                   scale, axis_name='sp', overlap=True,
                                   arm='dense', rp_k=None, wk=None,
                                   bk=None, node_mask=None,
                                   prefix_k=None, prefix_v=None,
                                   exclude_self=True) -> jnp.ndarray:
    """Sequence-parallel global attention: node axis sharded over the
    `axis_name` mesh axis, the SOURCE blocks (coords / features / mask)
    rotated one hop per step via `parallel.ring.ring_scan` while each
    device folds the visiting block into its rows' online-softmax state.
    Per-device memory is O(n_local^2) per step and the only collectives
    are the ring's ppermutes — `analyze_hlo_comm` proves the compiled
    program free of full-width all-gathers (the PR 11 residue: the
    flash path used to bypass the ring exchange scope entirely).

    Same argument contract as `flash_global_attention` plus the mesh;
    bit-compatible results (the fold is `_attend_block`, the same
    online softmax the kernel and the stream run)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.ring import pcast_varying, ring_scan, shard_map
    tie = wk is None
    cfg = FlashConfig(
        pairs=tuple((int(d), int(c)) for d, c in pairs),
        d_out=int(d_out), heads=int(heads), kv_heads=int(kv_heads),
        scale=float(scale), arm_v=arm, arm_k=arm, tie=tie,
        prefix=int(prefix_k.shape[2]) if prefix_k is not None else 0,
        has_mask=node_mask is not None, mode='global',
        exclude_self=bool(exclude_self))
    rp_v = tuple(p.reshape(1, -1) if p.ndim == 1 else p for p in rp_v)
    if rp_k is not None:
        rp_k = tuple(p.reshape(1, -1) if p.ndim == 1 else p for p in rp_k)
    n = q.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    if node_mask is None:
        node_mask = jnp.ones(coords.shape[:2], bool)

    row = lambda ndim: P(None, axis_name, *([None] * (ndim - 2)))  # noqa: E731
    sharded = [q, coords, node_mask, *xs]
    in_specs = [row(a.ndim) for a in sharded]
    n_xs = len(xs)
    has_prefix = prefix_k is not None
    if has_prefix:
        sharded += [prefix_k, prefix_v]
        in_specs += [row(4), row(4)]
    # weights replicated on every device (the ring rotates activations,
    # never parameters)
    repl = [*rp_v, wv, bv]
    if not tie:
        assert rp_k is not None, 'untied keys need their radial params'
        repl += [*rp_k, wk, bk]
    in_specs += [P()] * len(repl)

    def local(q, coords, nmask, *rest):
        xs_l = rest[:n_xs]
        rest = rest[n_xs:]
        if has_prefix:
            pk, pv = rest[0], rest[1]
            rest = rest[2:]
        else:
            pk = pv = None
        rpv = rest[:8]
        rest = rest[8:]
        wv_l, bv_l = rest[0], rest[1]
        rest = rest[2:]
        rpk = wk_l = bk_l = None
        if not tie:
            rpk = rest[:8]
            wk_l, bk_l = rest[8], rest[9]
        return _global_sharded_local(
            cfg, q, xs_l, coords, nmask, pk, pv, rpv, rpk, wv_l, bv_l,
            wk_l, bk_l, axis_name=axis_name, overlap=overlap,
            pcast=pcast_varying, ring=ring_scan)

    fn = shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=row(4))
    with jax.named_scope('flash_global_attention_sharded'):
        return fn(*sharded, *repl)


def _global_sharded_local(cfg, q, xs, coords, nmask, prefix_k, prefix_v,
                          rp_v, rp_k, wv, bv, wk, bk, *, axis_name,
                          overlap, pcast, ring):
    """Per-shard body: every operand is this device's row block.
    Queries stay pinned; (coords, mask, features) rotate as the source
    blocks. Exactly sp ppermutes per operand, no other collectives."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, nl = q.shape[:2]
    Dh = q.shape[-1]
    kv_h = cfg.kv_heads
    group = cfg.heads // kv_h
    qr = q.reshape(b, nl, kv_h, group, Dh)
    if prefix_k is not None:
        S0 = cfg.prefix
        pk = prefix_k.reshape(b, nl, S0, kv_h, Dh)
        pv = prefix_v.reshape(b, nl, S0, kv_h, Dh)
    else:
        pk = pv = None
    m, l, acc = _init_state(qr, pk, pv, cfg.scale, Dh)
    m, l, acc = (pcast(t, axis_name) for t in (m, l, acc))
    consts = {k: jnp.asarray(v, jnp.float32)
              for k, v in _arm_consts(cfg).items()}
    row_gid = my_idx * nl + jnp.arange(nl, dtype=jnp.int32)

    def fold(carry, blocks, t):
        m, l, acc = carry
        cj, mask_j, *xs_j = blocks
        owner = (my_idx + t) % axis_size
        rel = coords[:, :, None, :] - cj[:, None, :, :]
        h_v, h_k, sh, fr = _global_edge_payload(cfg, rel, rp_v, rp_k)
        xg = tuple(jnp.broadcast_to(x[:, None], (b, nl, *x.shape[1:]))
                   for x in xs_j)
        kv_v = _kv_block(cfg.arm_v, cfg.pairs, cfg.d_out, xg, h_v, sh,
                         fr, wv, bv, consts).reshape(b, nl, nl, kv_h, Dh)
        if cfg.tie:
            kv_k = kv_v
        else:
            kv_k = _kv_block(cfg.arm_k, cfg.pairs, cfg.d_out, xg, h_k,
                             sh, fr, wk, bk,
                             consts).reshape(b, nl, nl, kv_h, Dh)
        maskb = jnp.broadcast_to(mask_j[:, None, :], (b, nl, nl)) \
            if cfg.has_mask else None
        if cfg.exclude_self:
            col_gid = owner * nl + jnp.arange(nl, dtype=jnp.int32)
            notself = (row_gid[:, None] != col_gid[None, :])[None]
            maskb = notself if maskb is None else (maskb & notself)
        return _attend_block(qr, kv_k, kv_v, maskb, m, l, acc, cfg.scale)

    m, l, acc = ring(fold, (m, l, acc), (coords, nmask, *xs),
                     axis_name, overlap=overlap)
    return (acc / l[..., None]).reshape(b, nl, cfg.heads, Dh)
