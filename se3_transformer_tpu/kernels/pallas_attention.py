"""Pallas TPU kernel: fused multi-degree SE(3) attention.

The reference computes attention per degree with separate einsums
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:508-516):
logits summed jointly over (channel, m), softmax, then a weighted sum per
degree — with the [b, h, n, J] similarity/attention tensors round-tripping
memory between those steps (SURVEY.md §3.4 hot loop, §7.2 step 7b).

TPU-native formulation: attention stays PER DEGREE (each degree has its
own softmax, as in the reference), but within a degree the (dim_head, m)
axes are flattened into one feature axis D = dim_head * (2d+1) — the
logits reduce over both jointly — and one kernel fuses the whole
sim/softmax/weighted-sum chain over the kv slots in VMEM:

    per (b*h, n-block) program:
        sim[e, j] = scale * sum_D q[e, D] k[e, j, D]     (VPU reduce)
        attn      = softmax_j(sim + mask)                 (VMEM)
        out[e, D] = sum_j attn[e, j] v[e, j, D]           (VPU reduce)

so sim/attn never exist in HBM and k/v are read exactly once. J (self +
null + neighbors) is small (~K+2 <= 64), so the whole slot axis fits in
VMEM and no online-softmax machinery is needed — this is the
graph-attention analogue of a single flash-attention tile. The caller
(ops.attention.AttentionSE3) invokes it once per degree; degrees share
nothing but the mask, so per-degree calls lose no fusion opportunity.

Multi-query attention (kv_heads < heads) is handled in the index maps:
query-head programs map onto their shared kv head, so the 1-head k/v is
never materialized per query head.

Backward: the op is wrapped in jax.custom_vjp with the XLA reference
implementation's VJP (attention backward is matmul-shaped and XLA-fuses
well; the forward fusion is where the HBM win is). Numerics are gated
against the XLA path in tests (interpreter mode) and on-chip
(scripts/tpu_checks.py).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def attention_reference(q, k, v, mask, scale):
    """XLA reference: q [BH, n, D], k/v [BKV, n, J, D], mask [B, n, J] or
    None -> out [BH, n, D]. BH = B*h, BKV = B*kv_h; kv heads are shared
    by contiguous groups of query heads."""
    BH = q.shape[0]
    BKV = k.shape[0]
    group = BH // BKV  # query heads per kv head
    kq = jnp.repeat(k, group, axis=0)
    vq = jnp.repeat(v, group, axis=0)
    sim = jnp.einsum('bnd,bnjd->bnj', q, kq) * scale
    if mask is not None:
        h = BH // mask.shape[0]
        mq = jnp.repeat(mask, h, axis=0)
        sim = jnp.where(mq, sim, NEG_INF)
    attn = jax.nn.softmax(sim, axis=-1)
    return jnp.einsum('bnj,bnjd->bnd', attn, vq)


def _softmax_weighted_sum(q, k, v, sim, o_ref):
    m = jnp.max(sim, axis=-1, keepdims=True)
    p = jnp.exp(sim - m)
    attn = p / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = jnp.sum(attn[:, :, None] * v, axis=1).astype(o_ref.dtype)


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale):
    q = q_ref[0]            # [n_b, D]
    k = k_ref[0]            # [n_b, J, D]
    v = v_ref[0]            # [n_b, J, D]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale      # [n_b, J]
    sim = jnp.where(mask_ref[0], sim, NEG_INF)
    _softmax_weighted_sum(q, k, v, sim, o_ref)


def _kernel_nomask(q_ref, k_ref, v_ref, o_ref, *, scale):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale
    _softmax_weighted_sum(q, k, v, sim, o_ref)


def _pick_block_n(n: int, J: int, D: int,
                  vmem_budget: int = 10 * 2 ** 20) -> int:
    for block_n in (512, 256, 128, 64, 32, 16, 8):
        # k, v [n_b, J, D] dominate; q/out [n_b, D]; sim-class [n_b, J]
        total = block_n * (2 * J * D + 2 * D + 4 * J) * 4
        if total <= vmem_budget:
            # never exceed n rounded up to the 8-row sublane minimum
            # (a tiny input must not pad to a full 512-row block)
            return min(block_n, max(8, _round_up(n, 8)))
    return 8


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=('heads', 'scale', 'interpret'))
def _fused_attention_fwd_impl(q, k, v, mask, heads: int, scale: float,
                              interpret: bool = False):
    BH, n, D = q.shape
    BKV, _, J, _ = k.shape
    group = BH // BKV

    block_n = _pick_block_n(n, J, D)
    np_ = _round_up(n, block_n)
    if np_ != n:
        q = jnp.pad(q, ((0, 0), (0, np_ - n), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        if mask is not None:
            # padded rows: keep slots valid so their softmax stays finite
            mask = jnp.pad(mask, ((0, 0), (0, np_ - n), (0, 0)),
                           constant_values=True)

    in_specs = [
        pl.BlockSpec((1, block_n, D), lambda bh, e: (bh, e, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_n, J, D),
                     lambda bh, e: (bh // group, e, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_n, J, D),
                     lambda bh, e: (bh // group, e, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_n, J), lambda bh, e: (bh // heads, e, 0),
                         memory_space=pltpu.VMEM))
        args.append(mask)
        kernel = functools.partial(_kernel, scale=scale)
    else:
        # no mask input at all: the constant-True mask would only waste a
        # [1, block_n, J] DMA per program
        kernel = functools.partial(_kernel_nomask, scale=scale)

    out = pl.pallas_call(
        kernel,
        grid=(BH, np_ // block_n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_n, D), lambda bh, e: (bh, e, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, np_, D), jnp.float32),
        interpret=interpret,
    )(*args)
    return out[:, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_attention(q, k, v, mask, heads: int, scale: float,
                    interpret: bool = False):
    """Fused multi-degree attention. q [B*h, n, D], k/v [B*kv_h, n, J, D],
    mask [B, n, J] bool or None -> [B*h, n, D] float32."""
    return _fused_attention_fwd_impl(q, k, v, mask, heads, scale, interpret)


def _fa_fwd(q, k, v, mask, heads, scale, interpret):
    out = _fused_attention_fwd_impl(q, k, v, mask, heads, scale, interpret)
    return out, (q, k, v, mask)


def _fa_bwd(heads, scale, interpret, res, g):
    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, mask, scale),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


fused_attention.defvjp(_fa_fwd, _fa_bwd)
