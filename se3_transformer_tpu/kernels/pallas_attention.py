"""Pallas TPU kernel: fused multi-degree SE(3) attention.

The reference computes attention per degree with separate einsums
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:508-516):
logits summed jointly over (channel, m), softmax, then a weighted sum per
degree — with the [b, h, n, J] similarity/attention tensors round-tripping
memory between those steps (SURVEY.md §3.4 hot loop, §7.2 step 7b).

TPU-native formulation: attention stays PER DEGREE (each degree has its
own softmax, as in the reference), but within a degree the (dim_head, m)
axes are flattened into one feature axis D = dim_head * (2d+1) — the
logits reduce over both jointly — and one kernel fuses the whole
sim/softmax/weighted-sum chain over the kv slots in VMEM:

    per (b*h, n-block) program:
        sim[e, j] = scale * sum_D q[e, D] k[e, j, D]     (VPU reduce)
        attn      = softmax_j(sim + mask)                 (VMEM)
        out[e, D] = sum_j attn[e, j] v[e, j, D]           (VPU reduce)

so sim/attn never exist in HBM and k/v are read exactly once. J (self +
null + neighbors) is small (~K+2 <= 64), so the whole slot axis fits in
VMEM and no online-softmax machinery is needed — this is the
graph-attention analogue of a single flash-attention tile. The caller
(ops.attention.AttentionSE3) invokes it once per degree; degrees share
nothing but the mask, so per-degree calls lose no fusion opportunity.

Multi-query attention (kv_heads < heads) is handled in the index maps:
query-head programs map onto their shared kv head, so the 1-head k/v is
never materialized per query head.

Backward: a second fused kernel (custom_vjp) recomputes sim/softmax in
VMEM and emits dq/dk/dv in one kv pass — grid (n_blocks, bh) with bh
inner so shared-kv dk/dv blocks accumulate over consecutive
query-head-group iterations (multi-query). Numerics are gated against
the XLA path in tests (interpreter mode) and on-chip
(scripts/kernel_smoke.py, scripts/tpu_checks.py).
"""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def attention_reference(q, k, v, mask, scale):
    """XLA reference: q [BH, n, D], k/v [BKV, n, J, D], mask [B, n, J] or
    None -> out [BH, n, D]. BH = B*h, BKV = B*kv_h; kv heads are shared
    by contiguous groups of query heads."""
    BH = q.shape[0]
    BKV = k.shape[0]
    group = BH // BKV  # query heads per kv head
    kq = jnp.repeat(k, group, axis=0)
    vq = jnp.repeat(v, group, axis=0)
    sim = jnp.einsum('bnd,bnjd->bnj', q, kq) * scale
    if mask is not None:
        h = BH // mask.shape[0]
        mq = jnp.repeat(mask, h, axis=0)
        sim = jnp.where(mq, sim, NEG_INF)
    attn = jax.nn.softmax(sim, axis=-1)
    return jnp.einsum('bnj,bnjd->bnd', attn, vq)


def _softmax_weighted_sum(q, k, v, sim, o_ref):
    m = jnp.max(sim, axis=-1, keepdims=True)
    p = jnp.exp(sim - m)
    attn = p / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = jnp.sum(attn[:, :, None] * v, axis=1).astype(o_ref.dtype)


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale):
    q = q_ref[0]            # [n_b, D]
    k = k_ref[0]            # [n_b, J, D]
    v = v_ref[0]            # [n_b, J, D]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale      # [n_b, J]
    sim = jnp.where(mask_ref[0], sim, NEG_INF)
    _softmax_weighted_sum(q, k, v, sim, o_ref)


def _kernel_nomask(q_ref, k_ref, v_ref, o_ref, *, scale):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale
    _softmax_weighted_sum(q, k, v, sim, o_ref)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# Mosaic's scoped-vmem stack limit is 16 MiB; stay under it with slack
# for compiler temporaries. Verified the hard way: the first guess of
# this budget ignored tiling pads and OOM'd at the flagship shapes
# (n=1024, J=33) with "Scoped allocation ... exceeded scoped vmem limit".
_VMEM_LIMIT = 12 * 2 ** 20


def _block_row_bytes(J: int, D: int, bwd: bool) -> int:
    """VMEM bytes per node-row of the kernel working set, with the real
    TPU tile pads: the minor (lane) dim pads to 128, the second-minor
    (sublane) dim to 8 — so a [n_b, J, D] kv block occupies
    n_b * roundup(J,8) * roundup(D,128) f32 slots (D=8 inflates 16x),
    and a [n_b, J] sim-class array occupies n_b * roundup(J,128). Pallas
    double-buffers every in/out block across grid steps: x2."""
    Jp, Dp, Jl = _round_up(J, 8), _round_up(D, 128), _round_up(J, 128)
    if bwd:
        # in: k, v [n_b,J,D]; q, g [n_b,D]; mask. out: dq; dk, dv.
        # sim-class temporaries: sim, p/a, da, dsim + slack
        blocks = 4 * Jp * Dp + 3 * Dp + Jl
        temps = 6 * Jl
    else:
        # in: k, v; q; mask. out: out. temporaries: sim, p/attn + slack
        blocks = 2 * Jp * Dp + 2 * Dp + Jl
        temps = 4 * Jl
    return (2 * blocks + temps) * 4


def _pick_block_n(n: int, J: int, D: int, bwd: bool = False,
                  dtype: str = 'float32') -> int:
    """block_n resolution: the measured shape-keyed table
    (kernels.tuning) first, then the VMEM-ladder heuristic. The forward
    consults kind 'attention' (the tuner admits candidates against the
    BACKWARD row model, since training differentiates with the same
    block family); the backward consults its OWN kind 'attention_bwd'
    against its ~2x row model — previously the bwd ran the heuristic
    only, so scripts/tune_kernels.py could never promote a measured bwd
    block. `dtype` is the storage dtype of the q/k/v operands and keys
    the table entry. With an empty table every pick is bit-identical to
    the heuristic."""
    row = _block_row_bytes(J, D, bwd)
    cap = max(8, _round_up(n, 8))  # a tiny input must not pad to a full
    # 512-row block

    def _heuristic():
        for block_n in (512, 256, 128, 64, 32, 16, 8):
            if block_n * row <= _VMEM_LIMIT:
                return min(block_n, cap)
        return 8

    from . import tuning
    kind = 'attention_bwd' if bwd else 'attention'
    hit = tuning.lookup(kind, (n, J, D), dtype=dtype)
    if hit is not None:
        blocks, source = hit
        if len(blocks) == 1 and (
                source == 'forced'
                or tuning.validate_entry(kind, (n, J, D), blocks)):
            block_n = min(int(blocks[0]), cap)
            tuning.record_consult(kind, (n, J, D), dtype,
                                  source, (block_n,))
            return block_n
    block_n = _heuristic()
    tuning.record_consult(kind, (n, J, D), dtype, 'heuristic',
                          (block_n,))
    return block_n


def fused_attention_fits(J: int, D: int, bwd: bool = True) -> bool:
    """True when the fused kernel's working set fits the scoped-VMEM
    budget at SOME block size. The dispatch in ops.attention falls back
    to the XLA path when this is False (e.g. num_neighbors~512 at a wide
    dim_head) instead of surfacing a Mosaic VMEM error.

    bwd=True is DELIBERATELY conservative (ADVICE r3 #2): the module
    dispatch cannot know whether the caller will differentiate, so it
    budgets for the ~2x backward working set even in inference-only use.
    A config whose forward fits but backward doesn't therefore runs XLA;
    callers that never differentiate can query fits(bwd=False) and call
    kernels.pallas_attention.fused_attention directly."""
    return 8 * _block_row_bytes(J, D, bwd) <= _VMEM_LIMIT


@functools.partial(jax.jit, static_argnames=('heads', 'scale', 'interpret'))
def _fused_attention_fwd_impl(q, k, v, mask, heads: int, scale: float,
                              interpret: bool = False):
    BH, n, D = q.shape
    BKV, _, J, _ = k.shape
    group = BH // BKV

    block_n = _pick_block_n(n, J, D, dtype=jnp.dtype(q.dtype).name)
    np_ = _round_up(n, block_n)
    if np_ != n:
        q = jnp.pad(q, ((0, 0), (0, np_ - n), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        if mask is not None:
            # padded rows: keep slots valid so their softmax stays finite
            mask = jnp.pad(mask, ((0, 0), (0, np_ - n), (0, 0)),
                           constant_values=True)

    in_specs = [
        pl.BlockSpec((1, block_n, D), lambda bh, e: (bh, e, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_n, J, D),
                     lambda bh, e: (bh // group, e, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_n, J, D),
                     lambda bh, e: (bh // group, e, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_n, J), lambda bh, e: (bh // heads, e, 0),
                         memory_space=pltpu.VMEM))
        args.append(mask)
        kernel = functools.partial(_kernel, scale=scale)
    else:
        # no mask input at all: the constant-True mask would only waste a
        # [1, block_n, J] DMA per program
        kernel = functools.partial(_kernel_nomask, scale=scale)

    out = pl.pallas_call(
        kernel,
        grid=(BH, np_ // block_n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_n, D), lambda bh, e: (bh, e, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, np_, D), jnp.float32),
        interpret=interpret,
    )(*args)
    return out[:, :n]


# --------------------------------------------------------------------- #
# fused backward
# --------------------------------------------------------------------- #
# Per (node-block, bh) program, recompute sim/attn in VMEM (cheaper than
# round-tripping them through HBM) and emit all three cotangents:
#   dv_j  += a_j * g                      (accumulated over the head group)
#   da_j   = <g, v_j>
#   dsim_j = a_j * (da_j - sum_l a_l da_l)
#   dq     = scale * sum_j dsim_j k_j
#   dk_j  += scale * dsim_j * q           (accumulated over the head group)
# The grid is (n_e, BH) with bh INNER so the shared-kv dk/dv blocks are
# revisited on consecutive iterations (the legal accumulation pattern for
# multi-query attention, group = heads / kv_heads).


def _bwd_compute(q, k, v, g, sim, group, scale, dq_ref, dk_ref, dv_ref):
    bh = pl.program_id(1)
    m = jnp.max(sim, axis=-1, keepdims=True)
    p = jnp.exp(sim - m)
    a = p / jnp.sum(p, axis=-1, keepdims=True)            # [n_b, J]
    da = jnp.sum(v * g[:, None, :], axis=-1)              # [n_b, J]
    dsim = a * (da - jnp.sum(a * da, axis=-1, keepdims=True))
    dq_ref[0] = (scale * jnp.sum(dsim[:, :, None] * k, axis=1)
                 ).astype(dq_ref.dtype)
    dk_blk = scale * dsim[:, :, None] * q[:, None, :]     # [n_b, J, D]
    dv_blk = a[:, :, None] * g[:, None, :]                # [n_b, J, D]

    @pl.when(bh % group == 0)
    def _():
        dk_ref[0] = dk_blk.astype(dk_ref.dtype)
        dv_ref[0] = dv_blk.astype(dv_ref.dtype)

    @pl.when(bh % group != 0)
    def _():
        dk_ref[0] = dk_ref[0] + dk_blk.astype(dk_ref.dtype)
        dv_ref[0] = dv_ref[0] + dv_blk.astype(dv_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, mask_ref, g_ref,
                dq_ref, dk_ref, dv_ref, *, group, scale):
    q, k, v, g = q_ref[0], k_ref[0], v_ref[0], g_ref[0]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale
    sim = jnp.where(mask_ref[0], sim, NEG_INF)
    _bwd_compute(q, k, v, g, sim, group, scale, dq_ref, dk_ref, dv_ref)


def _bwd_kernel_nomask(q_ref, k_ref, v_ref, g_ref,
                       dq_ref, dk_ref, dv_ref, *, group, scale):
    q, k, v, g = q_ref[0], k_ref[0], v_ref[0], g_ref[0]
    sim = jnp.sum(k * q[:, None, :], axis=-1) * scale
    _bwd_compute(q, k, v, g, sim, group, scale, dq_ref, dk_ref, dv_ref)


@functools.partial(jax.jit, static_argnames=('heads', 'scale', 'interpret'))
def _fused_attention_bwd_impl(q, k, v, mask, g, heads: int, scale: float,
                              interpret: bool = False):
    BH, n, D = q.shape
    BKV, _, J, _ = k.shape
    group = BH // BKV

    # the backward holds ~2x the forward's kv-sized blocks (dk/dv
    # outputs); kind 'attention_bwd' keys its own measured entries
    block_n = _pick_block_n(n, J, D, bwd=True,
                            dtype=jnp.dtype(q.dtype).name)
    np_ = _round_up(n, block_n)
    if np_ != n:
        pad = ((0, 0), (0, np_ - n), (0, 0))
        q, g = jnp.pad(q, pad), jnp.pad(g, pad)
        k = jnp.pad(k, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, np_ - n), (0, 0), (0, 0)))
        if mask is not None:
            # padded rows: g is zero there, so grads vanish; keep slots
            # valid so the recomputed softmax stays finite
            mask = jnp.pad(mask, ((0, 0), (0, np_ - n), (0, 0)),
                           constant_values=True)

    q_spec = pl.BlockSpec((1, block_n, D), lambda e, bh: (bh, e, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_n, J, D),
                           lambda e, bh: (bh // group, e, 0, 0),
                           memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_n, J), lambda e, bh: (bh // heads, e, 0),
                         memory_space=pltpu.VMEM))
        args.append(mask)
        kernel = functools.partial(_bwd_kernel, group=group, scale=scale)
    else:
        kernel = functools.partial(_bwd_kernel_nomask, group=group,
                                   scale=scale)
    args.append(g)
    in_specs.append(q_spec)

    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(np_ // block_n, BH),
        in_specs=in_specs,
        out_specs=[q_spec, kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, np_, D), jnp.float32),
            jax.ShapeDtypeStruct((BKV, np_, J, D), jnp.float32),
            jax.ShapeDtypeStruct((BKV, np_, J, D), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    # cotangent dtypes must match the primals (custom_vjp contract); the
    # kernel accumulates in f32 regardless
    return (dq[:, :n].astype(q.dtype), dk[:, :n].astype(k.dtype),
            dv[:, :n].astype(v.dtype))


# --------------------------------------------------------------------- #
# SPMD partitioning rules
# --------------------------------------------------------------------- #
# The kernel is embarrassingly parallel over the node axis n (sequence
# parallelism — the long-context axis) and over the flattened batch*head
# axis; the slot (j) and feature (d) axes reduce inside and must be
# replicated. Without a rule GSPMD treats the Mosaic call as opaque and
# replicates the sharded operands. The leading axes of q [B*h, ...] and
# k/v [B*kv_h, ...] are DIFFERENT factor sizes, so the callbacks must
# check that the shard count divides B*kv_h (and B for the mask): then a
# q shard's kv-group range [bh//group] lands exactly on the matching k/v
# shard. Otherwise the leading-axis sharding is dropped (replicated).
# The backward needs no cross-shard reductions — every cotangent keeps
# its primal's axes, and multi-query dk/dv accumulation over the head
# group stays inside a shard (shards contain whole groups by the
# divisibility condition).


from .pallas_pairwise import (
    _axis_tuple as _att_axis_tuple, _spec_axes as _att_spec_axes,
)


def _att_resolve(mesh, arg_shapes, has_mask):
    """(bh_axes, n_axes) consistent with kv-group alignment; None = keep
    replicated."""
    def nshards(axes):
        s = 1
        for ax in _att_axis_tuple(axes):
            s *= mesh.shape[ax]
        return s

    def first_axes(dim):
        # any operand may carry the sharding (e.g. only the bwd cotangent
        # is node-sharded when it propagates from downstream)
        for a in arg_shapes:
            ax = _att_spec_axes(a.sharding, dim)
            if ax is not None:
                return ax
        return None

    q_sh, k_sh = arg_shapes[0], arg_shapes[1]
    a = first_axes(0)
    nax = first_axes(1)
    if set(_att_axis_tuple(a)) & set(_att_axis_tuple(nax)):
        a = None  # one mesh axis can't shard both; the node axis wins
    if a is not None:
        s = nshards(a)
        BKV = k_sh.shape[0]
        B = arg_shapes[3].shape[0] if has_mask else None
        if BKV % s != 0 or (B is not None and B % s != 0):
            a = None
    if nax is not None:
        s = nshards(nax)
        if q_sh.shape[1] % s != 0:
            nax = None
    return a, nax


@functools.lru_cache(maxsize=None)
def _att_partitioned(heads, scale, interpret, has_mask, bwd):
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P_

    if bwd:
        def impl(q, k, v, *rest):
            mask = rest[0] if has_mask else None
            g = rest[-1]
            return _fused_attention_bwd_impl(q, k, v, mask, g, heads,
                                             scale, interpret)
    else:
        def impl(q, k, v, *rest):
            mask = rest[0] if has_mask else None
            return _fused_attention_fwd_impl(q, k, v, mask, heads, scale,
                                             interpret)

    @custom_partitioning
    def f(*args):
        return impl(*args)

    def specs(P_, a, nax):
        q_s = P_(a, nax, None)
        kv_s = P_(a, nax, None, None)
        arg = [q_s, kv_s, kv_s]
        if has_mask:
            arg.append(P_(a, nax, None))
        if bwd:
            arg.append(q_s)  # g
            res = (q_s, kv_s, kv_s)
        else:
            res = (q_s,)
        return tuple(arg), res

    def partition(mesh, arg_shapes, result_shape):
        a, nax = _att_resolve(mesh, arg_shapes, has_mask)
        arg_specs, res_specs = specs(P_, a, nax)
        arg_sh = tuple(NamedSharding(mesh, s) for s in arg_specs)
        res_sh = tuple(NamedSharding(mesh, s) for s in res_specs)
        return (mesh, impl, res_sh if bwd else res_sh[0], arg_sh)

    def infer(mesh, arg_shapes, shape):
        a, nax = _att_resolve(mesh, arg_shapes, has_mask)
        m = arg_shapes[0].sharding.mesh
        _, res_specs = specs(P_, a, nax)
        res = tuple(NamedSharding(m, s) for s in res_specs)
        return res if bwd else res[0]

    mask_term = ', c n j' if has_mask else ''
    if bwd:
        rule = (f'a n d, b n j d, b n j d{mask_term}, a n d '
                f'-> a n d, b n j d, b n j d')
    else:
        rule = f'a n d, b n j d, b n j d{mask_term} -> a n d'
    # special-factor indices must be sorted by first appearance in the
    # rule: d (q's last dim) precedes the slot axis j
    from .pallas_pairwise import _def_partition_compat
    _def_partition_compat(f, partition=partition,
                          infer_sharding_from_operands=infer,
                          sharding_rule=rule,
                          need_replication_factors=('d', 'j'))
    return f


# --------------------------------------------------------------------- #
# J-on-lanes layout: RETIRED (round-4 decision table)
# --------------------------------------------------------------------- #
# VERDICT r3 #6 asked for data or retirement on the attention kernel's
# layout. A J-on-lanes forward variant (k/v blocked [n_b, D, J], J
# padding 33->128 = 3.9x instead of D=8->128 = 16x) was measured against
# XLA and the D-on-lanes kernel at every flagship per-degree shape
# (J=33, n=1024, scripts/tpu_checks.py, TPU v5e, 22:54Z round 4):
#
#   D=8 : xla 4.39 ms   D-lanes 4.30 (1.02x)   J-lanes 4.05 (1.08x)
#   D=24: xla 3.97 ms   D-lanes 4.34 (0.91x)   J-lanes 3.70 (1.07x)
#   D=40: xla 4.85 ms   D-lanes 4.34 (1.12x)   J-lanes 4.52 (1.07x)
#   D=56: xla 4.40 ms   D-lanes 4.48 (0.98x)   J-lanes 4.79 (0.92x)
#
# Neither layout reaches the 1.2x bar anywhere; both sit in the noise
# band around XLA, and attention is <2% of the flagship step (the
# pairwise conv kernels dominate). Decision: XLA is the attention path;
# the D-on-lanes kernel above stays as the numerics-validated opt-in
# (pallas_attention=True) with fwd+bwd+SPMD rules; the forward-only
# J-on-lanes experiment is deleted (this note is its record; the code
# is one git checkout away).

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_attention(q, k, v, mask, heads: int, scale: float,
                    interpret: bool = False):
    """Fused multi-degree attention. q [B*h, n, D], k/v [B*kv_h, n, J, D],
    mask [B, n, J] bool or None -> [B*h, n, D] float32. Partitions over
    sharded node / batch-head axes (see the SPMD rules above)."""
    # scope the kernel dispatch so xprof traces attribute it by name
    # (observability.timing.MODEL_SCOPES)
    with jax.named_scope('pallas_attention'):
        f = _att_partitioned(heads, scale, interpret, mask is not None,
                             False)
        args = (q, k, v) + ((mask,) if mask is not None else ())
        return f(*args)


def _fa_fwd(q, k, v, mask, heads, scale, interpret):
    out = fused_attention(q, k, v, mask, heads, scale, interpret)
    return out, (q, k, v, mask)


def _fa_bwd(heads, scale, interpret, res, g):
    q, k, v, mask = res
    with jax.named_scope('pallas_attention_bwd'):
        f = _att_partitioned(heads, scale, interpret, mask is not None,
                             True)
        args = (q, k, v) + ((mask,) if mask is not None else ()) + (g,)
        dq, dk, dv = f(*args)
    return dq, dk, dv, None


fused_attention.defvjp(_fa_fwd, _fa_bwd)
