"""Fiber: the type signature of an SE(3)-equivariant feature space.

A fiber is an ordered set of (degree, multiplicity) pairs describing a
feature dict {str(degree): [..., multiplicity, 2*degree+1]}. TPU-native
rework of the reference's nn.Module-based Fiber
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:18-59):
here it is a frozen, hashable dataclass, so it can be a static argument to
jit/flax modules, and feature dicts are plain JAX pytrees.
"""
from __future__ import annotations

import dataclasses
from itertools import product
from typing import Dict, Mapping, Sequence, Tuple, Union

FiberEl = Tuple[int, int]  # (degree, dim)


@dataclasses.dataclass(frozen=True)
class Fiber:
    structure: Tuple[FiberEl, ...]

    def __init__(self, structure: Union[Mapping[int, int], Sequence]):
        if isinstance(structure, Mapping):
            structure = [(int(d), int(m)) for d, m in structure.items()]
        structure = tuple((int(d), int(m)) for d, m in structure)
        object.__setattr__(self, 'structure', structure)

    @property
    def dims(self):
        return list({m: None for _, m in self.structure}.keys())

    @property
    def degrees(self):
        return [d for d, _ in self.structure]

    @staticmethod
    def create(num_degrees: int, dim: Union[int, Tuple[int, ...]]) -> 'Fiber':
        dims = dim if isinstance(dim, tuple) else (dim,) * num_degrees
        return Fiber(list(zip(range(num_degrees), dims)))

    def __getitem__(self, degree: int) -> int:
        return dict(self.structure)[degree]

    def __contains__(self, degree: int) -> bool:
        return degree in dict(self.structure)

    def __iter__(self):
        return iter(self.structure)

    def __mul__(self, other: 'Fiber'):
        """All (in, out) element pairs."""
        return product(self.structure, other.structure)

    def __and__(self, other: 'Fiber'):
        """Degrees present in both: [(degree, dim_self, dim_other), ...]."""
        out = []
        for degree, dim in self:
            if degree in other:
                out.append((degree, dim, other[degree]))
        return out

    def scale(self, mult: int) -> 'Fiber':
        return Fiber([(d, m * mult) for d, m in self.structure])

    def to(self, dim: int) -> 'Fiber':
        """Same degrees, constant multiplicity `dim`."""
        return Fiber([(d, dim) for d, _ in self.structure])


def fiber_of(features: Dict[str, 'jax.Array']) -> Fiber:  # noqa: F821
    """Infer the Fiber of a feature dict."""
    return Fiber({int(k): v.shape[-2] for k, v in features.items()})
