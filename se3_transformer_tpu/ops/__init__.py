from .fiber import Fiber, FiberEl, fiber_of
from .core import (
    LinearSE3, NormSE3, FeedForwardSE3, FeedForwardBlockSE3, residual_se3,
)
from .conv import ConvSE3, RadialFunc, pairwise_conv_contract
from .attention import AttentionSE3, OneHeadedKVAttentionSE3, AttentionBlockSE3
from .egnn import EGNN, EGnnNetwork, HtypesNorm
from .neighbors import (
    exclude_self_indices, remove_self, expand_adjacency,
    sparse_neighbor_mask, select_neighbors, Neighborhood,
)
from .rotary import sinusoidal_embeddings, apply_rotary_pos_emb
from .trunk import SequentialTrunk
