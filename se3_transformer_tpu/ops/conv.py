"""Tensor-field-network convolution (the compute hot spot).

TPU-native rework of reference ConvSE3 / RadialFunc / PairwiseConv
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:154-343).

Key departure from the reference: the reference materializes, per edge, the
full unary kernel matrix [(2*do+1)*c_out, (2*di+1)*c_in] (PairwiseConv,
:326-343) and then multiplies it with the gathered features, chunking the
node axis into `splits` pieces to survive the peak memory (:222-254). Here
the radial profile R, the angular basis B and the neighbor features x are
contracted in a fused einsum chain

    W[o, m_J..] = sum_i R[o, i, f] x[i, m_in]        (channel contraction)
    y[o, m_out] = sum_{m_in, f} W[o, m_in, f] B[m_out, m_in, f]

so the [oP x iQ] kernel never exists in HBM; XLA tiles the big channel
contraction onto the MXU and fuses the small (2l+1)-sized contractions into
it. No `splits` knob is needed — rematerialization (jax.checkpoint at the
trunk level) plus XLA fusion replace eager chunking.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..utils.helpers import (
    batched_index_select, fourier_encode, masked_mean, to_order,
)
from .core import LinearSE3, residual_se3
from .fiber import Fiber

Features = Dict[str, jnp.ndarray]
# edge_info = (neighbor_indices [b,n,k], neighbor_mask [b,n,k] | None,
#              edges [b,n,k,e] | None)
EdgeInfo = Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]


class RadialFunc(nn.Module):
    """Per-edge radial profile MLP (reference :270-299).

    edge scalar features [..., edge_dim+1] -> R [..., c_out, c_in, num_freq].
    This is the dominant matmul of the model: [b*n*k, mid] @ [mid, o*i*f].
    """
    num_freq: int
    in_dim: int
    out_dim: int
    edge_dim: int = 0
    mid_dim: int = 128

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Dense(self.mid_dim)(x)
        x = nn.LayerNorm()(x)
        x = nn.gelu(x)
        x = nn.Dense(self.mid_dim)(x)
        x = nn.LayerNorm()(x)
        x = nn.gelu(x)
        x = nn.Dense(self.num_freq * self.in_dim * self.out_dim)(x)
        return x.reshape(*x.shape[:-1], self.out_dim, self.in_dim,
                         self.num_freq)


def pairwise_conv_contract(R: jnp.ndarray, B: jnp.ndarray,
                           x: jnp.ndarray) -> jnp.ndarray:
    """Fused (radial x basis x features) contraction for one degree pair.

    R: [b, n, k, c_out, c_in, f]   radial profiles
    B: [b, n, k, 2*do+1, 2*di+1, f] angular basis
    x: [b, n, k, c_in, 2*di+1]     gathered neighbor features
    -> [b, n, k, c_out, 2*do+1]

    Replaces reference PairwiseConv's explicit frequency loop + kernel
    materialization (:336-343) and the kernel @ features einsum (:251).
    """
    # channel contraction first (big, MXU-friendly), small angular axes last
    W = jnp.einsum('...oif,...iq->...oqf', R, x)
    return jnp.einsum('...oqf,...pqf->...op', W, B)


class ConvSE3(nn.Module):
    """Graph TFN convolution over precomputed neighborhoods
    (reference :154-268)."""
    fiber_in: Fiber
    fiber_out: Fiber
    self_interaction: bool = True
    pool: bool = True
    edge_dim: int = 0
    fourier_encode_dist: bool = False
    num_fourier_features: int = 4

    @nn.compact
    def __call__(self, inp: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, basis: Dict[str, jnp.ndarray]
                 ) -> Features:
        neighbor_indices, neighbor_masks, edges = edge_info

        rel_dist_feats = rel_dist[..., None]  # [b, n, k, 1]
        if self.fourier_encode_dist:
            rel_dist_feats = fourier_encode(
                rel_dist_feats, num_encodings=self.num_fourier_features)

        edge_features = rel_dist_feats
        if edges is not None:
            edge_features = jnp.concatenate((rel_dist_feats, edges), axis=-1)

        # gather neighbor features once per input degree
        gathered = {}
        for degree_in, _ in self.fiber_in:
            key = str(degree_in)
            gathered[key] = batched_index_select(
                inp[key], neighbor_indices, axis=1)  # [b, n, k, c_in, 2di+1]

        outputs = {}
        for degree_out, m_out in self.fiber_out:
            acc = None
            for degree_in, m_in in self.fiber_in:
                num_freq = to_order(min(degree_in, degree_out))
                R = RadialFunc(
                    num_freq, m_in, m_out,
                    edge_dim=edge_features.shape[-1] - 1,
                    name=f'radial_{degree_in}_{degree_out}')(edge_features)
                B = basis[f'{degree_in},{degree_out}']
                y = pairwise_conv_contract(R, B, gathered[str(degree_in)])
                acc = y if acc is None else acc + y

            if self.pool:
                acc = masked_mean(acc, neighbor_masks, axis=2) \
                    if neighbor_masks is not None else acc.mean(axis=2)
            outputs[str(degree_out)] = acc

        if self.self_interaction:
            assert self.pool, 'must pool edges if followed with self interaction'
            self_out = LinearSE3(self.fiber_in, self.fiber_out,
                                 name='self_interact')(inp)
            outputs = residual_se3(outputs, self_out)

        return outputs
