"""Tensor-field-network convolution (the compute hot spot).

TPU-native rework of reference ConvSE3 / RadialFunc / PairwiseConv
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:154-343).

Key departure from the reference: the reference materializes, per edge, the
full unary kernel matrix [(2*do+1)*c_out, (2*di+1)*c_in] (PairwiseConv,
:326-343) and then multiplies it with the gathered features, chunking the
node axis into `splits` pieces to survive the peak memory (:222-254). Here
the angular basis is contracted with the neighbor features FIRST (cheap,
small axes), and the radial profile is applied as one big channel
contraction:

    V2[P, (i,f)]  = sum_Q B[P, Q, f] x[i, Q]          # VPU-sized
    out[P, o]     = sum_{(i,f)} V2[P, (i,f)] R[(i,f), o]   # MXU

so the [oP x iQ] kernel never exists, and on TPU the radial tensor R
itself never leaves VMEM either: kernels.pallas_pairwise fuses the final
radial matmul with the contraction (the XLA fallback materializes R, which
is what the einsum path costs anyway). No `splits` knob is needed —
rematerialization (jax.checkpoint at the trunk level) plus fusion replace
eager chunking.
"""
from __future__ import annotations

import re
from functools import partial
from typing import Callable, Dict, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.exchange import exchange_index_select
from ..quant.qtensor import QuantTensor, concat_weights
from ..utils.helpers import fourier_encode, masked_mean, to_order
from .core import LinearSE3, residual_se3
from .fiber import Fiber

Features = Dict[str, jnp.ndarray]
# edge_info = (neighbor_indices [b,n,k], neighbor_mask [b,n,k] | None,
#              edges [b,n,k,e] | None)
EdgeInfo = Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]

# radial-MLP hidden width (reference RadialFunc mid_dim, :283)
DEFAULT_MID_DIM = 128

# --------------------------------------------------------------------- #
# contraction backend registry
#
# 'dense' is the in-file Clebsch-Gordan tensor-product path (basis
# tensors from basis.get_basis, optionally fused into the Pallas
# kernels). Alternative backends register a pairwise contract callable
#     impl(h, w3, b3, payload, x, *, d_in, d_out, pallas,
#          pallas_interpret, edge_chunks, conv_bf16) -> [..., c_out, P]
# sharing the dense path's parameter layout (w3 [mid, c_in*F, c_out],
# b3 [c_in*F, c_out]) so backends can be swapped per layer with
# identical checkpoints. `payload` is whatever the backend's model-side
# builder put under its reserved key in the basis dict (the so2 backend
# stores its edge-frame harmonics under basis['so2'] —
# so2/contract.py). Built-ins resolve lazily to avoid import cycles.
# --------------------------------------------------------------------- #
CONV_BACKENDS: Dict[str, Optional[Callable]] = {'dense': None}
_LAZY_BACKENDS = {'so2': 'se3_transformer_tpu.so2.contract'}

# spec: one backend name for every layer, or first-match-wins
# (layer-name regex, backend) pairs — the parallel/rules.py idiom
BackendSpec = Union[str, Tuple[Tuple[str, str], ...]]


def register_conv_backend(name: str, impl: Callable) -> None:
    """Register a pairwise-contraction backend (see the signature
    contract above). Re-registration overwrites — latest wins."""
    CONV_BACKENDS[name] = impl


def get_conv_backend(name: str) -> Callable:
    """The registered contract callable for `name` ('dense' has no
    callable — its path is inline in PairwiseConvSE3/ConvSE3)."""
    if name not in CONV_BACKENDS and name in _LAZY_BACKENDS:
        import importlib
        importlib.import_module(_LAZY_BACKENDS[name])  # self-registers
    if name not in CONV_BACKENDS:
        raise KeyError(
            f'unknown conv backend {name!r} (registered: '
            f'{sorted(set(CONV_BACKENDS) | set(_LAZY_BACKENDS))})')
    return CONV_BACKENDS[name]


def resolve_conv_backend(spec: BackendSpec, layer_name: str) -> str:
    """Per-layer backend resolution: a plain string applies everywhere;
    a tuple of (pattern, backend) pairs is matched FIRST-MATCH-WINS
    against the layer name ('conv_in', 'preconv0', 'attn_block1/to_v',
    'conv_out', ...) with an implicit ('.*', 'dense') tail."""
    if isinstance(spec, str):
        return spec
    for pat, backend in spec:
        if re.search(pat, layer_name):
            return backend
    return 'dense'


class RadialFunc(nn.Module):
    """Per-edge radial profile MLP (reference :270-299).

    edge scalar features [..., edge_dim+1] -> R [..., c_out, c_in, num_freq].
    This is the unfused formulation: PairwiseConvSE3 uses it when
    `fused=False` (reference-ordered contraction, numerics oracle for the
    fused path — see tests/test_ops.py) and holds the equivalent
    parameters in fused [mid, c_in*F, c_out] layout otherwise.
    """
    num_freq: int
    in_dim: int
    out_dim: int
    edge_dim: int = 0
    mid_dim: int = DEFAULT_MID_DIM

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = radial_hidden(x, self.mid_dim)
        # explicit name: radial_hidden's trunk layers are explicitly
        # named Dense_0/Dense_1 (quant-aware clones), so the auto
        # counter would restart and collide without it — Dense_2 is the
        # path this layer has always had
        x = nn.Dense(self.num_freq * self.in_dim * self.out_dim,
                     name='Dense_2')(x)
        return x.reshape(*x.shape[:-1], self.out_dim, self.in_dim,
                         self.num_freq)


class _QuantDense(nn.Module):
    """nn.Dense with a quant-aware kernel, parameter-compatible with the
    flax original (same param names/shapes/initializers and the same
    params-rng derivation, so checkpoints and seeded inits are
    bit-identical) — needed because the radial trunk's kernels are
    int8 targets under the serving precision mixes (quant.rules) and
    nn.Dense cannot consume a QuantTensor. The fp32/bf16 paths replay
    nn.Dense's exact promote_dtype + dot_general sequence."""
    features: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param('kernel', nn.initializers.lecun_normal(),
                            (jnp.shape(x)[-1], self.features),
                            jnp.float32)
        bias = self.param('bias', nn.initializers.zeros,
                          (self.features,), jnp.float32)
        if isinstance(kernel, QuantTensor):
            # fused dequant-matmul: the int8 kernel contracts, the
            # per-output-channel scale folds into the product — the
            # fp32 kernel never exists outside this fusion. Invariant
            # inputs, so this is the int8-safe class (quant.rules).
            y = jax.lax.dot_general(
                x, jnp.asarray(kernel.q).astype(x.dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return y * kernel.scale[0] + bias
        from flax.linen.dtypes import promote_dtype
        x, kernel, bias = promote_dtype(x, kernel, bias,
                                        dtype=self.dtype)
        y = jax.lax.dot_general(x, kernel,
                                (((x.ndim - 1,), (0,)), ((), ())))
        return y + jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))


def radial_hidden(x: jnp.ndarray, mid_dim: int,
                  dtype=None) -> jnp.ndarray:
    """Shared 2-layer radial trunk: Dense -> LN -> GELU, twice.

    `dtype=bfloat16` runs the trunk's compute in bf16 (params stay f32).
    The trunk's inputs are rotation-INVARIANT scalars (distances, edge
    features), so its quantization noise is (nearly) identical between a
    rotated and an unrotated forward and cancels in the equivariance
    error — this is the principled TPU mixed-precision cut, unlike a
    global bf16 matmul policy which quantizes the equivariant
    contractions themselves (~1e-3 equivariance error on chip). The
    same invariance argument admits int8 kernels under the serving
    precision mixes (quant.rules), which is why the Dense layers are
    the quant-aware clone (explicit names keep the nn.Dense param
    paths, so checkpoints predate the swap unchanged)."""
    x = _QuantDense(mid_dim, dtype=dtype, name='Dense_0')(x)
    x = nn.LayerNorm(dtype=dtype, name='LayerNorm_0')(x)
    x = nn.gelu(x)
    x = _QuantDense(mid_dim, dtype=dtype, name='Dense_1')(x)
    x = nn.LayerNorm(dtype=dtype, name='LayerNorm_1')(x)
    x = nn.gelu(x)
    return x


class _DenseParams(nn.Module):
    """Parameter source for one radial-trunk Dense layer: declares the
    kernel/bias with names, shapes, and initializers IDENTICAL to
    `_QuantDense` without running the matmul. The global (kNN-free)
    attention mode uses this to export the raw radial weights to the
    streaming kernel — there is no per-edge input to run the layer on —
    while a `fuse_pairwise` checkpoint keeps loading unchanged."""
    in_dim: int
    features: int

    @nn.compact
    def __call__(self):
        kernel = self.param('kernel', nn.initializers.lecun_normal(),
                            (self.in_dim, self.features), jnp.float32)
        bias = self.param('bias', nn.initializers.zeros,
                          (self.features,), jnp.float32)
        if isinstance(kernel, QuantTensor):
            kernel = kernel.dequant()
        return kernel, bias


class _LayerNormParams(nn.Module):
    """Parameter source mirroring `nn.LayerNorm` (scale ones, bias
    zeros) — see `_DenseParams`."""
    features: int

    @nn.compact
    def __call__(self):
        scale = self.param('scale', nn.initializers.ones,
                           (self.features,), jnp.float32)
        bias = self.param('bias', nn.initializers.zeros,
                          (self.features,), jnp.float32)
        return scale, bias


def _use_pallas(pallas: Optional[bool], interpret: bool) -> bool:
    """The one dispatch rule for the fused pairwise kernels: explicit
    setting wins, else auto on TPU (by device kind, not platform name —
    the chip can register as e.g. 'axon'); interpreter mode forces the
    kernel."""
    if pallas is None:
        from ..utils.helpers import is_tpu_backend
        pallas = is_tpu_backend()
    return pallas or interpret


def _stream_node_chunks(contract, operands, edge_chunks: int):
    """Run contract(*operands) streaming the node axis (axis 1) in
    remat'd chunks via lax.map (the memory ceiling for huge channel
    counts; peak extra memory is one chunk's working set).

    When n is not divisible by edge_chunks the node axis is zero-PADDED
    up to the next multiple and the pad rows sliced off the result, so
    the requested memory ceiling holds at ANY n — including primes
    (VERDICT r3 weak #4: the old largest-divisor fallback silently
    disabled streaming for e.g. n=1021, forfeiting ~8 GB of headroom the
    flagship recipe relies on). Safe because every operand is a pure
    per-node tensor (no cross-node terms in the contraction), and exact
    under grad: the pad/slice transpose zeroes the pad rows' cotangents,
    so weight gradients accumulated over the padded chunk rows get only
    zero contributions."""
    n = operands[0].shape[1]
    c = min(edge_chunks, n)
    n_pad = -(-n // c) * c  # ceil to a multiple of c

    def split(a):
        if n_pad != n:
            pad = [(0, 0)] * a.ndim
            pad[1] = (0, n_pad - n)
            a = jnp.pad(a, pad)
        a = a.reshape(a.shape[0], c, n_pad // c, *a.shape[2:])
        return jnp.swapaxes(a, 0, 1)

    out = jax.lax.map(jax.checkpoint(lambda t: contract(*t)),
                      tuple(split(a) for a in operands))
    out = jnp.swapaxes(out, 0, 1)
    out = out.reshape(out.shape[0], n_pad, *out.shape[3:])
    return out[:, :n] if n_pad != n else out


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _pairwise_contract_pallas(h, w3, b3, v2, interpret=False,
                              precision=None):
    from ..kernels.pallas_pairwise import fused_pairwise_conv
    return fused_pairwise_conv(h, w3, v2, b3=b3, interpret=interpret,
                               precision=precision)


def _pc_fwd(h, w3, b3, v2, interpret=False, precision=None):
    return (_pairwise_contract_pallas(h, w3, b3, v2, interpret, precision),
            (h, w3, b3, v2))


def _pc_bwd(interpret, precision, res, g):
    # fused backward kernel: dR/R exist only as VMEM chunks (see
    # kernels.pallas_pairwise.fused_pairwise_conv_bwd)
    from ..kernels.pallas_pairwise import fused_pairwise_conv_bwd
    h, w3, b3, v2 = res
    dh, dw3, dv2, db3 = fused_pairwise_conv_bwd(h, w3, v2, g, b3=b3,
                                                interpret=interpret,
                                                precision=precision)
    return (dh.astype(h.dtype), dw3.astype(w3.dtype), db3.astype(b3.dtype),
            dv2.astype(v2.dtype))


_pairwise_contract_pallas.defvjp(_pc_fwd, _pc_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _pairwise_contract_pallas_bx(h, w3, b3, basis, x, interpret=False,
                                 precision=None):
    from ..kernels.pallas_pairwise import fused_pairwise_conv_bx
    return fused_pairwise_conv_bx(h, w3, basis, x, b3=b3,
                                  interpret=interpret,
                                  precision=precision)


def _pc_bx_fwd(h, w3, b3, basis, x, interpret=False, precision=None):
    return (_pairwise_contract_pallas_bx(h, w3, b3, basis, x, interpret,
                                         precision),
            (h, w3, b3, basis, x))


def _pc_bx_bwd(interpret, precision, res, g):
    # V2 materializes only here, in the backward; the forward never wrote
    # it to HBM. Reuses the fused backward kernel, then folds its dV2
    # cotangent back through the basis contraction (dbasis feeds
    # coordinate gradients when differentiable_coors is on).
    from ..kernels.pallas_pairwise import fused_pairwise_conv_bwd
    h, w3, b3, basis, x = res
    E, P, Q, F = basis.shape
    C = x.shape[1]
    # conv_bf16 residuals arrive bf16 (that's the remat/HBM saving);
    # gradient math runs f32 on the exactly-upcast quantized values
    b32, x32 = basis.astype(jnp.float32), x.astype(jnp.float32)
    v2 = jnp.einsum('epqf,ecq->epcf', b32, x32,
                    precision=precision).reshape(E, P, C * F)
    dh, dw3, dv2, db3 = fused_pairwise_conv_bwd(h, w3, v2, g, b3=b3,
                                                interpret=interpret,
                                                precision=precision)
    dv2 = dv2.reshape(E, P, C, F)
    dx = jnp.einsum('epqf,epcf->ecq', b32, dv2, precision=precision)
    dbasis = jnp.einsum('ecq,epcf->epqf', x32, dv2, precision=precision)
    return (dh.astype(h.dtype), dw3.astype(w3.dtype), db3.astype(b3.dtype),
            dbasis.astype(basis.dtype), dx.astype(x.dtype))


_pairwise_contract_pallas_bx.defvjp(_pc_bx_fwd, _pc_bx_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _pairwise_contract_pallas_bxf(h, w3, b3, basis_flat, x, pqf,
                                  interpret=False, precision=None):
    from ..kernels.pallas_pairwise import fused_pairwise_conv_bxf
    return fused_pairwise_conv_bxf(h, w3, basis_flat, x, pqf, b3=b3,
                                   interpret=interpret, precision=precision)


def _pc_bxf_fwd(h, w3, b3, basis_flat, x, pqf, interpret=False,
                precision=None):
    return (_pairwise_contract_pallas_bxf(h, w3, b3, basis_flat, x, pqf,
                                          interpret, precision),
            (h, w3, b3, basis_flat, x))


def _pc_bxf_bwd(pqf, interpret, precision, res, g):
    # flat twin of _pc_bx_bwd: the (p, f, q)-ordered flat basis reshapes
    # straight to [E, P, F, Q] — no transpose — and every einsum reads
    # that form, so the ~60x tile-padded [E, P, Q, F] buffer never
    # materializes in the backward either.
    from ..kernels.pallas_pairwise import fused_pairwise_conv_bwd
    h, w3, b3, basis_flat, x = res
    P, Q, F = pqf
    E = basis_flat.shape[0]
    C = x.shape[1]
    # conv_bf16 residuals arrive bf16 (see _pc_bx_bwd)
    b4 = basis_flat.astype(jnp.float32).reshape(E, P, F, Q)
    x32 = x.astype(jnp.float32)
    v2 = jnp.einsum('epfq,ecq->epcf', b4, x32,
                    precision=precision).reshape(E, P, C * F)
    dh, dw3, dv2, db3 = fused_pairwise_conv_bwd(h, w3, v2, g, b3=b3,
                                                interpret=interpret,
                                                precision=precision)
    dv2 = dv2.reshape(E, P, C, F)
    dx = jnp.einsum('epfq,epcf->ecq', b4, dv2, precision=precision)
    dbasis = jnp.einsum('ecq,epcf->epfq', x32, dv2,
                        precision=precision).reshape(E, P * F * Q)
    return (dh.astype(h.dtype), dw3.astype(w3.dtype), db3.astype(b3.dtype),
            dbasis.astype(basis_flat.dtype), dx.astype(x.dtype))


_pairwise_contract_pallas_bxf.defvjp(_pc_bxf_fwd, _pc_bxf_bwd)


def unflatten_basis(basis_flat: jnp.ndarray, P: int, Q: int,
                    F: int) -> jnp.ndarray:
    """[..., P*F*Q] (p, f, q)-ordered flat basis -> [..., P, Q, F]
    structured form (for the non-kernel paths that consume the
    reference-shaped layout)."""
    b = basis_flat.reshape(*basis_flat.shape[:-1], P, F, Q)
    return jnp.swapaxes(b, -1, -2)


def _basis_is_flat(basis: jnp.ndarray, x: jnp.ndarray) -> bool:
    """get_basis(layout='pfq_flat') entries are [..., P*F*Q] — one fewer
    axis than the neighbor features x [..., C, Q]; the structured form
    has one more."""
    return basis.ndim == x.ndim - 1


class PairwiseConvSE3(nn.Module):
    """Single (d_in -> d_out) pairwise kernel + contraction
    (reference PairwiseConv :301-343, fused).

    `pallas=None` auto-selects the TPU kernel; the parameter tree is
    identical for both paths, so checkpoints are portable and the Pallas
    path is numerics-gated against the XLA path in tests.
    """
    degree_in: int
    nc_in: int
    degree_out: int
    nc_out: int
    mid_dim: int = DEFAULT_MID_DIM
    pallas: Optional[bool] = None
    pallas_interpret: bool = False
    # stream the node axis in this many chunks through the contraction
    # (lax.map + remat): bounds peak memory to O(E/edge_chunks * c_in *
    # c_out * F) for huge configs (e.g. dim-512 flagship). None = off.
    edge_chunks: Optional[int] = None
    # contract the angular basis inside the Pallas kernel so the V2
    # intermediate never touches HBM (forward only; the backward
    # materializes it once). Requires the Pallas path; ignored otherwise.
    fuse_basis: bool = False
    # run the radial trunk + radial matmul in bf16 (MXU-native): its
    # inputs are rotation-invariant, so this preserves equivariance to
    # ~1e-6 unlike a global bf16 policy (see radial_hidden docstring)
    radial_bf16: bool = False
    # store the EQUIVARIANT kernel operands (V2 / basis / gathered
    # features) bf16: halves the dominant HBM streams of the
    # bandwidth-bound contraction, at ~1e-3 equivariance cost (the
    # quantized tensors rotate). Opt-in perf knob; see _radial_contract.
    conv_bf16: bool = False
    # False = reference-ordered unfused path through RadialFunc (per-edge
    # [c_out, c_in, F] kernel tensors, reference :326-343); the numerics
    # oracle for the fused paths above. Param layout differs.
    fused: bool = True
    # contraction backend (CONV_BACKENDS): 'dense' = the CG tensor
    # product below; 'so2' = the banded SO(2) reduction (so2/contract).
    # Non-dense backends share the fused parameter layout (w3/b3), so
    # the SAME checkpoint serves either backend.
    backend: str = 'dense'
    # so2 only, set by ConvSE3: the caller already rotated x into the
    # edge frame (shared across this layer's degree pairs) and will
    # rotate the accumulated per-degree output back itself — this
    # module then computes only the banded + radial middle. Rotations
    # are parameter-free, so the param tree is identical either way.
    so2_edge_frame_io: bool = False

    @nn.compact
    def __call__(self, edge_feats: jnp.ndarray, basis_slice: jnp.ndarray,
                 x: jnp.ndarray) -> jnp.ndarray:
        """edge_feats [b,n,k,e]; basis_slice [b,n,k,P,Q,F] (dense) or the
        backend's payload (e.g. the so2 edge-frame dict); x
        [b,n,k,c_in,Q] -> [b,n,k,c_out,P]. (With a shared radial trunk,
        ConvSE3 fuses all pairs of an output degree itself and never
        calls this module.)"""
        F = to_order(min(self.degree_in, self.degree_out))
        P = to_order(self.degree_out)
        Q = to_order(self.degree_in)
        IF = self.nc_in * F

        if self.backend != 'dense':
            impl = get_conv_backend(self.backend)
            assert self.fused, \
                f'backend {self.backend!r} requires the fused ' \
                f'parameterization (fused=False is the dense-path oracle)'
            h = radial_hidden(
                edge_feats, self.mid_dim,
                dtype=jnp.bfloat16 if self.radial_bf16 else None)
            w3 = self.param(
                'w3',
                nn.initializers.variance_scaling(
                    1.0, 'fan_in', 'truncated_normal',
                    in_axis=0, out_axis=(1, 2)),
                (h.shape[-1], IF, self.nc_out), jnp.float32)
            b3 = self.param('b3', nn.initializers.zeros,
                            (IF, self.nc_out), jnp.float32)
            extra = dict(edge_frame_io=True) if self.so2_edge_frame_io \
                else {}
            return impl(h, w3, b3, basis_slice, x,
                        d_in=self.degree_in, d_out=self.degree_out,
                        pallas=self.pallas,
                        pallas_interpret=self.pallas_interpret,
                        edge_chunks=self.edge_chunks,
                        conv_bf16=self.conv_bf16, **extra)

        use_bx = self.fuse_basis and _use_pallas(self.pallas,
                                                 self.pallas_interpret)
        if _basis_is_flat(basis_slice, x) and not use_bx:
            # a flat-layout basis reached a path that consumes the
            # structured reference shape (e.g. fuse_basis on a CPU run
            # without interpret mode)
            basis_slice = unflatten_basis(basis_slice, P, Q, F)

        if not self.fused:
            R = RadialFunc(num_freq=F, in_dim=self.nc_in,
                           out_dim=self.nc_out, mid_dim=self.mid_dim,
                           name='radial')(edge_feats)
            return pairwise_conv_contract(R, basis_slice, x)

        h = radial_hidden(
            edge_feats, self.mid_dim,
            dtype=jnp.bfloat16 if self.radial_bf16 else None)  # [b,n,k,mid]

        w3 = self.param(
            'w3',
            nn.initializers.variance_scaling(1.0, 'fan_in', 'truncated_normal',
                                             in_axis=0, out_axis=(1, 2)),
            (h.shape[-1], IF, self.nc_out), jnp.float32)
        b3 = self.param('b3', nn.initializers.zeros, (IF, self.nc_out),
                        jnp.float32)

        if use_bx:
            out = _radial_contract_bx(
                h, w3, b3, basis_slice, x,
                pallas_interpret=self.pallas_interpret,
                edge_chunks=self.edge_chunks, pqf=(P, Q, F),
                conv_bf16=self.conv_bf16)
            return jnp.swapaxes(out, -1, -2)  # [..., c_out, P]

        # V2[..., P, (i, f)] = sum_Q B[..., P, Q, f] x[..., i, Q]
        v2 = jnp.einsum('...pqf,...cq->...pcf', basis_slice, x)
        v2 = v2.reshape(*v2.shape[:-2], IF)  # [..., P, c_in*F]

        out = _radial_contract(h, w3, b3, v2, pallas=self.pallas,
                               pallas_interpret=self.pallas_interpret,
                               edge_chunks=self.edge_chunks,
                               conv_bf16=self.conv_bf16)
        return jnp.swapaxes(out, -1, -2)  # [..., c_out, P]


def _radial_contract(h: jnp.ndarray, w3: jnp.ndarray, b3: jnp.ndarray,
                     v2: jnp.ndarray, *, pallas: Optional[bool],
                     pallas_interpret: bool,
                     edge_chunks: Optional[int],
                     conv_bf16: bool = False) -> jnp.ndarray:
    """Dispatch the fused radial-matmul x basis contraction:
    h [b,n,k,mid], w3 [mid,IF,O], b3 [IF,O], v2 [b,n,k,P,IF]
    -> [b,n,k,P,O] via the Pallas kernel / XLA einsums, optionally
    streaming the node axis in `edge_chunks` remat'd chunks (memory
    ceiling for huge channel counts: peak extra memory is one chunk's
    R — XLA path — or just the kernel's VMEM tiles — Pallas path).

    conv_bf16 stores the V2 operand bf16 — HALF the dominant HBM stream
    (the program is bandwidth-bound, scripts/flop_audit.py) — while the
    apply math stays f32 on the quantized values. Unlike radial_bf16
    (invariant inputs, ~1e-6 equivariance cost) this quantizes an
    EQUIVARIANT tensor: expect ~1e-3-level equivariance error, the same
    class as a global bf16 matmul policy. Opt-in accordingly."""
    P, IF = v2.shape[-2], v2.shape[-1]
    O = w3.shape[-1]
    if conv_bf16:
        # cast BEFORE the chunk-streaming split so the streamed HBM
        # operand (and the remat residual) is already half-width
        v2 = v2.astype(jnp.bfloat16)

    if _use_pallas(pallas, pallas_interpret):
        # The bias rides as its own [S, 1] kernel operand — folding it
        # (ones column on h, bias row on w3) made the contraction dim
        # mid+1 = 129 and cost a structural ~2x on the dominant MXU dot
        # (kernels.pallas_pairwise docstring). Capture the active
        # matmul-precision policy at trace time: the custom_vjp backward
        # traces outside the model's default_matmul_precision context,
        # so it must be threaded in.
        prec = jax.config.jax_default_matmul_precision
        if isinstance(w3, QuantTensor):
            # quantized radial weights (serving precision mixes): the
            # int8/fp8 STORAGE rides into the kernel as-is and dequant
            # happens inside the tile via the scale-column epilogue —
            # the fp32 w3 never exists in HBM. No custom_vjp: the
            # quantized tree is an inference artifact, gradients
            # through it are a configuration error and fail loudly.
            from ..kernels.pallas_pairwise import fused_pairwise_conv
            w3_q, w3_scale = w3.q, w3.scale

            def contract(h_c, v2_c):
                lead_c = h_c.shape[:-1]
                E = 1
                for s in lead_c:
                    E *= s
                out = fused_pairwise_conv(
                    h_c.reshape(E, h_c.shape[-1]), w3_q,
                    v2_c.reshape(E, P, IF), b3=b3, w3_scale=w3_scale,
                    interpret=pallas_interpret, precision=prec)
                return out.reshape(*lead_c, P, O)
        else:
            w3c = w3.astype(h.dtype)

            def contract(h_c, v2_c):
                lead_c = h_c.shape[:-1]
                E = 1
                for s in lead_c:
                    E *= s
                h2 = h_c.reshape(E, h_c.shape[-1])
                out = _pairwise_contract_pallas(h2, w3c, b3,
                                                v2_c.reshape(E, P, IF),
                                                pallas_interpret, prec)
                return out.reshape(*lead_c, P, O)
    else:
        def contract(h_c, v2_c):
            # bias stays f32 (the Pallas path adds it to the f32
            # accumulator), so both dispatch paths compute identical
            # values even under radial_bf16
            if isinstance(w3, QuantTensor):
                # jit-level fused dequant-matmul (the XLA fallback the
                # ISSUE names): contract the storage form, fold the
                # per-output-channel scale in as the epilogue — the
                # fp32 weight exists at most as a fused temp inside
                # the dot, never as an argument buffer
                R = jnp.einsum('...m,mko->...ko', h_c,
                               jnp.asarray(w3.q).astype(h_c.dtype),
                               preferred_element_type=jnp.float32) \
                    * w3.scale[0] + b3
            else:
                R = jnp.einsum('...m,mko->...ko', h_c,
                               w3.astype(h_c.dtype),
                               preferred_element_type=jnp.float32) + b3
            return jnp.einsum('...pk,...ko->...po', v2_c, R)

    if edge_chunks is None:
        return contract(h, v2)
    return _stream_node_chunks(contract, (h, v2), edge_chunks)


def _radial_contract_bx(h: jnp.ndarray, w3: jnp.ndarray, b3: jnp.ndarray,
                        basis: jnp.ndarray, x: jnp.ndarray, *,
                        pallas_interpret: bool,
                        edge_chunks: Optional[int],
                        pqf: Optional[Tuple[int, int, int]] = None,
                        conv_bf16: bool = False) -> jnp.ndarray:
    """Basis-fused dispatch (Pallas only): h [b,n,k,mid], w3 [mid,C*F,O],
    b3 [C*F,O], basis [b,n,k,P,Q,F] (or [b,n,k,P*F*Q] flat when it came
    from get_basis(layout='pfq_flat') — pqf supplies (P, Q, F) then),
    x [b,n,k,C,Q] -> [b,n,k,P,O]. Same contraction as _radial_contract
    on V2 = basis . x, but V2 never exists outside kernel VMEM (see
    kernels.pallas_pairwise, bx/bxf variants).

    conv_bf16 stores the basis and gathered-feature operands bf16 (half
    the kernel's biggest HBM streams; math stays f32 on the quantized
    values — see _radial_contract's tradeoff note)."""
    if isinstance(w3, QuantTensor):
        # fuse_basis + quantized weights: dequantize as a TRANSIENT
        # inside the traced program (a weight-sized temp, tiny next to
        # the edge tensors this path streams) so the bx/bxf custom_vjp
        # plumbing stays untouched; the param-tree argument is still
        # the int8 storage. The plain-kernel path above gets the true
        # in-tile epilogue.
        w3 = w3.dequant()
    flat = _basis_is_flat(basis, x)
    if flat:
        assert pqf is not None, 'flat basis needs explicit (P, Q, F)'
        P, Q, F = pqf
    else:
        P, Q, F = basis.shape[-3:]
    C = x.shape[-2]
    O = w3.shape[-1]
    if conv_bf16:
        # before the chunk split: the streamed operands and the custom-vjp
        # residuals are then half-width too
        basis = basis.astype(jnp.bfloat16)
        x = x.astype(jnp.bfloat16)
    # bias un-folded: separate [S, 1] kernel operand (see _radial_contract)
    w3c = w3.astype(h.dtype)
    prec = jax.config.jax_default_matmul_precision

    def contract(h_c, basis_c, x_c):
        lead_c = h_c.shape[:-1]
        E = 1
        for s in lead_c:
            E *= s
        h2 = h_c.reshape(E, h_c.shape[-1])
        if flat:
            out = _pairwise_contract_pallas_bxf(
                h2, w3c, b3, basis_c.reshape(E, P * F * Q),
                x_c.reshape(E, C, Q), (P, Q, F), pallas_interpret, prec)
        else:
            out = _pairwise_contract_pallas_bx(
                h2, w3c, b3, basis_c.reshape(E, P, Q, F),
                x_c.reshape(E, C, Q), pallas_interpret, prec)
        return out.reshape(*lead_c, P, O)

    if edge_chunks is None:
        return contract(h, basis, x)
    return _stream_node_chunks(contract, (h, basis, x), edge_chunks)


def pairwise_conv_contract(R: jnp.ndarray, B: jnp.ndarray,
                           x: jnp.ndarray) -> jnp.ndarray:
    """Reference-ordered fused contraction for one degree pair (kept for
    tests / comparison): R [...,c_out,c_in,f], B [...,P,Q,f],
    x [...,c_in,Q] -> [...,c_out,P]."""
    W = jnp.einsum('...oif,...iq->...oqf', R, x)
    return jnp.einsum('...oqf,...pqf->...op', W, B)


class ConvSE3(nn.Module):
    """Graph TFN convolution over precomputed neighborhoods
    (reference :154-268)."""
    fiber_in: Fiber
    fiber_out: Fiber
    self_interaction: bool = True
    pool: bool = True
    edge_dim: int = 0
    fourier_encode_dist: bool = False
    num_fourier_features: int = 4
    pallas: Optional[bool] = None
    pallas_interpret: bool = False
    edge_chunks: Optional[int] = None
    # share one radial hidden trunk across all degree pairs (perf option;
    # the reference uses an independent MLP per pair, which dominates FLOPs
    # at small channel counts — parameterization differs when enabled)
    shared_radial_hidden: bool = False
    fuse_basis: bool = False
    radial_bf16: bool = False
    conv_bf16: bool = False
    # contraction backend for every degree pair of this layer
    # (CONV_BACKENDS; per-layer selection happens in the model via
    # resolve_conv_backend). Non-dense backends read their payload from
    # the basis dict's reserved key (e.g. basis['so2']) and share the
    # dense path's parameter layout.
    backend: str = 'dense'
    # fuse_pairwise: return the pairwise PROGRAM instead of the
    # contracted features — {'h': [b, n, k, mid] radial hidden,
    # 'pairs': ((d_in, c_in), ...), 'w3'/'b3': {str(d_out): grouped
    # param}} — so the streaming flash-attention kernel
    # (kernels.pallas_flash) can run the contraction per VMEM tile.
    # NOTHING is gathered and no basis tensor is consumed: the per-edge
    # keyed features never exist in HBM. Parameter names/shapes are
    # IDENTICAL to the shared-radial grouped path (_grouped_pair_params
    # + the same radial trunk call order), so one checkpoint serves the
    # fused and unfused attention paths alike.
    fuse_pairwise: bool = False
    # global_radial: the kNN-free escalation of fuse_pairwise — return
    # the pairwise program with the radial trunk's RAW parameters
    # (rp 8-tuple) instead of a precomputed per-edge hidden, because in
    # global attention no per-edge tensor of ANY kind exists in HBM: the
    # streaming kernel (kernels.pallas_flash global mode) rebuilds
    # rel_pos/distance/radial/SH per VMEM tile from coordinates. Param
    # names/shapes/initializers mirror radial_hidden's layers exactly
    # (_DenseParams/_LayerNormParams + _grouped_pair_params), so one
    # checkpoint serves the kNN-fused, unfused, and global paths alike.
    global_radial: bool = False

    def _grouped_pair_params(self, degree_in: int, degree_out: int,
                             mid: int, m_in: int, m_out: int):
        """The shared-trunk grouped (w3, b3) for one degree pair — ONE
        definition for the dense and so2 grouped branches, because the
        'one checkpoint serves any backend mix' guarantee is exactly
        these names/shapes/initializers staying identical."""
        F = to_order(min(degree_in, degree_out))
        IF = m_in * F
        w3 = self.param(
            f'w3_{degree_in}_{degree_out}',
            nn.initializers.variance_scaling(1.0, 'fan_in',
                                             'truncated_normal',
                                             in_axis=0, out_axis=(1, 2)),
            (mid, IF, m_out), jnp.float32)
        b3 = self.param(f'b3_{degree_in}_{degree_out}',
                        nn.initializers.zeros, (IF, m_out), jnp.float32)
        return w3, b3

    @nn.compact
    def __call__(self, inp: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, basis: Dict[str, jnp.ndarray]
                 ) -> Features:
        neighbor_indices, neighbor_masks, edges = edge_info

        if self.global_radial:
            # kNN-free pairwise-program mode (see the field comment).
            # Branches before any rel_dist use: the caller passes
            # rel_dist=None because distances are a per-tile kernel
            # quantity here, not a model-level tensor.
            assert self.shared_radial_hidden, \
                'global_radial requires shared_radial_hidden=True (the ' \
                'global kernel consumes the grouped w3/b3 layout)'
            assert not self.pool and not self.self_interaction, \
                'global_radial serves the attention kv path (pool=False)'
            assert self.backend in ('dense', 'so2'), \
                f'global_radial supports the dense/so2 arms, not ' \
                f'{self.backend!r}'
            assert not self.fourier_encode_dist and edges is None, \
                'global attention consumes raw distances only (the ' \
                'kernel rebuilds them from coordinates per tile; no ' \
                'fourier/edge features)'
            mid = DEFAULT_MID_DIM
            w1, b1 = _DenseParams(1, mid, name='Dense_0')()
            s1, o1 = _LayerNormParams(mid, name='LayerNorm_0')()
            w2, b2 = _DenseParams(mid, mid, name='Dense_1')()
            s2, o2 = _LayerNormParams(mid, name='LayerNorm_1')()
            w3s: Dict[str, jnp.ndarray] = {}
            b3s: Dict[str, jnp.ndarray] = {}
            for degree_out, m_out in self.fiber_out:
                ws, bs = [], []
                for degree_in, m_in in self.fiber_in:
                    w3, b3 = self._grouped_pair_params(
                        degree_in, degree_out, mid, m_in, m_out)
                    ws.append(w3)
                    bs.append(b3)
                w3s[str(degree_out)] = concat_weights(ws, axis=1)
                b3s[str(degree_out)] = jnp.concatenate(bs, axis=0)
            return dict(rp=(w1, b1, s1, o1, w2, b2, s2, o2),
                        pairs=tuple((d, c) for d, c in self.fiber_in),
                        arm=self.backend, w3=w3s, b3=b3s)

        rel_dist_feats = rel_dist[..., None]  # [b, n, k, 1]
        if self.fourier_encode_dist:
            rel_dist_feats = fourier_encode(
                rel_dist_feats, num_encodings=self.num_fourier_features)

        edge_features = rel_dist_feats
        if edges is not None:
            edge_features = jnp.concatenate((rel_dist_feats, edges), axis=-1)

        if self.fuse_pairwise:
            # pairwise-program mode (see the field comment): the radial
            # trunk runs here (per-edge h is the one per-edge tensor the
            # flash kernel still reads from HBM); gathers and the basis
            # contraction move inside the streaming kernel
            assert self.shared_radial_hidden, \
                'fuse_pairwise requires shared_radial_hidden=True (the ' \
                'flash kernel consumes the grouped w3/b3 layout)'
            assert not self.pool and not self.self_interaction, \
                'fuse_pairwise serves the attention kv path (pool=False)'
            assert self.backend in ('dense', 'so2'), \
                f'fuse_pairwise supports the dense/so2 arms, not ' \
                f'{self.backend!r}'
            hidden = radial_hidden(
                edge_features, DEFAULT_MID_DIM,
                dtype=jnp.bfloat16 if self.radial_bf16 else None)
            w3s: Dict[str, jnp.ndarray] = {}
            b3s: Dict[str, jnp.ndarray] = {}
            for degree_out, m_out in self.fiber_out:
                ws, bs = [], []
                for degree_in, m_in in self.fiber_in:
                    w3, b3 = self._grouped_pair_params(
                        degree_in, degree_out, hidden.shape[-1], m_in,
                        m_out)
                    ws.append(w3)
                    bs.append(b3)
                # quant-aware: grouped QuantTensors concatenate q and
                # scale along the same (non-contracted) IF axis
                w3s[str(degree_out)] = concat_weights(ws, axis=1)
                b3s[str(degree_out)] = jnp.concatenate(bs, axis=0)
            return dict(h=hidden,
                        pairs=tuple((d, c) for d, c in self.fiber_in),
                        arm=self.backend, w3=w3s, b3=b3s)

        # gather neighbor features once per input degree
        # (exchange_index_select: under the ring branch's exchange scope
        # this is the neighbor-sparse ring rotation; a plain dense gather
        # everywhere else — parallel/exchange.py)
        gathered = {}
        for degree_in, _ in self.fiber_in:
            key = str(degree_in)
            gathered[key] = exchange_index_select(
                inp[key], neighbor_indices, axis=1)  # [b, n, k, c_in, 2di+1]

        hidden = radial_hidden(
            edge_features, DEFAULT_MID_DIM,
            dtype=jnp.bfloat16 if self.radial_bf16 else None) \
            if self.shared_radial_hidden else None

        fuse_bx = self.fuse_basis and _use_pallas(self.pallas,
                                                  self.pallas_interpret)
        backend_impl = get_conv_backend(self.backend) \
            if self.backend != 'dense' else None
        so2_hoist = self.backend == 'so2'
        if so2_hoist:
            # rotation hoisting: rotate every input degree into the
            # edge frame ONCE (shared across all (d_in, d_out) pairs of
            # this layer) and rotate each output degree back once after
            # summing over input degrees. Rotations are parameter-free,
            # so the param tree matches the unhoisted path exactly; the
            # per-pair modules below run banded+radial only
            # (so2_edge_frame_io). Without this a degree-6 layer redoes
            # the Wigner application 49x instead of 13x — measured as
            # most of the so2 step on the toy sweep.
            from ..so2.contract import banded_z
            from ..so2.frames import rotate_in, rotate_out
            so2_frames = basis[self.backend]
            rotated = {str(di): rotate_in(gathered[str(di)],
                                          so2_frames, di)
                       for di, _ in self.fiber_in}

        outputs = {}
        for degree_out, m_out in self.fiber_out:
            if so2_hoist and self.shared_radial_hidden:
                # grouped so2: the edge-frame z segments share the P
                # axis and concatenate along the contracted IF axis
                # exactly like the dense path's v2 segments — ONE fused
                # radial contraction per output degree (same grouped
                # w3_{d_in}_{d_out} params as dense grouped mode)
                z_segs, w3s, b3s = [], [], []
                for degree_in, m_in in self.fiber_in:
                    w3, b3 = self._grouped_pair_params(
                        degree_in, degree_out, hidden.shape[-1], m_in,
                        m_out)
                    w3s.append(w3)
                    b3s.append(b3)
                    z_segs.append(banded_z(rotated[str(degree_in)],
                                           degree_in, degree_out))
                acc = _radial_contract(
                    hidden, concat_weights(w3s, axis=1),
                    jnp.concatenate(b3s, axis=0),
                    jnp.concatenate(z_segs, axis=-1),
                    pallas=self.pallas,
                    pallas_interpret=self.pallas_interpret,
                    edge_chunks=self.edge_chunks,
                    conv_bf16=self.conv_bf16)            # [..., P, O]
                acc = rotate_out(jnp.swapaxes(acc, -1, -2), so2_frames,
                                 degree_out)             # [..., O, P]
            elif so2_hoist:
                acc = None
                for degree_in, m_in in self.fiber_in:
                    y = PairwiseConvSE3(
                        degree_in, m_in, degree_out, m_out,
                        pallas=self.pallas,
                        pallas_interpret=self.pallas_interpret,
                        edge_chunks=self.edge_chunks,
                        fuse_basis=self.fuse_basis,
                        radial_bf16=self.radial_bf16,
                        conv_bf16=self.conv_bf16,
                        backend=self.backend,
                        so2_edge_frame_io=True,
                        name=f'pair_{degree_in}_{degree_out}')(
                            edge_features, so2_frames,
                            rotated[str(degree_in)])     # [..., O, P]
                    acc = y if acc is None else acc + y
                acc = rotate_out(acc, so2_frames, degree_out)
            elif self.shared_radial_hidden:
                # the shared trunk makes every (d_in -> d_out) pair differ
                # only in (w3, b3, v2), all concatenable along the
                # contracted IF axis: ONE fused contraction (one Pallas
                # launch / one big MXU matmul) per output degree instead of
                # one per degree pair. With fuse_basis the heterogeneous
                # (Q, F) segments can't share a chunk axis, so it's one
                # basis-fused launch per pair instead (same params).
                v2s, w3s, b3s = [], [], []
                acc = None
                for degree_in, m_in in self.fiber_in:
                    F = to_order(min(degree_in, degree_out))
                    P = to_order(degree_out)
                    Q = to_order(degree_in)
                    IF = m_in * F
                    w3, b3 = self._grouped_pair_params(
                        degree_in, degree_out, hidden.shape[-1], m_in,
                        m_out)
                    basis_pair = basis[f'{degree_in},{degree_out}']
                    if fuse_bx:
                        y = _radial_contract_bx(
                            hidden, w3, b3, basis_pair,
                            gathered[str(degree_in)],
                            pallas_interpret=self.pallas_interpret,
                            edge_chunks=self.edge_chunks, pqf=(P, Q, F),
                            conv_bf16=self.conv_bf16)
                        acc = y if acc is None else acc + y
                        continue
                    if _basis_is_flat(basis_pair, gathered[str(degree_in)]):
                        basis_pair = unflatten_basis(basis_pair, P, Q, F)
                    v2 = jnp.einsum('...pqf,...cq->...pcf',
                                    basis_pair,
                                    gathered[str(degree_in)])
                    v2s.append(v2.reshape(*v2.shape[:-2], IF))
                    w3s.append(w3)
                    b3s.append(b3)
                if not fuse_bx:
                    acc = _radial_contract(
                        hidden, concat_weights(w3s, axis=1),
                        jnp.concatenate(b3s, axis=0),
                        jnp.concatenate(v2s, axis=-1),
                        pallas=self.pallas,
                        pallas_interpret=self.pallas_interpret,
                        edge_chunks=self.edge_chunks,
                        conv_bf16=self.conv_bf16)
                acc = jnp.swapaxes(acc, -1, -2)  # [..., c_out, P]
            else:
                acc = None
                for degree_in, m_in in self.fiber_in:
                    basis_slice = basis[self.backend] \
                        if backend_impl is not None \
                        else basis[f'{degree_in},{degree_out}']
                    y = PairwiseConvSE3(
                        degree_in, m_in, degree_out, m_out,
                        pallas=self.pallas,
                        pallas_interpret=self.pallas_interpret,
                        edge_chunks=self.edge_chunks,
                        fuse_basis=self.fuse_basis,
                        radial_bf16=self.radial_bf16,
                        conv_bf16=self.conv_bf16,
                        backend=self.backend,
                        name=f'pair_{degree_in}_{degree_out}')(
                            edge_features,
                            basis_slice,
                            gathered[str(degree_in)])
                    acc = y if acc is None else acc + y

            if self.pool:
                acc = masked_mean(acc, neighbor_masks, axis=2) \
                    if neighbor_masks is not None else acc.mean(axis=2)
            outputs[str(degree_out)] = acc

        if self.self_interaction:
            assert self.pool, 'must pool edges if followed with self interaction'
            self_out = LinearSE3(self.fiber_in, self.fiber_out,
                                 name='self_interact')(inp)
            outputs = residual_se3(outputs, self_out)

        # Name the conv outputs for policy-based remat (trunk.py
        # remat_policy='save_conv_outputs'): under
        # save_only_these_names('conv_out') the reversible trunk's
        # backward replay fetches these tensors from storage instead of
        # re-running the radial contraction — whose apply matmul is ~95%
        # of all flagship FLOPs (utils/flops.py). The Pallas kernels'
        # custom_vjp residuals are their *inputs* (h, w3, v2/basis/x),
        # so with the output saved the replay DCEs the kernel forward
        # entirely and only recomputes the cheap glue (trunk MLP,
        # gather, norms). Outside jax.checkpoint the names are inert.
        from jax.ad_checkpoint import checkpoint_name
        outputs = {k: checkpoint_name(v, 'conv_out')
                   for k, v in outputs.items()}
        return outputs
