"""Core equivariant modules: Linear, Norm, Residual, FeedForward.

TPU-native flax.linen analogues of reference se3_transformer_pytorch.py:
  ResidualSE3 (:67), LinearSE3 (:78), NormSE3 (:97),
  FeedForwardSE3/FeedForwardBlockSE3 (:347-383).

Feature dicts are {str(degree): [..., channels, 2*degree+1]} pytrees. All
per-degree weights are independent parameters; the channel contraction is a
plain matmul over the channel axis, which XLA batches onto the MXU.
"""
from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..quant.qtensor import QuantTensor
from ..utils.helpers import safe_norm
from .fiber import Fiber


Features = Dict[str, jnp.ndarray]


def channel_mix(x: jnp.ndarray, w) -> jnp.ndarray:
    """The per-degree channel contraction `x [..., c, m] @ w [c, e] ->
    [..., e, m]`, quant-aware: a QuantTensor weight contracts in its
    int8/fp8 STORAGE form and the per-output-channel scale folds in as
    an epilogue — the fp32 weight never exists outside this fusion
    (serving's restore-time quantization rides on exactly that). A bf16
    weight promotes through the einsum; math stays f32 either way."""
    if isinstance(w, QuantTensor):
        out = jnp.einsum('...cm,ce->...em', x,
                         jnp.asarray(w.q).astype(x.dtype),
                         preferred_element_type=jnp.float32)
        # scale [1, e] -> [e, 1]: the output channel axis is -2
        return out * w.scale[0][:, None]
    return jnp.einsum('...cm,ce->...em', x, w)


def residual_se3(x: Features, res: Features) -> Features:
    """Degree-wise residual add; keys may differ (reference :67-76)."""
    out = {}
    for degree, tensor in x.items():
        out[degree] = tensor + res[degree] if degree in res else tensor
    return out


class LinearSE3(nn.Module):
    """Per-degree channel-mixing linear map (reference :78-95).

    Only degrees present in both fibers are produced, matching the reference's
    intersection semantics.
    """
    fiber_in: Fiber
    fiber_out: Fiber

    @nn.compact
    def __call__(self, x: Features) -> Features:
        out = {}
        for degree, dim_in, dim_out in (self.fiber_in & self.fiber_out):
            key = str(degree)
            w = self.param(
                f'w{key}',
                nn.initializers.normal(stddev=dim_in ** -0.5),
                (dim_in, dim_out), x[key].dtype)
            out[key] = channel_mix(x[key], w)
        return out


class NormSE3(nn.Module):
    """Norm-gated equivariant nonlinearity (reference :97-152).

    Per degree: split into (norm, unit direction), pass the norms through a
    learnable scale (or a gating matrix) and a nonlinearity, re-multiply the
    direction. Rotation-equivariant because only the invariant norm is
    transformed.
    """
    fiber: Fiber
    nonlin: Callable = nn.gelu
    gated_scale: bool = False
    eps: float = 1e-12

    @nn.compact
    def __call__(self, features: Features) -> Features:
        output = {}
        for degree, t in features.items():
            chan = t.shape[-2]
            norm = jnp.clip(safe_norm(t, axis=-1, keepdims=True),
                            self.eps, None)
            phase = t / norm

            scalars = norm[..., 0]  # [..., c]
            if self.gated_scale:
                w_gate = self.param(
                    f'w_gate{degree}',
                    lambda key, shape, dtype: jax.random.uniform(
                        key, shape, dtype, -1e-3, 1e-3),
                    (chan, chan), t.dtype)
                scaled = jnp.einsum('...c,ce->...e', scalars, w_gate)
            else:
                scale = self.param(
                    f'scale{degree}', nn.initializers.ones, (1, 1, chan),
                    t.dtype)
                scaled = scalars * scale.reshape((1,) * (scalars.ndim - 1) + (chan,))
            transformed = self.nonlin(scaled)
            output[degree] = transformed[..., None] * phase
        return output


class FeedForwardSE3(nn.Module):
    """Linear -> Norm-nonlinearity -> Linear with widening `mult`
    (reference :347-365)."""
    fiber: Fiber
    mult: int = 4

    @nn.compact
    def __call__(self, features: Features) -> Features:
        fiber_hidden = self.fiber.scale(self.mult)
        x = LinearSE3(self.fiber, fiber_hidden, name='project_in')(features)
        x = NormSE3(fiber_hidden, name='nonlin')(x)
        x = LinearSE3(fiber_hidden, self.fiber, name='project_out')(x)
        return x


class FeedForwardBlockSE3(nn.Module):
    """Prenorm + feedforward + residual (reference :367-383)."""
    fiber: Fiber
    norm_gated_scale: bool = False

    @nn.compact
    def __call__(self, features: Features) -> Features:
        res = features
        out = NormSE3(self.fiber, gated_scale=self.norm_gated_scale,
                      name='prenorm')(features)
        out = FeedForwardSE3(self.fiber, name='feedforward')(out)
        return residual_se3(out, res)
