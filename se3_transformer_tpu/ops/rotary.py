"""Rotary position embeddings for degree-0 channels.

Functional JAX analogue of reference rotary.py (SinusoidalEmbeddings /
apply_rotary_pos_emb). Rotary features are applied only to the invariant
(degree-0) q/k/v channels, so they do not interact with equivariance.
"""
from __future__ import annotations

import jax.numpy as jnp


def sinusoidal_embeddings(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    """t [...]-shaped positions -> [..., dim] rotary phase angles
    (reference rotary.py:5-13; frequencies repeated pairwise)."""
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = t[..., None].astype(jnp.float32) * inv_freq
    return jnp.repeat(freqs, 2, axis=-1)  # (d r) with r=2: f1,f1,f2,f2,...


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    # channels axis is -2 (layout [..., d, m]); pairs are consecutive
    x = x.reshape(*x.shape[:-2], -1, 2, x.shape[-1])
    x1, x2 = x[..., 0, :], x[..., 1, :]
    out = jnp.stack((-x2, x1), axis=-2)
    return out.reshape(*out.shape[:-3], -1, out.shape[-1])


def apply_rotary_pos_emb(t: jnp.ndarray, freqs: jnp.ndarray) -> jnp.ndarray:
    """Rotate the first rot_dim channels of t [..., d, m] by freqs [..., rot_dim]
    (reference rotary.py:20-24; note the trailing irrep axis m)."""
    freqs = freqs[..., None]  # broadcast over m
    rot_dim = freqs.shape[-2]
    t_rot, t_pass = t[..., :rot_dim, :], t[..., rot_dim:, :]
    t_rot = (t_rot * jnp.cos(freqs)) + (_rotate_half(t_rot) * jnp.sin(freqs))
    return jnp.concatenate((t_rot, t_pass), axis=-2)
