"""Trunk execution strategies (the reference's L3 layer).

Reference reversible.py provides two ways to run the stack of
(attention, feedforward) blocks: SequentialSequence (:189-198) and
ReversibleSequence (:200-220), the latter a hand-rolled RevNet with RNG
state capture/replay for O(1) activation memory.

TPU-native equivalents:

  * SequentialTrunk — plain unrolled loop (XLA fuses across blocks).
  * reversible=True -> the same trunk with every block wrapped in
    jax.checkpoint (flax nn.remat): activations are rematerialized in the
    backward pass, giving the same activation-memory class as RevNet with
    no inverse math and exact determinism (JAX PRNG keys are explicit, so
    the reference's Deterministic RNG fork at reversible.py:59-89 has no
    analogue to port — determinism is free).
"""
from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax.numpy as jnp

from .attention import AttentionBlockSE3
from .core import FeedForwardBlockSE3
from .fiber import Fiber

Features = Dict[str, jnp.ndarray]


def _resolve_remat_policy(name: Optional[str]):
    """Map the string knob to a jax.checkpoint policy (None = remat
    everything). Strings keep the flax module dataclass hashable and the
    knob serializable in configs."""
    if name is None:
        return None
    import jax
    if name == 'save_conv_outputs':
        return jax.checkpoint_policies.save_only_these_names('conv_out')
    raise ValueError(f'unknown remat_policy {name!r}; '
                     f"expected None or 'save_conv_outputs'")


class SequentialTrunk(nn.Module):
    """depth x (AttentionBlockSE3 -> FeedForwardBlockSE3); reversible=True
    rematerializes each block (reference ReversibleSequence replacement)."""
    fiber: Fiber
    depth: int
    heads: int = 8
    dim_head: int = 24
    attend_self: bool = False
    edge_dim: int = 0
    use_null_kv: bool = False
    fourier_encode_dist: bool = False
    rel_dist_num_fourier_features: int = 4
    global_feats_dim: Optional[int] = None
    linear_proj_keys: bool = False
    tie_key_values: bool = False
    one_headed_key_values: bool = False
    norm_gated_scale: bool = False
    reversible: bool = False
    # remat policy for reversible=True. None = full per-block remat (the
    # O(1)-activation default, step cost ~4x fwd). 'save_conv_outputs' =
    # jax.checkpoint_policies.save_only_these_names('conv_out'): the
    # ConvSE3 results (tagged in ops/conv.py) are stored instead of
    # recomputed, so the backward replay skips the radial contraction —
    # ~95% of flagship FLOPs — and re-runs only the cheap glue. Costs
    # ~sum-over-blocks of the conv output tensors (~1.7 GB at flagship
    # dim=64/n=1024/k=32: 2 convs x 6 blocks x [n, k+1, 64, 16] f32)
    # for an expected ~4x -> ~3.1x step-multiplier cut.
    remat_policy: Optional[str] = None
    pallas: Optional[bool] = None
    pallas_attention: Optional[bool] = None
    pallas_attention_interpret: bool = False
    shared_radial_hidden: bool = False
    edge_chunks: Optional[int] = None
    fuse_basis: bool = False
    pallas_interpret: bool = False
    radial_bf16: bool = False
    conv_bf16: bool = False
    # per-block conv backends for the attention value/key ConvSE3 paths
    # (resolved by the model from its conv_backend spec; None = dense
    # everywhere — ops.conv.CONV_BACKENDS)
    value_backends: Optional[tuple] = None
    key_backends: Optional[tuple] = None
    # per-block streaming-attention selection (resolved by the model
    # from its fuse_pairwise spec; None = unfused everywhere). A fused
    # block routes k/v + attention through kernels.pallas_flash.
    fused_attention: Optional[tuple] = None
    flash_interpret: bool = False
    # 'global' = the kNN-free large-assembly mode (every block; see
    # ops.attention.AttentionSE3.attention_mode)
    attention_mode: str = 'knn'
    global_materialize: bool = False

    @nn.compact
    def __call__(self, x: Features, edge_info, rel_dist, basis,
                 global_feats=None, pos_emb=None, mask=None) -> Features:
        # validate unconditionally: a typo'd or inapplicable policy must
        # raise, not silently no-op while configs/bench labels claim it
        policy = _resolve_remat_policy(self.remat_policy)
        if self.remat_policy is not None and not self.reversible:
            raise ValueError(
                f'remat_policy={self.remat_policy!r} requires '
                f'reversible=True (the policy governs what the '
                f'reversible backward stores vs recomputes)')
        attn_cls, ff_cls = AttentionBlockSE3, FeedForwardBlockSE3
        if self.reversible:
            attn_cls = nn.remat(AttentionBlockSE3, policy=policy)
            ff_cls = nn.remat(FeedForwardBlockSE3, policy=policy)

        for i in range(self.depth):
            x = attn_cls(
                self.fiber, heads=self.heads, dim_head=self.dim_head,
                backend_v=(self.value_backends[i]
                           if self.value_backends else 'dense'),
                backend_k=(self.key_backends[i]
                           if self.key_backends else 'dense'),
                attend_self=self.attend_self, edge_dim=self.edge_dim,
                use_null_kv=self.use_null_kv,
                fourier_encode_dist=self.fourier_encode_dist,
                rel_dist_num_fourier_features=self.rel_dist_num_fourier_features,
                global_feats_dim=self.global_feats_dim,
                linear_proj_keys=self.linear_proj_keys,
                tie_key_values=self.tie_key_values,
                one_headed_key_values=self.one_headed_key_values,
                norm_gated_scale=self.norm_gated_scale,
                pallas=self.pallas,
                pallas_attention=self.pallas_attention,
                pallas_attention_interpret=self.pallas_attention_interpret,
                shared_radial_hidden=self.shared_radial_hidden,
                edge_chunks=self.edge_chunks,
                fuse_basis=self.fuse_basis,
                radial_bf16=self.radial_bf16,
                conv_bf16=self.conv_bf16,
                pallas_interpret=self.pallas_interpret,
                fuse_pairwise=(self.fused_attention[i]
                               if self.fused_attention else False),
                flash_interpret=self.flash_interpret,
                attention_mode=self.attention_mode,
                global_materialize=self.global_materialize,
                name=f'attn_block{i}')(
                    x, edge_info, rel_dist, basis, global_feats, pos_emb,
                    mask)
            x = ff_cls(self.fiber, norm_gated_scale=self.norm_gated_scale,
                       name=f'ff_block{i}')(x)
        return x
