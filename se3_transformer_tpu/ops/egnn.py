"""E(n)-GNN backbone generalized to higher-degree features.

TPU-native rework of reference EGNN / EGnnNetwork
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:687-932).

Key departure: the reference materializes all-pairs relative higher-type
tensors [b, n, n, c, m] and then gathers neighbors (:792-803). Here the
gather happens first, so everything stays O(n * k): relative htypes are
formed directly on the [b, n, k] neighborhood. HtypesNorm is elementwise,
so gather-then-normalize is exactly equivalent.

Deviation from the reference (documented): the reference computes the
neighbor-masked htype weights but then uses the *unmasked* split views for
the update (masked_fill happens after .split at :823-829, out-of-place), so
padding neighbors leak into coordinate updates. We apply the mask for real.
"""
from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.exchange import exchange_index_select
from ..utils.helpers import broadcat, safe_norm
from .conv import EdgeInfo
from .core import FeedForwardBlockSE3
from .fiber import Fiber

Features = Dict[str, jnp.ndarray]


def _normal_dense(features: int, init_eps: float, name: str) -> nn.Dense:
    return nn.Dense(features,
                    kernel_init=nn.initializers.normal(stddev=init_eps),
                    name=name)


class HtypesNorm(nn.Module):
    """Norm-and-affine rescaling of higher-type vectors
    (reference :693-705)."""
    dim: int
    eps: float = 1e-8
    scale_init: float = 1e-2
    bias_init: float = 1e-2

    @nn.compact
    def __call__(self, htype: jnp.ndarray) -> jnp.ndarray:
        # htype [..., c, m]
        scale = self.param('scale',
                           nn.initializers.constant(self.scale_init),
                           (self.dim, 1), htype.dtype)
        bias = self.param('bias',
                          nn.initializers.constant(self.bias_init),
                          (self.dim, 1), htype.dtype)
        norm = safe_norm(htype, axis=-1, keepdims=True)
        normed = htype / jnp.clip(norm, self.eps, None)
        return normed * (norm * scale + bias)


class EGNN(nn.Module):
    """One EGNN layer over precomputed neighborhoods (reference :707-865)."""
    fiber: Fiber
    hidden_dim: int = 32
    edge_dim: int = 0
    init_eps: float = 1e-3
    coor_weights_clamp_value: Optional[float] = None

    @nn.compact
    def __call__(self, features: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, mask=None, **kwargs) -> Features:
        neighbor_indices, neighbor_masks, edges = edge_info

        node_dim = self.fiber[0]
        htype_items = [(d, v) for d, v in features.items() if d != '0']
        htype_degrees = [d for d, _ in htype_items]
        htype_dims = [v.shape[-2] for _, v in htype_items]

        nodes = features['0'][..., 0]  # [b, n, d]
        b, n, k = neighbor_indices.shape

        # relative higher types on the neighborhood (gather-first, O(n*k))
        rel_htypes = {}
        rel_htype_dists = []
        for degree, htype in htype_items:
            nbr = exchange_index_select(htype, neighbor_indices, axis=1)
            rel = htype[:, :, None] - nbr            # [b, n, k, c, m]
            rel_htypes[degree] = rel
            rel_htype_dists.append(safe_norm(rel, axis=-1))

        nodes_i = nodes[:, :, None]                   # [b, n, 1, d]
        nodes_j = exchange_index_select(nodes, neighbor_indices, axis=1)
        coor_rel_dist = rel_dist[..., None]           # [b, n, k, 1]

        edge_mlp_inputs = broadcat(
            (nodes_i, nodes_j, *rel_htype_dists, coor_rel_dist), axis=-1)
        if edges is not None:
            edge_mlp_inputs = jnp.concatenate((edge_mlp_inputs, edges), -1)

        edge_in_dim = edge_mlp_inputs.shape[-1]
        m_ij = _normal_dense(edge_in_dim * 2, self.init_eps, 'edge_mlp0')(
            edge_mlp_inputs)
        m_ij = nn.silu(m_ij)
        m_ij = _normal_dense(self.hidden_dim, self.init_eps, 'edge_mlp1')(m_ij)
        m_ij = nn.silu(m_ij)

        # higher-type updates
        htype_weights = _normal_dense(self.hidden_dim * 4, self.init_eps,
                                      'htypes_mlp0')(m_ij)
        htype_weights = nn.silu(htype_weights)
        htype_weights = _normal_dense(sum(htype_dims), self.init_eps,
                                      'htypes_mlp1')(htype_weights)

        if self.coor_weights_clamp_value is not None:
            c = self.coor_weights_clamp_value
            htype_weights = jnp.clip(htype_weights, -c, c)
        if neighbor_masks is not None:
            htype_weights = jnp.where(neighbor_masks[..., None],
                                      htype_weights, 0.)

        htype_updates = {}
        offset = 0
        for degree, dim in zip(htype_degrees, htype_dims):
            w = htype_weights[..., offset:offset + dim]  # [b, n, k, c]
            offset += dim
            normed = HtypesNorm(dim, name=f'htype_norm{degree}')(
                rel_htypes[degree])
            htype_updates[degree] = jnp.einsum('bijcm,bijc->bicm', normed, w)

        # node updates
        if neighbor_masks is not None:
            m_ij = jnp.where(neighbor_masks[..., None], m_ij, 0.)
        m_i = m_ij.sum(axis=-2)

        normed_nodes = nn.LayerNorm(name='node_norm')(nodes)
        node_mlp_in = jnp.concatenate((normed_nodes, m_i), axis=-1)
        h = _normal_dense(node_dim * 2, self.init_eps, 'node_mlp0')(node_mlp_in)
        h = nn.silu(h)
        h = _normal_dense(node_dim, self.init_eps, 'node_mlp1')(h)
        node_out = h + nodes

        out = dict(features)
        out['0'] = node_out[..., None]
        for degree in htype_degrees:
            out[degree] = features[degree] + htype_updates[degree]
            gate = nn.sigmoid(_normal_dense(
                dict(self.fiber.structure)[int(degree)], self.init_eps,
                f'htype_gate{degree}')(node_out))
            out[degree] = out[degree] * gate[..., None]
        return out


class EGnnNetwork(nn.Module):
    """depth x (EGNN [+ FeedForward]) trunk with self-loops prepended to the
    neighbor lists (reference :867-932)."""
    fiber: Fiber
    depth: int
    edge_dim: int = 0
    hidden_dim: int = 32
    coor_weights_clamp_value: Optional[float] = None
    feedforward: bool = False
    # rematerialize each layer's activations (the EGNN analogue of the
    # reference's reversible trunk memory class)
    reversible: bool = False

    @nn.compact
    def __call__(self, features: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, basis=None, global_feats=None,
                 pos_emb=None, mask=None, **kwargs) -> Features:
        neighbor_indices, neighbor_masks, edges = edge_info
        b, n, _ = neighbor_indices.shape

        # EGNN wants self-loops: prepend each node's own index
        self_idx = jnp.broadcast_to(
            jnp.arange(n, dtype=neighbor_indices.dtype)[None, :, None],
            (b, n, 1))
        neighbor_indices = jnp.concatenate((self_idx, neighbor_indices), -1)
        if neighbor_masks is not None:
            neighbor_masks = jnp.pad(
                neighbor_masks, ((0, 0), (0, 0), (1, 0)),
                constant_values=True)
        rel_dist = jnp.pad(rel_dist, ((0, 0), (0, 0), (1, 0)))
        if edges is not None:
            edges = jnp.pad(edges, ((0, 0), (0, 0), (1, 0), (0, 0)))

        edge_info = (neighbor_indices, neighbor_masks, edges)

        egnn_cls, ff_cls = EGNN, FeedForwardBlockSE3
        if self.reversible:
            egnn_cls = nn.remat(EGNN)
            ff_cls = nn.remat(FeedForwardBlockSE3)

        for i in range(self.depth):
            features = egnn_cls(
                self.fiber, hidden_dim=self.hidden_dim,
                edge_dim=self.edge_dim,
                coor_weights_clamp_value=self.coor_weights_clamp_value,
                name=f'egnn{i}')(features, edge_info, rel_dist, mask=mask)
            if self.feedforward:
                features = ff_cls(self.fiber, name=f'ff{i}')(features)
        return features
