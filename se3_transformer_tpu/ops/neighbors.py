"""Static-shape neighborhood construction (kNN + sparse adjacency + causal).

Jit-safe rework of the reference's eager neighbor pipeline
(/root/reference/se3_transformer_pytorch/se3_transformer_pytorch.py:1169-1294).
Every data-dependent quantity the reference computes with `.item()` /
dynamic topk sizes (:1208, :1253, :1277-1281) is replaced by static
configuration + fixed-size top-k with validity masks — the jit-safe
formulation of the whole pipeline. All functions are pure and fully
traceable; batch axis comes first everywhere.

Self-exclusion is done by *construction* (each query row enumerates the
n-1 other nodes in ascending index order) instead of boolean masked_select
(:1171-1172), which would be a dynamic-shape op under XLA.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.helpers import batched_index_select, safe_norm

FINF = float(jnp.finfo(jnp.float32).max)


def exclude_self_indices(n: int) -> jnp.ndarray:
    """[n, n-1] int32: row i lists all j != i in ascending order."""
    j = jnp.arange(n - 1)[None, :]
    i = jnp.arange(n)[:, None]
    return (j + (j >= i)).astype(jnp.int32)


def remove_self(t: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Drop the diagonal of a pairwise [b, n, n, ...] tensor -> [b, n, n-1, ...]
    using precomputed exclude_self_indices."""
    b, n = t.shape[0], t.shape[1]
    idx_b = jnp.broadcast_to(idx[None], (b, n, n - 1))
    return batched_index_select(t, idx_b, axis=2)


def expand_adjacency(adj_mat: jnp.ndarray, num_adj_degrees: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Grow an adjacency matrix to `num_adj_degrees` hops, labelling each
    newly reached ring with its hop count (reference :1177-1190).

    adj_mat: [b, n, n] bool (1-hop). Returns (expanded bool adjacency,
    int ring labels in 0..num_adj_degrees with 0 = unreachable).
    """
    adj_indices = adj_mat.astype(jnp.int32)
    adj = adj_mat
    for ind in range(num_adj_degrees - 1):
        degree = ind + 2
        next_adj = jnp.einsum('bij,bjk->bik', adj.astype(jnp.float32),
                              adj.astype(jnp.float32)) > 0
        new_ring = next_adj & ~adj
        adj_indices = jnp.where(new_ring, degree, adj_indices)
        adj = next_adj
    return adj, adj_indices


def sparse_neighbor_mask(adj_mat_noself: jnp.ndarray, num_sparse: int,
                         noise: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Select up to num_sparse adjacent nodes per query as 'bonded' neighbors
    (reference :1195-1217). adj_mat_noself: [b, n, n-1] bool. Tie-breaking
    noise (same role as :1211) must be identical across calls for
    determinism; defaults to zeros, which makes top-k tie-break by index."""
    values = adj_mat_noself.astype(jnp.float32)
    if noise is not None:
        values = values + noise
    top_vals, top_idx = jax.lax.top_k(values, num_sparse)
    selected = jnp.zeros_like(values).at[
        jnp.arange(values.shape[0])[:, None, None],
        jnp.arange(values.shape[1])[None, :, None],
        top_idx].set(top_vals)
    return selected > 0.5


class Neighborhood(NamedTuple):
    indices: jnp.ndarray          # [b, n, k] source-node ids
    mask: jnp.ndarray             # [b, n, k] validity
    rel_pos: jnp.ndarray          # [b, n, k, 3]
    rel_dist: jnp.ndarray         # [b, n, k]


def _top_k_smallest(ranking: jnp.ndarray, k: int,
                    block: int = 128) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """EXACT smallest-k over the last axis, blockwise.

    lax.top_k lowers to a bitonic sort over the full row — measured 66 ms
    for [1, 1024, 1023] k=32 on a v5e (round-3 stage_timings), as
    expensive as an entire ConvSE3. Splitting the row into `block`-wide
    chunks, taking k per chunk, then k of the k*chunks candidates is
    exact (any global top-k element is top-k within its chunk) and sorts
    only `block`-wide rows. Ties break toward lower source index, like a
    single top_k (candidates stay in ascending-index order across
    chunks).
    """
    m = ranking.shape[-1]
    if m <= max(block, 2 * k):
        neg_vals, idx = jax.lax.top_k(-ranking, k)
        return -neg_vals, idx
    nb = -(-m // block)
    pad = nb * block - m
    x = jnp.pad(ranking, [(0, 0)] * (ranking.ndim - 1) + [(0, pad)],
                constant_values=FINF)
    xb = x.reshape(*ranking.shape[:-1], nb, block)
    kb = min(k, block)
    neg_v, i_local = jax.lax.top_k(-xb, kb)            # [..., nb, kb]
    i_global = i_local + (jnp.arange(nb) * block)[..., :, None]
    cand_v = (-neg_v).reshape(*ranking.shape[:-1], nb * kb)
    cand_i = i_global.reshape(*ranking.shape[:-1], nb * kb)
    neg_v2, sel = jax.lax.top_k(-cand_v, k)
    return -neg_v2, jnp.take_along_axis(cand_i, sel, axis=-1)


def select_neighbors(
    rel_pos: jnp.ndarray,          # [b, n, n-1, 3] self-excluded offsets
    indices: jnp.ndarray,          # [b, n, n-1] self-excluded source ids
    total_neighbors: int,          # static K
    valid_radius: float,
    pair_mask: Optional[jnp.ndarray] = None,      # [b, n, n-1] node-pair mask
    neighbor_mask: Optional[jnp.ndarray] = None,  # [b, n, n-1] user mask
    sparse_mask: Optional[jnp.ndarray] = None,    # [b, n, n-1] bonded priority
    causal: bool = False,
) -> Neighborhood:
    """Fixed-K nearest-neighbor selection with sparse-bond priority and
    causal masking (reference :1241-1294).

    Ranking distance is modified exactly as the reference does: user
    neighbor_mask exclusions -> +inf (:1257), bonded neighbors -> 0 so they
    always win (:1262), future nodes -> +inf when causal (:1267). The
    unmodified distance is what downstream layers consume.
    """
    b, n = rel_pos.shape[0], rel_pos.shape[1]
    rel_dist = safe_norm(rel_pos, axis=-1)  # [b, n, n-1]

    ranking = rel_dist
    if neighbor_mask is not None:
        ranking = jnp.where(neighbor_mask, ranking, FINF)
    if sparse_mask is not None:
        ranking = jnp.where(sparse_mask, 0., ranking)
    if causal:
        # entry (i, j) of the self-excluded layout refers to source node
        # j + (j >= i); it is "future" iff source >= i, i.e. j >= i
        future = jnp.triu(jnp.ones((n, n - 1), bool))
        ranking = jnp.where(future[None], FINF, ranking)

    dist_rank, nearest = _top_k_smallest(ranking, total_neighbors)
    valid = dist_rank <= valid_radius

    out_dist = batched_index_select(rel_dist, nearest, axis=2)
    out_pos = batched_index_select(rel_pos, nearest, axis=2)
    out_idx = batched_index_select(indices, nearest, axis=2)
    if pair_mask is not None:
        valid = valid & batched_index_select(pair_mask, nearest, axis=2)
    return Neighborhood(out_idx, valid, out_pos, out_dist), nearest
