"""Multi-degree SE(3)-equivariant attention.

TPU-native rework of reference AttentionSE3 (:387-519),
OneHeadedKVAttentionSE3 (:522-654) and AttentionBlockSE3 (:656-683). Both
attention flavours share one implementation parameterized by `kv_heads`
(either `heads`, or 1 for the Shazeer multi-query variant) — the logits /
output einsums are the only difference.

KV slot order (left of the neighbor axis, matching reference concat order
:469-506): [global, null, self, neighbors]; the neighbor mask is left-padded
with True over the prepended slots (:510-513). Rotary embeddings are applied
to degree-0 q/k/v *before* null/global slots are prepended (:488-494).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..observability import named_scope
from ..parallel.exchange import exchange_index_select
from ..utils.helpers import to_order
from .conv import ConvSE3, EdgeInfo
from .core import LinearSE3, NormSE3, residual_se3
from .fiber import Fiber
from .rotary import apply_rotary_pos_emb

Features = Dict[str, jnp.ndarray]


class AttentionSE3(nn.Module):
    fiber: Fiber
    dim_head: int = 64
    heads: int = 8
    kv_heads: Optional[int] = None  # None -> heads; 1 -> multi-query
    attend_self: bool = False
    edge_dim: Optional[int] = None
    fourier_encode_dist: bool = False
    rel_dist_num_fourier_features: int = 4
    use_null_kv: bool = False
    global_feats_dim: Optional[int] = None
    linear_proj_keys: bool = False
    tie_key_values: bool = False
    pallas: Optional[bool] = None
    # fused attention kernel (kernels.pallas_attention): per-degree fused
    # sim/softmax/weighted-sum in VMEM, one kv pass. None = auto (TPU)
    pallas_attention: Optional[bool] = None
    pallas_attention_interpret: bool = False
    shared_radial_hidden: bool = False
    edge_chunks: Optional[int] = None
    fuse_basis: bool = False
    pallas_interpret: bool = False
    radial_bf16: bool = False
    conv_bf16: bool = False
    # conv backends for the value/key ConvSE3 paths (ops.conv
    # registry; resolved per layer by the model's conv_backend spec)
    backend_v: str = 'dense'
    backend_k: str = 'dense'
    # fuse_pairwise: route k/v + attention through the streaming
    # flash kernel (kernels.pallas_flash) — the per-edge basis, the
    # gathered/keyed features, and the [b, h, n, J] scores never exist
    # in HBM; the pairwise contraction (dense or so2 arm, per
    # backend_v/backend_k) runs per VMEM tile with an online softmax
    # and a recompute-in-backward custom_vjp. Requires
    # shared_radial_hidden; rotary/linear_proj_keys fall outside it.
    fuse_pairwise: bool = False
    flash_interpret: bool = False  # tests: interpreter-mode flash kernel
    # attention_mode='global': the kNN-free large-assembly mode — no
    # neighbor selection, no get_basis, no exchange_index_select; every
    # node attends to every node with the rel_pos/radial/SH payload
    # rebuilt per VMEM tile from coordinates (kernels.pallas_flash
    # global mode, O(n) activation memory). Coordinates (+ node mask)
    # ride in on the basis dict's reserved keys 'global_coords' /
    # 'global_mask'. Under an active exchange scope (sequence_parallel=
    # 'ring') the call routes to flash_global_attention_sharded: queries
    # stay pinned, kv blocks rotate over the ring — only ppermutes, no
    # full-width all-gather.
    attention_mode: str = 'knn'
    # the O(n^2)-memory control arm (assembly smoke / bench --assembly):
    # identical params and math, per-edge tensors fully materialized
    global_materialize: bool = False

    @nn.compact
    def __call__(self, features: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, basis: Dict[str, jnp.ndarray],
                 global_feats: Optional[Features] = None,
                 pos_emb: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
                 mask: Optional[jnp.ndarray] = None) -> Features:
        if self.attention_mode == 'global':
            assert pos_emb is None, \
                'global attention does not support rotary embeddings'
            return self._global_call(features, basis, global_feats)
        assert self.attention_mode == 'knn', \
            f'unknown attention_mode {self.attention_mode!r}'
        if self.fuse_pairwise:
            return self._flash_call(features, edge_info, rel_dist, basis,
                                    global_feats, pos_emb)
        h = self.heads
        kv_h = self.kv_heads if self.kv_heads is not None else self.heads
        one_headed = kv_h == 1
        neighbor_indices, neighbor_mask, edges = edge_info

        hidden_fiber = self.fiber.to(self.dim_head * h)
        kv_fiber = self.fiber.to(self.dim_head * kv_h)
        project_out = not (h == 1 and len(self.fiber.dims) == 1
                           and self.dim_head == self.fiber.dims[0])

        assert not (self.linear_proj_keys and self.tie_key_values), \
            'cannot do linear projection of keys and tied key/values together'

        conv_kwargs = dict(
            pool=False, self_interaction=False,
            edge_dim=self.edge_dim or 0,
            fourier_encode_dist=self.fourier_encode_dist,
            num_fourier_features=self.rel_dist_num_fourier_features,
            pallas=self.pallas,
            shared_radial_hidden=self.shared_radial_hidden,
            edge_chunks=self.edge_chunks,
            fuse_basis=self.fuse_basis,
            radial_bf16=self.radial_bf16,
            conv_bf16=self.conv_bf16,
            pallas_interpret=self.pallas_interpret)

        # named scopes ('attn_qkv' projections, 'attn_core' per-degree
        # sim/softmax/sum) keep xprof traces attributable; the whole call
        # additionally sits under the block's 'attention' scope
        with named_scope('attn_qkv'):
            queries = LinearSE3(self.fiber, hidden_fiber,
                                name='to_q')(features)
            values = ConvSE3(self.fiber, kv_fiber, name='to_v',
                             backend=self.backend_v, **conv_kwargs)(
                features, edge_info, rel_dist, basis)

            if self.linear_proj_keys:
                keys = LinearSE3(self.fiber, kv_fiber, name='to_k')(features)
                keys = {d: exchange_index_select(v, neighbor_indices, axis=1)
                        for d, v in keys.items()}
            elif self.tie_key_values:
                keys = values
            else:
                keys = ConvSE3(self.fiber, kv_fiber, name='to_k',
                               backend=self.backend_k, **conv_kwargs)(
                    features, edge_info, rel_dist, basis)

            if self.attend_self:
                self_keys = LinearSE3(self.fiber, kv_fiber,
                                      name='to_self_k')(features)
                self_values = LinearSE3(self.fiber, kv_fiber,
                                        name='to_self_v')(features)

            if global_feats is not None:
                g_in = Fiber.create(1, self.global_feats_dim)
                g_out = Fiber.create(1, self.dim_head * kv_h)
                global_keys = LinearSE3(g_in, g_out,
                                        name='to_global_k')(global_feats)
                global_values = LinearSE3(g_in, g_out,
                                          name='to_global_v')(global_feats)

        outputs = {}
        for degree in features.keys():
            m = to_order(int(degree))
            q, k, v = queries[degree], keys[degree], values[degree]
            b, n = q.shape[0], q.shape[1]

            # split heads: q [b, h, n, d, m]; k/v [b, kv_h, n, j, d, m]
            q = q.reshape(b, n, h, self.dim_head, m).transpose(0, 2, 1, 3, 4)
            k, v = [t.reshape(b, n, t.shape[2], kv_h, self.dim_head, m)
                    .transpose(0, 3, 1, 2, 4, 5) for t in (k, v)]

            if self.attend_self:
                s_k, s_v = self_keys[degree], self_values[degree]
                s_k, s_v = [t.reshape(b, n, kv_h, self.dim_head, m)
                            .transpose(0, 2, 1, 3, 4)[:, :, :, None]
                            for t in (s_k, s_v)]
                k = jnp.concatenate((s_k, k), axis=3)
                v = jnp.concatenate((s_v, v), axis=3)

            if pos_emb is not None and degree == '0':
                query_pos_emb, key_pos_emb = pos_emb
                q = apply_rotary_pos_emb(q, query_pos_emb[:, None, :, :])
                k = apply_rotary_pos_emb(k, key_pos_emb[:, None])
                v = apply_rotary_pos_emb(v, key_pos_emb[:, None])

            if self.use_null_kv:
                null_k = self.param(f'null_k{degree}', nn.initializers.zeros,
                                    (kv_h, self.dim_head, m), q.dtype)
                null_v = self.param(f'null_v{degree}', nn.initializers.zeros,
                                    (kv_h, self.dim_head, m), q.dtype)
                null_k, null_v = [
                    jnp.broadcast_to(t[None, :, None, None],
                                     (b, kv_h, n, 1, self.dim_head, m))
                    for t in (null_k, null_v)]
                k = jnp.concatenate((null_k, k), axis=3)
                v = jnp.concatenate((null_v, v), axis=3)

            if global_feats is not None and degree == '0':
                g_k, g_v = global_keys['0'], global_values['0']
                num_g = g_k.shape[1]
                g_k, g_v = [t.reshape(b, num_g, kv_h, self.dim_head, m)
                            .transpose(0, 2, 1, 3, 4)[:, :, None]
                            for t in (g_k, g_v)]
                g_k, g_v = [jnp.broadcast_to(
                    t, (b, kv_h, n, num_g, self.dim_head, m))
                    for t in (g_k, g_v)]
                k = jnp.concatenate((g_k, k), axis=3)
                v = jnp.concatenate((g_v, v), axis=3)

            scale = self.dim_head ** -0.5
            J = k.shape[3]

            padded_mask = None
            if neighbor_mask is not None:
                num_left_pad = J - neighbor_mask.shape[-1]
                padded_mask = jnp.pad(neighbor_mask,
                                      ((0, 0), (0, 0), (num_left_pad, 0)),
                                      constant_values=True)

            # auto-dispatch default: XLA. Measured on a v5e (round 3,
            # tpu_checks) at the flagship-relevant J=33: 0.90x vs XLA
            # in one session, 1.05x in another after the gather fix —
            # within session noise, and the kernel's D-on-lanes layout
            # pads small dim_head*m to 128 lanes, wasting VPU work.
            # Attention is <1% of the flagship step, so the conservative
            # default wins; the kernel stays available via
            # pallas_attention=True.
            use_fused = self.pallas_attention if self.pallas_attention \
                is not None else False
            from ..kernels.pallas_attention import fused_attention_fits
            if use_fused and not self.pallas_attention_interpret \
                    and not fused_attention_fits(J, self.dim_head * m):
                # a too-large slot axis (e.g. num_neighbors~512 at a wide
                # dim_head) must fall back to the XLA path, not surface a
                # Mosaic scoped-VMEM error (VERDICT r2 weak #4)
                if self.pallas_attention:  # explicit opt-in: say so —
                    # silently measuring XLA as "fused" corrupts benchmarks
                    import warnings
                    warnings.warn(
                        f'pallas_attention=True but the fused kernel '
                        f'working set (J={J}, D={self.dim_head * m}) '
                        f'exceeds the scoped-VMEM budget at any block '
                        f'size; using the XLA path', stacklevel=2)
                use_fused = False
            if use_fused or self.pallas_attention_interpret:
                from ..kernels.pallas_attention import fused_attention
                # flatten (dim_head, m) into one joint feature axis (the
                # logits reduce over both) and fold heads into batch
                q2 = q.reshape(b * h, n, self.dim_head * m)
                k2, v2 = [t.reshape(b * kv_h, n, J, self.dim_head * m)
                          for t in (k, v)]
                out = fused_attention(q2, k2, v2, padded_mask, h, scale,
                                      self.pallas_attention_interpret)
                out = out.reshape(b, h, n, self.dim_head, m)
            else:
                with named_scope('attn_core'):
                    if one_headed:
                        sim = jnp.einsum('bhidm,bijdm->bhij',
                                         q, k[:, 0]) * scale
                    else:
                        sim = jnp.einsum('bhidm,bhijdm->bhij', q, k) * scale
                    if padded_mask is not None:
                        sim = jnp.where(padded_mask[:, None], sim,
                                        jnp.finfo(sim.dtype).min)
                    attn = nn.softmax(sim, axis=-1)
                    if one_headed:
                        out = jnp.einsum('bhij,bijdm->bhidm', attn, v[:, 0])
                    else:
                        out = jnp.einsum('bhij,bhijdm->bhidm', attn, v)
            outputs[degree] = out.transpose(0, 2, 1, 3, 4).reshape(
                b, n, h * self.dim_head, m)

        if project_out:
            outputs = LinearSE3(hidden_fiber, self.fiber,
                                name='to_out')(outputs)
        return outputs

    def _flash_call(self, features: Features, edge_info: EdgeInfo,
                    rel_dist: jnp.ndarray, basis: Dict[str, jnp.ndarray],
                    global_feats: Optional[Features],
                    pos_emb) -> Features:
        """The streaming-kernel path: same parameters, same function as
        the unfused path above (parity-gated in tests/test_flash.py and
        `make flash-smoke`) — but the per-edge basis, the
        gathered/keyed features, and the score tensor are built per
        VMEM tile inside kernels.pallas_flash instead of in HBM."""
        from ..kernels.pallas_flash import flash_attention

        h = self.heads
        kv_h = self.kv_heads if self.kv_heads is not None else self.heads
        assert pos_emb is None, \
            'fuse_pairwise does not support rotary embeddings (they ' \
            'rewrite k/v per slot before the null/global prepends)'
        assert not self.linear_proj_keys, \
            'fuse_pairwise needs conv keys (linear_proj_keys gathers ' \
            'node-projected keys instead)'
        assert not self.conv_bf16, \
            'fuse_pairwise does not apply conv_bf16 (there is no ' \
            'materialized V2/basis/gathered operand to store bf16 — ' \
            'the knob would silently do nothing on this path)'
        neighbor_indices, neighbor_mask, _ = edge_info

        hidden_fiber = self.fiber.to(self.dim_head * h)
        kv_fiber = self.fiber.to(self.dim_head * kv_h)
        project_out = not (h == 1 and len(self.fiber.dims) == 1
                           and self.dim_head == self.fiber.dims[0])

        conv_kwargs = dict(
            pool=False, self_interaction=False,
            edge_dim=self.edge_dim or 0,
            fourier_encode_dist=self.fourier_encode_dist,
            num_fourier_features=self.rel_dist_num_fourier_features,
            shared_radial_hidden=True, fuse_pairwise=True,
            radial_bf16=self.radial_bf16)

        with named_scope('attn_qkv'):
            queries = LinearSE3(self.fiber, hidden_fiber,
                                name='to_q')(features)
            v_prog = ConvSE3(self.fiber, kv_fiber, name='to_v',
                             backend=self.backend_v, **conv_kwargs)(
                features, edge_info, rel_dist, basis)
            k_prog = None
            if not self.tie_key_values:
                k_prog = ConvSE3(self.fiber, kv_fiber, name='to_k',
                                 backend=self.backend_k, **conv_kwargs)(
                    features, edge_info, rel_dist, basis)
            if self.attend_self:
                self_keys = LinearSE3(self.fiber, kv_fiber,
                                      name='to_self_k')(features)
                self_values = LinearSE3(self.fiber, kv_fiber,
                                        name='to_self_v')(features)
            if global_feats is not None:
                g_in = Fiber.create(1, self.global_feats_dim)
                g_out = Fiber.create(1, self.dim_head * kv_h)
                global_keys = LinearSE3(g_in, g_out,
                                        name='to_global_k')(global_feats)
                global_values = LinearSE3(g_in, g_out,
                                          name='to_global_v')(global_feats)

        sh = basis.get('flash_sh')
        frames = basis.get('so2')
        outputs = {}
        for degree in features.keys():
            m = to_order(int(degree))
            Dh = self.dim_head * m
            b, n = features[degree].shape[:2]
            q = queries[degree].reshape(b, n, h, Dh)

            prefix_k, prefix_v = self._prefix_slots(
                degree, b, n, kv_h, Dh, q.dtype,
                global_keys if global_feats is not None else None,
                global_values if global_feats is not None else None,
                self_keys if self.attend_self else None,
                self_values if self.attend_self else None)

            xs = tuple(features[str(d_in)]
                       for d_in, _ in v_prog['pairs'])
            # quantized serving (quant.QuantTensor grouped weights):
            # split storage/scale so the int8 weight rides into the
            # kernel as-is and the scale dequants in-tile
            from ..quant.qtensor import weight_or_none
            wv, wv_scale = weight_or_none(v_prog['w3'][degree])
            kwargs = dict(sh=sh, frames=frames,
                          prefix_k=prefix_k, prefix_v=prefix_v,
                          wv_scale=wv_scale,
                          pallas=self.pallas,
                          interpret=self.flash_interpret)
            if k_prog is not None:
                wk, wk_scale = weight_or_none(k_prog['w3'][degree])
                kwargs.update(h_k=k_prog['h'], wk=wk, wk_scale=wk_scale,
                              bk=k_prog['b3'][degree],
                              arm_k=k_prog['arm'])
            out = flash_attention(
                q, xs, neighbor_indices, neighbor_mask, v_prog['h'],
                wv, v_prog['b3'][degree],
                pairs=v_prog['pairs'], d_out=int(degree), heads=h,
                kv_heads=kv_h, scale=self.dim_head ** -0.5,
                arm_v=v_prog['arm'], **kwargs)
            outputs[degree] = out.reshape(b, n, h * self.dim_head, m)

        if project_out:
            outputs = LinearSE3(hidden_fiber, self.fiber,
                                name='to_out')(outputs)
        return outputs

    def _prefix_slots(self, degree: str, b: int, n: int, kv_h: int,
                      Dh: int, dtype, global_keys, global_values,
                      self_keys, self_values):
        """The always-valid kv slots left of the neighbor/pair axis, in
        the unfused concat order [global, null, self] (the unfused mask
        left-pads True over them). Shared by the kNN flash path and the
        global path so the slot semantics — and the null_k/null_v param
        names — cannot drift apart."""
        m = to_order(int(degree))
        pre_k, pre_v = [], []
        if global_keys is not None and degree == '0':
            g_k, g_v = global_keys['0'], global_values['0']
            num_g = g_k.shape[1]
            for t, dst in ((g_k, pre_k), (g_v, pre_v)):
                t = t.reshape(b, num_g, kv_h * Dh)[:, None]
                dst.append(jnp.broadcast_to(
                    t, (b, n, num_g, kv_h * Dh)))
        if self.use_null_kv:
            null_k = self.param(f'null_k{degree}', nn.initializers.zeros,
                                (kv_h, self.dim_head, m), dtype)
            null_v = self.param(f'null_v{degree}', nn.initializers.zeros,
                                (kv_h, self.dim_head, m), dtype)
            for t, dst in ((null_k, pre_k), (null_v, pre_v)):
                dst.append(jnp.broadcast_to(
                    t.reshape(1, 1, 1, kv_h * Dh),
                    (b, n, 1, kv_h * Dh)))
        if self_keys is not None:
            for t, dst in ((self_keys[degree], pre_k),
                           (self_values[degree], pre_v)):
                dst.append(t.reshape(b, n, 1, kv_h * Dh))
        prefix_k = jnp.concatenate(pre_k, axis=2) if pre_k else None
        prefix_v = jnp.concatenate(pre_v, axis=2) if pre_v else None
        return prefix_k, prefix_v

    def _global_call(self, features: Features,
                     basis: Dict[str, jnp.ndarray],
                     global_feats: Optional[Features]) -> Features:
        """The kNN-free path (see the attention_mode field comment):
        same parameters as the fused kNN path — LinearSE3 'to_q',
        ConvSE3 'to_v'/'to_k' in global_radial program mode exporting
        the radial trunk + grouped w3/b3 raw, the same prefix slots —
        but no edge_info, no rel_dist, no basis tensors: the kernel
        rebuilds the pair payload from coordinates per tile."""
        from ..kernels.pallas_flash import (flash_global_attention,
                                            flash_global_attention_sharded)
        from ..parallel.exchange import active_exchange
        from ..quant.qtensor import QuantTensor

        h = self.heads
        kv_h = self.kv_heads if self.kv_heads is not None else self.heads
        assert not self.linear_proj_keys, \
            'global attention needs conv keys (linear_proj_keys gathers ' \
            'node-projected keys, which presumes a neighbor list)'
        assert not self.fourier_encode_dist and not (self.edge_dim or 0), \
            'global attention consumes raw distances only (no ' \
            'fourier/edge features — the kernel rebuilds distances ' \
            'from coordinates per tile)'
        assert not self.conv_bf16, \
            'global attention has no materialized conv operand to ' \
            'store bf16'
        coords = basis['global_coords']
        node_mask = basis.get('global_mask')

        hidden_fiber = self.fiber.to(self.dim_head * h)
        kv_fiber = self.fiber.to(self.dim_head * kv_h)
        project_out = not (h == 1 and len(self.fiber.dims) == 1
                           and self.dim_head == self.fiber.dims[0])

        conv_kwargs = dict(
            pool=False, self_interaction=False,
            shared_radial_hidden=True, fuse_pairwise=True,
            global_radial=True, radial_bf16=self.radial_bf16)
        no_edges = (None, None, None)

        with named_scope('attn_qkv'):
            queries = LinearSE3(self.fiber, hidden_fiber,
                                name='to_q')(features)
            v_prog = ConvSE3(self.fiber, kv_fiber, name='to_v',
                             backend=self.backend_v, **conv_kwargs)(
                features, no_edges, None, basis)
            k_prog = None
            if not self.tie_key_values:
                k_prog = ConvSE3(self.fiber, kv_fiber, name='to_k',
                                 backend=self.backend_k, **conv_kwargs)(
                    features, no_edges, None, basis)
            self_keys = self_values = None
            if self.attend_self:
                self_keys = LinearSE3(self.fiber, kv_fiber,
                                      name='to_self_k')(features)
                self_values = LinearSE3(self.fiber, kv_fiber,
                                        name='to_self_v')(features)
            global_keys = global_values = None
            if global_feats is not None:
                g_in = Fiber.create(1, self.global_feats_dim)
                g_out = Fiber.create(1, self.dim_head * kv_h)
                global_keys = LinearSE3(g_in, g_out,
                                        name='to_global_k')(global_feats)
                global_values = LinearSE3(g_in, g_out,
                                          name='to_global_v')(global_feats)

        def dq(w):
            # the global kernel takes fp weights (no in-tile dequant
            # epilogue on this path yet); a quantized checkpoint serves
            # via a transient dequant
            return w.dequant() if isinstance(w, QuantTensor) else w

        ex = active_exchange()
        outputs = {}
        for degree in features.keys():
            m = to_order(int(degree))
            Dh = self.dim_head * m
            b, n = features[degree].shape[:2]
            q = queries[degree].reshape(b, n, h, Dh)

            prefix_k, prefix_v = self._prefix_slots(
                degree, b, n, kv_h, Dh, q.dtype,
                global_keys, global_values, self_keys, self_values)

            xs = tuple(features[str(d_in)] for d_in, _ in v_prog['pairs'])
            kwargs = dict(
                pairs=v_prog['pairs'], d_out=int(degree), heads=h,
                kv_heads=kv_h, scale=self.dim_head ** -0.5,
                arm=v_prog['arm'], node_mask=node_mask,
                prefix_k=prefix_k, prefix_v=prefix_v,
                exclude_self=True)
            if k_prog is not None:
                kwargs.update(rp_k=k_prog['rp'], wk=dq(k_prog['w3'][degree]),
                              bk=k_prog['b3'][degree])
            args = (q, xs, coords, v_prog['rp'], dq(v_prog['w3'][degree]),
                    v_prog['b3'][degree])
            if ex is not None and not self.global_materialize:
                # sequence-parallel composition: the ring exchange scope
                # is LIVE on this path (the PR 11 residue — the kNN
                # flash gather bypassed it); queries stay pinned, the
                # kv side rotates via ppermute only
                out = flash_global_attention_sharded(
                    *args, mesh=ex.mesh, axis_name=ex.axis_name,
                    overlap=ex.overlap, **kwargs)
            else:
                out = flash_global_attention(
                    *args, pallas=self.pallas,
                    interpret=self.flash_interpret,
                    materialize=self.global_materialize, **kwargs)
            outputs[degree] = out.reshape(b, n, h * self.dim_head, m)

        if project_out:
            outputs = LinearSE3(hidden_fiber, self.fiber,
                                name='to_out')(outputs)
        return outputs


class OneHeadedKVAttentionSE3(AttentionSE3):
    """Shazeer multi-query attention: one k/v head shared across all query
    heads (reference :522-654)."""
    kv_heads: Optional[int] = 1


class AttentionBlockSE3(nn.Module):
    """Prenorm + attention + residual (reference :656-683)."""
    fiber: Fiber
    dim_head: int = 24
    heads: int = 8
    attend_self: bool = False
    edge_dim: Optional[int] = None
    use_null_kv: bool = False
    fourier_encode_dist: bool = False
    rel_dist_num_fourier_features: int = 4
    global_feats_dim: Optional[int] = None
    linear_proj_keys: bool = False
    tie_key_values: bool = False
    one_headed_key_values: bool = False
    norm_gated_scale: bool = False
    pallas: Optional[bool] = None
    pallas_attention: Optional[bool] = None
    pallas_attention_interpret: bool = False
    shared_radial_hidden: bool = False
    edge_chunks: Optional[int] = None
    fuse_basis: bool = False
    pallas_interpret: bool = False
    radial_bf16: bool = False
    conv_bf16: bool = False
    backend_v: str = 'dense'
    backend_k: str = 'dense'
    fuse_pairwise: bool = False
    flash_interpret: bool = False
    attention_mode: str = 'knn'
    global_materialize: bool = False

    @nn.compact
    def __call__(self, features: Features, edge_info: EdgeInfo,
                 rel_dist: jnp.ndarray, basis: Dict[str, jnp.ndarray],
                 global_feats: Optional[Features] = None,
                 pos_emb=None, mask=None) -> Features:
        res = features
        out = NormSE3(self.fiber, gated_scale=self.norm_gated_scale,
                      name='prenorm')(features)
        with named_scope('attention'):
            out = AttentionSE3(
                self.fiber, heads=self.heads, dim_head=self.dim_head,
                kv_heads=1 if self.one_headed_key_values else None,
                backend_v=self.backend_v, backend_k=self.backend_k,
                attend_self=self.attend_self, edge_dim=self.edge_dim,
                use_null_kv=self.use_null_kv,
                fourier_encode_dist=self.fourier_encode_dist,
                rel_dist_num_fourier_features=(
                    self.rel_dist_num_fourier_features),
                global_feats_dim=self.global_feats_dim,
                linear_proj_keys=self.linear_proj_keys,
                tie_key_values=self.tie_key_values,
                pallas=self.pallas,
                pallas_attention=self.pallas_attention,
                pallas_attention_interpret=self.pallas_attention_interpret,
                shared_radial_hidden=(self.shared_radial_hidden
                                      or self.fuse_pairwise),
                edge_chunks=self.edge_chunks,
                fuse_basis=self.fuse_basis,
                radial_bf16=self.radial_bf16,
                conv_bf16=self.conv_bf16,
                pallas_interpret=self.pallas_interpret,
                fuse_pairwise=self.fuse_pairwise,
                flash_interpret=self.flash_interpret,
                attention_mode=self.attention_mode,
                global_materialize=self.global_materialize,
                name='attn')(out, edge_info, rel_dist, basis, global_feats,
                             pos_emb, mask)
        return residual_se3(out, res)
