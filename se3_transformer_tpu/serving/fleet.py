"""Cross-host serving control plane: a fleet of per-host routers.

PR 8/12 finished the single-host story — each process owns its
replicas, so one host preemption is a total outage. This module is the
tier above (ROADMAP item 5): each **host** (one process running a
`Router` over its replicas) becomes a FAULT DOMAIN behind a minimal
RPC surface, and a `FleetRouter` front-end routes across them:

  * `HostServer` — wraps one host's `Router` behind five JSON-safe RPC
    methods (`ping` / `stats` / `infer` / `swap` / `drain`). A single
    serve-loop thread owns ALL router interactions (submit, pump,
    deadline flushes, swaps) — RPC threads only enqueue and wait — so
    the PR 8 router stays single-threaded exactly as its tests pin it.
    `stats` is the routing signal: per-bucket depth, cumulative p99,
    precision mixes, retry/failure counters, post-warmup compiles —
    scraped straight off the existing `Router`/`RouterTelemetry`
    surfaces, no second bookkeeping.

  * `FleetRouter` — the front-end. The PR 12 breaker state machine
    (`serving.health.HealthMonitor`) lifted one level: one breaker per
    HOST, driven by RPC outcomes and heartbeat staleness (healthy ->
    degraded -> quarantined; recovery via exponential-backoff half-open
    `ping` probes from `pump()` — a SIGKILLed host that restarts on its
    port closes its breaker through probe traffic, no operator).
    Placement is health-aware least-loaded over the scraped signals
    (fleet-side in-flight RPCs + host-reported queue depth, degraded
    after healthy, p99 tie-break). Failed RPCs redispatch CROSS-HOST
    (bounded by `max_retries`, excluding the host that just failed) and
    deadlines propagate as remaining budget in the payload; after the
    budget, requests resolve through the `_fail_request` choke point —
    the same zero-lost contract the single-host router carries, now
    fleet-wide (and the same weaken surface: the fleet-chaos smoke
    nulls `host_exclusion` to prove the gate fires).

  * **Canaried rollout** — `rollout(new_ref, rollback_ref, traffic)`
    reuses the hosts' drain/swap contract: swap ONE canary host, drive
    pinned probe traffic through it, gate on its serve evidence
    (every probe answered, zero lost, latency within budget, zero new
    host-side structured failures), then roll the rest — or AUTO
    ROLL-BACK the canary to `rollback_ref` and leave the fleet on the
    old weights. Every decision lands in `rollout_events` (the `fleet`
    record's rollout evidence).

The whole tier is telemetry-first: `record_body()` assembles the new
schema'd `fleet` record — per-host breaker snapshots + scraped stats,
host transitions, cross-host retries, rollout/rollback events,
heartbeat accounting, and the load-bearing fleet-wide `lost_requests`.
`make serve-fleet-smoke` (scripts/fleet_chaos_smoke.py) gates it.
"""
from __future__ import annotations

import queue
import threading
import time
import warnings
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..inference.admission import (
    RequestFailed, RequestRejected, deadline_error, fit_bucket,
    oversize_error, retries_exhausted_error,
)
from ..inference.batching import PendingResult
from ..observability.tracing import CONTROL_KIND, Tracer
from .health import QUARANTINED, HealthConfig, HealthMonitor
from .router import Router
from .transport import TransportError

__all__ = ['HostServer', 'FleetRouter']

# host error codes the fleet treats as a HOST failure (redispatch +
# breaker) rather than a request verdict: the host's own retry budget
# spending means its replicas are failing; 'internal'/'host_timeout'
# mean the host process itself is sick
_HOST_FAILURE_CODES = ('retries_exhausted', 'internal', 'host_timeout')


class _Call:
    __slots__ = ('method', 'payload', 'event', 'response', 'on_done')

    def __init__(self, method: str, payload: dict,
                 on_done: Optional[Callable] = None):
        self.method = method
        self.payload = payload
        self.event = threading.Event()
        self.response: Optional[dict] = None
        self.on_done = on_done

    def respond(self, response: dict):
        self.response = response
        self.event.set()
        if self.on_done is not None:
            try:
                self.on_done(response)
            except Exception as e:  # a buggy completion callback must
                #                     not take the serve loop with it
                warnings.warn(f'{self.method!r} completion callback '
                              f'raised {type(e).__name__}: {e}',
                              RuntimeWarning)


class HostServer:
    """One host's RPC surface over its `Router`.

        server = HostServer(router, host_id=0, telemetry=tele)
        server.handle('infer', dict(tokens=[...], coords=[...]))
        server.handle('swap', dict(directory=ckpt_dir, step=None))
        server.stop()        # drains the router, joins the loop

    A dedicated serve-loop thread owns the router: it dequeues calls,
    submits infers, pumps (deadline flushes, retries, probes), resolves
    watched requests, and periodically flushes the telemetry — RPC
    threads never touch router state. `handle` is therefore safe from
    any number of transport threads.

    `on_swap(payload, events)` is an optional hook invoked (on the loop
    thread) after a completed swap — the chaos harness uses it to arm a
    deterministic poison against the step the canary just restored.
    """

    METHODS = ('ping', 'stats', 'infer', 'swap', 'drain')

    def __init__(self, router: Router, host_id: int = 0, *,
                 telemetry=None, clock: Callable[[], float] = time.monotonic,
                 default_timeout_s: float = 30.0,
                 flush_every_batches: int = 8,
                 on_swap: Optional[Callable] = None):
        self.router = router
        self.host_id = int(host_id)
        self.telemetry = telemetry
        self.clock = clock
        self.default_timeout_s = float(default_timeout_s)
        self.flush_every_batches = int(flush_every_batches)
        self.on_swap = on_swap
        # host-side request tracing: spans are only recorded for
        # requests whose RPC payload carries a trace context, so an
        # untraced fleet pays nothing. The router's id namespace gets
        # the host prefix here too — per-router monotonic ints collide
        # across hosts once record streams merge.
        self.tracer = Tracer(origin=f'host{self.host_id}',
                             host=self.host_id, clock=clock)
        router.attach_tracer(self.tracer)
        if router.id_prefix is None:
            router.id_prefix = f'h{self.host_id}'
        self.started_at = clock()
        self.calls: Dict[str, int] = {m: 0 for m in self.METHODS}
        # handle() runs on arbitrary transport threads (one per socket
        # connection) — the per-method counters need their own lock
        self._calls_lock = threading.Lock()
        self._pump_errors_seen: set = set()
        self._inbox: 'queue.Queue[_Call]' = queue.Queue()
        self._stop = threading.Event()
        self._flushed_at_batches = 0
        self._thread = threading.Thread(
            target=self._loop, name=f'host{self.host_id}-serve',
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------ #
    def handle(self, method: str, payload: Optional[dict] = None,
               timeout_s: Optional[float] = None) -> dict:
        """Transport entry (any thread): enqueue onto the serve loop,
        wait for the response. The wait is bounded by the request's own
        timeout budget plus slack — a wedged loop answers
        `host_timeout`, which the fleet counts as a host failure."""
        if method not in self.METHODS:
            return dict(ok=False, error=dict(
                code='unknown_method',
                message=f'{method!r} not in {self.METHODS}'))
        with self._calls_lock:
            self.calls[method] = self.calls.get(method, 0) + 1
        if method == 'ping':
            # fast path off the serve loop: ping answers PROCESS
            # liveness, so a half-open probe can close the breaker even
            # while the loop is inside a long synchronous dispatch —
            # traffic then re-judges the host on real outcomes
            now = self.clock()
            return dict(ok=True, host=self.host_id, t=round(now, 4),
                        uptime_s=round(now - self.started_at, 3))
        call = _Call(method, dict(payload or {}))
        self._inbox.put(call)
        budget = timeout_s
        if budget is None:
            budget = call.payload.get('timeout_s')
        wait = (float(budget) if budget is not None
                else self.default_timeout_s) + 5.0
        if not call.event.wait(timeout=max(0.05, wait)):
            return dict(ok=False, error=dict(
                code='host_timeout',
                message=f'{method!r} timed out after {wait:.1f}s inside '
                        f'host {self.host_id}\'s serve loop'))
        return call.response

    def handle_async(self, method: str, payload: Optional[dict] = None,
                     reply: Optional[Callable] = None,
                     timeout_s: Optional[float] = None) -> None:
        """Transport entry, callback form (any thread): enqueue onto
        the serve loop and return immediately — `reply(response)`
        fires exactly once when the response is ready (from the serve
        loop thread for watched infers; the binary frame-pump server
        hands it straight to its writer pool, so the loop never blocks
        on a slow client socket). The binary server rides this so a
        slow infer never parks one of its pump threads and in-flight
        depth stays bounded by admission control, not the pool size.

        Unlike the blocking `handle`, a WEDGED serve loop here answers
        nothing — the caller's own transport deadline raises
        `TransportError`, which the fleet counts as the same host
        failure `host_timeout` maps to."""
        if method not in self.METHODS:
            reply(dict(ok=False, error=dict(
                code='unknown_method',
                message=f'{method!r} not in {self.METHODS}')))
            return
        with self._calls_lock:
            self.calls[method] = self.calls.get(method, 0) + 1
        if method == 'ping':
            # same fast path off the serve loop as `handle`: probes
            # answer PROCESS liveness even mid-dispatch
            now = self.clock()
            reply(dict(ok=True, host=self.host_id, t=round(now, 4),
                       uptime_s=round(now - self.started_at, 3)))
            return
        self._inbox.put(_Call(method, dict(payload or {}),
                              on_done=reply))

    def stop(self, drain: bool = True):
        """End the serve loop (then drain the router by default, so
        everything already admitted answers — the graceful-shutdown
        path `scripts/serve.py --host` walks on SIGTERM)."""
        self._stop.set()
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():
            # the loop is wedged (a watched request behind a stuck
            # runner) and STILL OWNS the router — draining from this
            # thread would break the single-owner invariant and mutate
            # batcher/retry state concurrently. Loud skip instead.
            warnings.warn(
                f'host {self.host_id}: serve loop did not exit within '
                f'30s of stop() — skipping the router drain (the loop '
                f'thread still owns the router)', RuntimeWarning)
            return
        if drain:
            self.router.drain()

    # ------------------------------------------------------------------ #
    # the serve loop: the ONLY thread that touches the router
    # ------------------------------------------------------------------ #
    def _loop(self):
        watched: List[Tuple[PendingResult, _Call]] = []
        while not (self._stop.is_set() and self._inbox.empty()
                   and not watched):
            try:
                call = self._inbox.get(timeout=0.002)
            except queue.Empty:
                call = None
            if call is not None:
                try:
                    self._handle_call(call, watched)
                except Exception as e:
                    # NO handler exception may kill this thread: a dead
                    # loop wedges every future RPC into host_timeout
                    # and the host can never rejoin the fleet. Answer
                    # structurally — an alive host saying "no" is the
                    # whole transport contract.
                    call.respond(dict(ok=False, error=dict(
                        code='internal',
                        message=f'{call.method!r} handler raised '
                                f'{type(e).__name__}: {e}')))
            try:
                self.router.pump()
            except Exception as e:
                # a raising sync runner without failure hooks lands
                # here (its requests already resolved done-with-error
                # inside dispatch_batch) — but a PERSISTENT pump bug
                # would too, re-raising every iteration. Warn once per
                # distinct error so a wedged host leaves evidence
                # instead of silence.
                key = f'{type(e).__name__}: {e}'
                if key not in self._pump_errors_seen:
                    self._pump_errors_seen.add(key)
                    warnings.warn(
                        f'host {self.host_id}: router.pump raised '
                        f'{key} (warned once; the serve loop '
                        f'continues)', RuntimeWarning)
            if watched:
                done = [(p, c) for p, c in watched if p.done]
                if done:
                    watched[:] = [(p, c) for p, c in watched if not p.done]
                    for p, c in done:
                        c.respond(self._infer_response(p))
            try:
                self._maybe_flush()
            except Exception as e:   # a failing telemetry bank (disk
                #                      full, rotated file) must not
                #                      take the serve loop with it
                warnings.warn(f'host {self.host_id}: telemetry flush '
                              f'failed: {type(e).__name__}: {e}',
                              RuntimeWarning)

    def _handle_call(self, call: _Call, watched: list):
        method, payload = call.method, call.payload
        now = self.clock()
        if method == 'stats':
            call.respond(dict(ok=True, stats=self._stats_body(now)))
        elif method == 'drain':
            call.respond(dict(ok=True, batches=self.router.drain()))
        elif method == 'swap':
            try:
                events = self.router.swap_from_checkpoint(
                    payload['directory'], payload.get('step'))
                if self.on_swap is not None:
                    self.on_swap(payload, events)
                call.respond(dict(ok=True, events=events,
                                  tag=events[0]['tag'] if events else None))
            except Exception as e:
                call.respond(dict(ok=False, error=dict(
                    code='internal',
                    message=f'swap failed: {type(e).__name__}: {e}')))
        elif method == 'infer':
            try:
                tokens = np.asarray(payload['tokens'])
                coords = np.asarray(payload['coords'],
                                    np.float32).reshape(-1, 3)
                pending = self.router.submit(
                    tokens, coords, timeout_s=payload.get('timeout_s'),
                    trace=payload.get('trace'))
            except RequestRejected as e:
                call.respond(dict(ok=False, error=dict(
                    code=e.code, message=str(e), detail=e.detail)))
                return
            except Exception as e:
                call.respond(dict(ok=False, error=dict(
                    code='internal',
                    message=f'{type(e).__name__}: {e}')))
                return
            watched.append((pending, call))

    def _infer_response(self, p: PendingResult) -> dict:
        if p.ok:
            # the result stays a numpy array — LocalTransport hands the
            # buffer through untouched and the binary framing ships it
            # raw; only the legacy JSON wire degrades it to lists (its
            # server's json.dumps default= hook), so the old tolist()
            # copy tax is paid exactly where a text wire demands it
            resp = dict(ok=True,
                        result=np.asarray(p.result),
                        latency_ms=round((p.latency_s or 0.0) * 1e3, 3))
        else:
            err = p.error
            if isinstance(err, (RequestFailed, RequestRejected)):
                resp = dict(ok=False, error=dict(
                    code=err.code, message=str(err), detail=err.detail))
            else:
                resp = dict(ok=False, error=dict(
                    code='internal',
                    message=f'{type(err).__name__}: {err}'))
        tr = getattr(p, 'trace', None)
        if tr:
            # ship the request's host-side spans back to the fleet
            # front-end (error verdicts carry their story too); popping
            # keeps the host tracer bounded by what is still in flight
            spans = self.tracer.pop_trace(tr['ctx'])
            if spans:
                resp['spans'] = spans
        return resp

    def _stats_body(self, now: float) -> dict:
        """The per-host routing signal, scraped off the surfaces that
        already exist (router counters, the shared PhaseTimer's
        cumulative per-bucket p99, RouterTelemetry's compile verdict) —
        the fleet routes on THESE, so they must be the same numbers the
        serve records carry."""
        r = self.router
        cum = r.workers[0].engine.timer.cumulative_summary()
        p99 = {phase[len('bucket_'):]: st.get('p99_ms')
               for phase, st in cum.items() if phase.startswith('bucket_')}
        post_warmup = None
        slo = None
        if self.telemetry is not None:
            self.telemetry._check_runtime()     # fold in compile deltas
            post_warmup = self.telemetry.post_warmup_compiles
            # mergeable per-bucket latency histograms + cumulative
            # answered/failed: the fleet's SLOAggregator folds these,
            # so fleet percentiles are EXACT merges, never averaged
            slo = self.telemetry.slo_snapshot()
        body = dict(
            host=self.host_id, t=round(now, 4),
            buckets=list(r.buckets),
            queue_depth=r.queue_depth,
            depth_by_bucket={str(b): d
                             for b, d in r.depth_by_bucket.items()},
            p99_ms_by_bucket=p99,
            precision_mixes=sorted({
                getattr(w.engine, 'precision_name', 'fp32')
                for w in r.workers}),
            model_families=sorted({
                getattr(w.engine, 'model_family', 'se3_v1')
                for w in r.workers}),
            served=sum(w.served_rows for w in r.workers),
            batches=r.batches_dispatched,
            retries=r.retries,
            request_failures=r.request_failures,
            timeouts=r.timeouts,
            deadline_sheds=r.deadline_sheds,
            swaps=len(r.swap_events),
            health=r.health.snapshot(),
            post_warmup_compiles=post_warmup,
        )
        if slo is not None:
            body.update(slo)
        return body

    def _maybe_flush(self):
        if self.telemetry is None:
            return
        batches = self.router.batches_dispatched
        if batches - self._flushed_at_batches >= self.flush_every_batches:
            self._flushed_at_batches = batches
            self.telemetry.flush()


class _HostHandle:
    """Fleet-side view of one host: its transport plus the scraped
    signal cache and in-flight accounting (mutated under the fleet's
    lock)."""

    def __init__(self, host_id: int, transport):
        self.id = int(host_id)
        self.transport = transport
        self.outstanding = 0            # fleet-side in-flight RPCs
        self.stats: dict = {}           # last successful scrape
        self.last_ok_at: Optional[float] = None
        self.last_attempt_at: Optional[float] = None
        self.last_stale_mark: Optional[float] = None
        self.last_error: Optional[str] = None


class FleetRouter:
    """Health-aware cross-host placement + retry + canaried rollout.

        transports = {0: SocketTransport(...), 1: ..., 2: ...}
        with FleetRouter(transports, max_retries=2,
                         default_timeout_s=30.0) as fleet:
            pending = fleet.submit(tokens, coords)   # async: a pool
            fleet.pump()          # heartbeats, staleness, probes
            event, probes = fleet.rollout(new_ref, old_ref, traffic)
            fleet.drain()         # barrier: every submit resolved

    `submit` returns immediately (a worker-pool thread walks the
    dispatch: pick host -> RPC -> redispatch-on-failure -> resolve);
    `drain()` barriers the pool. Every submit ends answered or with a
    structured error through `_fail_request` — the fleet-wide zero-lost
    contract (`host_exclusion = False` is the chaos smoke's weaken
    hook: quarantine and failed-host exclusion stop steering placement,
    so a dead host keeps eating traffic and the gate must fire).
    """

    def __init__(self, transports, *,
                 health: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 max_retries: int = 2,
                 default_timeout_s: Optional[float] = None,
                 heartbeat_every_s: float = 0.5,
                 heartbeat_timeout_s: float = 2.0,
                 stale_after_s: float = 5.0,
                 concurrency: int = 8,
                 tracer: Optional[Tracer] = None,
                 slo=None):
        if isinstance(transports, dict):
            items = sorted(transports.items())
        else:
            items = list(enumerate(transports))
        assert items, 'a fleet needs at least one host'
        self.hosts: Dict[int, _HostHandle] = {
            int(k): _HostHandle(k, t) for k, t in items}
        self.health = HealthMonitor(list(self.hosts),
                                    config=health, clock=clock)
        self.clock = clock
        self.max_retries = int(max_retries)
        assert self.max_retries >= 0
        self.default_timeout_s = default_timeout_s
        self.heartbeat_every_s = float(heartbeat_every_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.stale_after_s = float(stale_after_s)
        self.host_exclusion = True      # the chaos weaken hook
        # observability plane (both optional, both zero-cost when
        # absent): `tracer` mints one trace per submit and folds the
        # hosts' returned spans; `slo` (observability.slo.SLOAggregator)
        # is fed every successful stats scrape
        self.tracer = tracer
        self.slo = slo
        self.buckets: Optional[tuple] = None   # learned from scrapes
        self._lock = threading.Lock()
        self._executor = ThreadPoolExecutor(
            max_workers=max(2, int(concurrency)),
            thread_name_prefix='fleet')
        self._futures: List[Future] = []
        self._next_id = 0
        # fleet-wide counters (under _lock; the fleet record reads them)
        self.submitted = 0
        self.answered = 0
        self.cross_host_retries = 0
        self.request_failures = 0
        self.timeouts = 0
        self.heartbeats_ok = 0
        self.heartbeats_failed = 0
        self.stale_marks = 0
        self.rollout_events: List[dict] = []
        self.rollbacks = 0
        self.rollouts = 0

    # ------------------------------------------------------------------ #
    @property
    def queue_depth(self) -> int:
        """Fleet-side in-flight RPCs + the hosts' scraped queue depths
        (stale by at most a heartbeat interval)."""
        with self._lock:
            inflight = sum(h.outstanding for h in self.hosts.values())
            scraped = sum(h.stats.get('queue_depth', 0)
                          for h in self.hosts.values())
        return inflight + scraped

    def retry_after_hint(self, queue_depth: int) -> float:
        """Backoff hint for structured failures (the satellite
        contract: RequestFailed carries the same machine-readable
        `retry_after_s` an overload RequestRejected does). Per-request
        drain estimate from the scraped per-bucket p99s; 50 ms/request
        before any host reported latency."""
        per_row_s = 0.05
        with self._lock:
            p99s = [v for h in self.hosts.values()
                    for v in (h.stats.get('p99_ms_by_bucket') or {}).values()
                    if isinstance(v, (int, float))]
        if p99s:
            per_row_s = (sum(p99s) / len(p99s)) / 1e3
        return max(1, int(queue_depth)) * per_row_s

    def _fail_request(self, pending: PendingResult,
                      error: Exception) -> None:
        """THE terminal structured choke point, fleet tier — the same
        zero-lost contract `Router._fail_request` carries. Stamps the
        `retry_after_s` backoff hint when the error lacks one."""
        if isinstance(error, RequestFailed) and \
                'retry_after_s' not in error.detail:
            error.detail['retry_after_s'] = round(
                max(0.0, self.retry_after_hint(self.queue_depth)), 4)
        pending.error = error
        pending.done = True
        pending.completed_at = self.clock()
        with self._lock:
            self.request_failures += 1
        tr = getattr(pending, 'trace', None)
        if self.tracer is not None and tr:
            # a structured failure still closes the root span — the
            # completeness invariant covers failed requests too
            self.tracer.end(tr['root'],
                            status=getattr(error, 'code', None)
                            or type(error).__name__)

    # ------------------------------------------------------------------ #
    # placement
    # ------------------------------------------------------------------ #
    def _score(self, h: _HostHandle):
        # with `host_exclusion` nulled (the weaken arm) placement is
        # load-only: health must not steer traffic away from a sick
        # host, or the weakened gate would be protected by the very
        # mechanism it claims to have disabled
        rank = 0
        if self.host_exclusion and self.health.state(h.id) != 'healthy':
            rank = 1
        depth = h.outstanding + h.stats.get('queue_depth', 0)
        p99s = [v for v in (h.stats.get('p99_ms_by_bucket') or {}).values()
                if isinstance(v, (int, float))]
        return (depth, rank, max(p99s) if p99s else 0.0, h.id)

    def _host_capable(self, h: _HostHandle, length: Optional[int],
                      family: Optional[str]) -> bool:
        """Can this host serve a request of `length` tokens for model
        `family`, judged on its last scraped stats (bucket set + model
        families)? A host that has never been scraped counts as
        capable — ignorance must not black-hole traffic before the
        first heartbeat lands."""
        st = h.stats
        if not st:
            return True
        if length is not None and st.get('buckets'):
            if fit_bucket(tuple(int(b) for b in st['buckets']),
                          int(length)) is None:
                return False
        if family is not None and st.get('model_families'):
            if family not in st['model_families']:
                return False
        return True

    def _pick_host(self, exclude: Optional[int] = None,
                   length: Optional[int] = None,
                   family: Optional[str] = None) -> _HostHandle:
        """CAPABILITY filter first, then least-loaded over (fleet
        in-flight + scraped depth), healthy before degraded, scraped
        p99 tie-break. The capability filter (scraped bucket sets +
        model families) means a request sized for a big bucket never
        lands on a host that lacks it — in a heterogeneous fleet the
        incapable hosts simply leave the pool; if NO host is capable
        the request rejects structurally, naming per-host capabilities
        and which hosts are capable on each axis. Quarantined hosts
        and `exclude` (the host a retry just failed on) leave the pool
        — unless `host_exclusion` was nulled (the weaken arm), in
        which case placement is load-only and the chaos gate must
        catch the consequences. All-quarantined degrades to
        best-effort over the CAPABLE hosts (serving through a sick
        host beats black-holing; serving through an incapable one is
        just a slower reject)."""
        hosts = list(self.hosts.values())
        pool = [h for h in hosts
                if self._host_capable(h, length, family)]
        if not pool:
            by_len = [h.id for h in hosts
                      if self._host_capable(h, length, None)]
            by_fam = [h.id for h in hosts
                      if self._host_capable(h, None, family)]
            caps = {str(h.id): dict(
                        buckets=list(h.stats.get('buckets') or []),
                        model_families=list(
                            h.stats.get('model_families') or []))
                    for h in hosts}
            raise RequestRejected(
                'no_capable_host',
                f'no host serves length={length} '
                f'model_family={family!r}: capable by length '
                f'{by_len}, by family {by_fam}, per-host '
                f'capabilities {caps}',
                length=length, model_family=family,
                capable_by_length=by_len, capable_by_family=by_fam,
                host_capabilities=caps)
        capable = pool
        if self.host_exclusion:
            pool = [h for h in capable
                    if h.id != exclude
                    and self.health.state(h.id) != QUARANTINED]
            if not pool:
                pool = [h for h in capable
                        if h.id != exclude] or capable
        return min(pool, key=self._score)

    # ------------------------------------------------------------------ #
    # submission + dispatch
    # ------------------------------------------------------------------ #
    def submit(self, tokens, coords,
               timeout_s: Optional[float] = None,
               pin_host: Optional[int] = None,
               model_family: Optional[str] = None) -> PendingResult:
        """Admit one request; a pool thread dispatches it (cross-host
        retries included) and resolves the returned PendingResult.
        Oversize requests reject at the door once any host has reported
        its buckets (before that, the host's own rejection resolves the
        pending structurally — either way, never silence). The door
        gate uses the UNION of scraped bucket sets — in a
        heterogeneous fleet a request only rejects here when NO host
        could ever serve it; per-host placement then routes it to the
        hosts that actually have the bucket (and, when `model_family`
        is given, serve that family).

        `pin_host` pins the dispatch to ONE host, single-attempt (the
        rollout's canary probes ride this: a redispatch to a healthy
        sibling would mask exactly the failure the canary gate exists
        to observe)."""
        tokens = np.asarray(tokens)
        coords = np.asarray(coords, np.float32).reshape(-1, 3)
        length = len(tokens)
        bucket = -1
        if self.buckets:
            bucket = fit_bucket(self.buckets, length)
            if bucket is None:
                raise oversize_error(length, self.buckets[-1])
        submitted_at = self.clock()
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        deadline = (submitted_at + float(timeout_s)
                    if timeout_s is not None else None)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self.submitted += 1
        pending = PendingResult(rid, length, bucket, submitted_at,
                                deadline=deadline)
        if self.tracer is not None:
            # the single trace root: every span of this request — fleet
            # attempts, redispatches, and the hosts' returned admit/
            # dispatch trees — hangs under it, and exactly one terminal
            # site closes it (end() is idempotent)
            tid = self.tracer.mint()
            root = self.tracer.begin(tid, 'request', rid=rid,
                                     pinned=pin_host)
            pending.trace = dict(ctx=tid, root=root)
        self._track(self._executor.submit(
            self._dispatch, pending, tokens, coords, pin_host,
            model_family))
        return pending

    def _track(self, future: Future):
        with self._lock:
            # prune cleanly-finished futures so the list stays bounded
            self._futures = [f for f in self._futures if not f.done()]
            self._futures.append(future)

    def _dispatch(self, pending: PendingResult, tokens, coords,
                  pin_host: Optional[int] = None,
                  model_family: Optional[str] = None):
        """Worker-pool body: pick -> RPC -> redispatch or resolve.
        NEVER raises — every exit resolves the pending (the zero-lost
        contract is this function terminating structurally)."""
        exclude = None
        last_err: Optional[Exception] = None
        try:
            while True:
                now = self.clock()
                if pending.expired(now):
                    timeout_s = ((pending.deadline - pending.submitted_at)
                                 if pending.deadline is not None else 0.0)
                    with self._lock:
                        self.timeouts += 1
                    self._fail_request(pending, deadline_error(
                        now - pending.submitted_at, timeout_s,
                        attempts=pending.attempts))
                    return
                try:
                    host = (self.hosts[pin_host]
                            if pin_host is not None
                            else self._pick_host(
                                exclude=exclude,
                                length=pending.length,
                                family=model_family))
                except RequestRejected as e:
                    # capability reject: no host in the fleet serves
                    # this size/family — structured, names the capable
                    # hosts per axis, retrying cannot improve it
                    self._fail_request(pending, e)
                    return
                outcome, err = self._call_infer(host, pending,
                                                tokens, coords)
                if outcome in ('answered', 'resolved'):
                    return
                last_err = err
                pending.attempts += 1
                if pin_host is not None:
                    # pinned probe traffic (canary gating): one host,
                    # one attempt — a redispatch to a healthy sibling
                    # would MASK exactly the failure the gate exists
                    # to observe
                    self._fail_request(pending, retries_exhausted_error(
                        pending.attempts, last_err))
                    return
                if self.host_exclusion:
                    exclude = host.id
                if pending.attempts > self.max_retries:
                    self._fail_request(pending, retries_exhausted_error(
                        pending.attempts, last_err))
                    return
                with self._lock:
                    self.cross_host_retries += 1
                tr = getattr(pending, 'trace', None)
                if self.tracer is not None and tr:
                    # one redispatch span per cross_host_retries
                    # increment — the trace record's redispatch_hops
                    # reconciles against the counter exactly
                    self.tracer.add(tr['ctx'], 'redispatch',
                                    parent_id=tr['root']['span'],
                                    failed_host=host.id,
                                    attempt=pending.attempts)
        except Exception as e:   # defense in depth: a bug here must
            #                      still resolve the request, not lose it
            if not pending.done:
                self._fail_request(pending, retries_exhausted_error(
                    pending.attempts + 1, e))

    def _call_infer(self, host: _HostHandle, pending: PendingResult,
                    tokens, coords):
        """One RPC attempt -> ('answered' | 'resolved' | 'failed', err).
        'failed' means a HOST failure (transport error or a host-level
        error code): breaker fed, caller redispatches. 'resolved' means
        the request got a structured verdict (deadline / reject) that
        redispatching cannot improve."""
        now = self.clock()
        # arrays ride the payload as-is: zero-copy through
        # LocalTransport, raw framed segments through BinaryTransport;
        # the legacy JSON arm degrades them to lists at ITS wire
        payload = dict(tokens=np.asarray(tokens),
                       coords=np.asarray(coords))
        rpc_timeout = None
        if pending.deadline is not None:
            remaining = max(0.0, pending.deadline - now)
            payload['timeout_s'] = round(remaining, 4)
            rpc_timeout = remaining + 5.0
        att = None
        tr = getattr(pending, 'trace', None)
        if self.tracer is not None and tr:
            att = self.tracer.begin(tr['ctx'], 'attempt',
                                    parent_id=tr['root']['span'],
                                    host=host.id)
            # the trace context rides the payload (the transport is
            # payload-opaque); the host hangs its spans under `parent`
            # and ships them back in the response's `spans` key
            payload['trace'] = dict(trace=tr['ctx'],
                                    parent=att['span'])
        with self._lock:
            host.outstanding += 1
        try:
            res = host.transport.call('infer', payload,
                                      timeout_s=rpc_timeout)
        except TransportError as e:
            host.last_error = str(e)
            self.health.record_failure(host.id, e)
            if self.tracer is not None:
                # the host (or its link) died mid-RPC: its local spans
                # are simply lost — the fleet-side tree stays complete
                # through this attempt span and the retry path
                self.tracer.end(att, status='transport_error')
            return 'failed', e
        finally:
            with self._lock:
                host.outstanding -= 1
        if self.tracer is not None and att is not None:
            self.tracer.end(att, status=('ok' if res.get('ok') else
                                         (res.get('error') or {})
                                         .get('code')))
            self.tracer.extend(res.get('spans'))
        if res.get('ok'):
            self.health.record_success(host.id)
            pending.result = np.asarray(res['result'], np.float32)
            pending.done = True
            pending.completed_at = self.clock()
            with self._lock:
                self.answered += 1
            if self.tracer is not None and tr:
                self.tracer.end(tr['root'], status='ok')
            return 'answered', None
        err = (res.get('error') or {})
        code = err.get('code')
        message = err.get('message', 'host returned no message')
        detail = dict(err.get('detail') or {}, host=host.id)
        if code in _HOST_FAILURE_CODES or code is None:
            e = RuntimeError(f'host {host.id}: {code}: {message}')
            host.last_error = str(e)
            self.health.record_failure(host.id, e)
            return 'failed', e
        if code == 'deadline':
            with self._lock:
                self.timeouts += 1
            self._fail_request(pending,
                               RequestFailed(code, message, **detail))
        elif code in ('oversize', 'overloaded'):
            self._fail_request(pending,
                               RequestRejected(code, message, **detail))
        else:
            self._fail_request(pending,
                               RequestFailed(code, message, **detail))
        return 'resolved', None

    # ------------------------------------------------------------------ #
    # heartbeats, staleness, probes
    # ------------------------------------------------------------------ #
    def pump(self, now: Optional[float] = None) -> None:
        """The fleet heartbeat: scrape due hosts (routing signals),
        mark stale ones as failures, and issue half-open `ping` probes
        to quarantined hosts whose backoff elapsed (claimed atomically
        via `try_begin_probe`, so concurrent pumps never double-book).
        All RPCs run on the pool — pump never blocks the serve loop."""
        now = self.clock() if now is None else now
        for h in self.hosts.values():
            if self.health.state(h.id) == QUARANTINED:
                if self.health.try_begin_probe(h.id, now):
                    self._track(self._executor.submit(self._probe, h))
                continue
            if h.last_attempt_at is None or \
                    now - h.last_attempt_at >= self.heartbeat_every_s:
                h.last_attempt_at = now
                self._track(self._executor.submit(self._heartbeat, h))
            anchor = h.last_ok_at
            if anchor is not None and now - anchor >= self.stale_after_s \
                    and (h.last_stale_mark is None
                         or now - h.last_stale_mark >= self.stale_after_s):
                # the link isn't refusing, it's SILENT: staleness is a
                # failure signal of its own (a partitioned host must
                # leave rotation even if no RPC happens to fail)
                h.last_stale_mark = now
                with self._lock:
                    self.stale_marks += 1
                self.health.record_failure(h.id, RuntimeError(
                    f'heartbeat stale: host {h.id} last answered '
                    f'{now - anchor:.1f}s ago '
                    f'(stale_after_s={self.stale_after_s})'))

    def _heartbeat(self, h: _HostHandle):
        """Scrape one host's stats. Successes refresh the routing
        signal but do NOT feed the breaker (asymmetric by design: a
        host that answers pings while failing dispatches must not have
        its breaker reset by the pings); failures feed it.

        A `stats` timeout behind a long synchronous dispatch counts as
        a failure ON PURPOSE — a host too busy to report within
        `heartbeat_timeout_s` is degraded service, and steering load
        away is the right response. Recovery is cheap: `ping` probes
        answer off the serve loop (process liveness), so a merely-busy
        host closes its breaker the moment its backoff elapses. Size
        `heartbeat_timeout_s` above the worst healthy batch latency."""
        try:
            res = h.transport.call('stats',
                                   timeout_s=self.heartbeat_timeout_s)
        except TransportError as e:
            h.last_error = str(e)
            with self._lock:
                self.heartbeats_failed += 1
            self.health.record_failure(h.id, e)
            return
        if res.get('ok'):
            h.stats = res.get('stats') or {}
            h.last_ok_at = self.clock()
            h.last_stale_mark = None
            if self.slo is not None:
                # the heartbeat loop IS the SLO scrape: stats carry the
                # host's cumulative mergeable histograms and counters
                self.slo.fold(h.id, h.stats)
            with self._lock:
                self.heartbeats_ok += 1
                if h.stats.get('buckets'):
                    # fleet-level buckets = UNION over scraped hosts:
                    # the door-level oversize gate only rejects what NO
                    # host could serve; per-host capability filtering
                    # in _pick_host handles heterogeneity
                    self.buckets = tuple(sorted(
                        {int(b) for b in h.stats['buckets']}
                        | set(self.buckets or ())))
        else:
            with self._lock:
                self.heartbeats_failed += 1
            self.health.record_failure(h.id, RuntimeError(
                f'stats RPC returned error: {res.get("error")}'))

    def _probe(self, h: _HostHandle):
        """The half-open probe (claimed before submission): one `ping`.
        Success closes the breaker back to degraded — the host rejoins
        rotation and dispatch successes walk it to healthy; failure
        doubles the backoff. A restarted process on the same port
        recovers through exactly this path."""
        span = None
        if self.tracer is not None:
            # control-plane trace: excluded from request completeness
            span = self.tracer.begin(
                self.tracer.mint(CONTROL_KIND), 'probe', host=h.id)
        try:
            res = h.transport.call('ping',
                                   timeout_s=self.heartbeat_timeout_s)
        except TransportError as e:
            h.last_error = str(e)
            self.health.record_failure(h.id, e)
            if self.tracer is not None:
                self.tracer.end(span, status='transport_error')
            return
        ok = bool(res.get('ok'))
        if self.tracer is not None:
            self.tracer.end(span, status='ok' if ok else 'error')
        if ok:
            self.health.record_success(h.id)
            h.last_ok_at = self.clock()
            h.last_stale_mark = None
        else:
            self.health.record_failure(h.id, RuntimeError(
                f'probe ping returned error: {res.get("error")}'))

    # ------------------------------------------------------------------ #
    # canaried rollout
    # ------------------------------------------------------------------ #
    def _swap(self, h: _HostHandle, ref: dict) -> str:
        res = h.transport.call('swap', dict(ref),
                               timeout_s=self.heartbeat_timeout_s + 30.0)
        if not res.get('ok'):
            raise TransportError(
                f'host {h.id} swap to {ref} failed: {res.get("error")}')
        return res.get('tag') or '?'

    def rollout(self, new_ref: dict, rollback_ref: dict,
                canary_traffic: Sequence[tuple], *,
                canary: Optional[int] = None,
                latency_budget_ms: Optional[float] = None,
                timeout_s: Optional[float] = None):
        """Fleet-wide rolling weight rollout over the hosts' drain/swap
        contract, gated by a CANARY:

          1. swap ONE host (`canary`, default: the best-ranked live
             host) to `new_ref` ({'directory': ..., 'step': ...} — the
             host's `swap_from_checkpoint` handles torn-latest
             fallback and tags the step actually restored);
          2. drive `canary_traffic` ([(tokens, coords), ...]) PINNED to
             the canary — single-attempt, failures resolve structurally
             on the canary instead of being masked by redispatch;
          3. gate on the canary's serve evidence: every probe answered,
             zero lost, max latency within `latency_budget_ms` (when
             given), and zero NEW host-side structured failures across
             the swap (scraped stats delta);
          4. gate passed -> roll every other host; gate failed -> AUTO
             ROLL-BACK the canary to `rollback_ref` and leave the rest
             of the fleet untouched.

        Returns `(event, probes)`: the JSON-safe rollout event (also
        appended to `rollout_events` — the fleet record's evidence) and
        the probe PendingResults (callers fold them into their
        zero-lost accounting)."""
        pool = [h for h in self.hosts.values()
                if self.health.state(h.id) != QUARANTINED]
        assert pool, 'every host is quarantined — nothing to canary'
        canary_host = (self.hosts[int(canary)] if canary is not None
                       else min(pool, key=self._score))
        pre = self._scrape_sync(canary_host)
        span = None
        if self.tracer is not None:
            # control-plane trace over the whole canary decision
            span = self.tracer.begin(
                self.tracer.mint(CONTROL_KIND), 'rollout',
                canary=canary_host.id)
        event = dict(t=round(self.clock(), 3), canary=canary_host.id,
                     new=dict(new_ref))
        try:
            event['canary_tag'] = self._swap(canary_host, new_ref)
        except TransportError as e:
            self.health.record_failure(canary_host.id, e)
            event.update(passed=False, rolled_back=False,
                         aborted=f'canary swap failed: {e}')
            with self._lock:
                self.rollout_events.append(event)
            if self.tracer is not None:
                self.tracer.end(span, status='aborted')
            return event, []
        # the probes ride the SAME admission path as every request
        # (oversize gate included), just pinned single-attempt
        probes = [self.submit(tokens, coords, timeout_s=timeout_s,
                              pin_host=canary_host.id)
                  for tokens, coords in canary_traffic]
        self._wait_for(probes)
        post = self._scrape_sync(canary_host)
        answered = sum(1 for p in probes if p.ok)
        lost = sum(1 for p in probes if not p.done)
        lat = [p.latency_s * 1e3 for p in probes
               if p.ok and p.latency_s is not None]
        failures_delta = None
        if pre is not None and post is not None:
            failures_delta = (post.get('request_failures', 0)
                              - pre.get('request_failures', 0))
        gate = dict(requests=len(probes), answered=answered,
                    failures=len(probes) - answered, lost=lost,
                    max_latency_ms=round(max(lat), 3) if lat else None,
                    latency_budget_ms=latency_budget_ms,
                    host_request_failures_delta=failures_delta)
        passed = (len(probes) > 0 and answered == len(probes)
                  and lost == 0
                  and (failures_delta in (None, 0))
                  and (latency_budget_ms is None
                       or (lat and max(lat) <= latency_budget_ms)))
        event.update(gate=gate, passed=bool(passed))
        if passed:
            rolled = []
            for h in sorted(self.hosts.values(), key=lambda h: h.id):
                if h.id == canary_host.id:
                    continue
                try:
                    rolled.append(dict(host=h.id,
                                       tag=self._swap(h, new_ref)))
                except TransportError as e:
                    self.health.record_failure(h.id, e)
                    rolled.append(dict(host=h.id, error=str(e)))
            event.update(rolled=rolled, rolled_back=False)
            with self._lock:
                self.rollouts += 1
        else:
            rb_ok = True
            try:
                rb_tag = self._swap(canary_host, rollback_ref)
            except TransportError as e:
                # the canary is STRANDED on the bad weights — that must
                # never read as an observed rollback (the gated
                # `rollbacks` counter only counts swaps that landed)
                self.health.record_failure(canary_host.id, e)
                rb_ok = False
                rb_tag = f'ROLLBACK FAILED: {e}'
            event.update(rolled=[], rolled_back=rb_ok,
                         rollback=dict(ref=dict(rollback_ref),
                                       tag=rb_tag, ok=rb_ok))
            with self._lock:
                if rb_ok:
                    self.rollbacks += 1
        with self._lock:
            self.rollout_events.append(event)
        if self.tracer is not None:
            self.tracer.end(
                span, status='passed' if passed else 'rolled_back')
        return event, probes

    def _scrape_sync(self, h: _HostHandle) -> Optional[dict]:
        try:
            res = h.transport.call('stats',
                                   timeout_s=self.heartbeat_timeout_s)
        except TransportError:
            return None
        if res.get('ok'):
            h.stats = res.get('stats') or {}
            h.last_ok_at = self.clock()
            if self.slo is not None:
                self.slo.fold(h.id, h.stats)
            return h.stats
        return None

    def scrape(self) -> int:
        """Synchronously scrape every host's stats ONCE (fold into the
        SLO aggregator when attached) — the end-of-run flush a smoke
        uses so the final `slo` record reflects the hosts' cumulative
        counters, not the last heartbeat's. Returns hosts scraped."""
        return sum(1 for h in self.hosts.values()
                   if self._scrape_sync(h) is not None)

    def _wait_for(self, probes: Sequence[PendingResult],
                  timeout_s: float = 120.0):
        t0 = time.monotonic()
        while any(not p.done for p in probes):
            if time.monotonic() - t0 > timeout_s:
                break
            time.sleep(0.005)

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def drain(self) -> None:
        """Barrier: every dispatch/probe/heartbeat the fleet started
        has finished (each resolves its own request structurally, so
        after drain() the caller's pendings are all done-or-failed)."""
        while True:
            with self._lock:
                futures, self._futures = self._futures, []
            if not futures:
                return
            for f in futures:
                f.exception()   # _dispatch resolves internally; a bug
                #                 surfacing here must not wedge drain

    def close(self) -> None:
        self.drain()
        self._executor.shutdown(wait=True)

    def __enter__(self) -> 'FleetRouter':
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # ------------------------------------------------------------------ #
    # the `fleet` record
    # ------------------------------------------------------------------ #
    def record_body(self, pending: Optional[Sequence[PendingResult]] = None,
                    label: str = 'fleet') -> dict:
        """Assemble the schema'd `fleet` record body (the caller logs
        it: `logger.log_record('fleet', **body)`): per-host breaker
        snapshots + last scraped signals, the merged host-transition
        log, cross-host retry / failure / heartbeat counters, rollout
        and rollback evidence, and the load-bearing fleet-wide
        `lost_requests` over the caller's submitted `pending` list
        (None limits lost accounting to what the fleet can see, i.e.
        0 — pass the real list)."""
        pending = list(pending or [])
        # per-host transport counters (only transports that expose
        # them — BinaryTransport and SocketTransport do, the wire-free
        # LocalTransport has nothing to count), aggregated fleet-wide:
        # sums for the monotonic counters, max for the peak gauge
        tstats = {}
        for hid, h in sorted(self.hosts.items()):
            snap = getattr(h.transport, 'transport_stats', None)
            if callable(snap):
                tstats[str(hid)] = snap()
        transport_section = None
        if tstats:
            transport_section = {
                k: sum(s.get(k, 0) for s in tstats.values())
                for k in ('connections_opened', 'reconnects',
                          'bytes_sent', 'bytes_received',
                          'frame_errors')}
            transport_section['peak_in_flight'] = max(
                s.get('peak_in_flight', 0) for s in tstats.values())
            transport_section['by_host'] = tstats
        hsnap = self.health.snapshot()
        hosts = {}
        for hid, h in sorted(self.hosts.items()):
            entry = dict(hsnap[str(hid)])
            entry['outstanding'] = h.outstanding
            if h.stats:
                entry['stats'] = {
                    k: h.stats.get(k)
                    for k in ('queue_depth', 'served', 'batches',
                              'request_failures', 'retries', 'timeouts',
                              'precision_mixes', 'model_families',
                              'swaps', 'post_warmup_compiles')
                    if k in h.stats}
            if h.last_error:
                entry['last_error'] = h.last_error
            hosts[str(hid)] = entry
        transitions = [dict(e, host=e['replica'])
                       for e in self.health.transitions]
        with self._lock:
            body = dict(
                label=label,
                hosts=hosts,
                host_transitions=transitions,
                recoveries=self.health.recoveries,
                cross_host_retries=self.cross_host_retries,
                request_failures=self.request_failures,
                timeouts=self.timeouts,
                heartbeats=dict(ok=self.heartbeats_ok,
                                failed=self.heartbeats_failed,
                                stale_marks=self.stale_marks),
                rollouts=dict(count=len(self.rollout_events),
                              completed=self.rollouts,
                              events=list(self.rollout_events)),
                rollbacks=self.rollbacks,
                submitted=self.submitted,
                answered=self.answered,
                resolved=sum(1 for p in pending if p.done),
                structured_failures=sum(
                    1 for p in pending
                    if p.done and p.error is not None),
                lost_requests=sum(1 for p in pending if not p.done),
            )
        if transport_section is not None:
            body['transport'] = transport_section
        return body
