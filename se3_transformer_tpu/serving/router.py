"""Continuous-batching router over N replica workers.

The millions-of-users front door (ROADMAP item 3): each replica owns a
sharded (or replicated) AOT engine; the router multiplies their
throughput with three policies, all deterministic and clock-injectable:

  * **continuous admission** — `submit` places a request straight into
    the chosen replica's in-flight bucket slot (`ContinuousBatcher`);
    a full slot dispatches inside `submit`, the deadline (`pump`) is
    only the fallback for slots that never fill;
  * **least-outstanding dispatch** — among non-draining replicas, the
    one with the fewest unanswered requests wins (ties break to the
    lowest replica id, so a single-replica router degenerates exactly
    to its batcher);
  * **rolling weight swaps** — `swap_weights` walks the replicas ONE AT
    A TIME: take the replica out of rotation, drain its slots (old
    weights answer everything already admitted), re-point its engine at
    the new params (zero recompiles — AOT executables take params as an
    argument), put it back. The other replicas keep serving throughout,
    so a checkpoint hot-reload (`swap_from_checkpoint`, off the
    training-side async-checkpoint path) drops zero requests.

On top of placement, the router owns the single-host FAULT DOMAIN:

  * **replica health + circuit breaking** (`serving.health`) — every
    dispatch outcome feeds a per-replica breaker (healthy -> degraded
    -> quarantined); quarantined replicas drop out of least-outstanding
    rotation and recover through exponential-backoff half-open PROBE
    traffic (one request at a time), not a restart.
  * **bounded retry-with-redispatch** — a failed batch's requests are
    taken over (never resolved-with-raw-error, never silently dropped)
    and redispatched onto sibling replicas at the next `pump()`; once
    `max_retries` redispatches have failed, the request resolves with a
    structured `RequestFailed('retries_exhausted')`.
  * **deadline propagation** — `submit(..., timeout_s=...)` (or the
    router-wide `default_timeout_s`) stamps `submitted_at + timeout_s`
    onto the request; expired requests shed BEFORE dispatch (they never
    consume a batch row) and resolve with `RequestFailed('deadline')`.

The counters these paths produce (`retries`, `request_failures`,
`timeouts`, `deadline_sheds`, per-replica health) fold into the
`serve`/`fault` telemetry records — the routing signals the cross-host
tier (ROADMAP item 5) consumes.

Structured shedding reuses the PR 2 `AdmissionController` — oversize
and overload rejections raise `RequestRejected` before touching any
compiled path, counted for the serve record; the router wires its
queue-depth x per-bucket-p50 estimate in as the controller's
`retry_hint`, so overload sheds carry a machine-readable
`retry_after_s`.

Dispatch is non-blocking when the workers were built with
`async_dispatch=True` (ReplicaWorker): a filled slot submits its
execution to the replica's own single-thread executor, so the submit
loop keeps admitting while engines run and N replicas' executions
overlap on a multi-chip host. The router's verbs are unchanged —
`drain`/`swap_weights` barrier per replica, so the rolling-swap
zero-drop contract holds in either mode; `close()` (or exiting the
router's `with` block — it is a context manager, so the dispatch
executors shut down on error paths too) ends the stream.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..inference.admission import (
    AdmissionController, RequestFailed, fit_bucket, oversize_error,
    deadline_error, retries_exhausted_error,
)
from ..inference.batching import PendingResult
from .health import QUARANTINED, HealthConfig, HealthMonitor
from .replica import ReplicaWorker


class Router:
    """Admission + placement + fault domain + lifecycle over replicas.

        workers = [ReplicaWorker(i, engine_i) for i ...]
        with Router(workers, admission=ctl, max_retries=2,
                    default_timeout_s=30.0) as router:
            pending = router.submit(tokens, coords)   # may raise
            router.pump()             # deadlines, retries, probes
            router.swap_weights(new_params)           # rolling hot-reload
            router.drain()                            # end of stream
        # __exit__ -> close(): executors shut down even on error paths
    """

    def __init__(self, workers: Sequence[ReplicaWorker],
                 admission: Optional[AdmissionController] = None,
                 clock: Callable[[], float] = time.monotonic,
                 health: Optional[HealthConfig] = None,
                 max_retries: int = 1,
                 default_timeout_s: Optional[float] = None):
        self.workers: List[ReplicaWorker] = list(workers)
        assert self.workers, 'a router needs at least one replica'
        buckets = {w.engine.buckets for w in self.workers}
        assert len(buckets) == 1, \
            f'replicas disagree on buckets: {sorted(buckets)} — the ' \
            f'router routes by bucket, so every replica must compile ' \
            f'the same set'
        self.buckets = self.workers[0].engine.buckets
        self.admission = admission
        self.clock = clock
        self._next_id = 0
        # request-id namespace: per-router ids are monotonic ints and
        # COLLIDE once several routers' record streams merge — owners
        # (HostServer) set id_prefix to a host component and ids become
        # globally unique strings like 'h1-17' (tracing depends on it)
        self.id_prefix: Optional[str] = None
        # request tracing (observability.tracing.Tracer): attach_tracer
        # fans it out to every replica batcher so admit/batch_fill/
        # dispatch/device_run/retry spans share one recorder
        self.tracer = None
        self.swap_events: List[dict] = []
        # ---- fault domain ------------------------------------------- #
        self.health = HealthMonitor([w.id for w in self.workers],
                                    config=health, clock=clock)
        self.max_retries = int(max_retries)
        assert self.max_retries >= 0
        self.default_timeout_s = default_timeout_s
        self.retries = 0            # redispatches performed
        self.request_failures = 0   # structured terminal failures
        self._retry_timeouts = 0    # deadline failures from the queue
        # a failed batch's requests land here (from dispatch hooks —
        # possibly on an executor thread) and are redispatched or
        # structurally failed by the next pump()/drain() on the serve
        # loop's thread, so retries never mutate a sibling's batcher
        # cross-thread
        self._retry_lock = threading.Lock()
        self._retry_queue: List[tuple] = []
        self._failed: List[PendingResult] = []   # for pop_completed
        self._failed_capacity = 65536
        for w in self.workers:
            w.batcher.on_success = self._success_hook(w.id)
            w.batcher.on_failure = self._failure_hook(w.id)
            # deadline resolutions inside the batcher carry the same
            # retry_after_s hint _fail_request stamps — terminal
            # failures back clients off uniformly wherever they resolve
            w.batcher.retry_hint = self.retry_after_hint
        if admission is not None and admission.retry_hint is None:
            admission.retry_hint = self.retry_after_hint

    # ------------------------------------------------------------------ #
    @property
    def queue_depth(self) -> int:
        with self._retry_lock:
            retrying = len(self._retry_queue)
        return sum(w.outstanding for w in self.workers) + retrying

    @property
    def depth_by_bucket(self) -> dict:
        """Open-slot depth per bucket across the replicas — one of the
        per-host routing signals the cross-host tier scrapes."""
        depths = {b: 0 for b in self.buckets}
        for w in self.workers:
            for b, n in w.batcher.depth_by_bucket.items():
                depths[b] = depths.get(b, 0) + n
        return depths

    @property
    def continuous_admissions(self) -> int:
        return sum(w.batcher.continuous_admissions for w in self.workers)

    @property
    def deadline_flushes(self) -> int:
        return sum(w.batcher.deadline_flushes for w in self.workers)

    @property
    def batches_dispatched(self) -> int:
        return sum(w.batcher.batches_dispatched for w in self.workers)

    @property
    def timeouts(self) -> int:
        """Requests resolved RequestFailed('deadline') anywhere: shed or
        expired in a slot, or expired on the retry queue."""
        return sum(w.batcher.timeouts
                   for w in self.workers) + self._retry_timeouts

    @property
    def deadline_sheds(self) -> int:
        return sum(w.batcher.deadline_sheds for w in self.workers)

    @property
    def max_len(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, length: int) -> Optional[int]:
        return fit_bucket(self.buckets, length)

    def attach_tracer(self, tracer) -> None:
        """Wire one span recorder through the router AND every replica
        batcher — the whole host records into a single Tracer so
        `pop_trace` can ship a request's full host-side story back in
        the RPC response."""
        self.tracer = tracer
        for w in self.workers:
            w.batcher.tracer = tracer

    def retry_after_hint(self, queue_depth: int) -> float:
        """Overload-shed backoff hint: queue depth x the per-request
        drain estimate (mean per-bucket p50 over the shared timer,
        divided by the batch size). Falls back to 50 ms/request before
        any latency sample exists."""
        per_row_s = 0.05
        timer = getattr(self.workers[0].engine, 'timer', None)
        if timer is not None:
            summary = timer.cumulative_summary()
            p50s = [v.get('p50_ms') for k, v in summary.items()
                    if k.startswith('bucket_') and v.get('p50_ms')]
            if p50s:
                batch = max(1, self.workers[0].engine.batch_size)
                per_row_s = (sum(p50s) / len(p50s)) / 1e3 / batch
        return max(1, int(queue_depth)) * per_row_s

    # ------------------------------------------------------------------ #
    # fault-domain hooks + the retry queue
    # ------------------------------------------------------------------ #
    def _success_hook(self, replica_id: int):
        def hook(rows: int):
            self.health.record_success(replica_id)
        return hook

    def _failure_hook(self, replica_id: int):
        def hook(bucket, tokens, coords, pending, exc) -> bool:
            self.health.record_failure(replica_id, exc)
            with self._retry_lock:
                for p, t, c in zip(pending, tokens, coords):
                    self._retry_queue.append((p, t, c, replica_id, exc))
            return True   # taken over: redispatch or fail structurally
        return hook

    def _fail_request(self, pending: PendingResult,
                      error: RequestFailed) -> None:
        """Terminal structured resolution — the one choke point the
        zero-lost-requests contract rides (the chaos harness's weakened
        arm overrides exactly this to prove the gate fires).

        Every terminal failure leaves carrying the same machine-readable
        `retry_after_s` hint overload sheds already carry (queue depth x
        per-request drain estimate), so fleet-level redispatch and
        external clients back off uniformly instead of hot-looping a
        struggling router."""
        if isinstance(error, RequestFailed) and \
                'retry_after_s' not in error.detail:
            error.detail['retry_after_s'] = round(
                max(0.0, self.retry_after_hint(self.queue_depth)), 4)
        pending.error = error
        pending.done = True
        pending.completed_at = self.clock()
        self.request_failures += 1
        self._failed.append(pending)
        if len(self._failed) > self._failed_capacity:
            del self._failed[:-self._failed_capacity]

    def process_failures(self, now: Optional[float] = None) -> int:
        """Drain the retry queue: redispatch each failed request onto a
        sibling (attempts budget and deadline permitting) or resolve it
        with a structured RequestFailed. Returns requests redispatched.
        Runs on the serve loop's thread (from pump/drain)."""
        with self._retry_lock:
            drained, self._retry_queue = self._retry_queue, []
        if not drained:
            return 0
        now = self.clock() if now is None else now
        redispatched = 0
        for p, tokens, coords, failed_on, exc in drained:
            p.attempts += 1
            if p.expired(now):
                timeout_s = ((p.deadline - p.submitted_at)
                             if p.deadline is not None else 0.0)
                self._retry_timeouts += 1
                self._fail_request(p, deadline_error(
                    now - p.submitted_at, timeout_s, attempts=p.attempts))
            elif p.attempts > self.max_retries:
                self._fail_request(
                    p, retries_exhausted_error(p.attempts, exc))
            else:
                self.retries += 1
                worker = self._pick_worker(exclude=failed_on)
                tr = getattr(p, 'trace', None)
                if self.tracer is not None and tr:
                    self.tracer.add(tr['ctx'], 'retry',
                                    parent_id=tr['parent'],
                                    failed_on=failed_on,
                                    replica=worker.id,
                                    attempt=p.attempts)
                worker.admit(p.bucket, tokens, coords, p)
                redispatched += 1
        return redispatched

    # ------------------------------------------------------------------ #
    def _pick_worker(self, exclude: Optional[int] = None) -> ReplicaWorker:
        """Health-aware least-outstanding placement.

        1. A quarantined replica whose probe backoff elapsed gets THIS
           request (half-open: exactly one until the outcome lands) —
           recovery happens via probe traffic, not a restart.
        2. Otherwise: least-outstanding among non-draining, non-
           quarantined replicas (degraded ranks after healthy at equal
           depth; ties break to the lowest id, so an all-healthy fleet
           behaves exactly as before health existed).
        3. Last resort (every live replica quarantined): least-
           outstanding among ALL live replicas — serving through a sick
           replica beats black-holing the request.

        `exclude` (a replica id) steers retries away from the replica
        that just failed whenever a sibling exists.
        """
        live = [w for w in self.workers if not w.draining]
        assert live, 'every replica is draining — rolling swaps take ' \
                     'one replica out at a time, so this is a bug'
        now = self.clock()
        for w in live:
            # atomic claim: check-and-begin under the monitor's lock, so
            # a concurrent picker can never double-book the half-open slot
            if w.id != exclude and self.health.try_begin_probe(w.id, now):
                return w

        def rank(w):
            state = self.health.state(w.id)
            return (w.outstanding, 0 if state == 'healthy' else 1, w.id)

        routable = [w for w in live
                    if self.health.state(w.id) != QUARANTINED
                    and w.id != exclude]
        if not routable:
            routable = [w for w in live if w.id != exclude] or live
        return min(routable, key=rank)

    def submit(self, tokens, coords,
               timeout_s: Optional[float] = None,
               trace: Optional[dict] = None) -> PendingResult:
        """Admit + place one request; its slot dispatches on fill.

        Raises RequestRejected (oversize / overloaded) without touching
        any compiled path; the bucket fit is checked BEFORE admission
        accounting (same contract as MicroBatcher.submit).
        `timeout_s` (default: the router's `default_timeout_s`) stamps
        the request's deadline; the result then either answers in time
        or resolves with a structured RequestFailed('deadline').

        `trace` is an incoming trace context (`{'trace': <id>,
        'parent': <span id>}` — the fleet RPC payload's `trace` key):
        when present and a tracer is attached, an `admit` span lands
        under the caller's parent and every downstream span of this
        request hangs under the admit span."""
        tokens = np.asarray(tokens)
        length = len(tokens)
        bucket = self.bucket_for(length)
        if bucket is None:
            if self.admission is not None:
                self.admission.reject_oversize(length, self.buckets[-1])
            raise oversize_error(length, self.buckets[-1])
        if self.admission is not None:
            self.admission.admit(length, queue_depth=self.queue_depth)
        worker = self._pick_worker()
        submitted_at = self.clock()
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        deadline = (submitted_at + float(timeout_s)
                    if timeout_s is not None else None)
        rid = (self._next_id if self.id_prefix is None
               else f'{self.id_prefix}-{self._next_id}')
        pending = PendingResult(rid, length, bucket,
                                submitted_at, deadline=deadline)
        self._next_id += 1
        if self.tracer is not None and trace and trace.get('trace'):
            admit = self.tracer.add(trace['trace'], 'admit',
                                    parent_id=trace.get('parent'),
                                    ts=submitted_at, rid=rid,
                                    bucket=int(bucket),
                                    replica=worker.id)
            pending.trace = dict(ctx=trace['trace'],
                                 parent=admit['span'])
        worker.admit(bucket, tokens, coords, pending)
        return pending

    def pump(self, now: Optional[float] = None) -> int:
        """The fault-domain heartbeat: redispatch/fail queued retries,
        expire per-request deadlines, then deadline-FLUSH every slot
        whose oldest request hit `max_wait_ms`. Returns batches
        dispatched by the flush fallback."""
        now = self.clock() if now is None else now
        self.process_failures(now)
        return sum(w.flush_due(now) for w in self.workers)

    def next_deadline(self, now: Optional[float] = None) -> Optional[float]:
        """Sleep hint: seconds until the earliest fallback deadline."""
        now = self.clock() if now is None else now
        deadlines = [d for d in (w.batcher.next_deadline(now)
                                 for w in self.workers) if d is not None]
        return min(deadlines) if deadlines else None

    def drain(self) -> int:
        """Dispatch every partial slot on every replica (end of
        stream), barrier on any async dispatches, and settle the retry
        queue — when it returns, everything admitted has answered or
        failed structurally. Returns batches dispatched.

        Termination is guaranteed: every redispatch increments the
        request's `attempts`, so a request can bounce at most
        `max_retries` times before `process_failures` resolves it."""
        total = 0
        for _ in range(self.max_retries + 2):
            total += sum(w.drain() for w in self.workers)
            if not self.process_failures():
                with self._retry_lock:
                    settled = not self._retry_queue
                if settled and not any(w.batcher.depth
                                       for w in self.workers):
                    break
        return total

    def close(self) -> None:
        """Drain, then shut down the replicas' dispatch executors
        (idempotent; no-op for synchronous replicas)."""
        self.drain()
        for w in self.workers:
            w.close()

    def __enter__(self) -> 'Router':
        return self

    def __exit__(self, exc_type, exc, tb):
        # executors must shut down on error paths too — a leaked
        # replica thread outlives the serve loop otherwise
        self.close()
        return False

    def pop_completed(self) -> List[PendingResult]:
        done: List[PendingResult] = []
        for w in self.workers:
            done += w.batcher.pop_completed()
        done += self._failed
        self._failed = []
        return done

    # ------------------------------------------------------------------ #
    def swap_weights(self, params, tag: Optional[str] = None) -> List[dict]:
        """Rolling weight swap: one replica at a time drains and
        re-points at `params` while the rest keep serving. Returns the
        swap events (also appended to `swap_events` for telemetry)."""
        events = []
        for w in self.workers:
            event = w.swap_weights(params)
            event['t'] = round(self.clock(), 3)
            if tag is not None:
                event['tag'] = tag
            self.swap_events.append(event)
            events.append(event)
            # a drain can strand failed requests on the retry queue
            # while this replica is out of rotation — settle them now
            # so the rolling swap itself never delays a retry
            self.process_failures()
        return events

    def swap_from_checkpoint(self, directory: str,
                             step: Optional[int] = None) -> List[dict]:
        """Hot-reload the latest (or a named) training checkpoint into
        every replica — params-only restore off the async-checkpoint
        path (which falls back past a corrupt/partial latest step to
        the newest valid one), then the rolling swap. The tag names the
        step actually restored, so a fallback is visible in the swap
        event."""
        from ..training.checkpoint import CheckpointManager
        mgr = CheckpointManager(directory)
        params = mgr.restore_params(step)
        restored = (step if step is not None
                    else mgr.last_restored_step)
        tag = f'{directory}@{restored if restored is not None else "latest"}'
        return self.swap_weights(params, tag=tag)
