"""Continuous-batching router over N replica workers.

The millions-of-users front door (ROADMAP item 3): each replica owns a
sharded (or replicated) AOT engine; the router multiplies their
throughput with three policies, all deterministic and clock-injectable:

  * **continuous admission** — `submit` places a request straight into
    the chosen replica's in-flight bucket slot (`ContinuousBatcher`);
    a full slot dispatches inside `submit`, the deadline (`pump`) is
    only the fallback for slots that never fill;
  * **least-outstanding dispatch** — among non-draining replicas, the
    one with the fewest unanswered requests wins (ties break to the
    lowest replica id, so a single-replica router degenerates exactly
    to its batcher);
  * **rolling weight swaps** — `swap_weights` walks the replicas ONE AT
    A TIME: take the replica out of rotation, drain its slots (old
    weights answer everything already admitted), re-point its engine at
    the new params (zero recompiles — AOT executables take params as an
    argument), put it back. The other replicas keep serving throughout,
    so a checkpoint hot-reload (`swap_from_checkpoint`, off the
    training-side async-checkpoint path) drops zero requests.

Structured shedding reuses the PR 2 `AdmissionController` — oversize
and overload rejections raise `RequestRejected` before touching any
compiled path, counted for the serve record.

Dispatch is non-blocking when the workers were built with
`async_dispatch=True` (ReplicaWorker): a filled slot submits its
execution to the replica's own single-thread executor, so the submit
loop keeps admitting while engines run and N replicas' executions
overlap on a multi-chip host. The router's verbs are unchanged —
`drain`/`swap_weights` barrier per replica, so the rolling-swap
zero-drop contract holds in either mode; call `close()` at end of
stream to shut the executors down.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..inference.admission import (
    AdmissionController, fit_bucket, oversize_error,
)
from ..inference.batching import PendingResult
from .replica import ReplicaWorker


class Router:
    """Admission + placement + lifecycle over a fleet of replicas.

        workers = [ReplicaWorker(i, engine_i) for i ...]
        router = Router(workers, admission=ctl)
        pending = router.submit(tokens, coords)   # may raise
        router.pump()                             # deadline fallback
        router.swap_weights(new_params)           # rolling hot-reload
        router.drain()                            # end of stream
    """

    def __init__(self, workers: Sequence[ReplicaWorker],
                 admission: Optional[AdmissionController] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.workers: List[ReplicaWorker] = list(workers)
        assert self.workers, 'a router needs at least one replica'
        buckets = {w.engine.buckets for w in self.workers}
        assert len(buckets) == 1, \
            f'replicas disagree on buckets: {sorted(buckets)} — the ' \
            f'router routes by bucket, so every replica must compile ' \
            f'the same set'
        self.buckets = self.workers[0].engine.buckets
        self.admission = admission
        self.clock = clock
        self._next_id = 0
        self.swap_events: List[dict] = []

    # ------------------------------------------------------------------ #
    @property
    def queue_depth(self) -> int:
        return sum(w.outstanding for w in self.workers)

    @property
    def continuous_admissions(self) -> int:
        return sum(w.batcher.continuous_admissions for w in self.workers)

    @property
    def deadline_flushes(self) -> int:
        return sum(w.batcher.deadline_flushes for w in self.workers)

    @property
    def batches_dispatched(self) -> int:
        return sum(w.batcher.batches_dispatched for w in self.workers)

    @property
    def max_len(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, length: int) -> Optional[int]:
        return fit_bucket(self.buckets, length)

    # ------------------------------------------------------------------ #
    def _pick_worker(self) -> ReplicaWorker:
        """Least-outstanding among non-draining replicas (ties: lowest
        id — deterministic, and a 1-replica router degenerates to its
        batcher)."""
        live = [w for w in self.workers if not w.draining]
        assert live, 'every replica is draining — rolling swaps take ' \
                     'one replica out at a time, so this is a bug'
        return min(live, key=lambda w: (w.outstanding, w.id))

    def submit(self, tokens, coords) -> PendingResult:
        """Admit + place one request; its slot dispatches on fill.

        Raises RequestRejected (oversize / overloaded) without touching
        any compiled path; the bucket fit is checked BEFORE admission
        accounting (same contract as MicroBatcher.submit)."""
        tokens = np.asarray(tokens)
        length = len(tokens)
        bucket = self.bucket_for(length)
        if bucket is None:
            if self.admission is not None:
                self.admission.reject_oversize(length, self.buckets[-1])
            raise oversize_error(length, self.buckets[-1])
        if self.admission is not None:
            self.admission.admit(length, queue_depth=self.queue_depth)
        worker = self._pick_worker()
        pending = PendingResult(self._next_id, length, bucket,
                                self.clock())
        self._next_id += 1
        worker.admit(bucket, tokens, coords, pending)
        return pending

    def pump(self, now: Optional[float] = None) -> int:
        """Deadline FALLBACK across the fleet: dispatch every slot whose
        oldest request hit `max_wait_ms`. Returns batches dispatched."""
        now = self.clock() if now is None else now
        return sum(w.flush_due(now) for w in self.workers)

    def next_deadline(self, now: Optional[float] = None) -> Optional[float]:
        """Sleep hint: seconds until the earliest fallback deadline."""
        now = self.clock() if now is None else now
        deadlines = [d for d in (w.batcher.next_deadline(now)
                                 for w in self.workers) if d is not None]
        return min(deadlines) if deadlines else None

    def drain(self) -> int:
        """Dispatch every partial slot on every replica (end of
        stream) and barrier on any async dispatches — when it returns,
        everything admitted has answered. Returns batches dispatched."""
        return sum(w.drain() for w in self.workers)

    def close(self) -> None:
        """Drain, then shut down the replicas' dispatch executors
        (no-op for synchronous replicas)."""
        self.drain()
        for w in self.workers:
            w.close()

    def pop_completed(self) -> List[PendingResult]:
        done: List[PendingResult] = []
        for w in self.workers:
            done += w.batcher.pop_completed()
        return done

    # ------------------------------------------------------------------ #
    def swap_weights(self, params, tag: Optional[str] = None) -> List[dict]:
        """Rolling weight swap: one replica at a time drains and
        re-points at `params` while the rest keep serving. Returns the
        swap events (also appended to `swap_events` for telemetry)."""
        events = []
        for w in self.workers:
            event = w.swap_weights(params)
            event['t'] = round(self.clock(), 3)
            if tag is not None:
                event['tag'] = tag
            self.swap_events.append(event)
            events.append(event)
        return events

    def swap_from_checkpoint(self, directory: str,
                             step: Optional[int] = None) -> List[dict]:
        """Hot-reload the latest (or a named) training checkpoint into
        every replica — params-only restore off the async-checkpoint
        path, then the rolling swap."""
        from ..training.checkpoint import CheckpointManager
        params = CheckpointManager(directory).restore_params(step)
        tag = f'{directory}@{step if step is not None else "latest"}'
        return self.swap_weights(params, tag=tag)
