"""Multi-replica sharded serving: continuous batching over a device mesh.

The scale-out layer above `inference/` (ROADMAP item 3). Where PR 2
built one replica — AOT bucketed engine, micro-batcher with deadline
flushes, admission control — this package multiplies and upgrades it:

  * `replica`  — `ContinuousBatcher`: requests admit into partially-
    filled **in-flight** bucket slots; a slot dispatches the moment it
    fills (inside `admit` — no flush barrier), with the deadline only
    as a fallback for slots that never fill. `ReplicaWorker` pairs a
    batcher with its `InferenceEngine` and owns `drain()` /
    `swap_weights()` (zero-recompile weight hot-reload).
  * `router`   — `Router`: least-outstanding dispatch across N replica
    workers, structured shedding via the PR 2 `AdmissionController`,
    rolling weight swaps (one replica drains at a time while the rest
    keep serving — zero dropped requests), `swap_from_checkpoint` off
    the training-side async-checkpoint path.
  * `telemetry` — `RouterTelemetry`: cross-replica SLO aggregation
    folded into the existing schema'd `serve` record — aggregate
    per-bucket p50/p95/p99 (one shared PhaseTimer), per-replica depth,
    swap events, and the `continuous_admissions` proof counter.

Sharding composes orthogonally: each replica's engine may carry a mesh
and a `parallel.rules` rule set ('tp' / 'fsdp'), so one large model
spans chips (TP/FSDP) while DP replicas multiply throughput.

  * `health`   — the single-host fault domain (docs/ROBUSTNESS.md):
    per-replica health state machines (healthy -> degraded ->
    quarantined with exponential-backoff half-open probes) driven by
    dispatch outcomes; the router drops quarantined replicas out of
    rotation, retries failed batches onto siblings (bounded —
    after-budget failures resolve as structured `RequestFailed`),
    propagates per-request deadlines, and folds it all into the
    `serve`/`fault` records. Chaos gate: `make chaos-smoke`.

  * `transport` + `fleet` — the CROSS-HOST tier (ROADMAP item 5): a
    minimal pluggable RPC transport (in-process `LocalTransport` for
    tests; `BinaryTransport`/`BinaryServer` — persistent pooled
    connections, correlation-id multiplexing, length-prefixed binary
    frames with raw numpy array segments — as the production wire;
    newline-JSON `SocketTransport` kept as the legacy escape hatch),
    `HostServer` exposing one host's router behind five JSON-safe
    methods, and `FleetRouter` — the PR 12 breaker lifted to HOST
    granularity (RPC outcomes + heartbeat staleness drive it, half-open
    `ping` probes close it), health-aware placement on scraped per-host
    signals, cross-host retry-with-redispatch with deadline
    propagation, and canaried weight rollouts that AUTO-ROLL-BACK on a
    failed canary gate. Chaos gate: `make serve-fleet-smoke`.

Entry point: `scripts/serve.py --replicas N` (one host),
`--fleet N` / `--host` (many); smoke gates: `make serve-multi-smoke`,
`make chaos-smoke`, `make serve-fleet-smoke`.
"""
from .fleet import FleetRouter, HostServer  # noqa: F401
from .health import HealthConfig, HealthMonitor, ReplicaHealth  # noqa: F401
from .replica import ContinuousBatcher, ReplicaWorker  # noqa: F401
from .router import Router  # noqa: F401
from .telemetry import RouterTelemetry  # noqa: F401
from .transport import (  # noqa: F401
    BinaryServer, BinaryTransport, LocalTransport, SocketTransport,
    TransportError, serve_binary, serve_socket,
)
