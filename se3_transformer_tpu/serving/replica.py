"""Replica worker: one engine plus its in-flight bucket slots.

The PR 2 `MicroBatcher` queues requests per bucket and FLUSHES — on
batch-full or on a deadline — which makes the deadline a structural part
of the dispatch path: a serve loop that wants low latency must pump
aggressively, and a drain is a barrier over every queue. Continuous
batching inverts that: each bucket owns an open **slot** (a partially
filled, in-flight batch) that requests are admitted into at any time; a
slot dispatches the MOMENT it fills, inside `admit` itself, and the
deadline exists only as a FALLBACK for slots that never fill (counted
separately — `deadline_flushes` on a healthy loaded replica stays near
zero while `continuous_admissions` grows).

`ReplicaWorker` pairs a `ContinuousBatcher` with the `InferenceEngine`
that executes its slots, and owns the replica-local lifecycle verbs the
router composes: `drain()` (dispatch every partial slot) and
`swap_weights()` (drain, then re-point the engine at new params — AOT
executables take params as a call argument, so a swap costs zero
recompiles; the engine's params setter re-places into the same
partition-rule shardings).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..inference.admission import deadline_error
from ..inference.batching import PendingResult, dispatch_batch


class _Slot:
    """One in-flight bucket batch: open for admission until full.
    (Deadlines key off each request's own `submitted_at`, not slot
    age — a slot carries no clock state.)"""

    __slots__ = ('bucket', 'tokens', 'coords', 'pending')

    def __init__(self, bucket: int):
        self.bucket = bucket
        self.tokens: List[np.ndarray] = []
        self.coords: List[np.ndarray] = []
        self.pending: List[PendingResult] = []

    def __len__(self):
        return len(self.pending)


class ContinuousBatcher:
    """Admit requests into partially-filled in-flight bucket slots.

        cb = ContinuousBatcher(engine.run, engine.buckets,
                               engine.batch_size, max_wait_ms=50.0)
        cb.admit(bucket, tokens, coords, pending)  # dispatches on fill
        cb.flush_due()                             # deadline FALLBACK
        cb.drain()                                 # shutdown / swap

    There is no flush barrier: a slot that fills dispatches inside
    `admit` (the `continuous_admissions` counter records every request
    that joined an already-open slot — the proof continuous batching is
    actually happening), and `flush_due` only exists so a trickle of
    requests that never fills a slot still answers within
    `max_wait_ms`. The runner contract and error semantics ARE
    `MicroBatcher`'s: both route through the shared
    `inference.batching.dispatch_batch` (pad / slice-to-true-rows /
    resolve-every-request-on-a-raising-runner), so the two batchers
    cannot drift.

    With `executor` set (the ReplicaWorker `async_dispatch=True` path),
    a filled slot SUBMITS its dispatch to that executor instead of
    blocking the admit caller — on a multi-chip host, N replicas'
    executions then overlap instead of serializing through the router's
    submit loop. Semantics shift accordingly: a raising runner still
    resolves every request of its batch done-with-error (that happens
    inside dispatch_batch on the worker thread), but the exception
    re-raises at the next `wait()` barrier (drain / swap / close)
    rather than inside admit; `inflight` counts submitted-but-unanswered
    requests so the router's least-outstanding signal keeps seeing work
    the executor has not finished.
    """

    def __init__(self, runner: Callable, buckets: Sequence[int],
                 batch_size: int, max_wait_ms: float = 50.0,
                 clock: Callable[[], float] = time.monotonic,
                 executor: Optional[ThreadPoolExecutor] = None):
        self.runner = runner
        self.buckets = tuple(sorted(int(b) for b in buckets))
        assert self.buckets, 'no buckets'
        self.batch_size = int(batch_size)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.clock = clock
        self.executor = executor
        self._slots: Dict[int, _Slot] = {}
        self.continuous_admissions = 0   # joined an in-flight slot
        self.deadline_flushes = 0        # fallback dispatches
        self.batches_dispatched = 0
        self.rows_dispatched = 0         # real (non-dummy) rows
        # fault-domain hooks (serving.Router wires them; None = the
        # original PR 8 semantics, every test of which still holds):
        # on_success(rows) feeds the health breaker; on_failure(bucket,
        # tokens, coords, pending, exc) -> True takes ownership of a
        # failed batch's requests for retry-with-redispatch
        self.on_success: Optional[Callable[[int], None]] = None
        self.on_failure: Optional[Callable] = None
        # retry_hint(queue_depth) -> seconds: when wired (the Router
        # points it at its retry_after_hint), deadline resolutions
        # carry the same machine-readable retry_after_s backoff hint
        # overload sheds do — clients back off uniformly
        self.retry_hint: Optional[Callable[[int], float]] = None
        # request tracing (observability.tracing.Tracer; Router wires
        # it): batch_fill spans here, queue_wait/dispatch/device_run
        # inside dispatch_batch. None = span-free, zero overhead.
        self.tracer = None
        # per-request deadline accounting: requests resolved with a
        # structured RequestFailed('deadline') — shed at dispatch time
        # (deadline_sheds) or expired while waiting in an open slot
        self.timeouts = 0
        self.deadline_sheds = 0
        # completed results: drained by telemetry via pop_completed();
        # bounded like MicroBatcher.completed (submitters keep their
        # own PendingResult either way)
        self.completed: List[PendingResult] = []
        self._completed_capacity = 65536
        # async-dispatch bookkeeping (unused on the sync path)
        self._futures: List[Future] = []
        self._inflight_rows = 0
        self._inflight_lock = threading.Lock()
        # executor threads publish into `completed` while the main
        # thread's pop_completed swaps it — every access goes through
        # this lock (each dispatch resolves into a private list first,
        # so dispatch_batch itself never touches the shared one)
        self._completed_lock = threading.Lock()

    # ------------------------------------------------------------------ #
    @property
    def depth(self) -> int:
        """Requests sitting in open slots (not yet dispatched)."""
        return sum(len(s) for s in self._slots.values())

    @property
    def depth_by_bucket(self) -> Dict[int, int]:
        """Open-slot depth per bucket (a routing signal: the fleet tier
        scrapes it off the host's stats RPC)."""
        return {s.bucket: len(s) for s in self._slots.values() if len(s)}

    @property
    def inflight(self) -> int:
        """Requests submitted to the executor but not yet answered
        (always 0 on the sync path — dispatch completes inline)."""
        with self._inflight_lock:
            return self._inflight_rows

    def admit(self, bucket: int, tokens, coords,
              pending: PendingResult) -> PendingResult:
        """Admit one request into its bucket's in-flight slot; the slot
        dispatches immediately (no pump, no barrier) when it fills."""
        assert bucket in self.buckets, f'{bucket} is not a configured bucket'
        slot = self._slots.get(bucket)
        if slot is None:
            slot = self._slots[bucket] = _Slot(bucket)
        elif slot.pending:
            self.continuous_admissions += 1
            tr = getattr(pending, 'trace', None)
            if self.tracer is not None and tr:
                # the request joined an ALREADY-open in-flight slot —
                # the continuous-batching event worth seeing per trace
                self.tracer.add(tr['ctx'], 'batch_fill',
                                parent_id=tr['parent'],
                                bucket=int(bucket),
                                fill=len(slot.pending) + 1)
        slot.tokens.append(np.asarray(tokens))
        slot.coords.append(np.asarray(coords, np.float32).reshape(-1, 3))
        slot.pending.append(pending)
        if len(slot) >= self.batch_size:
            self._dispatch(slot)
        return pending

    def flush_due(self, now: Optional[float] = None) -> int:
        """Deadline FALLBACK: dispatch every slot whose oldest request
        has waited `max_wait_ms`. Returns batches dispatched. Expired
        requests (per-request deadline, not the slot deadline) are
        resolved with a structured timeout first — they must never
        consume a batch row."""
        now = self.clock() if now is None else now
        self.expire_due(now)
        n = 0
        for slot in list(self._slots.values()):
            if slot.pending and \
                    now - slot.pending[0].submitted_at >= self.max_wait_s:
                self._dispatch(slot)
                self.deadline_flushes += 1
                n += 1
        return n

    def expire_due(self, now: Optional[float] = None) -> int:
        """Resolve every open-slot request whose own deadline
        (`PendingResult.deadline`) has passed with a structured
        `RequestFailed('deadline')` — a request that can no longer be
        answered in time must not wait for a batch to fill. Returns
        requests expired."""
        now = self.clock() if now is None else now
        n = 0
        for slot in list(self._slots.values()):
            n += self._shed_expired(slot, now)
            if not slot.pending:
                self._slots.pop(slot.bucket, None)
        return n

    def _shed_expired(self, slot: _Slot, now: float) -> int:
        """THE expired-request filter (expire_due and the pre-dispatch
        shed both route through it, so the two paths cannot drift):
        drop deadline-expired requests from the slot's parallel lists
        and resolve them done-with-structured-timeout. Returns how
        many were shed."""
        keep = [i for i, p in enumerate(slot.pending)
                if not p.expired(now)]
        if len(keep) == len(slot.pending):
            return 0
        expired = [p for p in slot.pending if p.expired(now)]
        slot.tokens = [slot.tokens[i] for i in keep]
        slot.coords = [slot.coords[i] for i in keep]
        slot.pending = [slot.pending[i] for i in keep]
        self._resolve_failed(expired, now=now)
        return len(expired)

    def _resolve_failed(self, expired: Sequence[PendingResult],
                        now: Optional[float] = None) -> None:
        """Resolve timed-out requests done-with-structured-error and
        publish them to `completed` (the telemetry latency feed sees
        sheds too)."""
        now = self.clock() if now is None else now
        hint = None
        if self.retry_hint is not None:
            try:
                hint = max(0.0, float(self.retry_hint(self.depth)))
            except Exception:
                hint = None     # a broken estimator must not turn a
                #                 structured timeout into a crash
        for p in expired:
            timeout_s = ((p.deadline - p.submitted_at)
                         if p.deadline is not None else 0.0)
            p.error = deadline_error(now - p.submitted_at, timeout_s,
                                     attempts=p.attempts,
                                     retry_after_s=hint)
            p.done = True
            p.completed_at = now
            self.timeouts += 1
        with self._completed_lock:
            self.completed.extend(expired)
            if len(self.completed) > self._completed_capacity:
                del self.completed[:-self._completed_capacity]

    def drain(self) -> int:
        """Dispatch every non-empty slot (shutdown / weight swap)."""
        n = 0
        for slot in list(self._slots.values()):
            if slot.pending:
                self._dispatch(slot)
                n += 1
        return n

    def next_deadline(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the earliest fallback deadline; None when idle."""
        oldest = [s.pending[0].submitted_at
                  for s in self._slots.values() if s.pending]
        if not oldest:
            return None
        now = self.clock() if now is None else now
        return max(0.0, min(oldest) + self.max_wait_s - now)

    def pop_completed(self) -> List[PendingResult]:
        with self._completed_lock:
            done, self.completed = self.completed, []
        return done

    def wait(self) -> None:
        """Barrier over every async dispatch in flight; re-raises the
        FIRST runner exception (its requests already resolved
        done-with-error inside dispatch_batch — this surfaces the
        failure to the serving loop the way the sync path's raising
        admit does). No-op on the sync path."""
        futures, self._futures = self._futures, []
        first_err = None
        for f in futures:
            try:
                f.result()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    # ------------------------------------------------------------------ #
    def _dispatch(self, slot: _Slot):
        # the slot closes the moment it dispatches; the next admit for
        # this bucket opens a fresh one (on a raising runner the
        # requests resolve done-with-error, never silently re-slotted)
        self._slots.pop(slot.bucket, None)
        # shed-before-dispatch: an expired request must not ride (or
        # pad out) a batch whose answer it can no longer use
        self.deadline_sheds += self._shed_expired(slot, self.clock())
        if not slot.pending:
            return
        pending = slot.pending

        def run():
            # dispatch_batch resolves into a PRIVATE list; the shared
            # `completed` is only touched under the lock — an executor
            # thread appending into a list pop_completed just swapped
            # out would silently lose those results from the serve
            # record otherwise
            done_local: List[PendingResult] = []
            try:
                dispatch_batch(self.runner, slot.bucket, self.batch_size,
                               slot.tokens, slot.coords, pending,
                               done_local, self._completed_capacity,
                               self.clock, on_success=self.on_success,
                               on_failure=self.on_failure,
                               tracer=self.tracer)
            finally:
                with self._completed_lock:
                    self.completed.extend(done_local)
                    if len(self.completed) > self._completed_capacity:
                        del self.completed[:-self._completed_capacity]
        self.batches_dispatched += 1
        self.rows_dispatched += len(pending)
        if self.executor is None:
            run()
            return
        with self._inflight_lock:
            self._inflight_rows += len(pending)
        # drop cleanly-finished futures so the list stays bounded
        # without a barrier; errored ones are KEPT until wait() can
        # re-raise them
        self._futures = [f for f in self._futures
                         if not f.done() or f.exception() is not None]

        def tracked():
            try:
                run()
            finally:
                with self._inflight_lock:
                    self._inflight_rows -= len(pending)

        self._futures.append(self.executor.submit(tracked))


class ReplicaWorker:
    """One serving replica: an engine plus its continuous batcher.

        worker = ReplicaWorker(0, engine, max_wait_ms=50.0)
        worker.admit(bucket, tokens, coords, pending)
        worker.swap_weights(new_params)     # drain, re-point, zero drops

    `outstanding` (requests admitted but unanswered) is the router's
    least-outstanding load signal; `draining=True` takes the replica
    out of dispatch rotation while a swap is in flight.

    `async_dispatch=True` gives the replica a single-thread executor
    and routes every slot dispatch through it: the router's submit loop
    never blocks on an engine execution, so on a multi-chip host the N
    replicas' executions OVERLAP instead of serializing (the PR 8
    residue — the synchronous router was measured replica-sequential by
    construction). One thread per replica keeps each engine's
    executions serialized with respect to THEMSELVES (AOT executables
    are not assumed re-entrant) while distinct replicas run
    concurrently. `drain()` and `swap_weights()` barrier on the
    executor, so the rolling-swap contract (old weights answer
    everything already admitted) and the deterministic-clock test
    semantics are unchanged; runner errors surface at those barriers
    instead of inside admit (see ContinuousBatcher.wait).
    """

    def __init__(self, replica_id: int, engine, *,
                 max_wait_ms: float = 50.0,
                 clock: Callable[[], float] = time.monotonic,
                 async_dispatch: bool = False,
                 fault_injector=None):
        self.id = int(replica_id)
        self.engine = engine
        self.executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f'replica{self.id}') \
            if async_dispatch else None
        runner = engine.run
        if fault_injector is not None:
            # the chaos harness's crash/latency site: fires BEFORE the
            # engine runs, so an injected exception walks the exact
            # path a real engine failure walks (dispatch_batch error
            # contract -> retry/health hooks)
            def runner(bucket, tokens, coords, mask, _run=engine.run,
                       _inj=fault_injector, _rid=self.id):
                _inj.fire('replica_dispatch', replica=_rid, bucket=bucket)
                return _run(bucket, tokens, coords, mask)
        self.batcher = ContinuousBatcher(
            runner, engine.buckets, engine.batch_size,
            max_wait_ms=max_wait_ms, clock=clock,
            executor=self.executor)
        self.draining = False
        self.swaps = 0

    # ------------------------------------------------------------------ #
    @property
    def outstanding(self) -> int:
        # open-slot depth + async dispatches not yet answered: the
        # least-outstanding router must keep seeing a replica's work
        # until the executor finishes it
        return self.batcher.depth + self.batcher.inflight

    @property
    def served_rows(self) -> int:
        return int(sum(self.engine.rows_served.values()))

    def admit(self, bucket: int, tokens, coords,
              pending: PendingResult) -> PendingResult:
        assert not self.draining, \
            f'replica {self.id} is draining — the router must not ' \
            f'admit into it'
        return self.batcher.admit(bucket, tokens, coords, pending)

    def flush_due(self, now=None) -> int:
        return self.batcher.flush_due(now)

    def drain(self) -> int:
        """Dispatch every partial slot AND barrier on any async
        dispatches — after drain() returns, everything admitted has
        answered (the end-of-stream / pre-swap contract)."""
        n = self.batcher.drain()
        self.batcher.wait()
        return n

    def swap_weights(self, params) -> dict:
        """Drain the in-flight slots (old weights answer everything
        already admitted — the drain barriers on the executor, so an
        async dispatch can never race the re-point), then re-point the
        engine at `params`. AOT executables take params as a call
        argument, so the swap compiles NOTHING — the engine's params
        setter re-places into the same partition-rule shardings.
        Returns the swap event for the telemetry stream."""
        self.draining = True
        try:
            drained = self.drain()
            self.engine.params = params
        finally:
            self.draining = False
        self.swaps += 1
        return dict(replica=self.id, drained_batches=drained,
                    swap_index=self.swaps)

    def close(self) -> None:
        """Shut the executor down (idempotent; sync replicas no-op)."""
        if self.executor is not None:
            self.executor.shutdown(wait=True)

    def snapshot(self) -> dict:
        """Per-replica depth/served/swap counters for the serve record.
        `precision` surfaces the engine's weight-precision mix — the
        router accepts replicas built at DIFFERENT mixes (heterogeneous
        serving), so the record must say which replica ran which."""
        return dict(depth=self.batcher.depth,
                    precision=getattr(self.engine, 'precision_name',
                                      'fp32'),
                    model_family=getattr(self.engine, 'model_family',
                                         'se3_v1'),
                    served=self.served_rows,
                    batches=self.batcher.batches_dispatched,
                    continuous_admissions=self.batcher.continuous_admissions,
                    deadline_flushes=self.batcher.deadline_flushes,
                    timeouts=self.batcher.timeouts,
                    deadline_sheds=self.batcher.deadline_sheds,
                    swaps=self.swaps,
                    draining=self.draining)
