"""Minimal pluggable RPC transport for the cross-host serving tier.

The fleet front-end (`serving.fleet.FleetRouter`) speaks to per-host
`Router`s through exactly one verb:

    response = transport.call(method, payload, timeout_s=...)

where `payload` and `response` are JSON-safe dicts and EVERY failure of
the link itself — connection refused, reset mid-read, timeout, injected
partition — surfaces as `TransportError`. That one exception class is
the fleet's host-failure signal: the host breaker records it, the
request redispatches onto a sibling host. Application-level failures
(an oversize reject, a deadline, a spent retry budget INSIDE the host)
ride the response envelope (`{'ok': False, 'error': {...}}`) and are
NOT transport errors — a host that answers "no" is alive.

Three implementations, one contract (`tests/test_fleet.py` and
`tests/test_transport.py` pin all of them):

  * `LocalTransport` — in-process: calls the `HostServer.handle` of the
    wrapped host directly. The unit-test and single-process arm — the
    fleet logic is identical, only the wire is gone. Numpy arrays in
    payload/response pass through UNCHANGED (no `tolist()` round-trip:
    the fleet and the host share the buffers).
  * `BinaryTransport` / `BinaryServer` / `serve_binary` — the
    production arm: persistent pooled connections, correlation-id
    multiplexing (many in-flight calls share one connection; one
    reader thread per connection demuxes responses to waiting
    callers), and length-prefixed binary framing where numpy arrays
    ride as raw dtype+shape-tagged buffer segments:

        MAGIC(4B) | u32 env_len | u32 body_len |
        env JSON (control envelope: id/method/payload minus arrays,
                  plus the array manifest [{path, dtype, shape}, ...]) |
        raw array bytes, concatenated in manifest order

    Zero `tolist()`/`json.loads` on the array hot path — JSON is
    reserved for the small control envelope; the receive side
    reconstructs arrays as `np.frombuffer` views of the frame buffer.
    A dead connection fails its in-flight calls with `TransportError`
    and the NEXT call reconnects — a host restart on the same port
    stays transparent, exactly like the legacy arm. Server-side there
    is no thread-per-connection: one demux thread reads frames off
    every connection, `HostServer.handle_async` enqueues onto the
    host's single serve-loop thread (its ownership contract is
    unchanged), and a small frame-pump pool writes responses back.
  * `SocketTransport` / `serve_socket` — the legacy arm kept as the
    `--transport legacy` escape hatch: newline-delimited JSON over a
    TCP socket, one request per connection. Arrays degrade to lists at
    this wire (`json.dumps(default=...)`), so callers may pass numpy
    payloads to either arm.

All arms fire the seeded `faults.FaultInjector` at the `transport`
site before sending (ctx: method, host), so the fleet-chaos smoke's
RPC flakiness is deterministic: `latency` plans sleep (a slow link),
`exception` plans raise (a reset connection — re-raised as
`TransportError`, the path a real reset walks), and the cooperative
`drop` kind models a partition (the transport raises `TransportError`
without ever sending).
"""
from __future__ import annotations

import json
import queue
import select
import selectors
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..faults import InjectedFault

__all__ = ['TransportError', 'LocalTransport', 'SocketTransport',
           'SocketServer', 'serve_socket',
           'BinaryTransport', 'BinaryServer', 'serve_binary',
           'pack_frame', 'unpack_frame']


class TransportError(RuntimeError):
    """The link to a host failed (refused / reset / timeout / injected
    partition). The fleet treats this as a HOST outcome — breaker
    failure + cross-host redispatch — never as a request verdict."""


def _fire_transport_faults(injector, method: str, host: str) -> None:
    """Shared injection hook: one site, three deterministic failure
    modes (latency sleeps in place; exception and drop both surface as
    TransportError so they walk the exact path a real link failure
    walks)."""
    if injector is None:
        return
    try:
        kind = injector.fire('transport', method=method, host=host)
    except InjectedFault as e:
        raise TransportError(str(e)) from e
    if kind == 'drop':
        raise TransportError(
            f'injected partition: {method!r} to host {host} dropped '
            f'(request never sent, no response will come)')


# --------------------------------------------------------------------- #
# binary framing: JSON control envelope + raw array segments
# --------------------------------------------------------------------- #
_MAGIC = b'SE3B'
_HEADER = struct.Struct('>4sII')      # magic, env_len, body_len
_MAX_FRAME = 1 << 30                  # sanity bound: 1 GiB per frame


class FrameError(ValueError):
    """The byte stream is not a valid frame (bad magic / oversize /
    undecodable envelope). A framing error is unrecoverable for its
    connection — there is no way to resync a corrupted length-prefixed
    stream — so both ends count it and drop the connection; callers
    see the usual `TransportError` and the next call reconnects."""


def _np_jsonable(obj):
    """`json.dumps(default=...)` hook for the LEGACY arm only: numpy
    arrays degrade to lists at the text wire (the binary framing ships
    them raw), so callers may hand numpy payloads to either arm."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    raise TypeError(f'{type(obj).__name__} is not JSON serializable')


def pack_frame(msg: dict) -> List[object]:
    """Encode one message as a list of send buffers (header + envelope
    + one raw segment per numpy array — the segments are memoryviews
    of the arrays themselves, no copy). Every `np.ndarray` at any dict
    path inside `msg` is lifted out of the JSON envelope and tagged in
    the `_arrays` manifest as (dotted path, dtype, shape)."""
    arrays: List[tuple] = []

    def strip(node, prefix):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                p = f'{prefix}.{k}' if prefix else str(k)
                if isinstance(v, np.ndarray):
                    arrays.append((p, np.ascontiguousarray(v)))
                elif isinstance(v, np.generic):
                    out[k] = v.item()
                else:
                    out[k] = strip(v, p)
            return out
        return node

    env = strip(msg, '')
    env['_arrays'] = [dict(path=p, dtype=a.dtype.str, shape=list(a.shape))
                      for p, a in arrays]
    env_bytes = json.dumps(env).encode()
    body_len = sum(a.nbytes for _, a in arrays)
    if len(env_bytes) + body_len > _MAX_FRAME:
        raise FrameError(
            f'frame too large: {len(env_bytes) + body_len}B '
            f'> {_MAX_FRAME}B')
    bufs: List[object] = [_HEADER.pack(_MAGIC, len(env_bytes), body_len),
                          env_bytes]
    bufs.extend(a.data for _, a in arrays)
    return bufs


def unpack_frame(env_bytes, body) -> dict:
    """Decode one frame back into its message dict. Array segments
    become `np.frombuffer` views of `body` (zero-copy — read-only when
    `body` is bytes) reinserted at their manifest paths."""
    try:
        env = json.loads(bytes(env_bytes).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f'undecodable envelope: {e}') from e
    if not isinstance(env, dict):
        raise FrameError(f'envelope is {type(env).__name__}, not a dict')
    manifest = env.pop('_arrays', [])
    mv = memoryview(body)
    off = 0
    for d in manifest:
        try:
            dt = np.dtype(d['dtype'])
            shape = tuple(int(s) for s in d['shape'])
            n = 1
            for s in shape:
                n *= s
            nbytes = n * dt.itemsize
            arr = np.frombuffer(mv[off:off + nbytes],
                                dtype=dt).reshape(shape)
        except (KeyError, TypeError, ValueError) as e:
            raise FrameError(f'bad array segment {d!r}: {e}') from e
        off += nbytes
        node = env
        keys = str(d['path']).split('.')
        for k in keys[:-1]:
            nxt = node.get(k)
            if not isinstance(nxt, dict):
                raise FrameError(f'manifest path {d["path"]!r} does '
                                 f'not exist in the envelope')
            node = nxt
        node[keys[-1]] = arr
    if off != mv.nbytes:
        raise FrameError(f'frame body is {mv.nbytes}B but the manifest '
                         f'accounts for {off}B')
    return env


def _read_exact(sock: socket.socket, n: int) -> bytearray:
    """Blocking read of exactly `n` bytes (EOF mid-frame raises — the
    peer died, which the caller maps to a dead connection)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise ConnectionError(
                f'peer closed mid-frame ({got}/{n}B read)')
        got += k
    return buf


def _recv_frame(sock: socket.socket):
    """Blocking read of one whole frame -> (message dict, wire bytes)."""
    head = _read_exact(sock, _HEADER.size)
    magic, env_len, body_len = _HEADER.unpack(bytes(head))
    if magic != _MAGIC:
        raise FrameError(
            f'bad frame magic {magic!r} (protocol mismatch? a legacy '
            f'JSON peer cannot speak to a binary endpoint)')
    if env_len + body_len > _MAX_FRAME:
        raise FrameError(f'frame too large: {env_len + body_len}B')
    env_bytes = _read_exact(sock, env_len)
    body = _read_exact(sock, body_len) if body_len else b''
    return (unpack_frame(env_bytes, body),
            _HEADER.size + env_len + body_len)


class LocalTransport:
    """In-process transport: the wire-free arm of the contract.

        server = HostServer(router, host_id=0)
        t = LocalTransport(server, fault_injector=inj)
        t.call('ping')                     # -> {'ok': True, ...}
    """

    def __init__(self, server, fault_injector=None,
                 label: Optional[str] = None):
        self.server = server
        self.fault_injector = fault_injector
        self.label = label if label is not None else \
            f'local:{getattr(server, "host_id", "?")}'

    def call(self, method: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> dict:
        _fire_transport_faults(self.fault_injector, method, self.label)
        try:
            return self.server.handle(method, payload,
                                      timeout_s=timeout_s)
        except Exception as e:  # a crashed handler IS a dead host
            raise TransportError(
                f'{self.label}: {method!r} handler raised '
                f'{type(e).__name__}: {e}') from e

    def __repr__(self):
        return f'LocalTransport({self.label})'


class SocketTransport:
    """Newline-delimited JSON over TCP, one request per connection.

        t = SocketTransport('127.0.0.1', 9000)
        t.call('infer', dict(tokens=[...], coords=[...]), timeout_s=5)

    `timeout_s` bounds connect + send + the full response read — the
    deadline-propagation arm of the fleet contract (a hung host must
    cost one timeout, not a wedged front-end). Connecting per call
    makes a host RESTART transparent: the next call reaches whatever
    process now owns the port.
    """

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, fault_injector=None,
                 label: Optional[str] = None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.fault_injector = fault_injector
        self.label = label if label is not None else f'{host}:{port}'
        # wire accounting so the loadgen A/B can price this arm's
        # bytes-on-wire against the binary framing's
        self._stats_lock = threading.Lock()
        self._stats = dict(connections_opened=0, reconnects=0,
                           in_flight=0, peak_in_flight=0,
                           bytes_sent=0, bytes_received=0,
                           frame_errors=0)

    def transport_stats(self) -> dict:
        """Snapshot of the wire counters (same shape as the binary
        arm's, so records and the loadgen treat both uniformly —
        `connections_opened` counts one per call here, that being the
        whole point of the A/B)."""
        with self._stats_lock:
            return dict(self._stats)

    def call(self, method: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> dict:
        _fire_transport_faults(self.fault_injector, method, self.label)
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        # one ABSOLUTE deadline for connect + send + the full response
        # read: a per-recv timeout would let a host that trickles one
        # chunk per interval hold a fleet pool thread indefinitely —
        # exactly the wedged front-end this bound exists to prevent
        deadline = time.monotonic() + max(0.001, timeout)

        def remaining() -> float:
            left = deadline - time.monotonic()
            if left <= 0:
                raise socket.timeout(
                    f'transport deadline ({timeout:.3f}s) exhausted')
            return left

        # arrays degrade to lists at this wire (the binary arm ships
        # them raw) — callers hand numpy payloads to either arm
        line = json.dumps(dict(method=method, payload=payload or {}),
                          default=_np_jsonable) + '\n'
        data = line.encode()
        with self._stats_lock:
            self._stats['connections_opened'] += 1
            self._stats['in_flight'] += 1
            self._stats['peak_in_flight'] = max(
                self._stats['peak_in_flight'], self._stats['in_flight'])
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=remaining()) as s:
                s.settimeout(remaining())
                s.sendall(data)
                s.shutdown(socket.SHUT_WR)
                chunks = []
                while True:
                    s.settimeout(remaining())
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    chunks.append(chunk)
        except OSError as e:
            raise TransportError(
                f'{self.label}: {method!r} failed on the wire: '
                f'{type(e).__name__}: {e}') from e
        finally:
            with self._stats_lock:
                self._stats['in_flight'] -= 1
        raw = b''.join(chunks)
        with self._stats_lock:
            self._stats['bytes_sent'] += len(data)
            self._stats['bytes_received'] += len(raw)
        if not raw.strip():
            raise TransportError(
                f'{self.label}: {method!r} got an empty response '
                f'(host died mid-call?)')
        try:
            return json.loads(raw.decode())
        except ValueError as e:
            raise TransportError(
                f'{self.label}: {method!r} returned undecodable bytes '
                f'({len(raw)}B): {e}') from e

    def __repr__(self):
        return f'SocketTransport({self.label})'


class SocketServer:
    """Accept loop exposing a `HostServer` on a TCP port (daemon
    threads: one acceptor, one per in-flight connection — connections
    are one-shot, so the per-connection thread count tracks the fleet's
    in-flight RPC count, which the front-end already bounds)."""

    def __init__(self, handler: Callable, port: int = 0,
                 host: str = '127.0.0.1'):
        self.handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name=f'rpc-accept:{self.port}',
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return    # close() won the startup race — nothing to serve
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket):
        with conn:
            try:
                conn.settimeout(60.0)
                buf = b''
                while not buf.endswith(b'\n'):
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                req = json.loads(buf.decode())
                try:
                    resp = self.handler(req.get('method'),
                                        req.get('payload'),
                                        timeout_s=(req.get('payload') or
                                                   {}).get('timeout_s'))
                except Exception as e:  # handler crash -> app error, not
                    #                     a torn wire: the caller can at
                    #                     least read what happened
                    resp = dict(ok=False, error=dict(
                        code='internal',
                        message=f'{type(e).__name__}: {e}'))
                # numpy results (the no-tolist hot path) degrade to
                # lists at this legacy text wire
                conn.sendall((json.dumps(resp, default=_np_jsonable)
                              + '\n').encode())
            except (OSError, ValueError):
                pass    # torn connection / garbage line: the client's
                #         read fails and ITS TransportError carries the
                #         verdict — nothing useful to do server-side

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def serve_socket(server, port: int = 0,
                 host: str = '127.0.0.1') -> SocketServer:
    """Expose a `HostServer` on a TCP port; returns the running
    `SocketServer` (its `.port` is the bound port — pass 0 to let the
    OS pick, the worker prints it in its READY line)."""
    return SocketServer(server.handle, port=port, host=host)


# --------------------------------------------------------------------- #
# the production arm: pooled + multiplexed + binary-framed
# --------------------------------------------------------------------- #
class _Waiter:
    """One in-flight call's parking spot in a connection's demux
    table: the reader thread resolves it (response or link death), the
    calling thread waits on it under its own deadline."""

    __slots__ = ('event', 'response', 'error')

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[dict] = None
        self.error: Optional[str] = None


class _MuxConn:
    """One persistent connection: the socket, a send lock (frames from
    concurrent callers must not interleave), the correlation-id ->
    waiter table, and liveness."""

    __slots__ = ('sock', 'send_lock', 'lock', 'pending', 'alive')

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.lock = threading.Lock()
        self.pending: Dict[int, _Waiter] = {}
        self.alive = True


class BinaryTransport:
    """Persistent pooled binary-framed transport with correlation-id
    multiplexing — same one-verb `call()` surface and `TransportError`
    failure signal as the other arms, so the fleet runs unmodified.

        t = BinaryTransport('127.0.0.1', 9000, pool_size=2)
        t.call('infer', dict(tokens=np.arange(8), coords=...), timeout_s=5)

    Calls round-robin over `pool_size` persistent connections; many
    calls share each connection in flight at once (one reader thread
    per connection demuxes responses by correlation id). A dead
    connection — reset, EOF, frame corruption, send timeout — fails
    ONLY its own in-flight calls with `TransportError` and the next
    call reconnects, so a host restart on the same port stays exactly
    as transparent as the legacy connect-per-call arm. `timeout_s`
    still bounds connect + send + the response wait per call."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, fault_injector=None,
                 label: Optional[str] = None, pool_size: int = 2):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.fault_injector = fault_injector
        self.label = label if label is not None else f'{host}:{port}'
        self.pool_size = max(1, int(pool_size))
        self._slots: List[Optional[_MuxConn]] = [None] * self.pool_size
        self._ever_connected: set = set()
        self._lock = threading.Lock()      # slots + counters + corr ids
        self._rr = 0
        self._next_id = 0
        self._closed = False
        self._stats = dict(connections_opened=0, reconnects=0,
                           in_flight=0, peak_in_flight=0,
                           bytes_sent=0, bytes_received=0,
                           frame_errors=0)

    def transport_stats(self) -> dict:
        """Snapshot of the transport counters (the `transport` section
        of fleet/serve records and the loadgen A/B read these)."""
        with self._lock:
            return dict(self._stats)

    # ------------------------------------------------------------------ #
    def _checkout(self, deadline: float) -> _MuxConn:
        """Round-robin a live pooled connection, (re)connecting the
        slot if its connection died. Connect runs under the pool lock —
        reconnects are rare and serializing them keeps a thundering
        herd from opening `callers` sockets to a freshly restarted
        host."""
        with self._lock:
            if self._closed:
                raise TransportError(f'{self.label}: transport closed')
            slot = self._rr % self.pool_size
            self._rr += 1
            conn = self._slots[slot]
            if conn is not None and conn.alive:
                return conn
            left = deadline - time.monotonic()
            if left <= 0:
                raise TransportError(
                    f'{self.label}: deadline exhausted before connect')
            sock = socket.create_connection(
                (self.host, self.port), timeout=min(left, 10.0))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # bound each send syscall (SO_SNDTIMEO) so a wedged peer
            # with a full buffer surfaces as an OSError instead of
            # parking the caller forever; recv stays fully blocking —
            # the reader thread owns it and per-call deadlines are
            # enforced by the waiter, not the socket
            sec = max(1, int(self.timeout_s))
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            struct.pack('ll', sec, 0))
            sock.settimeout(None)
            conn = _MuxConn(sock)
            self._slots[slot] = conn
            self._stats['connections_opened'] += 1
            if slot in self._ever_connected:
                self._stats['reconnects'] += 1
            self._ever_connected.add(slot)
            threading.Thread(
                target=self._read_loop, args=(conn,),
                name=f'mux-read:{self.label}#{slot}', daemon=True).start()
            return conn

    def _read_loop(self, conn: _MuxConn):
        """The demux thread: one per connection, reads frames forever,
        routes each response to its correlation id's waiter. Any read
        failure kills the connection and fails everything in flight on
        it."""
        why = 'connection closed'
        try:
            while True:
                msg, nbytes = _recv_frame(conn.sock)
                with self._lock:
                    self._stats['bytes_received'] += nbytes
                with conn.lock:
                    waiter = conn.pending.pop(msg.get('id'), None)
                if waiter is not None:
                    waiter.response = msg.get('response')
                    waiter.event.set()
                # unknown id: the caller already gave up on its
                # deadline — the late response is discarded
        except FrameError as e:
            with self._lock:
                self._stats['frame_errors'] += 1
            why = f'frame error: {e}'
        except OSError as e:
            why = f'{type(e).__name__}: {e}'
        except Exception as e:      # pragma: no cover - defense in depth
            why = f'{type(e).__name__}: {e}'
        self._kill_conn(conn, why)

    def _kill_conn(self, conn: _MuxConn, why: str):
        with conn.lock:
            already_dead = not conn.alive
            conn.alive = False
            pending, conn.pending = dict(conn.pending), {}
        try:
            conn.sock.close()
        except OSError:
            pass
        if already_dead and not pending:
            return
        for waiter in pending.values():
            waiter.error = (f'connection lost in flight ({why}) — '
                            f'the next call reconnects')
            waiter.event.set()

    # ------------------------------------------------------------------ #
    def call(self, method: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> dict:
        _fire_transport_faults(self.fault_injector, method, self.label)
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + max(0.001, timeout)
        try:
            conn = self._checkout(deadline)
        except OSError as e:
            raise TransportError(
                f'{self.label}: {method!r} connect failed: '
                f'{type(e).__name__}: {e}') from e
        with self._lock:
            cid = self._next_id
            self._next_id += 1
        waiter = _Waiter()
        with conn.lock:
            if not conn.alive:
                raise TransportError(
                    f'{self.label}: {method!r} raced a dying '
                    f'connection — the next call reconnects')
            conn.pending[cid] = waiter
        bufs = pack_frame(dict(id=cid, method=method,
                               payload=payload or {}))
        nbytes = sum(memoryview(b).nbytes for b in bufs)
        with self._lock:
            self._stats['in_flight'] += 1
            self._stats['peak_in_flight'] = max(
                self._stats['peak_in_flight'], self._stats['in_flight'])
        try:
            try:
                with conn.send_lock:
                    for b in bufs:
                        conn.sock.sendall(b)
            except OSError as e:
                self._kill_conn(conn, f'send failed: {e}')
                raise TransportError(
                    f'{self.label}: {method!r} failed on the wire: '
                    f'{type(e).__name__}: {e}') from e
            with self._lock:
                self._stats['bytes_sent'] += nbytes
            left = deadline - time.monotonic()
            if not waiter.event.wait(timeout=max(0.001, left)):
                with conn.lock:
                    conn.pending.pop(cid, None)
                raise TransportError(
                    f'{self.label}: {method!r} deadline '
                    f'({timeout:.3f}s) exhausted waiting for the '
                    f'response (correlation id {cid})')
            if waiter.error is not None:
                raise TransportError(
                    f'{self.label}: {method!r} {waiter.error}')
            return waiter.response
        finally:
            with self._lock:
                self._stats['in_flight'] -= 1

    def close(self):
        """Close the pool (in-flight calls fail with TransportError).
        The fleet never calls this mid-run — it exists for clean
        shutdown in smokes/tests."""
        with self._lock:
            self._closed = True
            conns = [c for c in self._slots if c is not None]
        for conn in conns:
            self._kill_conn(conn, 'transport closed')

    def __repr__(self):
        return f'BinaryTransport({self.label}, pool={self.pool_size})'


class _ServerConn:
    """Server-side connection state: the nonblocking socket, its
    partial-frame read buffer, and a send lock (pump threads must not
    interleave response frames)."""

    __slots__ = ('sock', 'buf', 'send_lock', 'open')

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = bytearray()
        self.send_lock = threading.Lock()
        self.open = True


class BinaryServer:
    """Frame-pump server for the binary multiplexed arm.

    No thread-per-connection: one acceptor, ONE demux thread that
    `select()`s over every connection and parses complete frames, and
    a small frame-pump pool that executes/ships responses. With an
    `async_handler` (`HostServer.handle_async`) the demux thread only
    ENQUEUES each call onto the host's serve loop — the serve loop
    still owns all router state, and its completion callback hands the
    response to a pump thread for the wire write, so a slow infer
    never parks a pump thread and in-flight depth is bounded by the
    host's admission control, not by this pool. With a plain sync
    `handler` (tests, loadgen echo servers) the pump threads run the
    handler directly, so at most `pumps` calls execute at once."""

    def __init__(self, handler: Callable, port: int = 0,
                 host: str = '127.0.0.1', *, pumps: int = 4,
                 async_handler: Optional[Callable] = None):
        self.handler = handler
        self.async_handler = async_handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._newq: 'queue.Queue' = queue.Queue()
        self._workq: 'queue.Queue' = queue.Queue()
        self._selector = selectors.DefaultSelector()
        self._slock = threading.Lock()
        self._stats = dict(connections_opened=0, reconnects=0,
                           in_flight=0, peak_in_flight=0,
                           bytes_sent=0, bytes_received=0,
                           frame_errors=0)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f'bin-accept:{self.port}',
            daemon=True)
        self._demux_thread = threading.Thread(
            target=self._demux_loop, name=f'bin-demux:{self.port}',
            daemon=True)
        self._pumps = [threading.Thread(
            target=self._pump_loop, name=f'bin-pump{i}:{self.port}',
            daemon=True) for i in range(max(1, int(pumps)))]
        self._accept_thread.start()
        self._demux_thread.start()
        for t in self._pumps:
            t.start()

    def transport_stats(self) -> dict:
        """Server-side wire counters (the host's serve records carry
        these; `reconnects` is always 0 server-side — only the client
        knows a fresh accept is a reconnect)."""
        with self._slock:
            return dict(self._stats)

    # ------------------------------------------------------------------ #
    def _accept_loop(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return    # close() won the startup race — nothing to serve
        while not self._stop.is_set():
            try:
                sock, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                sock.setblocking(False)
            except OSError:
                continue
            with self._slock:
                self._stats['connections_opened'] += 1
            # hand the socket to the demux thread, the selector's only
            # owner (registering from two threads is a select race)
            self._newq.put(_ServerConn(sock))

    def _demux_loop(self):
        while not self._stop.is_set():
            while True:
                try:
                    conn = self._newq.get_nowait()
                except queue.Empty:
                    break
                try:
                    self._selector.register(conn.sock,
                                            selectors.EVENT_READ, conn)
                except (OSError, ValueError):
                    conn.open = False
            try:
                events = self._selector.select(timeout=0.05)
            except OSError:
                continue
            for key, _ in events:
                self._pump_read(key.data)
        for key in list(self._selector.get_map().values()):
            self._drop_conn(key.data)
        self._selector.close()

    def _pump_read(self, conn: _ServerConn):
        """Drain the socket, carve complete frames off the buffer,
        dispatch each one."""
        while True:
            try:
                chunk = conn.sock.recv(1 << 18)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop_conn(conn)
                return
            if not chunk:
                self._drop_conn(conn)
                return
            conn.buf += chunk
            with self._slock:
                self._stats['bytes_received'] += len(chunk)
            if len(chunk) < (1 << 18):
                break
        while True:
            if len(conn.buf) < _HEADER.size:
                return
            magic, env_len, body_len = _HEADER.unpack_from(conn.buf)
            if magic != _MAGIC or env_len + body_len > _MAX_FRAME:
                # a corrupted length-prefixed stream cannot be
                # resynced: count it, drop the connection, let the
                # client's TransportError + reconnect tell the story
                with self._slock:
                    self._stats['frame_errors'] += 1
                self._drop_conn(conn)
                return
            total = _HEADER.size + env_len + body_len
            if len(conn.buf) < total:
                return
            frame = bytes(conn.buf[:total])
            del conn.buf[:total]
            mv = memoryview(frame)
            try:
                msg = unpack_frame(
                    mv[_HEADER.size:_HEADER.size + env_len],
                    mv[_HEADER.size + env_len:])
            except FrameError:
                with self._slock:
                    self._stats['frame_errors'] += 1
                self._drop_conn(conn)
                return
            self._dispatch(conn, msg)

    def _dispatch(self, conn: _ServerConn, msg: dict):
        cid = msg.get('id')
        method = msg.get('method')
        payload = msg.get('payload') or {}
        timeout_s = payload.get('timeout_s')
        with self._slock:
            self._stats['in_flight'] += 1
            self._stats['peak_in_flight'] = max(
                self._stats['peak_in_flight'], self._stats['in_flight'])
        replied = []

        def reply(response):
            # exactly-once: a buggy double-completion must not skew
            # the in-flight gauge or send a duplicate frame
            if replied:
                return
            replied.append(True)
            self._workq.put(('send', conn, cid, response))

        if self.async_handler is not None:
            try:
                self.async_handler(method, payload, reply,
                                   timeout_s=timeout_s)
            except Exception as e:   # a crashing enqueue still answers
                reply(dict(ok=False, error=dict(
                    code='internal',
                    message=f'{type(e).__name__}: {e}')))
        else:
            self._workq.put(('call', conn, cid, method, payload,
                             timeout_s, reply))

    def _pump_loop(self):
        while True:
            item = self._workq.get()
            if item is None:
                return
            if item[0] == 'call':
                _, conn, cid, method, payload, timeout_s, reply = item
                try:
                    resp = self.handler(method, payload,
                                        timeout_s=timeout_s)
                except Exception as e:  # handler crash -> app error,
                    #                     not a torn wire (same contract
                    #                     as the legacy server)
                    resp = dict(ok=False, error=dict(
                        code='internal',
                        message=f'{type(e).__name__}: {e}'))
                self._send_response(conn, cid, resp)
            else:
                _, conn, cid, resp = item
                self._send_response(conn, cid, resp)

    def _send_response(self, conn: _ServerConn, cid, response):
        try:
            try:
                bufs = pack_frame(dict(id=cid, response=response))
            except (FrameError, TypeError, ValueError) as e:
                # an unencodable response must still answer — the
                # caller gets a structured internal error, not silence
                with self._slock:
                    self._stats['frame_errors'] += 1
                bufs = pack_frame(dict(id=cid, response=dict(
                    ok=False, error=dict(
                        code='internal',
                        message=f'response not frameable: {e}'))))
            nbytes = sum(memoryview(b).nbytes for b in bufs)
            try:
                with conn.send_lock:
                    for b in bufs:
                        _sendall_nonblocking(conn.sock, b)
                with self._slock:
                    self._stats['bytes_sent'] += nbytes
            except OSError:
                self._drop_conn(conn)
        finally:
            with self._slock:
                self._stats['in_flight'] -= 1

    def _drop_conn(self, conn: _ServerConn):
        if not conn.open:
            return
        conn.open = False
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        self._demux_thread.join(timeout=2.0)
        for _ in self._pumps:
            self._workq.put(None)
        for t in self._pumps:
            t.join(timeout=2.0)


def serve_binary(server, port: int = 0, host: str = '127.0.0.1',
                 pumps: int = 4) -> BinaryServer:
    """Expose a `HostServer` on a TCP port over the binary multiplexed
    framing; returns the running `BinaryServer` (its `.port` is the
    bound port). Uses the host's `handle_async` when present so the
    serve loop keeps single ownership of the router and in-flight
    depth is never bounded by the pump pool."""
    return BinaryServer(server.handle, port=port, host=host,
                        pumps=pumps,
                        async_handler=getattr(server, 'handle_async',
                                              None))


def _sendall_nonblocking(sock: socket.socket, buf,
                         timeout_s: float = 30.0):
    """sendall for a nonblocking socket: spin send/wait-writable until
    the buffer is gone (raises socket.timeout if the peer stalls a
    full `timeout_s` — the connection is then dropped)."""
    mv = memoryview(buf)
    if mv.format != 'B':
        mv = mv.cast('B')
    deadline = time.monotonic() + timeout_s
    while mv.nbytes:
        try:
            n = sock.send(mv)
        except (BlockingIOError, InterruptedError):
            left = deadline - time.monotonic()
            if left <= 0:
                raise socket.timeout(
                    f'response send stalled for {timeout_s:.0f}s')
            select.select([], [sock], [], min(left, 0.5))
            continue
        mv = mv[n:]
