"""Minimal pluggable RPC transport for the cross-host serving tier.

The fleet front-end (`serving.fleet.FleetRouter`) speaks to per-host
`Router`s through exactly one verb:

    response = transport.call(method, payload, timeout_s=...)

where `payload` and `response` are JSON-safe dicts and EVERY failure of
the link itself — connection refused, reset mid-read, timeout, injected
partition — surfaces as `TransportError`. That one exception class is
the fleet's host-failure signal: the host breaker records it, the
request redispatches onto a sibling host. Application-level failures
(an oversize reject, a deadline, a spent retry budget INSIDE the host)
ride the response envelope (`{'ok': False, 'error': {...}}`) and are
NOT transport errors — a host that answers "no" is alive.

Two implementations, one contract (`tests/test_fleet.py` pins both):

  * `LocalTransport` — in-process: calls the `HostServer.handle` of the
    wrapped host directly. The unit-test and single-process arm — the
    fleet logic is identical, only the wire is gone.
  * `SocketTransport` / `serve_socket` — newline-delimited JSON over a
    TCP socket, one request per connection (a fleet front-end's call
    rate is batches, not packets — reconnect-per-call keeps a host
    restart transparent: the next call simply connects to the new
    process on the same port). `serve_socket` runs the accept loop for
    a `HostServer` on a daemon thread; `scripts/serve.py --host` is the
    process entry point.

Both fire the seeded `faults.FaultInjector` at the `transport` site
before sending (ctx: method, host), so the fleet-chaos smoke's RPC
flakiness is deterministic: `latency` plans sleep (a slow link),
`exception` plans raise (a reset connection — re-raised as
`TransportError`, the path a real reset walks), and the cooperative
`drop` kind models a partition (the transport raises `TransportError`
without ever sending).
"""
from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Optional

from ..faults import InjectedFault

__all__ = ['TransportError', 'LocalTransport', 'SocketTransport',
           'SocketServer', 'serve_socket']


class TransportError(RuntimeError):
    """The link to a host failed (refused / reset / timeout / injected
    partition). The fleet treats this as a HOST outcome — breaker
    failure + cross-host redispatch — never as a request verdict."""


def _fire_transport_faults(injector, method: str, host: str) -> None:
    """Shared injection hook: one site, three deterministic failure
    modes (latency sleeps in place; exception and drop both surface as
    TransportError so they walk the exact path a real link failure
    walks)."""
    if injector is None:
        return
    try:
        kind = injector.fire('transport', method=method, host=host)
    except InjectedFault as e:
        raise TransportError(str(e)) from e
    if kind == 'drop':
        raise TransportError(
            f'injected partition: {method!r} to host {host} dropped '
            f'(request never sent, no response will come)')


class LocalTransport:
    """In-process transport: the wire-free arm of the contract.

        server = HostServer(router, host_id=0)
        t = LocalTransport(server, fault_injector=inj)
        t.call('ping')                     # -> {'ok': True, ...}
    """

    def __init__(self, server, fault_injector=None,
                 label: Optional[str] = None):
        self.server = server
        self.fault_injector = fault_injector
        self.label = label if label is not None else \
            f'local:{getattr(server, "host_id", "?")}'

    def call(self, method: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> dict:
        _fire_transport_faults(self.fault_injector, method, self.label)
        try:
            return self.server.handle(method, payload,
                                      timeout_s=timeout_s)
        except Exception as e:  # a crashed handler IS a dead host
            raise TransportError(
                f'{self.label}: {method!r} handler raised '
                f'{type(e).__name__}: {e}') from e

    def __repr__(self):
        return f'LocalTransport({self.label})'


class SocketTransport:
    """Newline-delimited JSON over TCP, one request per connection.

        t = SocketTransport('127.0.0.1', 9000)
        t.call('infer', dict(tokens=[...], coords=[...]), timeout_s=5)

    `timeout_s` bounds connect + send + the full response read — the
    deadline-propagation arm of the fleet contract (a hung host must
    cost one timeout, not a wedged front-end). Connecting per call
    makes a host RESTART transparent: the next call reaches whatever
    process now owns the port.
    """

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, fault_injector=None,
                 label: Optional[str] = None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.fault_injector = fault_injector
        self.label = label if label is not None else f'{host}:{port}'

    def call(self, method: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> dict:
        _fire_transport_faults(self.fault_injector, method, self.label)
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        # one ABSOLUTE deadline for connect + send + the full response
        # read: a per-recv timeout would let a host that trickles one
        # chunk per interval hold a fleet pool thread indefinitely —
        # exactly the wedged front-end this bound exists to prevent
        deadline = time.monotonic() + max(0.001, timeout)

        def remaining() -> float:
            left = deadline - time.monotonic()
            if left <= 0:
                raise socket.timeout(
                    f'transport deadline ({timeout:.3f}s) exhausted')
            return left

        line = json.dumps(dict(method=method,
                               payload=payload or {})) + '\n'
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=remaining()) as s:
                s.settimeout(remaining())
                s.sendall(line.encode())
                s.shutdown(socket.SHUT_WR)
                chunks = []
                while True:
                    s.settimeout(remaining())
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    chunks.append(chunk)
        except OSError as e:
            raise TransportError(
                f'{self.label}: {method!r} failed on the wire: '
                f'{type(e).__name__}: {e}') from e
        raw = b''.join(chunks)
        if not raw.strip():
            raise TransportError(
                f'{self.label}: {method!r} got an empty response '
                f'(host died mid-call?)')
        try:
            return json.loads(raw.decode())
        except ValueError as e:
            raise TransportError(
                f'{self.label}: {method!r} returned undecodable bytes '
                f'({len(raw)}B): {e}') from e

    def __repr__(self):
        return f'SocketTransport({self.label})'


class SocketServer:
    """Accept loop exposing a `HostServer` on a TCP port (daemon
    threads: one acceptor, one per in-flight connection — connections
    are one-shot, so the per-connection thread count tracks the fleet's
    in-flight RPC count, which the front-end already bounds)."""

    def __init__(self, handler: Callable, port: int = 0,
                 host: str = '127.0.0.1'):
        self.handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name=f'rpc-accept:{self.port}',
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return    # close() won the startup race — nothing to serve
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket):
        with conn:
            try:
                conn.settimeout(60.0)
                buf = b''
                while not buf.endswith(b'\n'):
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                req = json.loads(buf.decode())
                try:
                    resp = self.handler(req.get('method'),
                                        req.get('payload'),
                                        timeout_s=(req.get('payload') or
                                                   {}).get('timeout_s'))
                except Exception as e:  # handler crash -> app error, not
                    #                     a torn wire: the caller can at
                    #                     least read what happened
                    resp = dict(ok=False, error=dict(
                        code='internal',
                        message=f'{type(e).__name__}: {e}'))
                conn.sendall((json.dumps(resp) + '\n').encode())
            except (OSError, ValueError):
                pass    # torn connection / garbage line: the client's
                #         read fails and ITS TransportError carries the
                #         verdict — nothing useful to do server-side

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def serve_socket(server, port: int = 0,
                 host: str = '127.0.0.1') -> SocketServer:
    """Expose a `HostServer` on a TCP port; returns the running
    `SocketServer` (its `.port` is the bound port — pass 0 to let the
    OS pick, the worker prints it in its READY line)."""
    return SocketServer(server.handle, port=port, host=host)
