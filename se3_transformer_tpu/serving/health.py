"""Per-replica health: state machine + circuit breaker for the router.

A replica whose runner throws must not keep receiving least-outstanding
traffic forever — "fewest unanswered requests" describes a black hole as
well as it describes an idle healthy replica. This module gives the
router the missing signal:

  * `ReplicaHealth` — one replica's state machine, driven by per-dispatch
    outcomes (`record_success` / `record_failure` from the batcher's
    dispatch hooks):

        healthy --failure x degrade_after--> degraded
        degraded --failure x quarantine_after--> quarantined
        degraded --success x recover_after--> healthy
        quarantined --half-open probe success--> degraded

    Quarantine is a CIRCUIT BREAKER, not a tombstone: after an
    exponential backoff (`probe_backoff_s`, doubling to
    `probe_backoff_max_s` on each failed probe) the replica becomes
    probe-eligible and the router routes exactly ONE request into it
    (half-open — `begin_probe` pins `probe_due` false until the outcome
    lands). A probe success closes the breaker back to degraded and
    resets the backoff; normal traffic then walks it to healthy. No
    restart, no operator — recovery via probe traffic.

  * `HealthMonitor` — the fleet view the `Router` consults: thread-safe
    (outcomes arrive from async-dispatch executor threads), a merged
    transition log (the `fault` record's `health_transitions` payload),
    and the `recoveries` counter (`make chaos-smoke` gates on >= 1
    quarantine -> recovery transition being observed).

The member ids are OPAQUE ints: PR 12 drives one monitor per router
with replica ids; the cross-host tier (`serving.fleet.FleetRouter`)
drives a second monitor one level up with HOST ids — same breaker state
machine, same transition evidence, outcomes fed by RPC results and
heartbeat staleness instead of dispatch results. Concurrent callers
must claim probes through `try_begin_probe` (check + begin under ONE
lock acquisition) — a separate probe_due()/begin_probe() pair is a
race that double-books the half-open slot.

Every transition is recorded as a JSON-safe event so the chaos harness
and telemetry stream can prove the breaker actually cycled, not just
that the code exists.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

HEALTHY = 'healthy'
DEGRADED = 'degraded'
QUARANTINED = 'quarantined'
HEALTH_STATES = (HEALTHY, DEGRADED, QUARANTINED)


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Knobs of the per-replica breaker (see docs/ROBUSTNESS.md).

    degrade_after      consecutive failures before healthy -> degraded
    quarantine_after   consecutive failures before -> quarantined
    recover_after      consecutive successes before degraded -> healthy
    probe_backoff_s    first half-open probe delay after quarantine
    probe_backoff_max_s  backoff ceiling (doubles per failed probe)
    backoff_factor     multiplier applied per failed probe
    probe_timeout_s    a probe whose outcome never lands (the request
                       was deadline-shed before its batch ran — neither
                       a success nor a failure of the replica) is
                       ABANDONED after this long and the breaker
                       re-arms; without it, one shed probe would pin
                       probe_inflight forever and quarantine the
                       replica permanently
    """
    degrade_after: int = 1
    quarantine_after: int = 3
    recover_after: int = 2
    probe_backoff_s: float = 0.25
    probe_backoff_max_s: float = 30.0
    backoff_factor: float = 2.0
    probe_timeout_s: float = 60.0

    def __post_init__(self):
        assert self.degrade_after >= 1
        assert self.quarantine_after >= self.degrade_after
        assert self.recover_after >= 1
        assert self.probe_backoff_s > 0 and self.backoff_factor >= 1.0
        assert self.probe_timeout_s > 0


class ReplicaHealth:
    """One replica's breaker state; mutate only via the monitor (which
    holds the lock — outcomes arrive from executor threads)."""

    def __init__(self, replica_id: int, config: HealthConfig,
                 clock: Callable[[], float]):
        self.id = int(replica_id)
        self.config = config
        self.clock = clock
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.failures_total = 0
        self.successes_total = 0
        self.probes = 0
        self.probe_inflight = False
        self.probe_started_at: Optional[float] = None
        self._backoff = config.probe_backoff_s
        self.next_probe_at: Optional[float] = None
        self.transitions: List[dict] = []
        self.last_error: Optional[str] = None

    # ------------------------------------------------------------------ #
    def _transition(self, to: str, reason: str):
        if to == self.state:
            return
        self.transitions.append(dict(
            replica=self.id, t=round(self.clock(), 4),
            from_state=self.state, to_state=to, reason=reason))
        self.state = to

    def record_success(self):
        self.successes_total += 1
        self.consecutive_failures = 0
        self.consecutive_successes += 1
        if self.probe_inflight:
            # half-open probe answered: close the breaker back to
            # degraded (NOT straight to healthy — one good batch after a
            # quarantine is evidence of life, not of health) and reset
            # the backoff for any future quarantine
            self.probe_inflight = False
            self._backoff = self.config.probe_backoff_s
            self.next_probe_at = None
            self._transition(DEGRADED, 'probe_success')
            self.consecutive_successes = 1
        if self.state == DEGRADED and \
                self.consecutive_successes >= self.config.recover_after:
            self._transition(HEALTHY, 'recovered')

    def record_failure(self, error: Optional[BaseException] = None):
        self.failures_total += 1
        self.consecutive_successes = 0
        self.consecutive_failures += 1
        if error is not None:
            self.last_error = f'{type(error).__name__}: {error}'
        now = self.clock()
        if self.probe_inflight:
            # failed probe: stay quarantined, back off exponentially
            self.probe_inflight = False
            self._backoff = min(self._backoff * self.config.backoff_factor,
                                self.config.probe_backoff_max_s)
            self.next_probe_at = now + self._backoff
            return
        if self.state != QUARANTINED and \
                self.consecutive_failures >= self.config.quarantine_after:
            self._transition(QUARANTINED, 'failures')
            self.next_probe_at = now + self._backoff
        elif self.state == HEALTHY and \
                self.consecutive_failures >= self.config.degrade_after:
            self._transition(DEGRADED, 'failures')

    def probe_due(self, now: float) -> bool:
        if self.probe_inflight and self.probe_started_at is not None \
                and now - self.probe_started_at \
                >= self.config.probe_timeout_s:
            # the probe's outcome never landed — its request was
            # deadline-shed before the batch ran, which judges the
            # REQUEST, not the replica. Abandon it and re-arm, or this
            # breaker would stay half-open (and the replica
            # quarantined) forever.
            self.probe_inflight = False
            self.next_probe_at = now
        return (self.state == QUARANTINED and not self.probe_inflight
                and self.next_probe_at is not None
                and now >= self.next_probe_at)

    def begin_probe(self, now: Optional[float] = None):
        """Half-open: exactly one request in flight until its outcome
        (or the probe_timeout_s abandonment above)."""
        self.probes += 1
        self.probe_inflight = True
        self.probe_started_at = self.clock() if now is None else now

    def snapshot(self) -> dict:
        return dict(state=self.state,
                    consecutive_failures=self.consecutive_failures,
                    failures=self.failures_total,
                    successes=self.successes_total,
                    probes=self.probes,
                    probe_inflight=self.probe_inflight,
                    transitions=len(self.transitions),
                    last_error=self.last_error)


class HealthMonitor:
    """The fleet's health surface: per-replica breakers behind one lock.

        monitor = HealthMonitor([0, 1, 2], config, clock=clock)
        monitor.record_failure(0, err)      # from a dispatch hook
        monitor.state(0)                    # 'degraded'
        monitor.probe_due(0, now)           # breaker half-open?
        monitor.snapshot()                  # serve-record health section
    """

    def __init__(self, replica_ids, config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config if config is not None else HealthConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._replicas: Dict[int, ReplicaHealth] = {
            int(r): ReplicaHealth(r, self.config, clock)
            for r in replica_ids}

    def __getitem__(self, replica_id: int) -> ReplicaHealth:
        return self._replicas[int(replica_id)]

    def record_success(self, replica_id: int):
        with self._lock:
            self._replicas[int(replica_id)].record_success()

    def record_failure(self, replica_id: int,
                       error: Optional[BaseException] = None):
        with self._lock:
            self._replicas[int(replica_id)].record_failure(error)

    def state(self, replica_id: int) -> str:
        with self._lock:
            return self._replicas[int(replica_id)].state

    def probe_due(self, replica_id: int, now: float) -> bool:
        with self._lock:
            return self._replicas[int(replica_id)].probe_due(now)

    def begin_probe(self, replica_id: int):
        with self._lock:
            self._replicas[int(replica_id)].begin_probe()

    def try_begin_probe(self, replica_id: int,
                        now: Optional[float] = None) -> bool:
        """Atomically claim the half-open probe slot: probe_due check
        AND begin_probe under one lock acquisition, so N concurrent
        callers (async dispatch hooks, the fleet's heartbeat executor)
        can never double-book a probe — at most one returns True per
        breaker opening. Prefer this over the probe_due()/begin_probe()
        pair whenever more than one thread routes."""
        with self._lock:
            r = self._replicas[int(replica_id)]
            now = self.clock() if now is None else now
            if not r.probe_due(now):
                return False
            r.begin_probe(now)
            return True

    @property
    def transitions(self) -> List[dict]:
        """Merged, time-ordered transition log across the fleet."""
        with self._lock:
            events = [e for r in self._replicas.values()
                      for e in r.transitions]
        return sorted(events, key=lambda e: (e['t'], e['replica']))

    @property
    def recoveries(self) -> int:
        """Quarantine -> live transitions (the chaos-smoke proof bit)."""
        return sum(1 for e in self.transitions
                   if e['from_state'] == QUARANTINED)

    def snapshot(self) -> dict:
        """Per-replica health section of the serve/fault records."""
        with self._lock:
            return {str(rid): r.snapshot()
                    for rid, r in sorted(self._replicas.items())}
