"""Cross-replica SLO aggregation into the existing `serve` record.

One record shape for one-replica and N-replica serving: both emitters
extend `inference.telemetry.ServeTelemetryBase` (compile-delta
accumulation, bucket windows, requests section, latency drain), so the
PR 2 `serve` record keeps its required fields and multi-replica runs
fold in only the aggregation fields the router adds — per-replica depth
(`replicas`), rolling swap events (`swaps`), and the continuous-
batching proof counter (`continuous_admissions`). Consumers that only
understand single-replica records keep working; `obs_report --require
serve` gates the extended ones.

Aggregate per-bucket percentiles come from ONE PhaseTimer shared by
every replica's engine (the constructor enforces it): each `run()`
lands its device latency in the same `bucket_<L>` phase regardless of
replica, so the record's `buckets` section is the cross-replica SLO
surface directly — no percentile-merging approximations. Per-replica
skew is visible separately via `replicas[i].depth` / `.served`.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..inference.admission import AdmissionController
from ..inference.stats import agg_stats
from ..inference.telemetry import ServeTelemetryBase
from ..observability import MetricLogger, RetraceWatchdog
from .router import Router

# the _router_sections subset that also rides every `fault` record
_FAULT_SECTION_KEYS = ('health', 'retries', 'request_failures',
                       'timeouts', 'deadline_sheds')


class RouterTelemetry(ServeTelemetryBase):
    """Wire a router (+ admission) into the JSONL telemetry stream.

        tele = RouterTelemetry(router, admission, logger)
        tele.arm()              # AFTER every replica's warmup
        ... serve ...
        tele.flush()            # one extended `serve` record
        tele.close()            # cumulative `summary` record
        assert tele.post_warmup_compiles == 0
    """

    def __init__(self, router: Router,
                 admission: Optional[AdmissionController] = None,
                 logger: Optional[MetricLogger] = None,
                 watchdog: Optional[RetraceWatchdog] = None):
        timers = {id(w.engine.timer) for w in router.workers}
        assert len(timers) == 1, \
            'every replica engine must share ONE PhaseTimer (pass ' \
            'timer=... to each InferenceEngine) — aggregate percentiles ' \
            'cannot be merged from per-replica reservoirs'
        super().__init__(router.workers[0].engine.timer, admission,
                         logger, watchdog)
        self.router = router
        # optional host-side transport counters (serve.py attaches the
        # socket server's `transport_stats` here): when set, every
        # serve record carries a schema-validated `transport` section
        self.transport_source: Optional[Callable[[], dict]] = None
        for w in router.workers:
            for key, executable in w.engine.executables.items():
                self.watchdog.track(f'r{w.id}_bucket_{key[0]}', executable)

    def _pop_completed(self):
        return self.router.pop_completed()

    def _emit_cost_records(self):
        """Each replica's per-bucket cost ledger, replica-tagged, so
        capacity planning reads memory-per-bucket-per-replica off the
        record stream."""
        for w in self.router.workers:
            for key in sorted(w.engine.cost_payloads):
                body = dict(w.engine.cost_payloads[key])
                body['label'] = f'replica_{w.id},' + body['label']
                self.logger.log_record('cost', mirror=False, **body)

    def _router_sections(self) -> dict:
        """The aggregation fields the router adds to both records —
        including the fault-domain signals (per-replica health, retry /
        timeout / structured-failure counters) the cross-host tier
        routes on."""
        router = self.router
        return dict(
            replicas={str(w.id): w.snapshot() for w in router.workers},
            # the fleet's precision mixes at a glance (heterogeneous
            # serving: replicas may run different quant mixes; the
            # per-replica value is in each snapshot)
            precision_mixes=sorted({
                getattr(w.engine, 'precision_name', 'fp32')
                for w in router.workers}),
            # same heterogeneous-serving shape for the model families
            # (v1/v2 replicas may coexist behind one router; the
            # per-replica value is in each snapshot)
            model_families=sorted({
                getattr(w.engine, 'model_family', 'se3_v1')
                for w in router.workers}),
            swaps=dict(count=len(router.swap_events),
                       events=list(router.swap_events)),
            continuous_admissions=router.continuous_admissions,
            deadline_flushes=router.deadline_flushes,
            health=router.health.snapshot(),
            retries=router.retries,
            request_failures=router.request_failures,
            timeouts=router.timeouts,
            deadline_sheds=router.deadline_sheds,
        )

    def fault_flush(self, injector=None, pending=None,
                    label: str = 'fault') -> dict:
        """One schema'd `fault` record: what was injected, how the
        health breakers moved, how the retry/deadline machinery paid it
        down, and the load-bearing verdict — `lost_requests` (submits
        in `pending` that resolved neither answered nor structured
        error; the zero-lost contract `make chaos-smoke` gates on).

        `injector` (a faults.FaultInjector) contributes the injection
        log; `pending` is the caller's full list of submitted
        PendingResults (None -> lost accounting limited to what the
        router can see, i.e. 0 — pass the real list)."""
        router = self.router
        pending = list(pending or [])
        lost = sum(1 for p in pending if not p.done)
        inj = injector.snapshot() if injector is not None else dict(
            seed=None, injections=[], injections_total=0, by_site={})
        # the fault-domain signals come from the SAME assembly the
        # serve records use — the two record kinds cannot drift
        sections = self._router_sections()
        fields = dict(
            label=label,
            injections=inj['injections'],
            injections_total=inj['injections_total'],
            injections_by_site=inj['by_site'],
            injector_seed=inj['seed'],
            health_transitions=router.health.transitions,
            recoveries=router.health.recoveries,
            **{k: sections[k] for k in _FAULT_SECTION_KEYS},
            submitted=len(pending),
            resolved=sum(1 for p in pending if p.done),
            answered=sum(1 for p in pending if p.ok),
            structured_failures=sum(
                1 for p in pending if p.done and p.error is not None),
            lost_requests=lost,
        )
        return self._emit('fault', fields)

    def flush(self) -> dict:
        """One extended `serve` record: aggregate per-bucket window
        percentiles, request counters, per-replica depth, swap events,
        and the continuous-admission counter."""
        router = self.router
        runtime = self._check_runtime()
        fields = dict(
            requests=self._requests_section(
                sum(w.served_rows for w in router.workers)),
            buckets=self._bucket_windows(router.buckets),
            queue_depth=router.queue_depth,
            runtime=runtime,
            post_warmup_compiles=self.post_warmup_compiles,
            **self._router_sections(),
        )
        # latency fields (window stats + mergeable histograms) come
        # from the SAME base helper the single-engine emitter uses —
        # the two serve-record shapes cannot drift
        fields.update(self._latency_sections())
        if self.transport_source is not None:
            fields['transport'] = dict(self.transport_source())
        return self._emit('serve', fields)

    def close(self) -> dict:
        """Cumulative `summary` record across the fleet."""
        self._check_runtime()
        self._drain_latencies()
        router = self.router
        fields = dict(
            steps=router.batches_dispatched,
            metrics=dict(request_latency_ms=agg_stats(self._latency_agg)),
            timing=self.timer.cumulative_summary(),
            replicas={str(w.id): dict(w.snapshot(),
                                      engine=w.engine.stats())
                      for w in router.workers},
            swaps=dict(count=len(router.swap_events),
                       events=list(router.swap_events)),
            continuous_admissions=router.continuous_admissions,
            deadline_flushes=router.deadline_flushes,
            post_warmup_compiles=self.post_warmup_compiles,
            retrace_warnings_total=self.watchdog.warnings_total,
        )
        if self.admission is not None:
            fields['requests'] = self.admission.snapshot()
        return self._emit('summary', fields)
