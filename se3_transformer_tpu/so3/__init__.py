from .spherical_harmonics import (
    real_spherical_harmonics,
    real_spherical_harmonics_all,
    spherical_harmonics_angles,
    angles_to_xyz,
)
from .wigner import (
    rot, rot_z, rot_y, rot_to_euler, compose, irr_repr,
    wigner_d_from_rotation, x_to_alpha_beta,
)
