"""Real spherical harmonics, evaluated polynomially from Cartesian coordinates.

TPU-native replacement for the reference's memoized associated-Legendre
recursion over angles (/root/reference/se3_transformer_pytorch/
spherical_harmonics.py:34-123). Instead of (theta, phi) trigonometry we
evaluate the tesseral harmonics directly as polynomials in the unit-vector
components (x, y, z):

    Y_{l, m>0} = sqrt(2) K_{lm} Ptil_l^m(z) A_m(x, y)
    Y_{l, 0}   =         K_{l0} Ptil_l^0(z)
    Y_{l, m<0} = sqrt(2) K_{l|m|} Ptil_l^{|m|}(z) B_{|m|}(x, y)

where A_m + i B_m = (x + i y)^m (computed by a 2-term recursion) and
Ptil_l^m(z) = P_l^m(cos t)/sin^m t is the Condon-Shortley-free associated
Legendre polynomial divided by sin^m, itself a polynomial in z obtained by
the standard 3-term upward recursion. This formulation:

  * has no atan2/arccos/pole singularities (fully differentiable, no NaNs),
  * is a short static unroll over degrees (jit/XLA fuses it into the
    surrounding basis computation — pure VPU element-wise work),
  * is the single source of truth for basis conventions: the Wigner-D
    matrices in so3.wigner are *derived from these functions*, so the
    representation property Y(R x) = D(R) Y(x) holds by construction.

With this convention Y_1 is ordered (y, z, x) up to a positive constant
(m = -1, 0, 1), matching the common real-harmonics ordering.
"""
from __future__ import annotations

import math
from functools import lru_cache

import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=None)
def _norm_const(l: int, m: int) -> float:
    """Orthonormalization constant K_{lm} (m >= 0), including sqrt(2) for m>0."""
    k = math.sqrt((2 * l + 1) / (4 * math.pi)
                  * math.factorial(l - m) / math.factorial(l + m))
    if m > 0:
        k *= math.sqrt(2.0)
    return k


@lru_cache(maxsize=None)
def _double_factorial(n: int) -> int:
    out = 1
    while n > 1:
        out *= n
        n -= 2
    return out


def real_spherical_harmonics_all(l_max: int, xyz, xp=jnp) -> list:
    """All real SH for l = 0..l_max at unit vectors xyz[..., 3].

    Returns a list of arrays, entry l of shape [..., 2l+1] with m = -l..l.
    `xp` selects the array backend (jnp for traced TPU code, np for host
    float64 reference computations — both share the exact same math).
    """
    x, y, z = xyz[..., 0], xyz[..., 1], xyz[..., 2]

    # A_m + i B_m = (x + i y)^m by recursion
    A = [xp.ones_like(x)]
    B = [xp.zeros_like(x)]
    for m in range(1, l_max + 1):
        A.append(x * A[m - 1] - y * B[m - 1])
        B.append(x * B[m - 1] + y * A[m - 1])

    # Ptil_l^m(z): CS-phase-free associated Legendre / sin^m, polynomial in z.
    P = {}
    for m in range(0, l_max + 1):
        pmm = float(_double_factorial(2 * m - 1))
        P[(m, m)] = pmm * xp.ones_like(z)
        if m + 1 <= l_max:
            P[(m + 1, m)] = (2 * m + 1) * pmm * z
        for l in range(m + 2, l_max + 1):
            P[(l, m)] = ((2 * l - 1) * z * P[(l - 1, m)]
                         - (l + m - 1) * P[(l - 2, m)]) / (l - m)

    out = []
    for l in range(l_max + 1):
        cols = []
        for m in range(l, 0, -1):  # m = -l..-1 stored via B terms
            cols.append(_norm_const(l, m) * P[(l, m)] * B[m])
        cols.append(_norm_const(l, 0) * P[(l, 0)])
        for m in range(1, l + 1):
            cols.append(_norm_const(l, m) * P[(l, m)] * A[m])
        out.append(xp.stack(cols, axis=-1))
    return out


def real_spherical_harmonics(l: int, xyz, xp=jnp):
    """Real SH of a single degree l at unit vectors xyz[..., 3] -> [..., 2l+1]."""
    return real_spherical_harmonics_all(l, xyz, xp=xp)[l]


def angles_to_xyz(theta, phi, xp=np):
    """Unit vector from polar angle theta (from +z) and azimuth phi."""
    theta, phi = xp.asarray(theta), xp.asarray(phi)
    return xp.stack([
        xp.sin(theta) * xp.cos(phi),
        xp.sin(theta) * xp.sin(phi),
        xp.cos(theta),
    ], axis=-1)


def spherical_harmonics_angles(l: int, theta, phi, xp=np):
    """Real SH of degree l parameterized by angles (host/test convenience)."""
    return real_spherical_harmonics(l, angles_to_xyz(theta, phi, xp=xp), xp=xp)
