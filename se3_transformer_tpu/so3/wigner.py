"""Rotation utilities and real Wigner-D matrices (host-side, float64).

TPU-native replacement for the reference's irr_repr.py, which loads
precomputed "J" conjugation matrices from binary blobs
(/root/reference/se3_transformer_pytorch/irr_repr.py:12-30; the blobs are
absent from the snapshot). We instead *derive* the real Wigner-D matrices
directly from our own spherical-harmonic implementation: sample well-spread
unit vectors p_i, evaluate Y(p_i) and Y(R p_i), solve the (overdetermined)
linear system D Y(p) = Y(R p) in float64 and project the solution onto the
orthogonal group (SVD polar projection). This makes the SH code the single
source of truth for conventions — the representation property holds by
construction, and there are no angle-convention shims to keep in sync
(cf. the theta = pi - beta shim at reference irr_repr.py:103-104 and the
axis permutation at basis.py:76).

Everything here is cold-path host code (NumPy float64): it only runs when
building the Q_J intertwiner constants and in tests. Nothing in the traced
TPU model calls into this module.
"""
from __future__ import annotations

from functools import lru_cache

import numpy as np

from .spherical_harmonics import real_spherical_harmonics


def rot_z(gamma) -> np.ndarray:
    """3x3 rotation about the z axis (reference irr_repr.py:54-62)."""
    c, s = np.cos(gamma), np.sin(gamma)
    return np.array([[c, -s, 0.], [s, c, 0.], [0., 0., 1.]])


def rot_y(beta) -> np.ndarray:
    """3x3 rotation about the y axis (reference irr_repr.py:64-72)."""
    c, s = np.cos(beta), np.sin(beta)
    return np.array([[c, 0., s], [0., 1., 0.], [-s, 0., c]])


def rot(alpha, beta, gamma) -> np.ndarray:
    """ZYZ Euler-angle rotation R = Rz(alpha) Ry(beta) Rz(gamma)
    (reference irr_repr.py:86-90)."""
    return rot_z(alpha) @ rot_y(beta) @ rot_z(gamma)


def rot_to_euler(R: np.ndarray):
    """Extract ZYZ Euler angles (alpha, beta, gamma) from a rotation matrix."""
    beta = np.arccos(np.clip(R[2, 2], -1.0, 1.0))
    if abs(R[2, 2]) > 1 - 1e-12:  # gimbal: R is a pure z-rotation
        alpha = np.arctan2(R[1, 0], R[0, 0])
        if R[2, 2] < 0:
            alpha = -alpha
        return alpha, beta, 0.0
    alpha = np.arctan2(R[1, 2], R[0, 2])
    gamma = np.arctan2(R[2, 1], -R[2, 0])
    return alpha, beta, gamma


def compose(a, b, c, d, e, f):
    """Compose two ZYZ angle triples: R(out) = R(a,b,c) @ R(d,e,f)
    (reference irr_repr.py:92-101)."""
    return rot_to_euler(rot(a, b, c) @ rot(d, e, f))


def x_to_alpha_beta(x):
    """Unit vector -> (alpha, beta) with x = R(alpha, beta, 0) e_z
    (reference irr_repr.py:76-84)."""
    x = np.asarray(x, dtype=np.float64)
    x = x / np.linalg.norm(x, axis=-1, keepdims=True)
    beta = np.arccos(np.clip(x[..., 2], -1.0, 1.0))
    alpha = np.arctan2(x[..., 1], x[..., 0])
    return alpha, beta


@lru_cache(maxsize=None)
def _sample_points(l: int) -> np.ndarray:
    """Deterministic well-spread unit vectors, enough to overdetermine D_l."""
    n = max(8 * (2 * l + 1), 32)
    rng = np.random.RandomState(12345 + l)
    pts = rng.normal(size=(n, 3))
    return pts / np.linalg.norm(pts, axis=-1, keepdims=True)


def wigner_d_from_rotation(l: int, R: np.ndarray) -> np.ndarray:
    """Real Wigner-D matrix D_l(R) with D_l Y_l(p) = Y_l(R p), float64.

    Solved by least squares over sampled points and polished to an exactly
    orthogonal matrix via SVD polar projection (D is orthogonal because the
    real SH basis is orthonormal).
    """
    if l == 0:
        return np.ones((1, 1))
    R = np.asarray(R, dtype=np.float64)
    pts = _sample_points(l)
    Y = real_spherical_harmonics(l, pts, xp=np)            # [n, 2l+1]
    Yr = real_spherical_harmonics(l, pts @ R.T, xp=np)     # [n, 2l+1]
    # Yr = Y @ D^T  =>  D^T = lstsq(Y, Yr)
    Dt, *_ = np.linalg.lstsq(Y, Yr, rcond=None)
    U, _, Vt = np.linalg.svd(Dt.T)
    return U @ Vt


def irr_repr(order: int, alpha, beta, gamma) -> np.ndarray:
    """Irreducible representation of SO(3) in the real SH basis
    (reference irr_repr.py:44-52)."""
    return wigner_d_from_rotation(order, rot(alpha, beta, gamma))
