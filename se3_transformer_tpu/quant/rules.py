"""Precision-mix rules: ordered regex-on-param-path -> storage precision.

The `parallel.rules` / `conv_backend` idiom applied to serving
precision: an ordered list of ``(regex, precision[, ndim])`` rules
matched against the '/'-joined flax param path, FIRST MATCH WINS
(a rank-guarded rule only matches leaves of that rank — LinearSE3's
higher-degree mixers share the `w<d>` names with the radial weights,
so the guard is what keeps a num_degrees>=4 model's 2-d ``w3`` MIXER
out of the 3-d radial-``w3`` int8 class), with precisions

    'int8'      symmetric per-output-channel int8 (QuantTensor)
    'fp8_e4m3'  fp8 storage where the dtype exists (QuantTensor)
    'bf16'      plain bfloat16 cast (consumers promote back to f32)
    'fp32'      passthrough

The split the physics demands (ROADMAP item 3 / EquiformerV2): int8 is
restricted to the INVARIANT-INPUT matmuls — degree-0 LinearSE3 channel
mixers (`w0`: FF project_in/out, attention to_q/to_out/to_self_*,
self_interact), the radial matmul weights (`w3` / grouped
`w3_{din}_{dout}` — where the bytes are, shared by the dense AND so2
backends — and v2's per-m `wm{m}_{din}_{dout}` blocks, which are the
same invariant-input radial matmul in eSCN-direct form), and the
radial trunk's Dense kernels. Their inputs are
rotation-invariant scalars, so weight quantization error cancels in
the equivariance measurement. Higher-degree (l>0) channel mixers get a
bf16 PASSTHROUGH at most: rotation error compounds on exactly those
paths, and a rule that assigns int8/fp8 to one raises
`EquivariantPrecisionError` LOUDLY (never a silent accuracy cliff) —
the negative test in tests/test_quant.py pins it.
"""
from __future__ import annotations

import re
from typing import Dict, Sequence, Tuple, Union

import jax
import numpy as np

from .qtensor import QuantTensor, fp8_dtype, quantize

PRECISIONS = ('int8', 'fp8_e4m3', 'bf16', 'fp32')

# (regex, precision) or (regex, precision, required_ndim); matched
# against the '/'-joined param path, first match wins, implicit
# ('.*', 'fp32') tail. The rank guard is the parallel.rules idiom: a
# name-match with the wrong rank falls through to the NEXT rule —
# load-bearing here because LinearSE3's higher-degree channel mixers
# are ALSO named w<d> (a num_degrees>=4 model has a 2-d `w3` mixer
# that must never collide with the 3-d radial `w3` weights).
PrecisionRule = Union[Tuple[str, str], Tuple[str, str, int]]
PrecisionRules = Sequence[PrecisionRule]
MixSpec = Union[str, PrecisionRules]

# the invariant-input matmul weight classes int8/fp8 storage is safe
# for (weight error on these paths shifts ACCURACY, not equivariance —
# their inputs are rotation-invariant scalars / degree-0 features),
# each with the rank that identifies it:
#   w0 [in, out]           degree-0 LinearSE3 channel mixers
#   w3 / w3_i_o [m, IF, O] radial matmul weights (dense + so2 + flash)
#   wm{m}_i_o [mid, K, O]  v2 per-m banded radial blocks (eSCN-direct)
#   Dense_0/1 kernel       the radial trunk's hidden matmuls
# ('wm3' contains no digit after the leading w, so the `w\d+` mixer
# and `w3` radial patterns cannot collide with it — and vice versa)
_W0_RE = r'(^|/)w0$'
_W3_RE = r'(^|/)w3(_\d+_\d+)?$'
_WM_RE = r'(^|/)wm\d+_\d+_\d+$'
_RADIAL_DENSE_RE = r'(^|/)Dense_[01]/kernel$'
_INT8_SAFE = ((_W0_RE, 2), (_W3_RE, 3), (_WM_RE, 3),
              (_RADIAL_DENSE_RE, 2))

# higher-degree LinearSE3 channel mixers: bf16 at most (this also
# catches a 2-d `w3` MIXER after the rank guard rejects it above)
_WL_RE = r'(^|/)w[1-9]\d*$'


class EquivariantPrecisionError(ValueError):
    """An int8/fp8 rule matched a param outside the invariant-safe
    class — the l>0 accuracy cliff the precision layer exists to avoid."""


def _mix_rules(low: str) -> PrecisionRules:
    return (
        (_W0_RE, low, 2),
        (_W3_RE, low, 3),
        (_WM_RE, low, 3),
        (_RADIAL_DENSE_RE, low, 2),
        (_WL_RE, 'bf16'),
        (r'.*', 'fp32'),
    )


# shipped mixes — norms / biases / embeddings / gates stay fp32 in all
# of them (tiny, and several feed non-matmul consumers)
MIXES: Dict[str, PrecisionRules] = {
    'fp32': ((r'.*', 'fp32'),),
    'bf16': _mix_rules('bf16'),
    'int8_mix': _mix_rules('int8'),
    'fp8_mix': _mix_rules('fp8_e4m3'),
}


def resolve_mix(mix: MixSpec) -> PrecisionRules:
    """A mix by name or an explicit rule list, normalized. `fp8_mix`
    additionally requires the fp8 dtype to exist in this jax build."""
    if isinstance(mix, str):
        if mix not in MIXES:
            raise KeyError(f'unknown precision mix {mix!r} '
                           f'(shipped: {sorted(MIXES)})')
        if mix == 'fp8_mix' and fp8_dtype() is None:
            raise ValueError(
                "precision mix 'fp8_mix' needs jnp.float8_e4m3fn, which "
                "this jax build does not carry — use 'int8_mix'")
        return MIXES[mix]
    rules = tuple(mix)
    for rule in rules:
        prec = rule[1]
        if prec not in PRECISIONS:
            raise ValueError(f'rule ({rule[0]!r}, {prec!r}): precision '
                             f'must be one of {PRECISIONS}')
    return rules


def mix_name(mix: MixSpec) -> str:
    return mix if isinstance(mix, str) else 'custom'


def resolve_precision(rules: PrecisionRules, path: str,
                      ndim: int = None) -> str:
    """First-match-wins precision for one param path ('fp32' tail). A
    rule carrying a rank guard only matches leaves of that rank —
    otherwise scanning continues (the parallel.rules semantics)."""
    for rule in rules:
        pat, prec = rule[0], rule[1]
        guard = rule[2] if len(rule) > 2 else None
        if guard is not None and ndim is not None and ndim != guard:
            continue
        if re.search(pat, path):
            return prec
    return 'fp32'


def _path_of(key_path) -> str:
    parts = []
    for k in key_path:
        parts.append(str(getattr(k, 'key', getattr(k, 'name', k))))
    return '/'.join(parts)


def quantize_params(params, mix: MixSpec = 'int8_mix'):
    """Convert a restored (host) params pytree into its quantized form.

    Returns ``(qparams, report)``: the tree with int8/fp8 leaves as
    QuantTensor nodes (contracted axis 0 per-output-channel scales),
    bf16 leaves cast, everything else passed through — same tree paths,
    so `module.apply` and the partition-rule engine walk it unchanged.
    Runs on HOST numpy: the caller device_puts the RESULT, which is how
    the engine guarantees the fp32 degree-0 weights never materialize
    on device (test-pinned).

    `report` is the JSON-safe before/after ledger (per-precision leaf
    counts and bytes, the argument-bytes ratio) that rides the engine's
    `cost`/`serve` records.
    """
    rules = resolve_mix(mix)
    counts = {p: 0 for p in PRECISIONS}
    bytes_before = 0
    bytes_after = 0
    offenders = []

    def convert(key_path, leaf):
        nonlocal bytes_before, bytes_after
        path = _path_of(key_path)
        arr = np.asarray(leaf)
        nbytes = int(arr.size * arr.dtype.itemsize)
        bytes_before += nbytes
        if not np.issubdtype(arr.dtype, np.floating):
            bytes_after += nbytes
            return leaf
        prec = resolve_precision(rules, path, ndim=arr.ndim)
        counts[prec] += 1
        if prec == 'fp32':
            bytes_after += nbytes
            return leaf
        if prec == 'bf16':
            # host-side cast (ml_dtypes, the same bfloat16 jnp uses):
            # the quantization pass must never touch a device — the
            # caller's single device_put is the only transfer
            import ml_dtypes
            out = arr.astype(ml_dtypes.bfloat16)
            bytes_after += int(arr.size * 2)
            return out
        # int8 / fp8: the invariant-safe guard first — an equivariant
        # (l>0) weight matched by a low-precision rule is a config
        # error, not a quantization target. Rank-checked: a 2-d `w3`
        # is a higher-degree LinearSE3 MIXER, not the radial weight
        if not any(re.search(p, path) and arr.ndim == nd
                   for p, nd in _INT8_SAFE):
            offenders.append((path, prec))
            return leaf
        qt = quantize(arr, contract_axes=(0,), storage=prec)
        bytes_after += qt.nbytes
        return qt

    qparams = jax.tree_util.tree_map_with_path(convert, params)
    if offenders:
        shown = ', '.join(f'{p} -> {prec}' for p, prec in offenders[:8])
        raise EquivariantPrecisionError(
            f'{len(offenders)} param(s) outside the invariant-safe '
            f'weight classes matched an int8/fp8 rule ({shown}'
            f'{" ..." if len(offenders) > 8 else ""}) — higher-degree '
            f'kernels compound rotation error and may go bf16 at most '
            f'(see quant.rules)')
    report = dict(
        mix=mix_name(mix),
        leaves={p: n for p, n in counts.items() if n},
        params_bytes_fp32=int(bytes_before),
        params_bytes_quantized=int(bytes_after),
        bytes_ratio=round(bytes_after / max(bytes_before, 1), 4),
    )
    return qparams, report
