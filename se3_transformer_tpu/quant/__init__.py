"""Post-training quantization as a first-class serving precision layer.

ROADMAP item 3: per-replica HBM is the binding constraint on replica
count, and the fp32 param tree is the largest argument. This package
converts a restored checkpoint into a quantized pytree — int8 (or
fp8-e4m3) weights with per-output-channel fp32 scales for the
invariant-input matmuls, bf16 passthrough for higher-degree channel
mixers — selected by first-match-wins (param-path regex, precision)
rules mirroring the `conv_backend` / `partition_rules` idiom. Dequant
fuses into the consumers (LinearSE3's einsum, the radial-contract
Pallas/XLA paths, the flash kernel's in-tile radial matmul), so the
full-precision weights never materialize on device; every shipped mix
is gated on the equivariance-L2 harness + quantized-vs-fp32 parity
(`make quant-smoke`, tests/test_quant.py).

    from se3_transformer_tpu import quant
    qparams, report = quant.quantize_params(params, 'int8_mix')
    engine = InferenceEngine(module, params, precision='int8_mix')
"""
from .qtensor import (
    QuantTensor, concat_weights, dequantize, fp8_dtype, is_quantized,
    quantize, weight_or_none,
)
from .rules import (
    MIXES, PRECISIONS, EquivariantPrecisionError, mix_name,
    quantize_params, resolve_mix, resolve_precision,
)

__all__ = [
    'MIXES', 'PRECISIONS', 'EquivariantPrecisionError', 'QuantTensor',
    'concat_weights', 'dequantize', 'fp8_dtype', 'is_quantized',
    'mix_name', 'quantize', 'quantize_params', 'resolve_mix',
    'resolve_precision', 'weight_or_none',
]
