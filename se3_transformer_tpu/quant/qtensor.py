"""QuantTensor: an int8/fp8 weight + its per-output-channel scales, as
one pytree node that rides wherever the fp32 weight used to.

The serving memory problem is ARGUMENT bytes: a restored fp32 param
tree is the largest per-replica HBM resident, and PR 6's cost ledger
splits it out per bucket (`memory.argument_bytes`). Post-training
quantization replaces each matmul weight with

    q     int8 (or fp8-e4m3), the SAME shape as the fp32 weight
    scale fp32, the contracted axes collapsed to 1 (per-output-channel
          symmetric absmax scales, keepdims layout so `q * scale`
          broadcasts to the dequantized weight exactly)

and the consumers fuse the dequant as an epilogue — `scale * (q @ x)`
— so the fp32 weight never exists as a device buffer: int8 lives in
HBM, the upcast happens inside the consuming fusion / kernel tile.

Registered as a pytree node with `q` FIRST: flax's `Scope.param` shape
check zips `tree_leaves(value)` against the init_fn's abstract output
pairwise, so the stored value may carry extra trailing leaves (the
scale) as long as the first leaf has the declared shape — `q` does, by
construction. tests/test_quant.py pins this leaf order.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# symmetric quantization ranges per storage dtype
_INT8_MAX = 127.0
_FP8_E4M3_MAX = 448.0


def fp8_dtype():
    """jnp.float8_e4m3fn where this jax build carries it, else None
    (the fp8 mixes are gated on this — never a hard import error)."""
    return getattr(jnp, 'float8_e4m3fn', None)


@jax.tree_util.register_pytree_with_keys_class
class QuantTensor:
    """One quantized weight: `q` (int8/fp8, the fp32 weight's shape) +
    `scale` (fp32, contracted axes kept as size-1 dims). `q * scale`
    broadcasts to the dequantized weight; consumers instead fold the
    scale in AFTER their contraction (the fused-dequant epilogue)."""

    __slots__ = ('q', 'scale')

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    # pytree protocol — q FIRST (see the module docstring: flax's
    # param-shape check reads only the first leaf)
    def tree_flatten_with_keys(self):
        return (((jax.tree_util.GetAttrKey('q'), self.q),
                 (jax.tree_util.GetAttrKey('scale'), self.scale)), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # array-protocol surface the engine/rules plumbing reads
    @property
    def shape(self):
        return np.shape(self.q)

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return getattr(self.q, 'dtype', None)

    @property
    def nbytes(self) -> int:
        return int(_leaf_nbytes(self.q) + _leaf_nbytes(self.scale))

    def dequant(self, dtype=jnp.float32):
        """The full-precision weight — as a TRANSIENT value inside a
        traced program (or a host-side test oracle), never something to
        store: the whole point is that this product is an epilogue, not
        a buffer."""
        return jnp.asarray(self.q).astype(dtype) * jnp.asarray(self.scale)

    def __repr__(self):
        return (f'QuantTensor(q={self.shape}:{self.dtype}, '
                f'scale={np.shape(self.scale)})')


def _leaf_nbytes(a) -> int:
    size = int(np.prod(np.shape(a) or (1,)))
    return size * np.dtype(getattr(a, 'dtype', np.float32)).itemsize


def quantize(w, contract_axes: Sequence[int] = (0,),
             storage: str = 'int8') -> QuantTensor:
    """Symmetric per-output-channel quantization on HOST numpy (no
    device placement — restore-time quantization must finish before the
    first device_put so the fp32 tree never lands in HBM).

    `contract_axes` are the matmul's contracted dims (axis 0 for the
    [in, out...] weights this repo uses): the absmax reduces over them,
    every remaining dim keeps its own scale — the error bound is then
    max|w|/254 per channel for int8 (round-to-nearest of a symmetric
    127-level grid), pinned by tests/test_quant.py.
    """
    w = np.asarray(w, np.float32)
    axes = tuple(int(a) % w.ndim for a in contract_axes)
    amax = np.max(np.abs(w), axis=axes, keepdims=True)
    if storage == 'int8':
        qmax, dt = _INT8_MAX, np.int8
    elif storage == 'fp8_e4m3':
        dt = fp8_dtype()
        if dt is None:
            raise ValueError(
                'fp8_e4m3 storage requested but this jax build has no '
                'jnp.float8_e4m3fn — use an int8 mix instead')
        qmax = _FP8_E4M3_MAX
    else:
        raise ValueError(f'unknown quant storage {storage!r} '
                         f"(known: 'int8', 'fp8_e4m3')")
    scale = amax / qmax
    # an all-zero channel quantizes to zeros under ANY scale; 1.0 keeps
    # the divide clean without special-casing dequant
    scale = np.where(amax == 0.0, 1.0, scale).astype(np.float32)
    if storage == 'int8':
        q = np.clip(np.rint(w / scale), -_INT8_MAX, _INT8_MAX)
        q = q.astype(np.int8)
    else:
        q = np.asarray(w / scale).astype(dt)
    return QuantTensor(q, scale)


def dequantize(qt: QuantTensor) -> np.ndarray:
    """Host-side oracle: the fp32 weight the consumers' fused epilogues
    are numerically equivalent to (modulo one multiply reassociation)."""
    return (np.asarray(qt.q, np.float32)
            * np.asarray(qt.scale, np.float32))


def is_quantized(tree) -> bool:
    """True when any node of `tree` is a QuantTensor (the engine's
    params setter uses this to skip re-quantizing an already-quantized
    tree on a weight swap)."""
    found = False

    def probe(x):
        nonlocal found
        if isinstance(x, QuantTensor):
            found = True
        return x

    jax.tree_util.tree_map(
        probe, tree, is_leaf=lambda x: isinstance(x, QuantTensor))
    return found


def concat_weights(ws, axis: int):
    """Concatenate grouped per-pair radial weights along a NON-contracted
    axis, preserving quantization: QuantTensors concatenate q and scale
    along the same axis (the contracted dims are size-1 in the scale, so
    any concat axis the caller uses here is a per-channel axis in both).
    A mixed fp32/quantized group dequantizes the quantized members —
    first-match-wins rules make that configuration unusual, but a silent
    dtype error would be worse."""
    ws = list(ws)
    if not any(isinstance(w, QuantTensor) for w in ws):
        return jnp.concatenate(ws, axis=axis)
    if all(isinstance(w, QuantTensor) for w in ws) and len(
            {np.dtype(w.q.dtype) for w in ws}) == 1:
        return QuantTensor(
            jnp.concatenate([w.q for w in ws], axis=axis),
            jnp.concatenate([w.scale for w in ws], axis=axis))
    return jnp.concatenate(
        [w.dequant() if isinstance(w, QuantTensor) else w for w in ws],
        axis=axis)


def weight_or_none(w) -> Tuple[Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """(storage, scale) split for kernel plumbing: a QuantTensor yields
    (q, scale); a plain array yields (w, None)."""
    if isinstance(w, QuantTensor):
        return w.q, w.scale
    return w, None
