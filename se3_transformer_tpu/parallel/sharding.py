"""Sharded training-step construction (pjit over the dp/sp/tp mesh).

Builds a jitted SPMD train step: parameters and optimizer state are
replicated (they are tiny relative to the O(B*N*K) edge activations), data
is sharded dp over batch and sp over the node axis, and GSPMD propagates
shardings through the model — neighbor gathers over the full source-node
axis lower to all-gathers over ICI, loss reductions to psums. This replaces
the reference's absent distributed backend (SURVEY.md §2.9) with XLA
collectives rather than a hand-rolled NCCL/MPI layer.
"""
from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def _expect_unusable_batch_donation():
    """Batch leaves can never alias a step output (no output shares
    their shapes/dtypes — outputs alias the donated params/opt_state),
    so XLA reports every batch donation 'not usable'. That is expected
    on the donate_batch path — donation there only marks the buffers
    dead early — so silence exactly that warning instead of spamming
    every pipelined compile (docs/PERFORMANCE.md "When donation is
    safe"). Params/opt_state donations DO alias; a genuine aliasing
    regression there would surface as a perf/HBM change, not only as
    this message."""
    warnings.filterwarnings(
        'ignore', message='Some donated buffers were not usable')


# ---------------------------------------------------------------------- #
# tensor parallelism (SURVEY §5 "optional tensor sharding of the
# radial-MLP and head axes")
#
# The Megatron-style column/row rules that used to be hand-coded here
# now live as data in `parallel.rules.tp_rules` — serving
# (inference.engine, serving.*) consults the SAME rule engine, so
# training and serving shardings cannot drift. These two functions are
# thin callers kept for the established call sites.
# ---------------------------------------------------------------------- #
def param_partition_specs(params, mesh: Mesh, axis: Optional[str] = None,
                          rules=None):
    """Rule-engine-backed PartitionSpec tree for a model param pytree
    (see `parallel.rules`). Default rules: the built-in tensor-parallel
    set; `rules` may name another built-in set ('replicated' | 'tp' |
    'fsdp') or pass an explicit rule list. `axis` overrides the named
    set's own default mesh axis ('tp' for tp rules, 'dp' for fsdp) and
    is forwarded to the set factory — never silently dropped.
    Dimensions that do not divide their mesh axis demote to replication
    (audited with a summary warning, never silent)."""
    from .rules import match_partition_rules, resolve_rules, tp_rules
    if rules is None:
        rules = tp_rules(axis) if axis is not None else tp_rules()
    else:
        rules = resolve_rules(rules, axis)
    return match_partition_rules(rules, params, mesh=mesh)


def shard_params(params, mesh: Mesh, axis: Optional[str] = None,
                 rules=None):
    """Place a param pytree on the mesh with rule-engine sharding
    (tensor-parallel by default; `axis`/`rules` as in
    `param_partition_specs`)."""
    specs = param_partition_specs(params, mesh, axis, rules=rules)
    return jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, specs)


def composed_state_shardings(params, opt_state, mesh: Mesh,
                             rules='composed', axis: Optional[str] = None):
    """Place params + optimizer state for the composed dp x sp x tp mesh
    and hand back the pinned-sharding pair the step factories need.

    This is the ROADMAP item 4 route, end to end: params through the
    rule engine (default: the `composed` set — Megatron tp placements
    with a tp-free dim over dp), optimizer state through
    `shard_opt_state` under the SAME rules (adam's mu/nu inherit each
    param's audited spec; scalars like `count` replicate ON THE MESH —
    an eager `optimizer.init` leaves them on a SingleDeviceSharding and
    the pinned jit then rejects the device mix), then the leaves'
    actual NamedShardings collected into the
    `state_shardings=(param_shardings, opt_shardings)` pair.

    Pinning matters: on jax 0.4.37 the dp2/sp2/tp2 mesh dies in the
    GSPMD donation-aliasing INTERNAL error ("Expected aliased input ...
    to have the same size") whenever out_shardings are left to AUTO —
    GSPMD picks a finer output sharding than the donated input carries.
    Passing this pair to `make_sharded_train_step(...,
    state_shardings=...)` pins in AND out shardings on every donated
    state argument, so each alias stays shape-preserving and the
    combined mesh compiles and runs (the PR 13 fsdp fix, extended to
    all three axes).

    Returns (placed_params, placed_opt_state, state_shardings)."""
    from .rules import place_with_rules, resolve_rules, shard_opt_state
    resolved = resolve_rules(rules, axis)   # once: params and opt state
    params, _ = place_with_rules(params, mesh, resolved)
    opt_state, _ = shard_opt_state(opt_state, params, mesh, rules=resolved)
    shardings = tuple(
        jax.tree_util.tree_map(lambda leaf: leaf.sharding, tree)
        for tree in (params, opt_state))
    return params, opt_state, shardings


def make_sharded_train_step(loss_fn: Callable, optimizer,
                            mesh: Optional[Mesh] = None,
                            donate: bool = True,
                            donate_batch: bool = False,
                            tensor_parallel: bool = False,
                            sharded_state: bool = False,
                            state_shardings=None,
                            telemetry: bool = False):
    """loss_fn(params, batch, rng) -> (loss, aux). Returns
    step(params, opt_state, batch, rng) -> (params, opt_state, loss, aux),
    jitted; when `mesh` is given, the caller is expected to place `batch`
    with parallel.mesh.shard_batch. Params/opt_state are replicated by
    default; with `tensor_parallel=True` they instead keep the placement
    the caller gave them (see `shard_params`), so tp-partitioned weights
    stay partitioned through the update and GSPMD inserts the psum for
    the row-parallel contractions.

    `sharded_state=True` is the true-FSDP wiring (ROADMAP item 4's
    named next step): like tensor_parallel, params AND optimizer state
    follow the placement the caller gave them — the caller shards
    params with `shard_params(..., rules='fsdp')` and the optimizer
    state with `parallel.rules.shard_opt_state` (adam's mu/nu inherit
    their param's audited spec), and the step's in/out shardings stay
    None on both so the update runs shard-local and nothing
    re-replicates. Before this flag, opt state replicated by default on
    every chip — 2x the parameter memory — despite the specs existing.

    `state_shardings=(param_shardings, opt_shardings)` (pytrees of
    NamedSharding matching the state trees) PINS the step's in AND out
    shardings for params/opt_state to exactly those placements. This is
    the explicit-aliasing route around the jax-0.4.37 GSPMD donation
    bug (the PR 5 residue): with out_shardings left to AUTO, GSPMD may
    pick a FINER output sharding than the donated input carries (e.g.
    dp+sp on a multi-axis mesh where the input is dp-only) and the
    donation dies in an INTERNAL aliased-size error — pinning output
    to input keeps every alias shape-preserving. The caller knows the
    placements (it made them with shard_params/shard_opt_state), so it
    passes them; DenoiseTrainer does this under cfg.fsdp.

    With `telemetry=True` the step signature grows by exactly one
    argument/result — an `observability.MetricAccumulator` pytree that
    folds loss and global grad norm ON DEVICE (a handful of scalar ops,
    no host sync): step(params, opt_state, batch, rng, acc) ->
    (params, opt_state, loss, aux, acc). The host flushes the
    accumulator once per logging interval.

    Donation audit. `donate=True` donates params/opt_state (and the
    telemetry accumulator) — always safe: the caller rebinds all three
    to the step's outputs, and sharded buffers are donated in place so
    tp-partitioned training resumes/continues without a host round
    trip; with `sharded_state` the donated adam mu/nu are themselves
    sharded and alias their (identically-sharded) outputs shard-for-
    shard — the input and output live on the same devices with the
    same per-shard shapes, so donation stays an in-place alias, never
    a cross-device move; checkpointing snapshots device copies first
    (`training.checkpoint.snapshot_device_arrays`), so async saves
    survive the donation too. `donate_batch=True` additionally donates
    the batch pytree (argnum 2) and is OPT-IN: it is only safe when
    every batch the step sees is freshly built or freshly placed — the
    `training.pipeline.device_prefetch` path, or any caller going
    through `parallel.mesh.shard_batch` (which device_puts fresh
    arrays per call). A caller that feeds the SAME device batch to two
    steps must leave it off, or the second step reads deleted buffers.
    """

    def step(params, opt_state, batch, rng):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    def step_telemetry(params, opt_state, batch, rng, acc):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        acc = acc.update(loss=loss, grad_norm=optax.global_norm(grads))
        return params, opt_state, loss, aux, acc

    fn = step_telemetry if telemetry else step
    # the accumulator is replaced every step — donate it like the state;
    # the batch (argnum 2) only on request (see the donation audit above)
    donate_argnums = ((0, 1, 4) if telemetry else (0, 1)) if donate else ()
    if donate and donate_batch:
        donate_argnums = tuple(sorted(donate_argnums + (2,)))
        _expect_unusable_batch_donation()
    if mesh is None:
        return jax.jit(fn, donate_argnums=donate_argnums)

    repl = replicated(mesh)
    acc_in = (repl,) if telemetry else ()
    acc_out = (repl,) if telemetry else ()
    if state_shardings is not None:
        ps, os_ = state_shardings
        return jax.jit(fn, in_shardings=(ps, os_, None, repl) + acc_in,
                       out_shardings=(ps, os_, repl, repl) + acc_out,
                       donate_argnums=donate_argnums)
    if tensor_parallel or sharded_state:
        # None = follow the argument/result placement (params arrive
        # pre-sharded by shard_params, opt state — under sharded_state —
        # by shard_opt_state; donation keeps buffers in place)
        return jax.jit(fn, in_shardings=(None, None, None, repl) + acc_in,
                       out_shardings=(None, None, repl, repl) + acc_out,
                       donate_argnums=donate_argnums)
    return jax.jit(
        fn,
        in_shardings=(repl, repl, None, repl) + acc_in,
        out_shardings=(repl, repl, repl, repl) + acc_out,
        donate_argnums=donate_argnums)


def make_accumulating_train_step(loss_fn: Callable, optimizer,
                                 accum_steps: int,
                                 mesh: Optional[Mesh] = None,
                                 donate_batch: bool = False,
                                 tensor_parallel: bool = False,
                                 sharded_state: bool = False,
                                 state_shardings=None,
                                 telemetry: bool = False):
    """Gradient-accumulation variant (reference denoise.py:13,55 uses 16
    micro-steps). batch leaves must have a leading [accum_steps, ...] axis;
    micro-batches are consumed with lax.scan so the compiled program is
    O(1) in accum_steps.

    `telemetry=True` threads a MetricAccumulator exactly like
    make_sharded_train_step; the per-micro-step loss VECTOR folds in, so
    the flushed window's loss min/max expose a diverging micro-batch.
    `donate_batch=True` donates the stacked micro-batch pytree — same
    opt-in safety contract as make_sharded_train_step (fresh batch per
    step only). `sharded_state=True` follows the caller's params AND
    opt-state placement (the true-FSDP wiring — see
    make_sharded_train_step's donation audit: sharded mu/nu donate as
    in-place aliases)."""

    def _grads_and_losses(params, batch, rng):
        def micro(carry, xs):
            acc, rng = carry
            micro_batch, = xs
            rng, sub = jax.random.split(rng)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, micro_batch, sub)
            acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            return (acc, rng), loss

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (grads, _), losses = jax.lax.scan(micro, (zeros, rng), (batch,))
        return jax.tree_util.tree_map(lambda g: g / accum_steps,
                                      grads), losses

    def step(params, opt_state, batch, rng):
        grads, losses = _grads_and_losses(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # per-micro-step losses ride along (the reference prints every
        # outer step's loss, denoise.py:91 — the mean alone hides a
        # diverging micro-batch); same 4-arity as make_sharded_train_step
        return params, opt_state, losses.mean(), losses

    def step_telemetry(params, opt_state, batch, rng, acc):
        grads, losses = _grads_and_losses(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        acc = acc.update(loss=losses, grad_norm=optax.global_norm(grads))
        return params, opt_state, losses.mean(), losses, acc

    fn = step_telemetry if telemetry else step
    donate_argnums = (0, 1, 4) if telemetry else (0, 1)
    if donate_batch:
        donate_argnums = tuple(sorted(donate_argnums + (2,)))
        _expect_unusable_batch_donation()
    if mesh is None:
        return jax.jit(fn, donate_argnums=donate_argnums)
    repl = replicated(mesh)
    acc_s = (repl,) if telemetry else ()
    if state_shardings is not None:
        # pinned state placements (see make_sharded_train_step: the
        # explicit-aliasing route around the GSPMD donation bug)
        ps, os_ = state_shardings
        return jax.jit(fn, in_shardings=(ps, os_, None, repl) + acc_s,
                       out_shardings=(ps, os_, repl, repl) + acc_s,
                       donate_argnums=donate_argnums)
    if tensor_parallel or sharded_state:
        return jax.jit(fn, in_shardings=(None, None, None, repl) + acc_s,
                       out_shardings=(None, None, repl, repl) + acc_s,
                       donate_argnums=donate_argnums)
    return jax.jit(fn, in_shardings=(repl, repl, None, repl) + acc_s,
                   out_shardings=(repl, repl, repl, repl) + acc_s,
                   donate_argnums=donate_argnums)
