"""Sharded training-step construction (pjit over the dp/sp/tp mesh).

Builds a jitted SPMD train step: parameters and optimizer state are
replicated (they are tiny relative to the O(B*N*K) edge activations), data
is sharded dp over batch and sp over the node axis, and GSPMD propagates
shardings through the model — neighbor gathers over the full source-node
axis lower to all-gathers over ICI, loss reductions to psums. This replaces
the reference's absent distributed backend (SURVEY.md §2.9) with XLA
collectives rather than a hand-rolled NCCL/MPI layer.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def make_sharded_train_step(loss_fn: Callable, optimizer,
                            mesh: Optional[Mesh] = None,
                            donate: bool = True):
    """loss_fn(params, batch, rng) -> (loss, aux). Returns
    step(params, opt_state, batch, rng) -> (params, opt_state, loss, aux),
    jitted; when `mesh` is given, params/opt_state are replicated and the
    caller is expected to place `batch` with parallel.mesh.shard_batch.
    """

    def step(params, opt_state, batch, rng):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    donate_argnums = (0, 1) if donate else ()
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_argnums)

    repl = replicated(mesh)
    return jax.jit(
        step,
        in_shardings=(repl, repl, None, repl),
        out_shardings=(repl, repl, repl, repl),
        donate_argnums=donate_argnums)


def make_accumulating_train_step(loss_fn: Callable, optimizer,
                                 accum_steps: int,
                                 mesh: Optional[Mesh] = None):
    """Gradient-accumulation variant (reference denoise.py:13,55 uses 16
    micro-steps). batch leaves must have a leading [accum_steps, ...] axis;
    micro-batches are consumed with lax.scan so the compiled program is
    O(1) in accum_steps."""

    def step(params, opt_state, batch, rng):
        def micro(carry, xs):
            acc, rng = carry
            micro_batch, = xs
            rng, sub = jax.random.split(rng)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, micro_batch, sub)
            acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            return (acc, rng), loss

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (grads, _), losses = jax.lax.scan(micro, (zeros, rng), (batch,))
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, losses.mean()

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    repl = replicated(mesh)
    return jax.jit(step, in_shardings=(repl, repl, None, repl),
                   out_shardings=(repl, repl, repl),
                   donate_argnums=(0, 1))
