"""Device-mesh construction for dp/sp/tp SPMD execution.

The reference is single-process, single-device (SURVEY.md §2.9); the only
multi-device artifact is an aspirational comment (reference
reversible.py:91-92). Here multi-chip is first-class: one
jax.sharding.Mesh with three axes

  * dp — data parallel over the batch axis,
  * sp — sequence/node parallel over the query-node axis (the O(N^2)
    distance/top-k and O(N*K) basis/conv/attention work partition cleanly
    by query node; gathers of source-node features become XLA all-gathers
    over ICI),
  * tp — tensor parallel over heads/hidden channels.

XLA's GSPMD inserts the collectives (all_gather / psum / reduce_scatter)
from sharding annotations — there is no hand-written transport layer, which
is the TPU-native equivalent of an NCCL/MPI backend. `jax.distributed` +
the same mesh covers multi-host (ICI intra-slice, DCN across slices).
"""
from __future__ import annotations

import math
import warnings
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ('dp', 'sp', 'tp')


def _factorize(n: int, ways: int = 3) -> tuple:
    """Split n into `ways` near-equal power factors, largest first."""
    factors = [1] * ways
    remaining = n
    primes = []
    d = 2
    while remaining > 1:
        while remaining % d == 0:
            primes.append(d)
            remaining //= d
        d += 1
    for p in sorted(primes, reverse=True):
        j = int(np.argmin(factors))
        factors[j] *= p
    return tuple(sorted(factors, reverse=True))


def make_mesh(devices: Optional[Sequence] = None,
              dp: Optional[int] = None, sp: Optional[int] = None,
              tp: Optional[int] = None) -> Mesh:
    """Build a ('dp', 'sp', 'tp') mesh over the given (or all) devices.

    Unspecified axis sizes are auto-factorized from the device count —
    except tp, which defaults to 1 unless explicitly requested: tensor
    parallelism only does real work when the caller also partitions the
    params (parallel.sharding.shard_params), so auto-allocating devices
    to tp would silently make them redundant.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        tp = 1
    known = [a for a in (dp, sp, tp) if a is not None]
    rest = n // math.prod(known) if known else n
    if dp is None or sp is None or tp is None:
        missing = [dp, sp, tp].count(None)
        auto = list(_factorize(rest, missing))
        # the node (sp) axis gets the largest auto factor: batch sizes are
        # often tiny (the denoise example uses 1) while the node axis is
        # the long one, so defaulting dp large would make default configs
        # unshardable
        dims = []
        for a in (sp, dp, tp):
            dims.append(a if a is not None else auto.pop(0))
        sp_d, dp_d, tp_d = dims
        dims = [dp_d, sp_d, tp_d]
    else:
        dims = [dp, sp, tp]
    assert math.prod(dims) == n, \
        f'mesh {dims} does not cover {n} devices'
    mesh_devices = np.asarray(devices).reshape(dims)
    return Mesh(mesh_devices, MESH_AXES)


def mesh_shape_dict(mesh: Mesh) -> dict:
    """Ordered {axis: size} for a mesh, in device-array order — the
    shape `parallel.exchange.attribute_collective_axes` needs to map
    HLO replica-group device ids back onto mesh axes (device id =
    row-major index into this shape, which is how make_mesh lays
    devices out)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def mesh_points(n_devices: int = 8,
                sizes: Sequence[int] = (1, 2, 4)) -> list:
    """Every (dp, sp, tp) in sizes^3 whose product covers exactly
    `n_devices` — the composed-sweep enumeration (ROADMAP item 4:
    8 devices -> the 6 permutations of (1, 2, 4) plus (2, 2, 2)).
    Sorted for a deterministic sweep order."""
    return sorted((dp, sp, tp)
                  for dp in sizes for sp in sizes for tp in sizes
                  if dp * sp * tp == n_devices)


# canonical partition specs for the data pytree of a training step
def data_specs() -> dict:
    return dict(
        feats=P('dp', 'sp'),          # [b, n] token ids or [b, n, d]
        coors=P('dp', 'sp', None),    # [b, n, 3]
        mask=P('dp', 'sp'),           # [b, n]
        adj_mat=P('dp', 'sp', None),  # [b, n, n]
        targets=P('dp', 'sp', None),
    )


# the trainer/dataset key names map onto the canonical specs so every
# batch dict in the repo (DenoiseTrainer's seqs/coords/masks,
# PointCloudDataset's tokens/mask) places directly through shard_batch /
# batch_shardings without a rename dance at each call site
_KEY_ALIASES = dict(seqs='feats', tokens='feats', coords='coors',
                    masks='mask')


def resolve_data_spec(key: str, ndim: int, leading_axes: int = 0) -> P:
    """Canonical PartitionSpec for one batch entry, truncated/padded to its
    rank (shared by shard_batch and distributed.shard_host_local_batch so
    the two placement entry points cannot drift)."""
    spec = data_specs().get(_KEY_ALIASES.get(key, key), P('dp'))
    spec = P(*([None] * leading_axes), *spec)
    return P(*spec[:ndim]) if ndim < len(spec) else spec


def batch_shardings(batch: dict, mesh: Mesh,
                    leading_axes: int = 0) -> dict:
    """NamedSharding per batch key, with the divisibility fallback.

    Axes that do not divide evenly by their mesh axis fall back to
    replication for that dimension (e.g. batch_size=1 with dp>1), so any
    batch is placeable — but the fallback is LOUD: silently replicating
    would make "sharded training" mean "every device does the same
    work", so each degraded (key, dim) pair warns once. Works on host
    numpy or device arrays (only shapes are read) — the prefetch
    pipeline uses it to compute target shardings before transfer."""
    out = {}
    for k, v in batch.items():
        spec = resolve_data_spec(k, v.ndim, leading_axes)
        fixed = []
        for d, axis in enumerate(spec):
            if axis is None:
                fixed.append(None)
                continue
            size = mesh.shape[axis] if isinstance(axis, str) else 1
            if v.shape[d] % size == 0:
                fixed.append(axis)
            else:
                fixed.append(None)
                if size > 1:
                    warnings.warn(
                        f"shard_batch: '{k}' dim {d} (size {v.shape[d]}) "
                        f"does not divide mesh axis '{axis}' (size {size}) "
                        f"— replicating that dimension instead; those "
                        f"devices will do redundant work",
                        stacklevel=3)
        out[k] = NamedSharding(mesh, P(*fixed))
    return out


def shard_batch(batch: dict, mesh: Mesh, leading_axes: int = 0) -> dict:
    """Place a host batch dict onto the mesh with the canonical specs.

    `leading_axes` extra leading dims (e.g. a gradient-accumulation axis)
    are left unsharded. See `batch_shardings` for the divisibility
    fallback semantics."""
    shardings = batch_shardings(batch, mesh, leading_axes)
    return {k: jax.device_put(v, shardings[k]) for k, v in batch.items()}
