"""Ring sequence-parallel neighbor selection for long point clouds.

The O(N^2) pairwise distance matrix is the reference's long-context scaling
wall (it materializes [b, n, n-1] host tensors before top-k — reference
se3_transformer_pytorch.py:1222,1277; SURVEY.md §5 'long-context'). With
the node axis sharded over the `sp` mesh axis, this module computes exact
kNN without ever materializing a full distance row:

  each device holds a query block [b, n_local] and a source block; at every
  ring step it scores queries against the current source block, merges a
  running top-K via fixed-size top_k on the concatenation, and ppermutes
  the source block to the next device over ICI. After sp steps every query
  has its exact K nearest — peak memory O(n_local^2) instead of
  O(n_local * N).

Two comm disciplines (PR 5):

  * the ring is DOUBLE-BUFFERED by default (`overlap=True`): the
    ppermute moving the source block for step t+1 is issued *before*
    step t's score/merge, so the ICI transfer hides under the
    O(n_local^2) distance compute instead of serializing with it
    (`ring_scan` below — the same helper drives
    `parallel.exchange.neighbor_gather`);
  * scoring runs on SQUARED distances (one multiply-add per pair instead
    of a sqrt over [b, nl, nl] per ring step); the single sqrt happens
    once on the merged [b, nl, k] result. The transform is monotone, so
    selection order and the FINF / bonded-0 sentinel semantics are
    preserved exactly (`_unsquare_rank`).

This is the graph-transformer analogue of ring attention: the ring carries
key/source *coordinates* instead of k/v blocks, and what flows back is a
neighbor list that the (local, O(n_local * K)) conv/attention stage
consumes after a neighbor-sparse feature exchange (parallel.exchange).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.neighbors import FINF, _top_k_smallest

# --- jax version compat (this container ships jax 0.4.37) ----------------- #
# shard_map graduated from jax.experimental to jax.shard_map, and the vma
# (varying-manual-axes) tracking it enforces grew the jax.lax.pcast
# entry point, only on newer jax. Resolve both once here; exchange.py
# shares these shims.
try:
    _shard_map = jax.shard_map
    _SHARD_MAP_KW: dict = {}
except AttributeError:  # jax < 0.6
    from jax.experimental.shard_map import (  # type: ignore
        shard_map as _shard_map,
    )
    # the legacy rep-tracker mis-infers scan-carry TANGENT replication
    # when a shard_map is differentiated under a custom_vjp's jvp (the
    # reversible trunk): instantiated-zero tangents enter the carry with
    # rep None and the check rejects the (correct) program. jax's own
    # guidance for this false positive is check_rep=False — a static
    # checker toggle only, numerics unchanged. New-jax vma tracking
    # (pcast_varying below) stays fully checked.
    _SHARD_MAP_KW = dict(check_rep=False)


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable shard_map (see _SHARD_MAP_KW above); the ring and
    parallel.exchange build every collective region through this."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **_SHARD_MAP_KW)


def pcast_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Mark a per-shard constant as device-varying for shard_map's vma
    tracking; identity on jax versions that predate vma."""
    pcast = getattr(jax.lax, 'pcast', None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to='varying')


def ring_scan(body, carry, blocks, axis_name: str, overlap: bool = True):
    """Fold `body(carry, blocks, t) -> carry` over every ring position
    t = 0..sp-1, rotating `blocks` (a tuple of per-shard arrays sharing
    their leading layout) one hop per step so each device sees every
    device's block exactly once.

    overlap=True double-buffers the rotation: the ppermute producing the
    blocks for step t+1 is issued BEFORE step t's body, so on TPU the
    ICI transfer overlaps the body's compute (XLA's async
    collective-permute scheduler needs the transfer to be
    data-independent of the in-flight body, which this ordering
    guarantees; the serialized variant chains rotate-after-score). Both
    variants issue exactly `sp` ppermutes per block and produce
    bit-identical results — the off switch exists so the overlap can be
    A/B'd and disabled without changing numerics.

    The per-pair transfer is O(b * n_local) per step either way; what
    overlap buys is hiding that latency under the O(n_local^2) score.
    """
    axis_size = jax.lax.psum(1, axis_name)
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    def rotate(bs):
        # 'ici_wait' labels the transfer for xprof attribution
        # (observability.timing.MODEL_SCOPES): in an overlapped trace the
        # scope's exclusive time is the NON-hidden remainder of the hop
        with jax.named_scope('ici_wait'):
            return tuple(jax.lax.ppermute(b, axis_name, perm) for b in bs)

    if not overlap or axis_size == 1:
        def step(c, t):
            carry, bs = c
            carry = body(carry, bs, t)
            return (carry, rotate(bs)), None

        (carry, _), _ = jax.lax.scan(
            step, (carry, blocks), jnp.arange(axis_size, dtype=jnp.int32))
        return carry

    # double-buffered: cur holds the block for step t, nxt the one for
    # step t+1 (already in flight — its ppermute was issued one body
    # ago). The final block is scored outside the scan, so the loop
    # issues sp-1 hops and the prologue 1: sp total, same as serialized.
    nxt = rotate(blocks)

    def step(c, t):
        carry, cur, nxt = c
        fut = rotate(nxt)          # kick off the t+2 transfer first ...
        carry = body(carry, cur, t)  # ... then score block t under it
        return (carry, nxt, fut), None

    (carry, cur, _), _ = jax.lax.scan(
        step, (carry, blocks, nxt),
        jnp.arange(axis_size - 1, dtype=jnp.int32))
    return body(carry, cur, axis_size - 1)


def _unsquare_rank(rank_sq: jnp.ndarray) -> jnp.ndarray:
    """Map a merged SQUARED-distance ranking back to distance space with
    the sentinel semantics intact: excluded slots carry FINF (not
    sqrt(FINF)), bonded-priority slots carry exactly 0, and the gradient
    at zero distance is 0 rather than NaN (the safe_norm double-where —
    jnp.sqrt's gradient at 0 is inf*cotangent)."""
    is_zero = rank_sq == 0
    safe = jnp.sqrt(jnp.where(is_zero, 1.0, rank_sq))
    rank = jnp.where(is_zero, 0.0, safe)
    return jnp.where(rank_sq >= FINF, FINF, rank)


def _ring_knn_local(coors_q: jnp.ndarray, coors_src: jnp.ndarray,
                    mask_src: jnp.ndarray,
                    nm_rows: Optional[jnp.ndarray],
                    sp_rows: Optional[jnp.ndarray],
                    k: int, axis_name: str,
                    causal: bool = False,
                    overlap: bool = True
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-shard body (runs under shard_map). coors_q/coors_src are this
    device's [b, nl, 3] blocks, mask_src its [b, nl] source validity.
    nm_rows/sp_rows are this device's QUERY-row shards of the full-width
    per-pair predicates ([b, nl, N]): the user neighbor mask and the
    bonded (sparse-adjacency) priority — each ring step slices the
    source-block column window out of them. Returns (rank [b, nl, k],
    idx [b, nl, k]) with idx in GLOBAL node coordinates; rank is the
    MODIFIED ranking the dense path sorts by (reference
    se3_transformer_pytorch.py:1257,1262,1267 — neighbor-mask
    exclusions FINF, bonded 0, future FINF under causal), which is what
    the `rank <= valid_radius` validity rule must consume; masked-out
    sources never occupy a neighbor slot.

    The running merge lives in SQUARED-distance space (the sentinels
    FINF and 0 are fixed points of the monotone transform, so the
    selection is unchanged); `_unsquare_rank` restores distances once at
    the end."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, nl, _ = coors_q.shape

    best_r = jnp.full((b, nl, k), FINF, coors_q.dtype)
    best_i = jnp.zeros((b, nl, k), jnp.int32)
    # mark the running top-K as device-varying for shard_map's vma tracking
    best_r = pcast_varying(best_r, axis_name)
    best_i = pcast_varying(best_i, axis_name)
    q_global = my_idx * nl + jnp.arange(nl, dtype=jnp.int32)

    def score(carry, blocks, t):
        best_r, best_i = carry
        src, m_src = blocks
        # at ring step t, this device holds the block originally owned by
        # (my_idx + t) mod axis_size
        src_owner = (my_idx + t) % axis_size
        # SQUARED distances to the current source block (no per-step sqrt)
        diff = coors_q[:, :, None] - src[:, None, :]
        d = jnp.sum(diff * diff, axis=-1)
        src_global = src_owner * nl + jnp.arange(nl, dtype=jnp.int32)
        # exclude self-pairs (same global id) and masked-out sources
        self_mask = q_global[:, None] == src_global[None, :]
        d = jnp.where(self_mask[None], FINF, d)
        d = jnp.where(m_src[:, None, :], d, FINF)
        # per-pair semantics, in the dense path's exact order (so e.g. a
        # bonded pair overrides a neighbor-mask exclusion but loses to
        # causal masking, matching ops/neighbors.select_neighbors)
        col0 = src_owner * nl
        if nm_rows is not None:
            nm_blk = jax.lax.dynamic_slice_in_dim(nm_rows, col0, nl, axis=2)
            d = jnp.where(nm_blk, d, FINF)
        if sp_rows is not None:
            sp_blk = jax.lax.dynamic_slice_in_dim(sp_rows, col0, nl, axis=2)
            # a bond to a masked-out (padded) source must not resurrect
            # it at rank 0 — the never-select-masked contract above wins
            sp_blk = sp_blk & m_src[:, None, :]
            d = jnp.where(sp_blk, 0., d)
        if causal:
            # self-excluded dense layout masks exactly source > query
            # (reference :1267 via neighbors.select_neighbors)
            future = src_global[None, :] > q_global[:, None]
            d = jnp.where(future[None], FINF, d)

        cand_d = jnp.concatenate([best_r, d], axis=-1)
        cand_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(src_global[None, None], d.shape)],
            axis=-1)
        new_r, sel = _top_k_smallest(cand_d, k)
        new_i = jnp.take_along_axis(cand_i, sel, axis=-1)
        return new_r, new_i

    best_r, best_i = ring_scan(score, (best_r, best_i),
                               (coors_src, mask_src), axis_name,
                               overlap=overlap)
    return _unsquare_rank(best_r), best_i


def ring_knn(coors: jnp.ndarray, k: int, mesh: Mesh,
             axis_name: str = 'sp',
             mask: Optional[jnp.ndarray] = None,
             neighbor_mask: Optional[jnp.ndarray] = None,
             sparse_mask: Optional[jnp.ndarray] = None,
             causal: bool = False,
             overlap: bool = True
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN (self excluded) over a node-sharded coordinate tensor,
    with the dense path's full ranking semantics.

    coors [b, n, 3] with n divisible by mesh.shape[axis_name]; optional
    mask [b, n] excludes padded nodes from ever being selected as
    sources. neighbor_mask/sparse_mask are optional FULL-width per-pair
    predicates [b, n, n] (query-row sharded over the sp axis by
    construction; the column axis stays local — they are the
    user-supplied O(N^2) inputs of the adjacency configs, so holding a
    row shard is the natural cost). causal masks future sources
    (source id > query id), reference :1267. overlap double-buffers the
    ring's ppermutes so ICI hides under the score compute (bit-exact
    either way — `ring_scan`).

    Returns (rank [b, n, k], idx [b, n, k]) sharded the same way;
    indices are global node ids. `rank` is the dense path's MODIFIED
    ranking (bonded pairs 0, exclusions FINF): validity is
    `rank <= valid_radius`, and the true geometry is recomputed from
    `coors[idx]` by the caller. Plain-kNN callers can keep reading it
    as a distance (invalid slots carry FINF).

    INTENTIONAL divergence from the dense path on `mask`: masked-out
    sources are FINF'd in the ranking here (never selected), while
    select_neighbors lets them win slots by raw distance and only
    invalidates them afterwards — so on padded inputs the ring fills
    those slots with real farther neighbors instead of wasting them.
    Parity with the dense path is exact for full masks (the tests'
    contract); with padding the ring path strictly dominates.
    """
    n = coors.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    if mask is None:
        mask = jnp.ones(coors.shape[:2], bool)

    spec = P(None, axis_name, None)
    mspec = P(None, axis_name)
    in_specs = [spec, spec, mspec]
    args = [coors, coors, mask]
    # rows sharded like the queries, columns full: P(None, sp, None)
    for pred in (neighbor_mask, sparse_mask):
        if pred is not None:
            assert pred.shape[-2:] == (n, n), pred.shape
            in_specs.append(spec)
            args.append(pred)
    nm_pos = 3 if neighbor_mask is not None else None
    sp_pos = (3 + (neighbor_mask is not None)) \
        if sparse_mask is not None else None

    def body(*ops):
        return _ring_knn_local(
            ops[0], ops[1], ops[2],
            ops[nm_pos] if nm_pos is not None else None,
            ops[sp_pos] if sp_pos is not None else None,
            k=k, axis_name=axis_name, causal=causal, overlap=overlap)

    fn = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=(spec, spec))
    # scope the ring (scan of score/merge/ppermute) for xprof attribution
    # (observability.timing.MODEL_SCOPES)
    with jax.named_scope('ring_knn'):
        return fn(*args)


def dense_knn(coors: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-device reference: full [b, n, n] distances + top-k.

    Scores on squared distances with one safe sqrt at the end — the same
    formulation as the ring merge, so differentiating through the
    selection distances is NaN-free at coincident points (jnp.linalg.norm's
    gradient at zero distance is NaN; the model paths use safe_norm for
    the same reason)."""
    diff = coors[:, :, None] - coors[:, None, :]
    d = jnp.sum(diff * diff, axis=-1)
    n = coors.shape[1]
    d = jnp.where(jnp.eye(n, dtype=bool)[None], FINF, d)
    rank_sq, idx = _top_k_smallest(d, k)
    return _unsquare_rank(rank_sq), idx.astype(jnp.int32)
