"""Ring sequence-parallel neighbor selection for long point clouds.

The O(N^2) pairwise distance matrix is the reference's long-context scaling
wall (it materializes [b, n, n-1] host tensors before top-k — reference
se3_transformer_pytorch.py:1222,1277; SURVEY.md §5 'long-context'). With
the node axis sharded over the `sp` mesh axis, this module computes exact
kNN without ever materializing a full distance row:

  each device holds a query block [b, n_local] and a source block; at every
  ring step it scores queries against the current source block, merges a
  running top-K via fixed-size top_k on the concatenation, and ppermutes
  the source block to the next device over ICI. After sp steps every query
  has its exact K nearest — peak memory O(n_local^2) instead of
  O(n_local * N).

This is the graph-transformer analogue of ring attention: the ring carries
key/source *coordinates* instead of k/v blocks, and what flows back is a
neighbor list that the (local, O(n_local * K)) conv/attention stage
consumes after a feature all-gather.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.neighbors import FINF, _top_k_smallest


def _ring_knn_local(coors_q: jnp.ndarray, coors_src: jnp.ndarray,
                    mask_src: jnp.ndarray,
                    nm_rows: Optional[jnp.ndarray],
                    sp_rows: Optional[jnp.ndarray],
                    k: int, axis_name: str,
                    causal: bool = False
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-shard body (runs under shard_map). coors_q/coors_src are this
    device's [b, nl, 3] blocks, mask_src its [b, nl] source validity.
    nm_rows/sp_rows are this device's QUERY-row shards of the full-width
    per-pair predicates ([b, nl, N]): the user neighbor mask and the
    bonded (sparse-adjacency) priority — each ring step slices the
    source-block column window out of them. Returns (rank [b, nl, k],
    idx [b, nl, k]) with idx in GLOBAL node coordinates; rank is the
    MODIFIED ranking the dense path sorts by (reference
    se3_transformer_pytorch.py:1257,1262,1267 — neighbor-mask
    exclusions FINF, bonded 0, future FINF under causal), which is what
    the `rank <= valid_radius` validity rule must consume; masked-out
    sources never occupy a neighbor slot."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, nl, _ = coors_q.shape

    best_d = jnp.full((b, nl, k), FINF, coors_q.dtype)
    best_i = jnp.zeros((b, nl, k), jnp.int32)
    # mark the running top-K as device-varying for shard_map's vma tracking
    best_d = jax.lax.pcast(best_d, (axis_name,), to='varying')
    best_i = jax.lax.pcast(best_i, (axis_name,), to='varying')
    q_global = my_idx * nl + jnp.arange(nl, dtype=jnp.int32)

    def step(carry, t):
        best_d, best_i, src, m_src = carry
        # at ring step t, this device holds the block originally owned by
        # (my_idx + t) mod axis_size
        src_owner = (my_idx + t) % axis_size
        # distances to the current source block
        d = jnp.linalg.norm(coors_q[:, :, None] - src[:, None, :], axis=-1)
        src_global = src_owner * nl + jnp.arange(nl, dtype=jnp.int32)
        # exclude self-pairs (same global id) and masked-out sources
        self_mask = q_global[:, None] == src_global[None, :]
        d = jnp.where(self_mask[None], FINF, d)
        d = jnp.where(m_src[:, None, :], d, FINF)
        # per-pair semantics, in the dense path's exact order (so e.g. a
        # bonded pair overrides a neighbor-mask exclusion but loses to
        # causal masking, matching ops/neighbors.select_neighbors)
        col0 = src_owner * nl
        if nm_rows is not None:
            nm_blk = jax.lax.dynamic_slice_in_dim(nm_rows, col0, nl, axis=2)
            d = jnp.where(nm_blk, d, FINF)
        if sp_rows is not None:
            sp_blk = jax.lax.dynamic_slice_in_dim(sp_rows, col0, nl, axis=2)
            # a bond to a masked-out (padded) source must not resurrect
            # it at rank 0 — the never-select-masked contract above wins
            sp_blk = sp_blk & m_src[:, None, :]
            d = jnp.where(sp_blk, 0., d)
        if causal:
            # self-excluded dense layout masks exactly source > query
            # (reference :1267 via neighbors.select_neighbors)
            future = src_global[None, :] > q_global[:, None]
            d = jnp.where(future[None], FINF, d)

        cand_d = jnp.concatenate([best_d, d], axis=-1)
        cand_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(src_global[None, None], d.shape)],
            axis=-1)
        new_d, sel = _top_k_smallest(cand_d, k)
        new_i = jnp.take_along_axis(cand_i, sel, axis=-1)

        # rotate source blocks one hop around the ring (device i receives
        # the block from device i+1 over ICI)
        perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        src = jax.lax.ppermute(src, axis_name, perm)
        m_src = jax.lax.ppermute(m_src, axis_name, perm)
        return (new_d, new_i, src, m_src), None

    init = (best_d, best_i, coors_q, mask_src)
    (best_d, best_i, _, _), _ = jax.lax.scan(
        step, init, jnp.arange(axis_size, dtype=jnp.int32))
    return best_d, best_i


def ring_knn(coors: jnp.ndarray, k: int, mesh: Mesh,
             axis_name: str = 'sp',
             mask: Optional[jnp.ndarray] = None,
             neighbor_mask: Optional[jnp.ndarray] = None,
             sparse_mask: Optional[jnp.ndarray] = None,
             causal: bool = False
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN (self excluded) over a node-sharded coordinate tensor,
    with the dense path's full ranking semantics.

    coors [b, n, 3] with n divisible by mesh.shape[axis_name]; optional
    mask [b, n] excludes padded nodes from ever being selected as
    sources. neighbor_mask/sparse_mask are optional FULL-width per-pair
    predicates [b, n, n] (query-row sharded over the sp axis by
    construction; the column axis stays local — they are the
    user-supplied O(N^2) inputs of the adjacency configs, so holding a
    row shard is the natural cost). causal masks future sources
    (source id > query id), reference :1267.

    Returns (rank [b, n, k], idx [b, n, k]) sharded the same way;
    indices are global node ids. `rank` is the dense path's MODIFIED
    ranking (bonded pairs 0, exclusions FINF): validity is
    `rank <= valid_radius`, and the true geometry is recomputed from
    `coors[idx]` by the caller. Plain-kNN callers can keep reading it
    as a distance (invalid slots carry FINF).

    INTENTIONAL divergence from the dense path on `mask`: masked-out
    sources are FINF'd in the ranking here (never selected), while
    select_neighbors lets them win slots by raw distance and only
    invalidates them afterwards — so on padded inputs the ring fills
    those slots with real farther neighbors instead of wasting them.
    Parity with the dense path is exact for full masks (the tests'
    contract); with padding the ring path strictly dominates.
    """
    n = coors.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    if mask is None:
        mask = jnp.ones(coors.shape[:2], bool)

    spec = P(None, axis_name, None)
    mspec = P(None, axis_name)
    in_specs = [spec, spec, mspec]
    args = [coors, coors, mask]
    # rows sharded like the queries, columns full: P(None, sp, None)
    for pred in (neighbor_mask, sparse_mask):
        if pred is not None:
            assert pred.shape[-2:] == (n, n), pred.shape
            in_specs.append(spec)
            args.append(pred)
    nm_pos = 3 if neighbor_mask is not None else None
    sp_pos = (3 + (neighbor_mask is not None)) \
        if sparse_mask is not None else None

    def body(*ops):
        return _ring_knn_local(
            ops[0], ops[1], ops[2],
            ops[nm_pos] if nm_pos is not None else None,
            ops[sp_pos] if sp_pos is not None else None,
            k=k, axis_name=axis_name, causal=causal)

    fn = jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=(spec, spec))
    # scope the ring (scan of score/merge/ppermute) for xprof attribution
    # (observability.timing.MODEL_SCOPES)
    with jax.named_scope('ring_knn'):
        return fn(*args)


def dense_knn(coors: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-device reference: full [b, n, n] distances + top-k."""
    d = jnp.linalg.norm(coors[:, :, None] - coors[:, None, :], axis=-1)
    n = coors.shape[1]
    d = jnp.where(jnp.eye(n, dtype=bool)[None], FINF, d)
    dist, idx = _top_k_smallest(d, k)
    return dist, idx.astype(jnp.int32)
