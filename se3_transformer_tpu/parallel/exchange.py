"""Neighbor-sparse feature exchange for the sequence-parallel ring path.

After `parallel.ring.ring_knn` returns GLOBAL neighbor ids, every
consumer needs source-node values at those ids: coordinates, per-degree
features (once per conv/attention layer), masks. Expressed as a plain
`batched_index_select(values, idx, axis=1)` over a node-sharded operand,
GSPMD can only serve the global gather by ALL-GATHERING the full
[b, N, ...] operand onto every device — O(N) feature memory per shard
and full-width ICI traffic, which un-does exactly the O(n_local) memory
story the ring exists for.

`neighbor_gather` is the sparse replacement: a shard_map'd ring that
rotates the OWNED value blocks one hop per step (double-buffered via
`ring.ring_scan`, so the transfer hides under the select) and selects
on the fly — each device ends with only its O(n_local * k) neighbor
rows, exact-parity with the dense gather for in-range ids. Per-device
traffic is O(n_local * feature) per hop (the operand's shard size, paid
sp-1 times = one full rotation) versus the all-gather's same total but
with an O(N) resident copy and no overlap.

`rowwise_gather` covers the second gather family of the ring branch:
row-sharded FULL-column operands ([b, n, N, ...] edges / adjacency
labels) selected along the column axis by row-aligned ids. That gather
needs no communication at all — shard_map pins it local so GSPMD can
never decide to materialize the full operand. `bonded_priority_mask`
does the same for the jittered bonded-neighbor selection (noise scatter
+ per-row top-k): row-parallel by construction, yet GSPMD's scatter
partitioner serves the dense formulation with a full-width [b, N, N]
all-gather — measured, not hypothetical.

`exchange_scope` threads the mesh through the trunk without widening
every layer signature: inside the scope, `exchange_index_select`
(called by ConvSE3 / attention / EGNN neighbor gathers) routes
axis-1 gathers through `neighbor_gather`. The scope is TRACE-time
state, same discipline as jax.default_matmul_precision.

`analyze_hlo_comm` / `comm_payload` turn a compiled program's HLO text
into the schema'd `comm` record (observability.schema): per-class
collective counts + estimated bytes and the all-gather-free proof the
weak-scaling harness and `make ring-smoke` gate on.
"""
from __future__ import annotations

import contextlib
import re
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.helpers import batched_index_select
from .ring import pcast_varying, ring_scan, shard_map


# ------------------------------------------------------------------------- #
# neighbor-sparse gathers
# ------------------------------------------------------------------------- #
def _gather_local(vals: jnp.ndarray, idx: jnp.ndarray, axis_name: str,
                  overlap: bool = True) -> jnp.ndarray:
    """Per-shard body: vals is this device's [b, nl, *f] value block, idx
    its [b, nq, k] GLOBAL ids. Rotates value blocks around the ring; at
    each step the ids that fall inside the held block's global window
    select from it. In-range ids are hit exactly once over the full
    rotation, so the where-merge reproduces the dense gather verbatim;
    out-of-range ids (never produced by ring_knn) yield zeros."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    nl = vals.shape[1]

    out = jnp.zeros(idx.shape + vals.shape[2:], vals.dtype)
    out = pcast_varying(out, axis_name)

    def select(out, blocks, t):
        (blk,) = blocks
        owner = (my_idx + t) % axis_size
        local = idx - owner * nl
        hit = (local >= 0) & (local < nl)
        gathered = batched_index_select(
            blk, jnp.clip(local, 0, nl - 1), axis=1)   # [b, nq, k, *f]
        hit = hit.reshape(hit.shape + (1,) * (gathered.ndim - hit.ndim))
        return jnp.where(hit, gathered, out)

    return ring_scan(select, out, (vals,), axis_name, overlap=overlap)


def neighbor_gather(values: jnp.ndarray, idx: jnp.ndarray, mesh: Mesh,
                    axis_name: str = 'sp',
                    overlap: bool = True) -> jnp.ndarray:
    """Sparse equivalent of `batched_index_select(values, idx, axis=1)`
    for a node-sharded operand: values [b, n, *f] sharded over `axis_name`
    on axis 1, idx [b, n, k] global ids sharded the same way. Returns
    [b, n, k, *f] with identical sharding — no device ever holds more
    than its own value shard plus the in-flight hop buffer.

    Exact parity with the dense gather for in-range ids (the ring_knn
    contract: ids are always valid global node ids, even in invalid
    slots); masked/padded/bonded semantics live entirely in the ids and
    validity masks the caller computed, so they carry over unchanged.
    """
    n = values.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    vspec = P(None, axis_name, *([None] * (values.ndim - 2)))
    ispec = P(None, axis_name, None)
    ospec = P(None, axis_name, *([None] * (values.ndim - 1)))
    fn = shard_map(
        partial(_gather_local, axis_name=axis_name, overlap=overlap),
        mesh=mesh, in_specs=(vspec, ispec), out_specs=ospec)
    # 'exchange' scopes the rotation+select for xprof attribution
    # (observability.timing.MODEL_SCOPES)
    with jax.named_scope('exchange'):
        return fn(values, idx)


def rowwise_gather(values: jnp.ndarray, idx: jnp.ndarray, mesh: Mesh,
                   axis_name: str = 'sp') -> jnp.ndarray:
    """Column selection out of a query-row-sharded full-width operand:
    values [b, n, N, *f] (rows sharded over `axis_name`, column axis
    full — the layout of the ring branch's edge / adjacency-label
    tensors), idx [b, n, k] global COLUMN ids aligned with the rows.

    Every row's columns are locally resident, so this is zero-comm by
    construction; shard_map pins that, where leaving it to GSPMD's
    gather partitioner risks a full-operand materialization.
    """
    n = values.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    vspec = P(None, axis_name, *([None] * (values.ndim - 2)))
    ispec = P(None, axis_name, None)
    ospec = P(None, axis_name, *([None] * (values.ndim - 2)))
    fn = shard_map(lambda v, i: batched_index_select(v, i, axis=2),
                   mesh=mesh, in_specs=(vspec, ispec), out_specs=ospec)
    with jax.named_scope('exchange'):
        return fn(values, idx)


def _bonded_local(adj: jnp.ndarray, noise_n1: jnp.ndarray,
                  num_sparse: int, n: int, axis_name: str) -> jnp.ndarray:
    """Per-shard body: adj is this device's [b, nl, N] adjacency row
    block, noise_n1 its [b, nl, N-1] jitter rows (drawn in the dense
    path's self-excluded layout — the parity contract). Rebuilds the
    dense construction row-locally: scatter the noise to full width
    through the LOCAL rows' self-exclusion map, drop the diagonal, take
    the jittered per-row top-k."""
    from ..ops.neighbors import sparse_neighbor_mask

    b, nl, _ = adj.shape
    my_idx = jax.lax.axis_index(axis_name)
    gids = my_idx * nl + jnp.arange(nl, dtype=jnp.int32)
    # exclude_self_indices rows for the local block: global row g lists
    # source j + (j >= g), j in [0, N-1)
    j = jnp.arange(n - 1, dtype=jnp.int32)[None, :]
    self_excl = j + (j >= gids[:, None])
    noise_full = jnp.zeros((b, nl, n), noise_n1.dtype).at[
        :, jnp.arange(nl)[:, None], self_excl].set(noise_n1)
    not_self = gids[:, None] != jnp.arange(n)[None, :]
    adj_noself = adj.astype(bool) & not_self[None]
    return sparse_neighbor_mask(adj_noself, num_sparse, noise_full)


def bonded_priority_mask(adj_mat: jnp.ndarray, noise_n1: jnp.ndarray,
                         num_sparse: int, mesh: Mesh,
                         axis_name: str = 'sp') -> jnp.ndarray:
    """Row-sharded construction of the jittered bonded-priority mask
    (models _adjacency_predicates): adj_mat [b, N, N], noise_n1
    [b, N, N-1] (the dense layout's draw — same rng stream as the dense
    branch, so the jittered top-k picks identical bonded subsets),
    returns the [b, N, N] bool mask with rows sharded over `axis_name`.

    The dense formulation's noise scatter + per-row top-k are row-
    parallel by construction, but GSPMD's scatter partitioner serves
    them with a full-width [b, N, N] all-gather (measured — the exact
    artifact class `make ring-smoke` gates). shard_map pins every step
    to the local row block: zero collectives, exact parity (the ring
    sparse-adjacency tests compare the full model against the dense
    branch)."""
    n = adj_mat.shape[1]
    sp = mesh.shape[axis_name]
    assert n % sp == 0, f'n={n} must divide over {axis_name}={sp}'
    row = P(None, axis_name, None)
    fn = shard_map(
        partial(_bonded_local, num_sparse=num_sparse, n=n,
                axis_name=axis_name),
        mesh=mesh, in_specs=(row, row), out_specs=row)
    with jax.named_scope('exchange'):
        return fn(adj_mat, noise_n1)


# ------------------------------------------------------------------------- #
# trunk routing: trace-time exchange scope
# ------------------------------------------------------------------------- #
class _ExchangeScope(NamedTuple):
    mesh: Mesh
    axis_name: str
    overlap: bool


_SCOPES: list = []   # trace-time stack (same discipline as jax context
#                      managers: tracing is single-threaded per program)


@contextlib.contextmanager
def exchange_scope(mesh: Mesh, axis_name: str = 'sp',
                   overlap: bool = True):
    """While active, `exchange_index_select` routes node-axis neighbor
    gathers through `neighbor_gather(mesh, axis_name)`. Entered by the
    model's ring branch around the trunk so ConvSE3/attention/EGNN need
    no signature change; a no-op for every other caller."""
    _SCOPES.append(_ExchangeScope(mesh, axis_name, overlap))
    try:
        yield
    finally:
        _SCOPES.pop()


def active_exchange() -> Optional[_ExchangeScope]:
    return _SCOPES[-1] if _SCOPES else None


def exchange_index_select(values: jnp.ndarray, indices: jnp.ndarray,
                          axis: int = 1) -> jnp.ndarray:
    """`batched_index_select` that becomes neighbor-sparse under an
    active exchange scope. Falls back to the dense gather whenever the
    operand doesn't fit the exchange layout (non-node axis, node count
    not divisible over the mesh axis, non-[b, n, k] indices)."""
    scope = active_exchange()
    if scope is None or axis != 1 or indices.ndim != 3 \
            or values.ndim < 2 \
            or values.shape[:1] != indices.shape[:1] \
            or values.shape[1] % scope.mesh.shape[scope.axis_name] != 0 \
            or values.shape[1] != indices.shape[1]:
        return batched_index_select(values, indices, axis=axis)
    return neighbor_gather(values, indices, scope.mesh,
                           axis_name=scope.axis_name,
                           overlap=scope.overlap)


# ------------------------------------------------------------------------- #
# comm accounting from traced HLO
# ------------------------------------------------------------------------- #
_DTYPE_BYTES = dict(pred=1, s8=1, u8=1, s16=2, u16=2, bf16=2, f16=2,
                    s32=4, u32=4, f32=4, s64=8, u64=8, f64=8, c64=8,
                    c128=16)

# collective classes as they appear in post-SPMD HLO text. Sync ops
# carry a plain result shape; async pairs appear as <op>-start/-done
# where the -start result is a TUPLE — e.g. on TPU
#   %ags = (f32[1,256,3], f32[1,2048,3]) all-gather-start(...)
# (operand alias first, transferred result after, sometimes trailing
# u32[] context scalars). The shape field therefore matches EITHER a
# single shape token or a whole parenthesized tuple; the -start side is
# counted once and -done is skipped.
_COLLECTIVE_RE = re.compile(
    r'=\s*(?P<shapes>\([^()]*\)|\S+)\s+'
    r'(?P<cls>all-gather|all-reduce|collective-permute|all-to-all|'
    r'reduce-scatter)'
    r'(?P<phase>-start|-done)?\(')
_SHAPE_RE = re.compile(r'(\w+)\[([\d,]*)\]')
_GATHER_DIM_RE = re.compile(r'dimensions=\{(\d+)\}')


def analyze_hlo_comm(hlo_text: str,
                     full_width_dim: Optional[int] = None,
                     mesh_shape: Optional[dict] = None,
                     seq_axis: str = 'sp') -> dict:
    """Parse compiled (post-partitioning) HLO text into per-class
    collective counts and estimated byte volumes.

    full_width_dim: the GLOBAL node count N. An all-gather is flagged as
    full-width when its output carries the whole node axis — gather
    dimension >= 1 (node-sharded operands here are [b, n, ...] /
    [b, n, N, ...]; axis 0 is batch) with output size >= N at that
    dimension. Keying on the op's `dimensions={...}` attribute rather
    than any-dim-matches keeps replicated-parameter all-gathers (axis-0
    gathers whose sizes are unrelated to N) out of the proof bit
    `make ring-smoke` gates on. Byte estimates are shape upper bounds of
    each op's transferred result, per execution of the op's computation
    (loop trip counts are invisible in HLO text — stated as per-class
    *shape* bytes, not per-step traffic).

    mesh_shape (ordered {axis: size}, see `attribute_collective_axes`):
    makes the full-width scan AXIS-AWARE for composed meshes. The node
    axis is sharded over `seq_axis` only, so a >= N output dimension
    can only be materialized by gathering across the seq-axis device
    groups — an all-gather whose replica groups hold the seq coordinate
    fixed (a dp weight prefetch, a tp channel gather) cannot
    rematerialize the sequence even when an unrelated channel dim
    happens to reach N (heads*dim_head collides with toy node counts).
    A flagged line with no group attribute spans every device and stays
    counted; with seq_axis at size 1 nothing shards the sequence and no
    grouped gather is flagged.
    """
    seq_varies = None
    if mesh_shape is not None:
        axis_names = list(mesh_shape)
        sizes = [int(mesh_shape[a]) for a in axis_names]
        seq_idx = axis_names.index(seq_axis) if seq_axis in axis_names \
            else None

        def seq_varies(line):
            groups = _collective_groups(line)
            if groups is None:
                return True  # spans every device, incl. the seq axis
            if seq_idx is None:
                return False
            for grp in groups:
                base = _device_coords(grp[0], sizes)[seq_idx]
                for member in grp[1:]:
                    if _device_coords(member, sizes)[seq_idx] != base:
                        return True
            return False

    classes: dict = {}
    full_width_hits = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or m.group('phase') == '-done':
            continue
        cls = m.group('cls')
        shapes = []
        for dtype, dims_s in _SHAPE_RE.findall(m.group('shapes')):
            dims = [int(d) for d in dims_s.split(',') if d]
            size = _DTYPE_BYTES.get(dtype, 4)
            for d in dims:
                size *= d
            shapes.append((size, dtype, dims_s, dims))
        if not shapes:
            continue
        # async -start tuples: the transferred payload is the largest
        # element (the operand alias is 1/axis_size of it, the context
        # scalars are bytes); for sync ops there is exactly one
        size, dtype, dims_s, dims = max(shapes, key=lambda s: s[0])
        entry = classes.setdefault(cls, dict(count=0, bytes=0))
        entry['count'] += 1
        entry['bytes'] += size
        if cls == 'all-gather' and full_width_dim is not None:
            gd = _GATHER_DIM_RE.search(line)
            if gd is not None:
                axis = int(gd.group(1))
                full = axis >= 1 and axis < len(dims) \
                    and dims[axis] >= full_width_dim
            else:  # no dimensions attribute — conservative any-dim scan
                full = any(d >= full_width_dim for d in dims[1:])
            if full and seq_varies is not None and not seq_varies(line):
                full = False
            if full:
                full_width_hits.append(f'{dtype}[{dims_s}]')
    return dict(
        collectives=classes,
        full_width_all_gathers=full_width_hits,
        all_gather_free=not full_width_hits,
    )


# per-axis attribution: map each collective's replica groups back onto
# mesh axes. Post-SPMD HLO names groups either explicitly
# (replica_groups={{0,1},{2,3}}), in the iota form
# (replica_groups=[4,2]<=[8] or [4,2]<=[2,4]T(1,0)), or — for
# collective-permute — as source_target_pairs={{0,2},{2,0}}.
_EXPLICIT_GROUPS_RE = re.compile(
    r'replica_groups=\{(\{[^{}]*\}(?:,\{[^{}]*\})*)\}')
_IOTA_GROUPS_RE = re.compile(
    r'replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]'
    r'(?:T\(([\d,]+)\))?')
_PAIRS_RE = re.compile(
    r'source_target_pairs=\{(\{[^{}]*\}(?:,\{[^{}]*\})*)\}')


def _collective_groups(line: str) -> Optional[list]:
    """Device-id groups of one HLO collective line (each a list of
    ints), or None when the line carries no group attribute."""
    m = _EXPLICIT_GROUPS_RE.search(line) or _PAIRS_RE.search(line)
    if m is not None:
        return [[int(x) for x in grp.split(',') if x]
                for grp in m.group(1)[1:-1].split('},{')]
    m = _IOTA_GROUPS_RE.search(line)
    if m is not None:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(',')]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(p) for p in m.group(4).split(',')])
        return ids.reshape(n_groups, group_size).tolist()
    return None


def _device_coords(device_id: int, sizes) -> tuple:
    coords = []
    for size in reversed(sizes):
        coords.append(device_id % size)
        device_id //= size
    return tuple(reversed(coords))


def attribute_collective_axes(hlo_text: str, mesh_shape: dict) -> dict:
    """Per-mesh-axis collective {count, bytes} from partitioned HLO.

    mesh_shape: ordered {axis: size} as `parallel.mesh.mesh_shape_dict`
    returns it — device id = row-major index into that shape, which
    holds for `make_mesh` over the default device order (the CPU-sim
    meshes every sweep/test here runs on; a permuted physical mesh
    would need the id->coords map threaded through instead).

    Each collective op is classified by the mesh coordinates its
    replica groups (or ppermute source/target pairs) vary over: a group
    whose members differ only in the tp coordinate is tp traffic, the
    gradient psum over dp and sp lands under 'dp+sp', and an op whose
    groups never leave one device (or a mesh axis of size 1) counts as
    'local'. Byte values are the same per-op transferred-shape upper
    bounds `analyze_hlo_comm` reports, so the per-axis split sums to
    (at most) its per-class totals. Ops with no group attribute span
    every device and land on the joint label of all size>1 axes."""
    axis_names = list(mesh_shape)
    sizes = [int(mesh_shape[a]) for a in axis_names]
    wide = [a for a, s in zip(axis_names, sizes) if s > 1]
    out: dict = {}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or m.group('phase') == '-done':
            continue
        shapes = []
        for dtype, dims_s in _SHAPE_RE.findall(m.group('shapes')):
            dims = [int(d) for d in dims_s.split(',') if d]
            size = _DTYPE_BYTES.get(dtype, 4)
            for d in dims:
                size *= d
            shapes.append(size)
        if not shapes:
            continue
        nbytes = max(shapes)
        groups = _collective_groups(line)
        if groups is None:
            label = '+'.join(wide) if wide else 'local'
        else:
            varying = set()
            for grp in groups:
                base = _device_coords(grp[0], sizes)
                for member in grp[1:]:
                    for name, a, b in zip(axis_names, base,
                                          _device_coords(member, sizes)):
                        if a != b:
                            varying.add(name)
            label = '+'.join(a for a in axis_names if a in varying) \
                or 'local'
        entry = out.setdefault(label, {}).setdefault(
            m.group('cls'), dict(count=0, bytes=0))
        entry['count'] += 1
        entry['bytes'] += nbytes
    return out


def comm_payload(hlo_text: str, *, sp: int, ring_steps: int,
                 overlap: bool, exchange: bool,
                 full_width_dim: Optional[int] = None,
                 mesh_shape: Optional[dict] = None) -> dict:
    """The schema'd `comm` record body (observability.schema kind='comm',
    minus run_id): ring configuration + the HLO-derived collective
    accounting. Attachable verbatim to bench records and flush payloads.
    With `mesh_shape` (an ordered {axis: size} dict, see
    `attribute_collective_axes`) the payload additionally carries
    `axis_collectives` — the per-mesh-axis split the composed-mesh
    budgets gate on — and the full-width all-gather scan becomes
    axis-aware (only sp-varying gathers can rematerialize the
    sequence; see `analyze_hlo_comm`)."""
    payload = dict(sp=sp, ring_steps=ring_steps, overlap=overlap,
                   exchange=exchange)
    payload.update(analyze_hlo_comm(hlo_text, full_width_dim=full_width_dim,
                                    mesh_shape=mesh_shape))
    if mesh_shape is not None:
        payload['axis_collectives'] = attribute_collective_axes(
            hlo_text, mesh_shape)
        payload['mesh'] = dict(mesh_shape)
    return payload
