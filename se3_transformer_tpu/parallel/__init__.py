from .mesh import make_mesh, shard_batch, data_specs, MESH_AXES
from . import distributed
from .ring import ring_knn, dense_knn
from .exchange import (
    analyze_hlo_comm, bonded_priority_mask, comm_payload,
    exchange_index_select, exchange_scope, neighbor_gather, rowwise_gather,
)
from .rules import (
    RULE_SETS, fsdp_rules, match_partition_rules,
    opt_state_partition_specs, place_with_rules,
    replicated_rules, resolve_rules, shard_opt_state, tp_rules,
)
from .sharding import (
    make_sharded_train_step, make_accumulating_train_step, replicated,
    param_partition_specs, shard_params,
)
