from .mesh import (
    make_mesh, mesh_points, mesh_shape_dict, shard_batch, data_specs,
    MESH_AXES,
)
from . import distributed
from .ring import ring_knn, dense_knn
from .exchange import (
    analyze_hlo_comm, attribute_collective_axes, bonded_priority_mask,
    comm_payload, exchange_index_select, exchange_scope, neighbor_gather,
    rowwise_gather,
)
from .rules import (
    RULE_SETS, composed_rules, fsdp_rules, match_partition_rules,
    opt_state_partition_specs, place_with_rules,
    replicated_rules, resolve_rules, shard_opt_state, tp_rules,
)
from .sharding import (
    make_sharded_train_step, make_accumulating_train_step, replicated,
    composed_state_shardings, param_partition_specs, shard_params,
)
