"""Multi-host initialization and cross-slice mesh construction.

The reference has no distributed story (SURVEY.md §2.9). On TPU pods the
runtime is jax.distributed + GSPMD collectives: ICI within a slice, DCN
across slices. This module is the thin, idiomatic entry:

    from se3_transformer_tpu.parallel import distributed
    distributed.initialize()            # no-op on a single host
    mesh = distributed.pod_mesh(dp=..., sp=..., tp=...)

`pod_mesh` orders devices so the sp/tp axes map onto ICI neighbors
(`jax.experimental.mesh_utils.create_device_mesh`); with multiple slices
it uses `create_hybrid_device_mesh` so dp rides DCN (the
bandwidth-tolerant axis) and sp/tp stay on ICI.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from .mesh import MESH_AXES, make_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """jax.distributed.initialize with env fallbacks; returns True if a
    multi-process runtime was initialized (no-op for single host)."""
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get('SE3_TPU_NUM_PROCESSES', '1'))
    if num_processes <= 1 and coordinator_address is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return True


def shard_host_local_batch(batch: dict, mesh) -> dict:
    """Multi-host analogue of mesh.shard_batch: each process passes its
    OWN slice of the global batch (what its local data loader produced)
    and gets back global jax.Arrays laid out by the canonical
    mesh.data_specs. On a single host this equals shard_batch (minus the
    replication fallback — multi-host data must divide the mesh axes,
    anything else silently duplicates examples across hosts).

    The reference has no multi-process input pipeline at all (its loader
    feeds one cuda device, denoise.py:57-61); this is the TPU-pod
    equivalent: per-host loaders + jax.make_array_from_process_local_data
    assembling the logical global batch.

    Unlike shard_batch there is deliberately NO replication fallback for
    non-dividing axes (that would duplicate examples across hosts, not
    just waste devices) — such batches raise with an actionable error.
    Single-host callers who want graceful degradation should use
    shard_batch.
    """
    from jax.sharding import NamedSharding
    from .mesh import resolve_data_spec

    single = jax.process_count() == 1
    out = {}
    for k, v in batch.items():
        spec = resolve_data_spec(k, v.ndim)
        if single:
            # exact pre-check only when local == global; multi-process
            # global-shape assembly is validated by
            # make_array_from_process_local_data itself (the per-axis
            # process placement is not knowable from the local view)
            for d, axis in enumerate(spec):
                size = mesh.shape[axis] if isinstance(axis, str) else 1
                if v.shape[d] % size != 0:
                    raise ValueError(
                        f"shard_host_local_batch: '{k}' dim {d} (size "
                        f"{v.shape[d]}) does not divide mesh axis "
                        f"'{axis}' (size {size}); pad the batch to a "
                        f"multiple or use mesh.shard_batch")
        sharding = NamedSharding(mesh, spec)
        out[k] = jax.make_array_from_process_local_data(sharding, v)
    return out


def pod_mesh(dp: Optional[int] = None, sp: Optional[int] = None,
             tp: Optional[int] = None):
    """Mesh over all global devices with ICI-friendly ordering.

    Uses mesh_utils.create_device_mesh (hybrid variant across slices, so
    dp rides DCN); falls back to the plain reshape mesh when the physical
    topology is unknown (CPU simulation)."""
    devices = jax.devices()
    base = make_mesh(devices, dp=dp, sp=sp, tp=tp)  # resolves axis sizes
    dims = [base.shape[a] for a in MESH_AXES]
    slice_ids = sorted({getattr(d, 'slice_index', 0) for d in devices})
    n_slices = len(slice_ids)
    from jax.experimental import mesh_utils
    try:
        if n_slices > 1 and dims[0] % n_slices == 0:
            arr = mesh_utils.create_hybrid_device_mesh(
                [dims[0] // n_slices, dims[1], dims[2]],
                dcn_mesh_shape=[n_slices, 1, 1], devices=devices)
        else:
            arr = mesh_utils.create_device_mesh(dims, devices=devices)
        return jax.sharding.Mesh(arr, MESH_AXES)
    except (ValueError, AssertionError, NotImplementedError):
        # unknown/irregular topology (e.g. simulated CPU devices)
        return base
