"""Declarative partition rules: ordered regex-on-param-path -> PartitionSpec.

ROADMAP item 3's load-bearing refactor: ONE source of sharding truth that
training (`parallel.sharding`) and serving (`inference.engine`,
`serving.*`) both consult, replacing the ad-hoc per-site spec function
that lived inside `param_partition_specs`. The shape follows the serving
sharding maps in SNIPPETS.md [2] and [3]: an ordered list of
``(regex, PartitionSpec[, ndim])`` rules matched against the
``'/'``-joined flax parameter path, **first match wins**, with a LOUD
audit for leaves no rule covers — a silently-replicated tensor is the
classic way "sharded serving" degrades into every chip doing the same
work.

Four built-in rule sets over the existing ``('dp', 'sp', 'tp')`` mesh
axes (`RULE_SETS`):

  * ``replicated`` — everything P() (the PR 2 serving default);
  * ``tp``         — the Megatron column/row pattern the old
                     `param_partition_specs` hand-coded: radial final
                     weights/biases shard their output-channel axis,
                     attention/FF in-projections column-shard the head
                     axis, out-projections row-shard the input axis
                     (one psum per block);
  * ``fsdp``       — every non-scalar shards dim 0 over the dp axis
                     (parameter memory / replica-count lever; optimizer
                     state inherits the same specs for true FSDP);
  * ``composed``   — tp's Megatron placements verbatim, with the
                     REMAINDER (norms, embeddings, gates) sharded dim-0
                     over dp fsdp-style (the params/opt-state layout of
                     the one dp x sp x tp mesh, ROADMAP item 4; dp
                     stays off Megatron contraction dims — see
                     `composed_rules`).

`match_partition_rules(rules, params, mesh=...)` additionally audits
each matched spec against the leaf shape and the mesh: a spec whose
rank-guard fails falls through to the NEXT rule (so ``w3`` with an
unexpected rank ends at the catch-all, exactly like the old per-site
``ndim`` checks); a sharded dimension that does not divide its mesh
axis — or a mesh axis of size 1 — demotes to replication for that
dimension, collected into one summary warning. The result is pure and
inspectable: a pytree of PartitionSpec, no placement side effects.
"""
from __future__ import annotations

import re
import warnings
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (pattern, spec) or (pattern, spec, required_ndim)
Rule = Union[Tuple[str, P], Tuple[str, P, int]]
Rules = Sequence[Rule]

ON_UNMATCHED = ('error', 'warn', 'replicate')

# Megatron-style column/row families over the flax param tree (the
# comment block that documented these in parallel/sharding.py now lives
# as data): column-parallel = output (head/hidden) axis sharded,
# row-parallel = input axis sharded so the contraction psums over ICI.
_COLUMN_PARALLEL = ('to_q', 'to_self_k', 'to_self_v', 'to_global_k',
                    'to_global_v', 'to_k', 'project_in', 'self_interact')
_ROW_PARALLEL = ('to_out', 'project_out')


def path_of(key_path) -> str:
    """'/'-joined string form of a tree_map_with_path key path."""
    parts = []
    for k in key_path:
        parts.append(str(getattr(k, 'key', getattr(k, 'name', k))))
    return '/'.join(parts)


# --------------------------------------------------------------------- #
# built-in rule sets
# --------------------------------------------------------------------- #
def replicated_rules(axis: Optional[str] = None) -> Rules:
    """Everything replicated (the single-chip / PR 2 serving layout)."""
    return ((r'.*', P()),)


def tp_rules(axis: str = 'tp') -> Rules:
    """Tensor parallelism over `axis` — the rule-set form of the old
    ad-hoc `param_partition_specs` body. Rank guards reproduce its
    exact ndim checks: a name-match with the wrong rank falls through
    to the catch-all replication rule.

    Quantized trees (quant.QuantTensor pytree nodes) descend one level
    deeper: the weight's `q` storage and `scale` leaves surface as
    '<weight>/q' and '<weight>/scale' paths. `q` keeps the fp32
    weight's shape and shards exactly like it; `scale` keeps the
    contracted axis as size 1 (per-output-channel layout), so it
    shards with the OUTPUT axis wherever the weight's output axis is
    sharded (column-parallel) and replicates under row-parallel specs
    (the per-output epilogue runs after the psum on the full output
    axis). Before these rules, quantized params silently fell through
    to the catch-all and REPLICATED under tp-sharded serving — the
    ROADMAP item 3 residue."""
    col = '|'.join(_COLUMN_PARALLEL)
    row = '|'.join(_ROW_PARALLEL)
    return (
        # radial final weight [mid, c_in*F, c_out] — both the per-pair
        # 'w3'/'b3' (PairwiseConvSE3) and the shared-trunk group layout
        # 'w3_{d_in}_{d_out}' (ConvSE3): shard the OUTPUT channel axis.
        # Quantized: q [mid, IF, O] int8 + scale [1, IF, O] both carry
        # the same rank and a divisible output axis
        (r'(^|/)w3(_\d+_\d+)?(/(?:q|scale))?$', P(None, None, axis), 3),
        (r'(^|/)b3(_\d+_\d+)?$', P(None, axis), 2),
        # v2 per-m radial blocks 'wm{m}_{d_in}_{d_out}' [mid, K, O] and
        # their biases (v2/conv.py): same layout family as w3/b3 — the
        # output-channel axis shards, quantized q/scale descend alike
        (r'(^|/)wm\d+_\d+_\d+(/(?:q|scale))?$', P(None, None, axis), 3),
        (r'(^|/)bm\d+_\d+_\d+$', P(None, axis), 2),
        # attention/FF in-projections: column-shard the output axis
        # (= heads * dim_head, i.e. head sharding); scale [1, out]
        # shards its output axis right along
        (rf'(^|/)(?:{col})/w\d+(/(?:q|scale))?$', P(None, axis), 2),
        # out-projections: row-shard the INPUT axis — the classic
        # column->row pair with one psum per block. The quantized q
        # row-shards like the weight; the per-OUTPUT scale replicates
        # (its epilogue multiplies the full post-psum output, and its
        # size-1 input dim would only demote noisily)
        (rf'(^|/)(?:{row})/w\d+(/q)?$', P(axis, None), 2),
        (rf'(^|/)(?:{row})/w\d+/scale$', P(), 2),
        # everything else (norms, embeddings, gates) is tiny: replicate
        (r'.*', P()),
    )


def fsdp_rules(axis: str = 'dp') -> Rules:
    """Fully-sharded parameters: every non-scalar leaf shards dim 0
    over `axis` (indivisible dims demote to replication under the mesh
    audit). Applied to optimizer state too, this is true FSDP — the
    ROADMAP item 5 extension rides the same rule set. Quantized
    `scale` leaves (size-1 contracted dim 0, a few KB) replicate
    explicitly instead of demoting with a warning on every placement;
    the int8 `q` storage falls through to the catch-all and shards
    dim 0 like the fp32 weight it replaced. The scale rule is anchored
    to the quantizable weight names (w<d> / w3_i_o / Dense kernel) so
    flax's LayerNorm `scale` param keeps its plain dim-0 treatment."""
    return (
        # wm\d+_\d+_\d+ covers the v2 per-m radial blocks (v2/conv.py)
        (r'(^|/)(?:w\d+(?:_\d+_\d+)?|wm\d+_\d+_\d+|kernel)/scale$',
         P()),
        (r'.*', P(axis)),
    )


def composed_rules(axis: str = 'tp', dp_axis: str = 'dp') -> Rules:
    """TP + dp-sharded-remainder composition for the one dp x sp x tp
    mesh (ROADMAP item 4): every Megatron-family leaf keeps exactly its
    `tp_rules` placement, and the REMAINDER (norms, embeddings, gates —
    everything tp leaves replicated) shards dim 0 over dp, fsdp-style.

    dp deliberately does NOT touch the Megatron weights' contraction
    dims. Sharding a contraction dim of a matmul whose other operand is
    sequence-sharded (column-parallel [in, out] with `in` over dp while
    activations ride P(dp, sp, None)) makes GSPMD rematerialize the
    FULL sequence — an sp-group all-gather of the [b, n, ...]
    activation per projection — which both breaks the all-gather-free
    contract and dwarfs any memory saved on the weight. The composed
    layout therefore is:

      * radial final weights w3 / w3_{i}_{o} / wm{m}_{i}_{o}
        [mid, IF, O]: P(None, None, tp); quantized `q` rides along,
        `scale` [1, IF, O] matches (its size-1 mid dim would demote
        noisily under any dp placement anyway).
      * radial biases b3/bm [IF, O]: P(None, tp).
      * column-parallel projections [in, out]: P(None, tp); their
        per-output scales [1, out] likewise.
      * row-parallel out-projections [in, out]: P(tp, None); the
        per-output scale stays replicated (its epilogue runs on the
        full post-psum output).
      * everything else: fsdp-style dim-0 over dp, with the
        quantized-scale guard from `fsdp_rules`. Dim-0 weight gathers
        are prefetched parameter traffic, not sequence traffic — the
        full-width scan in `exchange.analyze_hlo_comm` ignores dim 0
        by construction.

    Indivisible dims demote per-dimension under the mesh audit exactly
    as in the single-axis sets — a (2,2,2) toy mesh with odd channel
    counts degrades loudly, never silently. Like tp_rules/fsdp_rules
    this is pure spec data; the explicit-aliasing step wiring that
    makes the composed mesh actually RUN on jax 0.4.37 lives in
    `parallel.sharding.composed_state_shardings`."""
    col = '|'.join(_COLUMN_PARALLEL)
    row = '|'.join(_ROW_PARALLEL)
    return (
        (r'(^|/)(?:w3(_\d+_\d+)?|wm\d+_\d+_\d+)/scale$',
         P(None, None, axis), 3),
        (r'(^|/)w3(_\d+_\d+)?(/q)?$', P(None, None, axis), 3),
        (r'(^|/)b3(_\d+_\d+)?$', P(None, axis), 2),
        (r'(^|/)wm\d+_\d+_\d+(/q)?$', P(None, None, axis), 3),
        (r'(^|/)bm\d+_\d+_\d+$', P(None, axis), 2),
        (rf'(^|/)(?:{col})/w\d+/scale$', P(None, axis), 2),
        (rf'(^|/)(?:{col})/w\d+(/q)?$', P(None, axis), 2),
        (rf'(^|/)(?:{row})/w\d+(/q)?$', P(axis, None), 2),
        (rf'(^|/)(?:{row})/w\d+/scale$', P(), 2),
        # remainder: fsdp dim-0 over dp (same scale guard as fsdp_rules)
        (r'(^|/)(?:w\d+(?:_\d+_\d+)?|wm\d+_\d+_\d+|kernel)/scale$',
         P()),
        (r'.*', P(dp_axis)),
    )


RULE_SETS = dict(replicated=replicated_rules, tp=tp_rules,
                 fsdp=fsdp_rules, composed=composed_rules)


def resolve_rules(rules: Union[str, Rules],
                  axis: Optional[str] = None) -> Rules:
    """A rule set by name ('replicated' | 'tp' | 'fsdp') or an explicit
    rule list, normalized to a tuple of rules. `axis` overrides a named
    set's default mesh axis; combining it with an explicit rule list is
    an error (the list already names its axes) — never a silent drop."""
    if isinstance(rules, str):
        if rules not in RULE_SETS:
            raise KeyError(f'unknown rule set {rules!r} '
                           f'(built-ins: {sorted(RULE_SETS)})')
        factory = RULE_SETS[rules]
        return factory(axis) if axis is not None else factory()
    if axis is not None:
        raise ValueError('axis= only applies to a NAMED rule set; an '
                         'explicit rule list already names its axes')
    return tuple(rules)


# --------------------------------------------------------------------- #
# the matcher
# --------------------------------------------------------------------- #
def match_partition_rules(rules: Union[str, Rules], params,
                          mesh: Optional[Mesh] = None,
                          on_unmatched: str = 'error'):
    """PartitionSpec pytree for `params` under first-match-wins rules.

    * Scalar leaves (rank 0 or a single element) are never worth a
      collective: they get P() without consuming a rule.
    * A rule with a rank guard only matches leaves of that rank;
      otherwise scanning continues with the next rule.
    * `on_unmatched` ('error' by default — the audit is LOUD): a leaf
      no rule matches raises, listing the offending paths; 'warn'
      replicates with one summary warning; 'replicate' is the silent
      opt-out for throwaway trees.
    * With `mesh`, matched specs are audited against leaf shapes: a
      sharded dimension that does not divide its mesh axis demotes to
      replication for that dimension (one summary warning names every
      demotion); axes of size 1 are dropped silently — sharding over a
      size-1 axis is replication wearing a costume, and dropping it
      keeps tp=1 configs bit-identical to the replicated path. An axis
      name the mesh does not carry is a configuration error and raises.
    """
    if on_unmatched not in ON_UNMATCHED:
        raise ValueError(f'on_unmatched={on_unmatched!r} not in '
                         f'{ON_UNMATCHED}')
    compiled = []
    for rule in resolve_rules(rules):
        pat, spec = rule[0], rule[1]
        ndim = rule[2] if len(rule) > 2 else None
        compiled.append((re.compile(pat), spec, ndim))
    unmatched, demoted = [], []
    axis_sizes = (dict(zip(mesh.axis_names, mesh.devices.shape))
                  if mesh is not None else None)

    def audit(name, spec, shape):
        if axis_sizes is None:
            return spec
        if len(spec) > len(shape):
            demoted.append(f'{name}: spec {spec} exceeds rank '
                           f'{len(shape)}')
            return P()
        fixed = []
        for d, ax in enumerate(spec):
            if ax is None:
                fixed.append(None)
                continue
            axes = (ax,) if isinstance(ax, str) else tuple(ax)
            missing = [a for a in axes if a not in axis_sizes]
            if missing:
                raise ValueError(
                    f'partition rule for {name!r} names mesh axis '
                    f'{missing} but the mesh only carries '
                    f'{sorted(axis_sizes)}')
            size = int(np.prod([axis_sizes[a] for a in axes]))
            if size == 1:
                fixed.append(None)           # size-1 axis: drop quietly
            elif shape[d] % size:
                demoted.append(f'{name}: dim {d} (size {shape[d]}) does '
                               f'not divide {"*".join(axes)} ({size})')
                fixed.append(None)
            else:
                fixed.append(ax)
        return P(*fixed)

    def assign(key_path, leaf):
        name = path_of(key_path)
        shape = tuple(getattr(leaf, 'shape', ()) or ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pat, spec, ndim in compiled:
            if ndim is not None and len(shape) != ndim:
                continue
            if pat.search(name):
                return audit(name, spec, shape)
        unmatched.append(name)
        return P()

    specs = jax.tree_util.tree_map_with_path(assign, params)
    if unmatched:
        msg = (f'{len(unmatched)} parameter leaves matched NO partition '
               f'rule (e.g. {unmatched[:5]}); end the rule list with '
               f"('.*', P()) to replicate the remainder explicitly")
        if on_unmatched == 'error':
            raise ValueError(msg)
        if on_unmatched == 'warn':
            warnings.warn(msg, stacklevel=2)
    if demoted:
        shown = '; '.join(demoted[:8])
        more = f' (+{len(demoted) - 8} more)' if len(demoted) > 8 else ''
        warnings.warn(f'partition rules demoted {len(demoted)} '
                      f'dimension(s) to replication: {shown}{more}',
                      stacklevel=2)
    return specs


def opt_state_partition_specs(rules: Union[str, Rules], params, opt_state,
                              mesh: Optional[Mesh] = None,
                              on_unmatched: str = 'error'):
    """PartitionSpec pytree for an OPTIMIZER state under the same rules
    that shard the params (the ROADMAP item 5 'true FSDP' first step:
    `fsdp` rules previously applied to params only, leaving adam's
    mu/nu — 2x the parameter memory — replicated on every chip).

    Optimizer states mirror the param tree inside wrapper containers
    (optax's ScaleByAdamState.mu/nu are param-structured pytrees, the
    chain adds tuple indices), so each state leaf's path looks like
    '0/mu/<param path>'. Resolution per leaf:

      * a param whose path is a SUFFIX of the state leaf's path AND
        whose shape matches inherits that param's AUDITED spec —
        mu/nu shard exactly like their parameter, mesh demotions
        included, so gather/update math stays elementwise-local;
      * scalar leaves (adam's `count`, schedule states) replicate;
      * anything else (a state leaf with no param twin, e.g.
        factored-second-moment slices) falls back to matching `rules`
        against its own path — same audit, same `on_unmatched`
        contract as match_partition_rules.
    """
    param_specs = match_partition_rules(rules, params, mesh=mesh,
                                        on_unmatched=on_unmatched)
    # flatten side by side (identical treedefs; PartitionSpec is a
    # tuple subclass, so the spec tree needs the explicit is_leaf)
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_specs = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    by_path = {
        path_of(kp): (tuple(getattr(leaf, 'shape', ()) or ()), spec)
        for (kp, leaf), spec in zip(flat_params, flat_specs)}

    def assign(key_path, leaf):
        shape = tuple(getattr(leaf, 'shape', ()) or ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        parts = path_of(key_path).split('/')
        for i in range(len(parts)):
            hit = by_path.get('/'.join(parts[i:]))
            if hit is not None and hit[0] == shape:
                return hit[1]
        # no param twin: match the rules against the leaf's OWN path —
        # rebuilt as a nested singleton tree so name-anchored rules
        # (e.g. tp's `(^|/)w3...`) see the same '/'-joined path they
        # would on a param, not the empty string a bare leaf yields
        tree = leaf
        for part in reversed(parts):
            tree = {part: tree}
        spec_tree = match_partition_rules(rules, tree, mesh=mesh,
                                          on_unmatched=on_unmatched)
        return jax.tree_util.tree_leaves(
            spec_tree, is_leaf=lambda x: isinstance(x, P))[0]

    return jax.tree_util.tree_map_with_path(assign, opt_state)


def shard_opt_state(opt_state, params, mesh: Mesh,
                    rules: Union[str, Rules] = 'fsdp',
                    axis: Optional[str] = None,
                    on_unmatched: str = 'error'):
    """Place an optimizer state on the mesh under the params' rule set
    (default: the fsdp set — dim-0 sharding over dp). Returns
    (placed_opt_state, specs)."""
    specs = opt_state_partition_specs(resolve_rules(rules, axis), params,
                                      opt_state, mesh=mesh,
                                      on_unmatched=on_unmatched)
    placed = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        opt_state, specs)
    return placed, specs


def place_with_rules(params, mesh: Mesh, rules: Union[str, Rules],
                     on_unmatched: str = 'error'):
    """Match rules, then device_put every leaf into its NamedSharding.
    Returns (placed_params, specs) — the specs ride along so callers
    (e.g. the AOT engine) can build sharded abstract values without
    re-matching."""
    specs = match_partition_rules(rules, params, mesh=mesh,
                                  on_unmatched=on_unmatched)
    placed = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, specs)
    return placed, specs
