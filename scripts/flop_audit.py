"""Audit the flagship step's TRUE FLOP count (VERDICT r3 weak #1 and #6).

The official records' step_tflops/mfu_bf16_peak came from XLA
cost_analysis of the TPU program — which cannot see inside Pallas
custom kernels, where the radial matmuls (the dominant FLOPs) run, AND
counts a lax.map (edge_chunks) body once instead of trip-count times.
This script compiles the SAME training step with pallas=False on CPU
and prints its cost analysis, plus an analytic per-component model
(se3_transformer_tpu.utils.flops) for cross-checking. Run with
--edge-chunks 0 for the clean audit (no lax.map: every FLOP visible).

Measured (dim=64 flagship, n=1024, k=32): analytic 83.2 TFLOP/step;
XLA-visible with edge_chunks=8: 12.16 (map bodies once); TPU Pallas
path records: 2.05 (kernels invisible too). bench.py now records the
analytic number alongside the XLA-visible one.

Usage: python scripts/flop_audit.py [--dim 64] [--nodes 1024] [--k 32]
       [--compile] [--edge-chunks 0]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analytic_model(dim, depth, num_degrees, n, k, heads, dim_head,
                   mid=128):  # trunk width; bias un-folded in round 4
    """Forward-pass FLOPs (multiply+add = 2) of the flagship's dominant
    terms. Per edge-conv over fibers (c per degree), the radial weight
    application h[mid] @ w3[mid, c_in*F, c_out] dominates:
    2*mid*sum_pairs(c_in*F*c_out) per edge."""
    E = n * k
    c = dim
    sumF = sum(2 * min(di, do) + 1
               for di in range(num_degrees) for do in range(num_degrees))
    # radial apply per full hidden->hidden conv (all pairs)
    radial_per_conv = 2 * E * mid * sumF * c * c
    # v2 basis contraction: sum_pairs P*Q*F*c per edge (tiny next to radial)
    sumPQF = sum((2 * do + 1) * (2 * di + 1) * (2 * min(di, do) + 1)
                 for di in range(num_degrees) for do in range(num_degrees))
    v2_per_conv = 2 * E * sumPQF * c
    # kernel-feature contraction out[e,P,o] = v2[e,P,IF] R[e,IF,o]
    sumPIFO = sum((2 * do + 1) * c * (2 * min(di, do) + 1) * c
                  for di in range(num_degrees) for do in range(num_degrees))
    contract_per_conv = 2 * E * sumPIFO
    # radial trunk (shared): 2 layers mid x mid per edge per conv
    trunk_per_conv = 2 * E * (2 * mid * mid)

    conv = radial_per_conv + v2_per_conv + contract_per_conv + trunk_per_conv
    # per attention block: k-conv + v-conv (hidden->kv, kv dim =
    # heads*dim_head per degree ~= c) + attention einsums (small)
    att_sim = 2 * E * heads * sum(dim_head * (2 * d + 1)
                                  for d in range(num_degrees)) * 2
    block = 2 * conv + att_sim
    # conv_in: input degree 0 only -> hidden (pairs (0, do))
    sumF_in = num_degrees  # F=1 for every (0, do)
    conv_in = 2 * E * mid * sumF_in * c * c
    fwd = depth * block + conv_in + conv  # + conv_out ~ one more conv
    return dict(conv_tflop=conv / 1e12, fwd_tflop=fwd / 1e12,
                # reversible remat: step ~= fwd + (re-fwd + bwd 2x) = 4x
                step_tflop_4x=4 * fwd / 1e12)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--dim', type=int, default=64)
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--k', type=int, default=32)
    ap.add_argument('--compile', action='store_true',
                    help='also compile the pallas=False step on CPU and '
                         'print XLA cost analysis (slow: ~10-15 min)')
    ap.add_argument('--edge-chunks', type=int, default=None,
                    help='0 = unchunked (no lax.map undercount); default '
                         'keeps the recipe default (8)')
    args = ap.parse_args(argv)

    print(json.dumps(dict(analytic=analytic_model(
        args.dim, 6, 4, args.nodes, args.k, 8, max(8, args.dim // 8)))),
        flush=True)
    try:
        import jax as _jax
        _jax.config.update('jax_platforms', 'cpu')
        from se3_transformer_tpu.training import recipes as _recipes
        from se3_transformer_tpu.utils.flops import (
            train_step_flops_estimate,
        )
        _m = _recipes.RECIPES['flagship'](
            dim=args.dim, num_neighbors=args.k, output_degrees=2,
            reduce_dim_out=True)
        print(json.dumps(dict(package_estimate_tflop=round(
            train_step_flops_estimate(_m, args.nodes, args.k) / 1e12, 2))),
            flush=True)
    except Exception as e:  # noqa: BLE001
        print(f'package estimate failed: {e}', file=sys.stderr)

    if not args.compile:
        return

    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    import optax
    from se3_transformer_tpu.training import recipes

    kwargs = {}
    if args.edge_chunks is not None:
        # 0 means "no chunking at all" (recipe default is 8)
        kwargs['edge_chunks'] = args.edge_chunks or None
    module = recipes.RECIPES['flagship'](
        dim=args.dim, num_neighbors=args.k, output_degrees=2,
        reduce_dim_out=True, pallas=False, **kwargs)
    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, args.nodes, args.dim)),
                        jnp.float32)
    coords = jnp.asarray(np.cumsum(
        rng.normal(size=(1, args.nodes, 3)), axis=1), jnp.float32)
    masks = jnp.ones((1, args.nodes), bool)

    def loss_fn(params, coords, key):
        noise = jax.random.normal(key, coords.shape, coords.dtype)
        noised = coords + noise
        out = module.apply({'params': params}, feats, noised, mask=masks,
                           return_type=1)
        return (((noised + out) - coords) ** 2).sum(-1).mean()

    shapes = jax.eval_shape(
        lambda key: module.init(key, feats, coords, mask=masks,
                                return_type=1), jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)['params']
    opt = optax.adam(1e-4)
    opt_state = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(opt.init, params))

    @jax.jit
    def step(params, opt_state, coords, key):
        loss, grads = jax.value_and_grad(loss_fn)(params, coords, key)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    lowered = step.lower(params, opt_state, coords, jax.random.PRNGKey(1))
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    flops = float(cost.get('flops', 0.0))
    print(json.dumps(dict(xla_path_step_tflop=round(flops / 1e12, 3),
                          note='pallas=False: every FLOP visible to XLA '
                               'cost analysis')), flush=True)


if __name__ == '__main__':
    main()
