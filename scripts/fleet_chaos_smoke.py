"""Fleet chaos smoke: the CROSS-HOST fault domain under deterministic
fire, with real processes dying.

Usage:
    python scripts/fleet_chaos_smoke.py [--hosts 3] [--requests 36]
        [--buckets 8,16] [--batch-size 2] [--timeout-s 30]
        [--max-retries 2] [--seed 0] [--kill-at 10]
        [--canary-requests 6] [--metrics FLEET_CHAOS.jsonl]
        [--out SUMMARY.json] [--weaken none|noexclude]

Three `scripts/serve.py --host` worker PROCESSES (each a full PR 8/12
stack: AOT engines, continuous batcher, router, breakers) serve a
mixed-length stream through a `serving.fleet.FleetRouter` while the
smoke injects, deterministically:

  * a host DEATH   — host 0 is SIGKILLed mid-run (a real preemption: no
    drain, no goodbye). Its in-flight and subsequent RPCs fail, the
    fleet redispatches them CROSS-HOST (zero lost), heartbeat failures
    walk the HOST breaker to quarantined, and after the smoke restarts
    the process on the same port, half-open ping probes close the
    breaker back — recovery observed, not assumed;
  * transport flakiness — a seeded `FaultInjector` `transport` site
    plans a latency spike and a partition-style drop on the fleet's
    RPCs (same seed, same faults), so a cross-host retry is exercised
    even before the kill;
  * a POISONED CANARY — a rolling weight rollout (checkpoint step 1 ->
    step 2 over the hosts' drain/swap contract) canaries on a host
    started with `--poison-step 2`: the moment the canary restores the
    new step, its every dispatch fails. The canary gate (pinned probe
    traffic + the host's scraped serve evidence) must FAIL and the
    fleet must AUTO-ROLL-BACK to step 1, leaving every other host
    untouched on the old weights.

Exit is non-zero unless ALL of:
  * zero lost requests FLEET-WIDE (every submit — including the
    sacrificial canary probes — resolves answered or structured-error);
  * every non-probe in-range request is ANSWERED (redispatch must
    actually pay the kill down, not just fail structurally);
  * >= 1 HOST quarantine -> recovery transition observed;
  * the rollout event shows the canary swapped to step 2, the gate
    failing, an auto-rollback to step 1, and ZERO sibling swaps;
  * the planned transport faults fired (latency + drop);
  * zero post-warmup compiles on every host (scraped at the end);
  * every host exits 0 on graceful SIGTERM (the shutdown satellite);
  * tracing survives the chaos: zero orphan spans and completeness 1.0
    across the SIGKILL (the dead host's spans die with it; the
    fleet-side tree must stay single-rooted through the redispatch)
    and >= 1 multi-host trace;
  * the banked stream (run_meta + schema'd `fleet`/`trace` records)
    validates.

`--weaken noexclude` is the injection arm of the `make
serve-fleet-smoke` pair: host exclusion is NULLED (placement ignores
breaker state, retries stop avoiding the host that just failed) and
the killed host never restarts — the dead lowest-id host keeps eating
traffic, requests exhaust their budgets unanswered and no recovery is
ever observed, so the run MUST exit rc==1, proving the gates fire
rather than decorate. The make target asserts the pair.
"""
import argparse
import atexit
import json
import os
import shutil
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='multi-process cross-host fleet chaos gate (CPU)')
    ap.add_argument('--hosts', type=int, default=3)
    ap.add_argument('--requests', type=int, default=36,
                    help='phase-A stream length (the kill lands inside)')
    ap.add_argument('--post-requests', type=int, default=8,
                    help='phase-C stream length (after the rollback the '
                         'fleet must still answer everything)')
    ap.add_argument('--buckets', type=str, default='8,16')
    ap.add_argument('--batch-size', type=int, default=2)
    ap.add_argument('--max-wait-ms', type=float, default=10.0)
    ap.add_argument('--timeout-s', type=float, default=30.0)
    ap.add_argument('--max-retries', type=int, default=2)
    ap.add_argument('--pace-ms', type=float, default=30.0)
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--kill-at', type=int, default=10,
                    help='SIGKILL host 0 after this many phase-A '
                         'submits')
    ap.add_argument('--restart-after-s', type=float, default=1.0)
    ap.add_argument('--canary-requests', type=int, default=6)
    ap.add_argument('--latency-budget-ms', type=float, default=30000.0,
                    help='canary-gate latency ceiling (generous on a '
                         'loaded CPU host — the poisoned canary fails '
                         'on ANSWERS, not latency)')
    ap.add_argument('--recovery-deadline-s', type=float, default=240.0,
                    help='bound on waiting for the restarted host to '
                         'warm up and close its breaker via probes')
    ap.add_argument('--ckpt-dir', type=str, default=None)
    ap.add_argument('--checkpoint', default=None, help=argparse.SUPPRESS)
    ap.add_argument('--metrics', type=str, default=None)
    ap.add_argument('--out', type=str, default=None)
    ap.add_argument('--transport', choices=('binary', 'legacy'),
                    default='binary',
                    help='fleet wire under chaos: the pooled '
                         'multiplexed binary framing (default) or the '
                         'legacy connect-per-call JSON escape hatch')
    ap.add_argument('--weaken', choices=('none', 'noexclude'),
                    default='none',
                    help="'noexclude': null host exclusion (placement "
                         'ignores breaker state, retries stop avoiding '
                         'the failed host) and skip the restart — the '
                         'gates MUST fire (rc 1), proving they are '
                         'live')
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    import numpy as np

    from serve import (
        build_module_and_params, spawn_host, stop_host, wait_host_ready,
    )
    from se3_transformer_tpu.faults import FaultInjector
    from se3_transformer_tpu.observability import (
        MetricLogger, Tracer, trace_record_body,
    )
    from se3_transformer_tpu.observability.report import (
        summarize_fleet_records,
    )
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        BinaryTransport, FleetRouter, HealthConfig, SocketTransport,
    )
    from se3_transformer_tpu.training.checkpoint import CheckpointManager

    buckets = tuple(int(b) for b in args.buckets.split(','))
    weakened = args.weaken == 'noexclude'
    kill_host = 0       # lowest id: the weaken arm's tie-breaks land on
    #                     it, so nulled exclusion keeps feeding it
    canary = args.hosts - 1

    # ---- the weight refs: step 1 = current, step 2 = rollout target -- #
    cfg, _, params_old = build_module_and_params(args, buckets)
    _, _, params_new = build_module_and_params(args, buckets,
                                               seed=args.seed + 1)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix='fleet_ckpt_')
    if args.ckpt_dir is None:
        atexit.register(shutil.rmtree, ckpt_dir, ignore_errors=True)
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(1, dict(params=params_old))
    mgr.save(2, dict(params=params_new))
    mgr.close()
    print(f'checkpoints: step 1 (current) + step 2 (rollout target) '
          f'in {ckpt_dir}')

    # ---- spawn the host processes (canary carries the poison) -------- #
    tmp = tempfile.mkdtemp(prefix='fleet_chaos_')
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)

    def host_kw(i, port=0):
        return dict(
            port=port, buckets=args.buckets, batch_size=args.batch_size,
            replicas=1, seed=args.seed, max_wait_ms=args.max_wait_ms,
            timeout_s=args.timeout_s, max_retries=1,
            checkpoint=ckpt_dir, checkpoint_step=1,
            metrics=os.path.join(tmp, f'host_{i}.jsonl'),
            poison_step=2 if i == canary else None,
            transport=args.transport)

    print(f'spawning {args.hosts} host processes '
          f'(canary={canary} poisoned at step 2)...')
    procs = [spawn_host(i, **host_kw(i)) for i in range(args.hosts)]

    def kill_everything():
        for p in procs:
            if p is not None and p.poll() is None:
                p.kill()
    atexit.register(kill_everything)

    ports, sinks = [], []
    for p in procs:
        port, sink = wait_host_ready(p)
        ports.append(port)
        sinks.append(sink)
    print(f'fleet up: hosts on ports {ports}')

    # ---- the fleet front-end + the seeded transport fault plan ------- #
    inj = FaultInjector(seed=args.seed)
    inj.plan('transport', 'latency', every=11, latency_s=0.02)
    inj.plan('transport', 'drop', at=(5,), match=dict(method='infer'))
    # the chaos gates (SIGKILL reconnect, seeded drop/latency faults,
    # canary rollback) run on the production binary wire by default —
    # --transport legacy re-runs them on the JSON escape hatch
    transport_cls = (BinaryTransport if args.transport == 'binary'
                     else SocketTransport)
    transports = {i: transport_cls('127.0.0.1', port,
                                   fault_injector=inj)
                  for i, port in enumerate(ports)}
    health = HealthConfig(quarantine_after=3, recover_after=2,
                          probe_backoff_s=0.25, probe_backoff_max_s=2.0)
    logger = MetricLogger(args.metrics, run_meta=dict(
        mode='fleet_chaos', hosts=args.hosts, ports=ports,
        buckets=list(buckets), seed=args.seed, weaken=args.weaken,
        kill_host=kill_host, canary=canary))
    rng = np.random.RandomState(args.seed)
    pending, probes = [], []
    rollout_event = None
    killed_at_t = None
    restarted = False
    ok = True

    def mk_request():
        b = buckets[int(rng.randint(0, len(buckets)))]
        low = 1 if b == buckets[0] else buckets[0] + 1
        length = int(rng.randint(low, b + 1))
        return (rng.randint(0, cfg.num_tokens, size=length),
                rng.normal(size=(length, 3)).astype(np.float32))

    # every submit is traced: under the SIGKILL the dead host's own
    # spans are simply lost with the process, but the fleet-side span
    # tree must STAY complete (the failed attempt ends transport_error,
    # the redispatch hop is recorded, the retry attempt carries the
    # sibling host) — zero orphans even across a host death
    tracer = Tracer(origin='fleet')
    with FleetRouter(transports, health=health,
                     max_retries=args.max_retries,
                     default_timeout_s=args.timeout_s,
                     heartbeat_every_s=0.2,
                     stale_after_s=3.0, tracer=tracer) as fleet:
        if weakened:
            # THE WEAKENED ARM: the exclusion mechanism — quarantine
            # filtering, failed-host avoidance, health-ranked placement
            # — is a no-op. The dead host keeps eating traffic; the
            # gates below MUST catch it (rc 1) or they are decoration.
            print('WEAKENED GATE ARM: host exclusion nulled, no '
                  'restart (this run must exit 1)')
            fleet.host_exclusion = False

        # scrape until the routing signals (and buckets) arrive
        t0 = time.monotonic()
        while fleet.buckets is None and time.monotonic() - t0 < 30:
            fleet.pump()
            time.sleep(0.05)
        assert fleet.buckets == buckets, \
            f'scraped buckets {fleet.buckets} != served {buckets}'

        # ---- phase A: the stream, with a mid-run SIGKILL ------------- #
        for i in range(args.requests):
            if i == args.kill_at:
                print(f'SIGKILL host {kill_host} (pid '
                      f'{procs[kill_host].pid}) after {i} submits — a '
                      f'real preemption, no drain')
                os.kill(procs[kill_host].pid, signal.SIGKILL)
                procs[kill_host].wait()
                killed_at_t = time.monotonic()
            if killed_at_t is not None and not restarted \
                    and not weakened \
                    and time.monotonic() - killed_at_t \
                    >= args.restart_after_s:
                print(f'restarting host {kill_host} on port '
                      f'{ports[kill_host]}...')
                procs[kill_host] = spawn_host(
                    kill_host, **host_kw(kill_host,
                                         port=ports[kill_host]))
                restarted = True
            tokens, coords = mk_request()
            pending.append(fleet.submit(tokens, coords))
            fleet.pump()
            time.sleep(args.pace_ms / 1e3)
        fleet.drain()
        if killed_at_t is not None and not restarted and not weakened:
            # the stream outran the restart delay — restart now, the
            # recovery must still be OBSERVED via probes below
            remaining = args.restart_after_s - (time.monotonic()
                                                - killed_at_t)
            if remaining > 0:
                time.sleep(remaining)
            print(f'restarting host {kill_host} on port '
                  f'{ports[kill_host]} (post-stream)...')
            procs[kill_host] = spawn_host(
                kill_host, **host_kw(kill_host, port=ports[kill_host]))
            restarted = True
        answered_a = sum(1 for p in pending if p.ok)
        print(f'phase A: {answered_a}/{len(pending)} answered, '
              f'{fleet.cross_host_retries} cross-host retries, '
              f'host {kill_host} state '
              f'{fleet.health.state(kill_host)!r}')
        logger.log_record('fleet', mirror=False,
                          **fleet.record_body(pending, label='phase_a'))

        # ---- phase B: wait for the restarted host's recovery --------- #
        if restarted:
            # the respawned process re-warms (persistent jit cache makes
            # it quick) and must close its breaker via ping probes — the
            # recovery is OBSERVED, never assumed
            wait_host_ready(procs[kill_host])
            print(f'host {kill_host} restarted and READY — waiting for '
                  f'the breaker to close via probes')
            t0 = time.monotonic()
            while fleet.health.recoveries == 0 and \
                    time.monotonic() - t0 < args.recovery_deadline_s:
                fleet.pump()
                time.sleep(0.1)
            fleet.drain()
            print(f'recoveries={fleet.health.recoveries}, host '
                  f'{kill_host} state '
                  f'{fleet.health.state(kill_host)!r}')

        # ---- phase C: the canaried rollout (must auto-roll-back) ----- #
        canary_traffic = [mk_request()
                          for _ in range(args.canary_requests)]
        rollout_event, probes = fleet.rollout(
            dict(directory=ckpt_dir, step=2),
            dict(directory=ckpt_dir, step=1),
            canary_traffic, canary=canary,
            latency_budget_ms=args.latency_budget_ms,
            timeout_s=args.timeout_s)
        pending += probes
        print(f'rollout: canary={rollout_event["canary"]} '
              f'tag={rollout_event.get("canary_tag")!r} '
              f'gate={rollout_event.get("gate")} '
              f'rolled_back={rollout_event.get("rolled_back")}')

        # ---- phase D: the fleet must still serve after the rollback -- #
        post = []
        for _ in range(args.post_requests):
            tokens, coords = mk_request()
            post.append(fleet.submit(tokens, coords))
            fleet.pump()
            time.sleep(args.pace_ms / 1e3)
        # the poisoned canary quarantined during the gate; give its
        # probe recovery a bounded chance too (more breaker evidence)
        t0 = time.monotonic()
        while fleet.health.state(canary) == 'quarantined' \
                and time.monotonic() - t0 < 30:
            fleet.pump()
            time.sleep(0.1)
        fleet.drain()
        pending += post
        print(f'phase D: {sum(1 for p in post if p.ok)}/{len(post)} '
              f'answered after the rollback')

        # ---- final evidence: scraped stats + the banked record ------- #
        final_stats = {}
        for hid, t in transports.items():
            try:
                res = t.call('stats', timeout_s=5.0)
                final_stats[hid] = res.get('stats') or {}
            except Exception as e:
                final_stats[hid] = dict(error=str(e))
        body = fleet.record_body(pending, label='fleet_chaos')
        logger.log_record('fleet', mirror=False, **body)
        resolved = sum(1 for p in pending if p.done)
        trace_body = trace_record_body(tracer, label='fleet_chaos',
                                       expected=resolved)
        logger.log_record('trace', mirror=False, **trace_body)
    logger.close()

    # ---- graceful shutdown: every host must exit 0 on SIGTERM -------- #
    rcs = [stop_host(p) for p in procs]
    print(f'host exit codes on graceful SIGTERM: {rcs}')

    # ---- gates ------------------------------------------------------- #
    probe_ids = {p.request_id for p in probes}
    lost = [p.request_id for p in pending if not p.done]
    if lost:
        print(f'FAIL: {len(lost)} submitted requests LOST fleet-wide '
              f'(resolved neither answered nor structured-error): '
              f'{lost[:10]}')
        ok = False
    unanswered = [p.request_id for p in pending
                  if not p.ok and p.request_id not in probe_ids]
    if unanswered:
        print(f'FAIL: {len(unanswered)} non-probe requests resolved '
              f'unanswered — cross-host redispatch must pay the kill '
              f'down: {unanswered[:10]}')
        ok = False
    killed_recovered = any(
        e.get('from_state') == 'quarantined'
        and e.get('host') == kill_host
        for e in body['host_transitions'])
    if body['recoveries'] < 1 or not killed_recovered:
        print(f'FAIL: the SIGKILLed host {kill_host} was never '
              f'observed recovering (quarantine -> live via probe '
              f'after restart); transitions: '
              f'{body["host_transitions"]}')
        ok = False
    if body['cross_host_retries'] < 1:
        print('FAIL: zero cross-host retries — nothing was ever '
              'redispatched onto a sibling host')
        ok = False
    if rollout_event is None or not rollout_event.get('rolled_back'):
        print('FAIL: the poisoned canary rollout did NOT auto-roll-'
              'back — the gate decorated instead of deciding')
        ok = False
    else:
        if not str(rollout_event.get('canary_tag', '')).endswith('@2'):
            print(f'FAIL: canary swap tag '
                  f'{rollout_event.get("canary_tag")!r} — expected the '
                  f'rollout target step 2')
            ok = False
        rb = rollout_event.get('rollback') or {}
        if not str(rb.get('tag', '')).endswith('@1'):
            print(f'FAIL: rollback tag {rb.get("tag")!r} — expected '
                  f'the previous step 1')
            ok = False
        # EVERY non-canary host must show zero swaps — the restarted
        # kill_host included (its fresh process counts from 0, so a
        # rollout that wrongly rolled it would show there too)
        sibling_swaps = {hid: (final_stats.get(hid) or {}).get('swaps')
                         for hid in range(args.hosts) if hid != canary}
        if any(s != 0 for s in sibling_swaps.values()):
            print(f'FAIL: sibling hosts swapped during a rolled-back '
                  f'canary: {sibling_swaps} (must all be 0)')
            ok = False
    by_site = inj.snapshot()['by_site']
    for needed in ('transport:latency', 'transport:drop'):
        if not by_site.get(needed):
            print(f'FAIL: planned transport fault {needed!r} never '
                  f'fired — the chaos proved less than it claims')
            ok = False
    compiles = {hid: (final_stats.get(hid) or {})
                .get('post_warmup_compiles') for hid in final_stats}
    if any(c is None or c != 0 for c in compiles.values()):
        print(f'FAIL: post-warmup compiles per host {compiles} — the '
              f'rollout/rollback swaps and the chaos must not break '
              f'the AOT contract')
        ok = False
    if any(rc != 0 for rc in rcs):
        print(f'FAIL: host exit codes {rcs} — graceful SIGTERM must '
              f'drain, bank telemetry, and exit 0')
        ok = False
        for i, rc in enumerate(rcs):
            if rc != 0:
                print(f'--- host {i} tail ---')
                print(''.join(sinks[i][-15:]) if i < len(sinks) else '?')
    # tracing must survive the chaos: a SIGKILLed host takes its own
    # spans down with it, but every fleet-side tree must stay complete
    # — a single orphan means some latency can no longer be attributed
    if trace_body['orphan_spans'] != 0:
        print(f'FAIL: {trace_body["orphan_spans"]} orphan span(s) '
              f'under SIGKILL/redispatch — the span trees must stay '
              f'single-rooted across a host death')
        ok = False
    if trace_body['completeness_total'] < 1.0:
        print(f'FAIL: trace completeness '
              f'{trace_body["completeness_total"]} < 1.0 '
              f'({trace_body["complete_trees"]}/{trace_body["traces"]} '
              f'complete over {resolved} resolved)')
        ok = False
    if trace_body['multi_host_traces'] < 1:
        print('FAIL: no multi-host trace — a redispatched request '
              'must show attempts on >= 2 hosts')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records '
                  f'{info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        weaken=args.weaken,
        requests=dict(submitted=len(pending),
                      answered=sum(1 for p in pending if p.ok),
                      structured_failures=sum(
                          1 for p in pending
                          if p.done and p.error is not None),
                      lost=len(lost), unanswered_non_probe=len(unanswered)),
        fleet=summarize_fleet_records(
            [dict(body, kind='fleet')]),
        rollout=rollout_event,
        trace={k: trace_body[k] for k in (
            'traces', 'complete_trees', 'orphan_spans',
            'multi_host_traces', 'redispatch_hops',
            'completeness_total')},
        injections=by_site,
        host_rcs=rcs,
        post_warmup_compiles=compiles,
    )
    print(json.dumps(report, indent=2, default=str))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2, default=str)
        print(f'report -> {args.out}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
