"""On-TPU validation: equivariance + Pallas numerics + kernel speedup.

Runs on the real chip (the pytest suite runs on a simulated CPU mesh).
Checks:
  1. model equivariance at f32 matmul precision (<1e-4, the reference's
     acceptance bound) — TPU's default bf16 matmuls are also measured for
     reference;
  2. Pallas fused pairwise kernel vs XLA einsum path numerics;
  3. wall-clock of the pallas path vs the XLA path on a conv-heavy config.

Usage: python scripts/tpu_checks.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
from se3_transformer_tpu.utils.helpers import fetch_sync_tail
import jax.numpy as jnp
import numpy as np

from se3_transformer_tpu.models.se3_transformer import SE3TransformerModule


def check_equivariance(precision: str, radial_bf16: bool = False,
                       conv_bf16: bool = False):
    from se3_transformer_tpu.utils.validation import equivariance_l2

    module = SE3TransformerModule(
        dim=16, depth=1, attend_self=True, num_neighbors=8, num_degrees=3,
        output_degrees=2, fourier_encode_dist=True,
        radial_bf16=radial_bf16, conv_bf16=conv_bf16)
    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, 32, 16)), jnp.float32)
    coors = jnp.asarray(rng.normal(size=(1, 32, 3)), jnp.float32)
    mask = jnp.ones((1, 32), bool)
    # jit the init: eager init dispatches thousands of tiny ops through the
    # device tunnel (minutes of latency); one compiled program is seconds
    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    with jax.default_matmul_precision(precision):
        params = init_fn(jax.random.PRNGKey(0), feats, coors, mask=mask,
                         return_type=1)['params']
    err = equivariance_l2(module, params, feats, coors, mask,
                          precision=precision)
    apply_fn = jax.jit(module.apply, static_argnames=('return_type',))
    scale = float(np.abs(np.asarray(apply_fn(
        {'params': params}, feats, coors, mask=mask, return_type=1))).max())
    return err, err / max(scale, 1e-12)


def check_equivariance_sparse_only(precision: str = 'float32'):
    """The sparse-neighbors-only config: the reference runs its analogue in
    float64 (tests/test_equivariance.py:234-260); on TPU there is no x64,
    so this config needs its own f32 tolerance check on chip."""
    from se3_transformer_tpu.utils.validation import equivariance_l2

    module = SE3TransformerModule(
        dim=16, depth=1, attend_self=True, num_degrees=2, output_degrees=2,
        num_neighbors=0, attend_sparse_neighbors=True, num_adj_degrees=2,
        adj_dim=4)
    rng = np.random.RandomState(0)
    n = 32
    feats = jnp.asarray(rng.normal(size=(1, n, 16)), jnp.float32)
    coors = jnp.asarray(rng.normal(size=(1, n, 3)), jnp.float32)
    mask = jnp.ones((1, n), bool)
    seq = np.arange(n)
    adj = jnp.asarray((seq[:, None] >= seq[None, :] - 1)
                      & (seq[:, None] <= seq[None, :] + 1))
    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    with jax.default_matmul_precision(precision):
        params = init_fn(jax.random.PRNGKey(0), feats, coors, mask=mask,
                         adj_mat=adj, return_type=1)['params']
    return equivariance_l2(module, params, feats, coors, mask,
                           precision=precision, adj_mat=adj)


def bench_conv(pallas: bool, n=512, k=24, dim=32, degrees=3, iters=10,
               fuse_basis=False, radial_bf16=False, conv_bf16=False):
    from se3_transformer_tpu.basis import get_basis
    from se3_transformer_tpu.ops import ConvSE3, Fiber
    from se3_transformer_tpu.utils import batched_index_select

    rng = np.random.RandomState(0)
    fiber = Fiber.create(degrees, dim)
    feats = {str(d): jnp.asarray(rng.normal(size=(1, n, dim, 2 * d + 1)),
                                 jnp.float32) for d in range(degrees)}
    coors = jnp.asarray(rng.normal(size=(1, n, 3)) * 3, jnp.float32)
    idx = jnp.asarray(rng.randint(0, n, (1, n, k)), jnp.int32)
    mask = jnp.ones((1, n, k), bool)

    conv = ConvSE3(fiber, fiber, pallas=pallas, fuse_basis=fuse_basis,
                   radial_bf16=radial_bf16, conv_bf16=conv_bf16)

    # jit the input prep: eager gathers/basis would round-trip thousands of
    # tiny ops through the device tunnel (minutes of latency). fuse_basis
    # measures the FLAT basis layout — what the model actually feeds the
    # bx kernel since round 4 (docs/DESIGN.md §2a)
    layout = 'pfq_flat' if fuse_basis else 'pqf'

    @jax.jit
    def prep(coors):
        coors_j = batched_index_select(coors, idx, axis=1)
        rel_pos = coors[:, :, None, :] - coors_j
        rel_dist = jnp.linalg.norm(rel_pos, axis=-1)
        basis = get_basis(rel_pos, degrees - 1, layout=layout)
        return rel_dist, basis

    rel_dist, basis = prep(coors)
    args = (feats, (idx, mask, None), rel_dist, basis)
    params = jax.jit(conv.init)(jax.random.PRNGKey(0), *args)
    fwd = jax.jit(lambda p, a: conv.apply(p, *a))
    out = jax.block_until_ready(fwd(params, args))
    fetch_sync_tail(out)  # warm the gating fetch (its own tiny program)

    t0 = time.time()
    for _ in range(iters):
        out = fwd(params, args)
    fetch_sync_tail(out)  # one-element host fetch gates completion
    dt = (time.time() - t0) / iters

    # numerics comparison at f32 precision (timing above uses the default
    # policy both paths share)
    with jax.default_matmul_precision('float32'):
        out = jax.jit(lambda p, a: conv.apply(p, *a))(params, args)
    return dt, jax.block_until_ready(out)


def check_fused_backward(n=256, k=16, dim=24, degrees=3,
                         interpret=False):
    """Pallas fwd+bwd vs XLA gradients on-chip (the interpret-mode tests
    cover logic; this covers Mosaic lowering)."""
    from se3_transformer_tpu.basis import get_basis
    from se3_transformer_tpu.ops import ConvSE3, Fiber
    from se3_transformer_tpu.utils import batched_index_select

    rng = np.random.RandomState(0)
    fiber = Fiber.create(degrees, dim)
    feats = {str(d): jnp.asarray(rng.normal(size=(1, n, dim, 2 * d + 1)),
                                 jnp.float32) for d in range(degrees)}
    coors = jnp.asarray(rng.normal(size=(1, n, 3)) * 3, jnp.float32)
    idx = jnp.asarray(rng.randint(0, n, (1, n, k)), jnp.int32)
    mask = jnp.ones((1, n, k), bool)
    @jax.jit
    def prep(coors):
        coors_j = batched_index_select(coors, idx, axis=1)
        rel = coors[:, :, None, :] - coors_j
        rd = jnp.linalg.norm(rel, axis=-1)
        return rd, get_basis(rel, degrees - 1)

    rd, basis = prep(coors)

    conv_pl = ConvSE3(fiber, fiber, pallas=False,
                      pallas_interpret=True) if interpret \
        else ConvSE3(fiber, fiber, pallas=True)
    conv_x = ConvSE3(fiber, fiber, pallas=False)
    params = jax.jit(conv_x.init)(jax.random.PRNGKey(0), feats,
                                  (idx, mask, None), rd, basis)

    def loss(conv):
        return lambda p: sum(
            (conv.apply(p, feats, (idx, mask, None), rd, basis)[d] ** 2).sum()
            for d in map(str, range(degrees)))

    # gate gradients at f32 matmul precision (the policy the equivariance
    # bound is stated at); the default-policy path is timed in bench_conv
    with jax.default_matmul_precision('float32'):
        g_pl = jax.jit(jax.grad(loss(conv_pl)))(params)
        g_x = jax.jit(jax.grad(loss(conv_x)))(params)
    worst = 0.0
    for a, b in zip(jax.tree_util.tree_leaves(g_pl),
                    jax.tree_util.tree_leaves(g_x)):
        scale = float(jnp.abs(b).max()) + 1e-9
        worst = max(worst, float(jnp.abs(a - b).max()) / scale)
    return worst


def bench_attention(variant: str, B=1, h=8, n=1024, J=33, D=56, iters=20):
    """Attention path comparison at a flagship per-degree shape
    (D = dim_head*(2*deg+1) with dim_head=8 -> 8/24/40/56; J = k+1 kv
    slots) — the model dispatches one kernel per degree. Variants:
    'xla' einsum path, 'fused' D-on-lanes kernel (the J-on-lanes
    experiment was retired round 4 — decision table in
    kernels/pallas_attention.py)."""
    from se3_transformer_tpu.kernels.pallas_attention import (
        attention_reference, fused_attention,
    )
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.normal(size=(B * h, n, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B * h, n, J, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B * h, n, J, D)), jnp.float32)
    mask = jnp.asarray(rng.rand(B, n, J) > 0.2)
    mask = mask.at[:, :, 0].set(True)
    scale = D ** -0.5

    impl = dict(
        xla=lambda q, k, v: attention_reference(q, k, v, mask, scale),
        fused=lambda q, k, v: fused_attention(q, k, v, mask, h, scale),
    )[variant]
    fn = jax.jit(impl)
    out = jax.block_until_ready(fn(q, k, v))
    fetch_sync_tail(out)  # warm the gating fetch (its own tiny program)
    t0 = time.time()
    for _ in range(iters):
        out = fn(q, k, v)
    fetch_sync_tail(out)  # one-element host fetch gates completion
    return (time.time() - t0) / iters, out


def main():
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    print(f'backend: {jax.default_backend()}')

    for prec in ('float32', 'bfloat16'):
        err, rel = check_equivariance(prec)
        status = 'PASS' if (prec != 'float32' or err < 1e-4) else 'FAIL'
        print(f'equivariance @ matmul_precision={prec}: abs={err:.2e} '
              f'rel={rel:.2e} [{status if prec == "float32" else "info"}]')

    err_rb, rel_rb = check_equivariance('float32', radial_bf16=True)
    print(f'equivariance @ f32 + radial_bf16: abs={err_rb:.2e} '
          f'rel={rel_rb:.2e} [{"PASS" if err_rb < 1e-4 else "FAIL"}]')

    # conv_bf16 quantizes EQUIVARIANT operands: expected ~1e-3-class
    # error (the documented tradeoff, ops/conv.py) — info + sanity bound,
    # not the 1e-4 gate
    err_cb, rel_cb = check_equivariance('float32', conv_bf16=True)
    print(f'equivariance @ f32 + conv_bf16: abs={err_cb:.2e} '
          f'rel={rel_cb:.2e} '
          f'[{"PASS" if err_cb < 5e-2 else "FAIL"} (5e-2 sanity bound)]')

    err_sp = check_equivariance_sparse_only()
    print(f'equivariance sparse-only @ f32: abs={err_sp:.2e} '
          f'[{"PASS" if err_sp < 1e-4 else "FAIL"}]')

    gworst = check_fused_backward()
    print(f'fused bwd vs XLA grads: rel={gworst:.2e} '
          f'[{"PASS" if gworst < 1e-4 else "FAIL"}]')

    t_xla, out_xla = bench_conv(pallas=False)
    t_pl, out_pl = bench_conv(pallas=True)
    diff = max(float(jnp.abs(out_xla[d] - out_pl[d]).max())
               for d in out_xla)
    print(f'ConvSE3 fwd: xla {t_xla*1e3:.1f} ms, pallas {t_pl*1e3:.1f} ms '
          f'({t_xla/t_pl:.2f}x), max|diff|={diff:.2e} '
          f'[{"PASS" if diff < 1e-3 else "FAIL"}]')

    t_bx, out_bx = bench_conv(pallas=True, fuse_basis=True)
    diff = max(float(jnp.abs(out_xla[d] - out_bx[d]).max())
               for d in out_xla)
    print(f'ConvSE3 fwd fuse_basis: {t_bx*1e3:.1f} ms '
          f'({t_xla/t_bx:.2f}x vs xla, {t_pl/t_bx:.2f}x vs pallas), '
          f'max|diff|={diff:.2e} [{"PASS" if diff < 1e-3 else "FAIL"}]')

    t_rb, out_rb = bench_conv(pallas=True, fuse_basis=True,
                              radial_bf16=True)
    # one normalization scale for BOTH bf16 rel-diff gates below — they
    # must stay comparable
    scale = max(float(jnp.abs(out_xla[d]).max()) for d in out_xla)
    diff = max(float(jnp.abs(out_xla[d] - out_rb[d]).max())
               for d in out_xla) / scale
    print(f'ConvSE3 fwd fuse_basis+radial_bf16: {t_rb*1e3:.1f} ms '
          f'({t_xla/t_rb:.2f}x vs xla), rel diff={diff:.2e} '
          f'[{"PASS" if diff < 3e-2 else "FAIL"}]')

    t_cb, out_cb = bench_conv(pallas=True, fuse_basis=True,
                              radial_bf16=True, conv_bf16=True)
    diff = max(float(jnp.abs(out_xla[d] - out_cb[d]).max())
               for d in out_xla) / scale
    print(f'ConvSE3 fwd fuse_basis+radial_bf16+conv_bf16: '
          f'{t_cb*1e3:.1f} ms ({t_xla/t_cb:.2f}x vs xla), '
          f'rel diff={diff:.2e} [{"PASS" if diff < 3e-2 else "FAIL"}]')

    # attention numerics + wall-clock at every flagship per-degree
    # shape. Layout DECIDED round 4 (retirement table in
    # kernels/pallas_attention.py): XLA is the attention path; the
    # D-on-lanes kernel stays the validated opt-in.
    for D in (8, 24, 40, 56):
        t_ax, out_ax = bench_attention('xla', D=D)
        t_af, out_af = bench_attention('fused', D=D)
        adiff = float(jnp.abs(out_ax - out_af).max())
        ok = adiff < 1e-3
        print(f'attention fwd D={D}: xla {t_ax*1e3:.2f} ms, '
              f'fused(D-lanes) {t_af*1e3:.2f} ms ({t_ax/t_af:.2f}x), '
              f'max|diff| fused={adiff:.2e} '
              f'[{"PASS" if ok else "FAIL"}]')


if __name__ == '__main__':
    main()
