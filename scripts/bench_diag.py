"""Diagnose the 2487-nodes*steps/s conservative bench artifact (19:29Z).

Replicates bench.py's on-chip conservative flagship program EXACTLY
(donated buffers, same seeds) and prints what bench discards: the
per-step loss sequence and per-step wall time. --mode aot runs the
lower().compile() executable bench times; --mode jit runs the plain
jitted call. Deterministic seeds => the two modes' loss sequences must
match across separate processes if the AOT program is computing the
same function.

Run only with a free tunnel.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--mode', choices=('aot', 'jit'), default='aot')
    ap.add_argument('--steps', type=int, default=6)
    ap.add_argument('--fast', action='store_true')
    ap.add_argument('--remat', default=None)
    ap.add_argument('--async-loop', action='store_true',
                    help='bench-style: dispatch all steps, block once at '
                         'the end (vs per-step blocking)')
    args = ap.parse_args(argv)

    import jax
    from _flagship_common import build_flagship_step
    print('backend:', jax.default_backend(), flush=True)
    step, params, opt_state, data, key, _ = build_flagship_step(
        fast=args.fast, remat=args.remat)

    exec_fn = step
    if args.mode == 'aot':
        t0 = time.time()
        exec_fn = step.lower(params, opt_state, data, key).compile()
        print(f'AOT compile: {time.time() - t0:.1f} s', flush=True)

    # bench warmup call (key, as bench uses it)
    t0 = time.time()
    params, opt_state, loss, _ = exec_fn(params, opt_state, data, key)
    loss = jax.block_until_ready(loss)
    print(f'warmup: loss={float(loss):.3f}  {time.time() - t0:.1f} s',
          flush=True)

    losses, times = [], []
    if args.async_loop:
        t0 = time.time()
        for _ in range(args.steps):
            key, sub = jax.random.split(key)
            params, opt_state, loss, _ = exec_fn(params, opt_state, data,
                                                 sub)
            losses.append(loss)
        jax.block_until_ready(loss)
        dt = time.time() - t0
        t1 = time.time()
        losses = [float(l) for l in losses]
        print(f'async loop: {dt:.2f} s for {args.steps} steps '
              f'({dt / args.steps * 1e3:.0f} ms/step); float() of all '
              f'losses took a further {time.time() - t1:.2f} s', flush=True)
        times = [dt / args.steps]
    else:
        for _ in range(args.steps):
            key, sub = jax.random.split(key)
            t0 = time.time()
            params, opt_state, loss, _ = exec_fn(params, opt_state, data,
                                                 sub)
            loss = jax.block_until_ready(loss)
            times.append(time.time() - t0)
            losses.append(float(loss))
    print(f'{args.mode}: losses=' + ' '.join(f'{l:.4f}' for l in losses),
          flush=True)
    print(f'{args.mode}: per-step s=' + ' '.join(f'{t:.2f}' for t in times),
          flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
