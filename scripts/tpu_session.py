"""One-shot patient TPU session: wait for the chip, validate, benchmark.

The axon tunnel is single-client and wedges when a claim-holding process
is killed. So this script NEVER times itself out: if the chip is busy or
wedged it blocks harmlessly at backend init (a blocked waiter holds no
claim) and proceeds the moment the lease frees up. Once it has the chip
it runs the full on-chip suite in ONE process — the kernel_smoke canary,
the flagship benchmark (the round's key deliverable, so it runs before
the longer checks in case the tunnel dies mid-session), tpu_checks
(equivariance at f32/bf16, fused Pallas kernel numerics + speedup),
baseline configs, the flagship profile with per-scope device-time
attribution (observability.profiling), and the perf-regression gate
(scripts/perf_gate.py vs PERF_BUDGETS.json) — and exits cleanly so the
chip is released.

Usage: python scripts/tpu_session.py [logfile]
"""
import datetime
import os
import sys
import traceback

LOG = sys.argv[1] if len(sys.argv) > 1 else '/tmp/tpu_session.log'


def log(msg):
    stamp = datetime.datetime.utcnow().strftime('%H:%M:%S')
    line = f'[{stamp}] {msg}'
    print(line, flush=True)
    with open(LOG, 'a') as f:
        f.write(line + '\n')


def _best_probe_batch(probe_path):
    """(batch, edge_chunks) of the highest-throughput fitting fast
    batch>1 probe point (dim=64, n=1024, on-chip, measured under the
    CURRENT package code), or None.
    Drives the batched flagship record: the probe measures which batch
    still fits HBM and what it yields; the bench then records the best
    one at full step count. The whole append-only file is scanned — the
    probe skips already-measured points (--skip-done), so after a
    tunnel death the winning batch record may predate this session's
    probe run; the code_rev filter (the package-tree fingerprint
    tpu_probe stamps into every record) keeps stale-build numbers out
    of the election, and MIN_REAL_STEP_MS guards against dying-tunnel
    artifact records (a 31 ms flagship "timing" was appended seconds
    before the 13:29Z death)."""
    import json
    import tpu_probe
    fingerprint = tpu_probe.package_fingerprint()
    if fingerprint is None:
        # without a build identity the election cannot distinguish
        # stale-build records; refuse rather than elect a wrong batch
        return None
    best, best_tput = None, 0.0
    try:
        with open(probe_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                b = rec.get('batch', 1)
                if (rec.get('fits') and rec.get('fast') and b and b > 1
                        and rec.get('dim') == 64 and rec.get('n') == 1024
                        and rec.get('backend') not in (None, 'cpu')
                        and rec.get('code_rev') == fingerprint
                        and rec.get('step_ms', 0)
                        > tpu_probe.min_real_step_ms(1024)
                        and rec.get('nodes_steps_per_sec', 0) > best_tput):
                    # carry the measured chunk setting with the batch:
                    # the bench must run the exact program the probe
                    # proved to fit (a b>1 fitting chunked can OOM
                    # unchunked)
                    best = (b, rec.get('edge_chunks', 0))
                    best_tput = rec['nodes_steps_per_sec']
    except OSError:
        return None
    return best


def _start_stop_watchdog():
    """While the session is BLOCKED WAITING at backend init (no claim
    held — the one state that's safe to abandon), honor the round-end
    stop file by exiting hard. Disarmed the moment the chip is acquired:
    a claim-holding session must run to completion and release cleanly
    (killing it wedges the single-client tunnel). Returns the disarm
    callable."""
    import threading
    acquired = threading.Event()
    # SE3_TPU_STOP_FILE override: tests must point this at a scratch
    # path — touching the real one stops the production loop
    stop_path = os.environ.get('SE3_TPU_STOP_FILE') or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        '.tpu_stop')

    def watch():
        while not acquired.wait(timeout=20):
            if os.path.exists(stop_path):
                # double-check around a generous grace sleep: if init
                # completes while we decide, the claim is held — do NOT
                # exit. Claim acquisition isn't atomic with
                # acquired.set(), so a seconds-wide window remains where
                # a just-granted lease dies with us; accepted, because
                # the stop file is only ever touched at round end when
                # the operator has already decided to give up the chip.
                import time
                time.sleep(15)
                if acquired.is_set():
                    return
                log('stop file present while waiting at init — exiting 0')
                if acquired.is_set():  # last-instant re-check after I/O
                    return
                os._exit(0)

    threading.Thread(target=watch, daemon=True).start()
    return acquired.set


def main():
    log(f'pid={os.getpid()} waiting for TPU (blocking, no timeout)...')
    disarm_stop_watchdog = _start_stop_watchdog()
    import jax
    try:
        devs = jax.devices()
    except RuntimeError as e:
        # the tunnel can also FAIL init outright (not just block) while
        # recovering; that state is retryable only from a fresh process
        # (jax caches the failed backend) — exit 3 so a supervisor can
        # relaunch us (scripts/tpu_session_loop.sh retries on rc=3)
        log(f'backend unavailable (retryable): {e}')
        return 3
    disarm_stop_watchdog()
    log(f'devices: {devs}')
    if jax.default_backend() == 'cpu':
        # jax can also fall back to CPU silently when the tunnel's plugin
        # fails init — that's the same retryable condition as the
        # RuntimeError above, not a terminal config error. Any non-cpu
        # name is the chip (the plugin platform may be named 'axon', not
        # 'tpu' — VERDICT r3 missing #1)
        log('backend is cpu (tunnel down? retryable) — exiting 3')
        return 3

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))  # repo root (bench, package)
    sys.path.insert(0, here)                   # scripts/ (tpu_checks)

    # pin the probe fingerprint to the code THIS session loads: a commit
    # landing mid-session must not relabel old-code measurements with the
    # new tree hash. ignore_env: a stale SE3_TPU_CODE_REV inherited from
    # the launching shell must not win over the real git lookup. The
    # eager package import in the same breath makes the pinned rev the
    # code actually in memory for every later stage.
    import tpu_probe
    rev = tpu_probe.package_fingerprint(ignore_env=True)
    if rev:
        os.environ['SE3_TPU_CODE_REV'] = rev
        log(f'code_rev pinned: {rev}')
    else:
        # git lookup failed: a stale inherited pin must not win either
        os.environ.pop('SE3_TPU_CODE_REV', None)
        log('code_rev unavailable (git lookup failed); env pin cleared')
    import se3_transformer_tpu  # noqa: F401 - eager load at the pinned rev

    # persist compiles across session relaunches: the tunnel can die
    # mid-session and every recompile over it costs minutes
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    log(f'compilation cache: {enable_compilation_cache()}')

    failed = [False]
    tunnel_died = [False]

    def note_failure(tb: str):
        # a mid-session tunnel death (the chip lease is gone, compiles
        # fail UNAVAILABLE / broken pipe) is RETRYABLE from a fresh
        # process — exit 3 so the session loop relaunches, instead of
        # rc=2 which ends the loop with stages uncollected
        # shared classifier (helpers): a deterministic HBM OOM is NOT a
        # tunnel death even when the axon client wraps it in a
        # remote_compile error — relaunching would just re-pay the
        # compile and OOM again, forever (the b=4-probe cycle of 19:14Z).
        # RELAUNCH_NEEDED is the explicit poisoned-allocator signal
        # (tpu_probe's post-OOM canary): the failed work is already
        # durably recorded, only a fresh process can allocate again.
        from se3_transformer_tpu.utils.helpers import (
            is_oom_error, is_tunnel_error,
        )
        if 'relaunch_needed' in tb.lower():
            tunnel_died[0] = True
            return
        if is_tunnel_error(tb):
            tunnel_died[0] = True
            return
        if is_oom_error(tb):
            # an OOM that poisoned the allocator dooms every later
            # stage in this process — canary-probe and relaunch if so
            try:
                import jax.numpy as jnp
                (jnp.zeros((8,), jnp.float32) + 1).block_until_ready()
            except Exception:  # noqa: BLE001
                log('allocator poisoned after OOM; relaunching')
                tunnel_died[0] = True

    def run_stage(title, fn, fatal=True):
        """One crash-isolated stage: log the banner, run fn, classify any
        failure (tunnel death => the caller aborts with rc=3; ordinary
        failure => failed, keep gathering data; fatal=False failures are
        logged only). Returns True when remaining stages may proceed."""
        log(f'--- {title} ---')
        try:
            fn()
        except Exception:
            tb = traceback.format_exc()
            if fatal:
                # classify tunnel deaths only for fatal stages: a death
                # in a trailing non-fatal stage (profile) must NOT turn
                # a session whose deliverables are already saved into an
                # rc=3 full relaunch
                note_failure(tb)
                failed[0] = True
            log(f'{title} FAILED{"" if fatal else " (non-fatal)"}:\n' + tb)
        if tunnel_died[0]:
            log('tunnel died; abandoning remaining stages (retryable)')
            return False
        return True

    def save_bench(rec):
        # persist to the repo so the numbers survive a tunnel death in a
        # later stage. JSONL append: a crash mid-write can only lose the
        # line being written, never earlier sessions' records — and an IO
        # problem must not mark a completed bench as failed. The SCHEMA
        # gate is different: an on-chip record without equivariance_l2
        # raises OUT of this function (VERDICT r4 next #5) — the stage
        # fails loudly and the record stays in the log only.
        from _flagship_common import validate_bench_record
        validate_bench_record(rec)
        try:
            import json
            path = os.path.join(os.path.dirname(here),
                                'BENCH_SESSION.jsonl')
            with open(path, 'a') as f:
                f.write(json.dumps(rec) + '\n')
        except Exception as e:
            log(f'save_bench warning (bench itself succeeded): {e}')

    def stage_kernel_smoke():
        import kernel_smoke
        if kernel_smoke.main() != 0:
            failed[0] = True
            log('kernel_smoke: FAILURES (continuing to gather data)')
        else:
            log('kernel_smoke: all pass')

    def make_bench_stage(fast, batch=None, edge_chunks=None, cb16=False):
        def stage():
            import bench
            if batch is not None:
                os.environ['SE3_TPU_BENCH_BATCH'] = str(batch)
                # the probe-elected chunk setting travels with the
                # batch: the bench must run the program the probe
                # proved fits (0 = unchunked)
                if edge_chunks is not None:
                    os.environ['SE3_TPU_BENCH_CHUNKS'] = str(edge_chunks)
                # the reduced twin DOES run for batched records now: its
                # compile is jit-cached from this session's bench_fast
                # stage (identical twin config), and a null
                # equivariance_l2 would be refused by the schema gate
                # (VERDICT r4 next #5 — the round-4 b=2/ec=8 nulls)
            if cb16:
                # conv_bf16 A/B arm (VERDICT r4 next #2): same recipe,
                # bf16-STORED equivariant operands, labelled cb16
                os.environ['SE3_TPU_BENCH_CB16'] = '1'
            try:
                rec = bench.main('tpu', fast=fast)
                log(f'bench fast={fast} batch={batch or 1} '
                    f'cb16={cb16}: {rec}')
                save_bench(rec)
            finally:
                if batch is not None:
                    os.environ.pop('SE3_TPU_BENCH_BATCH', None)
                    os.environ.pop('SE3_TPU_BENCH_CHUNKS', None)
                    # NOTE: SE3_TPU_BENCH_EQ deliberately NOT popped —
                    # this stage no longer sets it, and popping would
                    # erase an operator-provided opt-in for later stages
                if cb16:
                    os.environ.pop('SE3_TPU_BENCH_CB16', None)
        return stage

    def stage_baselines():
        import run_baselines
        out_path = os.path.join(os.path.dirname(here), 'BASELINES_TPU.json')
        args = ['--steps', '5', '--out', out_path]
        if 'convergence' in active_stage_keys:
            # the convergence stage reruns the two flagship configs at 50
            # steps and merge-on-write replaces the 5-step rows — running
            # them here too would double the session's costliest configs
            from se3_transformer_tpu.training.recipes import RECIPES
            rest = [nm for nm in RECIPES
                    if nm not in ('flagship', 'flagship_fast')]
            args += ['--configs'] + rest
        run_baselines.main(args)
        log(f'run_baselines: completed ({out_path})')

    def stage_convergence():
        # VERDICT r4 next #4: >=50 flagship steps so the banked rows carry
        # a real convergence signal (loss trajectory + grad norms), not a
        # 5-step blip. Merge-on-write keeps the other configs' rows.
        import run_baselines
        out_path = os.path.join(os.path.dirname(here), 'BASELINES_TPU.json')
        run_baselines.main(['--steps', '50',
                            '--configs', 'flagship', 'flagship_fast',
                            '--out', out_path])
        log(f'run_baselines convergence (50 steps): completed ({out_path})')

    probe_path = os.path.join(os.path.dirname(here), 'PROBE_TPU.jsonl')

    def stage_probe():
        import tpu_probe
        # --skip-done: the loop re-runs this stage after every tunnel
        # death; already-measured points must not burn another cycle.
        # The non-reversible arm stays off (--nonrev) — its compile
        # killed the tunnel at 12:51Z and 13:29Z
        tpu_probe.main(['--steps', '3', '--fast', '--skip-done',
                        '--batches', '2', '4', '8'])
        log('tpu_probe: completed (PROBE_TPU.jsonl)')

    def stage_batched_record():
        best = _best_probe_batch(probe_path)
        if best is None:
            log('no fitting batch>1 probe point; skipping batched record')
        else:
            b, ec = best
            make_bench_stage(fast=True, batch=b, edge_chunks=ec)()

    def stage_block_ab():
        """VERDICT r4 next #9: one same-session confirmation pair for the
        (512,16) conservative forward-block default — the round-4
        adoption rested on A/Bs under tunnel noise (2.3x spread on
        identical code). Both arms run back-to-back in THIS session:
        default (the 7 MiB picker's (512,16)) vs the pre-adoption
        (512,8). The kernel jit wrappers' caches are cleared between
        arms — the env override is read at trace time, so a stale traced
        kernel would silently measure the same program twice (the
        retired kernel_tune.py sweep learned this the hard way)."""
        import json
        import bench
        # the shared helper (also used by bench/engine/tune_kernels)
        # clears the attention caches too — a local subset copy would
        # drift exactly the way the round-4 helpers review called out
        from se3_transformer_tpu.kernels.tuning import clear_kernel_caches

        path = os.path.join(os.path.dirname(here), 'BLOCK_AB.jsonl')
        # BOTH arms pinned via env override (the highest-priority path):
        # with the measured table now in front of the heuristic, an
        # unpinned "default" arm would silently measure whatever entry a
        # previous tune stage promoted — mislabeling the A/B
        arms = [('default_512_16', {'SE3_TPU_BLOCK_E': '512',
                                    'SE3_TPU_BLOCK_IF': '16'}),
                ('override_512_8', {'SE3_TPU_BLOCK_E': '512',
                                    'SE3_TPU_BLOCK_IF': '8'})]
        for arm, env in arms:
            saved = {k: os.environ.pop(k) for k in list(os.environ)
                     if k.startswith('SE3_TPU_BLOCK_')}
            os.environ.update(env)
            try:
                clear_kernel_caches()
                rec = bench.main('tpu', fast=False)
                rec['arm'] = arm
                rec['override_env'] = env
                rec['session'] = 'same_session_pair'
                with open(path, 'a') as f:
                    f.write(json.dumps(rec) + '\n')
                log(f'block_ab {arm}: {rec["value"]} '
                    f'({rec["step_ms"]} ms/step)')
            finally:
                for k in list(os.environ):
                    if k.startswith('SE3_TPU_BLOCK_'):
                        os.environ.pop(k)
                os.environ.update(saved)
        clear_kernel_caches()

    def stage_kernel_tune():
        """END-TO-END autotune (scripts/tune_kernels.py — supersedes the
        retired standalone kernel_tune.py sweep whose rankings were
        measured opposite to end-to-end): candidates run through the
        real bench step in alternating A/B pairs; winners land in the
        persistent shape-keyed table (kernels/tuning.py) and the next
        bench stages consult them (their records carry kernel_tuning).
        In-process by construction, so it cannot deadlock against our
        own single-client tunnel claim."""
        import tune_kernels
        rc = tune_kernels.main(
            ['--out', os.path.join(os.path.dirname(here), 'TUNE.jsonl'),
             '--steps', '10', '--pairs', '2', '--max-candidates', '4'])
        log(f'tune_kernels: completed rc={rc} (TUNE.jsonl)')
        if rc:
            # the tuner's gate is its exit code (a promoted entry that
            # failed the adoption-proof re-trace, or candidate errors);
            # swallowing it would record a failed sweep as a green stage
            raise RuntimeError(f'tune_kernels exited rc={rc}')

    def stage_tpu_checks():
        import tpu_checks
        tpu_checks.main()
        log('tpu_checks: completed')

    def stage_obs_summary():
        """Render this session's banked records into the round-close
        summary shape (observability.report): best-of-session per metric
        label, outlier flags, best single window — the artifact the
        round-close process used to hand-assemble from comment blocks.
        Filtered to the pinned code_rev so stale-build rows stay out."""
        import json
        from se3_transformer_tpu.observability.report import (
            load_jsonl, summarize_bench_records,
        )
        root = os.path.dirname(here)
        recs = []
        for name in ('BENCH_SESSION.jsonl', 'BLOCK_AB.jsonl'):
            p = os.path.join(root, name)
            if os.path.exists(p):
                recs += load_jsonl(p)
        rev = os.environ.get('SE3_TPU_CODE_REV')
        summary = summarize_bench_records(recs, code_rev=rev)
        if not summary['groups'] and rev:
            # nothing banked under this rev (e.g. every bench stage died)
            # — summarize everything rather than write an empty artifact
            summary = summarize_bench_records(recs)
        out = os.path.join(root, 'SESSION_SUMMARY.json')
        with open(out, 'w') as f:
            json.dump(summary, f, indent=1)
        log(f'obs_summary: {len(summary["groups"])} metric groups '
            f'-> {out}')

    def stage_profile():
        """Flagship trace + per-scope device-time attribution
        (observability.profiling — supersedes the retired
        stage_timings.py wall-clock stage: one traced step attributes
        every MODEL_SCOPES region at once instead of re-jitting each
        stage as its own upper-bound program) + the cost ledger, banked
        as schema'd cost/profile records in PROFILE_SESSION.jsonl."""
        import numpy as np
        import jax.numpy as jnp
        from se3_transformer_tpu.training.recipes import flagship
        module = flagship()
        rng = np.random.RandomState(0)
        feats = jnp.asarray(rng.normal(size=(1, 1024, 64)), jnp.float32)
        coors = jnp.asarray(rng.normal(size=(1, 1024, 3)) * 3, jnp.float32)
        mask = jnp.ones((1, 1024), bool)
        params = jax.jit(module.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        compiled = jax.jit(lambda p, c: module.apply(
            {'params': p}, feats, c, mask=mask, return_type=1)) \
            .lower(params, coors).compile()
        jax.block_until_ready(compiled(params, coors))  # warm dispatch
        from se3_transformer_tpu.observability.costs import cost_payload
        from se3_transformer_tpu.observability.profiling import (
            capture_step_profile, profile_payload,
        )
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        hlo_text = compiled.as_text()
        cost = cost_payload(compiled, label='flagship_fwd,n=1024,dim=64',
                            hlo_text=hlo_text)
        capture_step_profile(lambda: compiled(params, coors),
                             log_dir='/tmp/flagship_trace', steps=2)
        prof = profile_payload(
            '/tmp/flagship_trace', label='flagship_fwd,n=1024,dim=64',
            hlo_text=hlo_text, flops_per_step=cost['flops'], steps=2)
        write_record_stream(
            os.path.join(os.path.dirname(here), 'PROFILE_SESSION.jsonl'),
            f'session_{os.getpid()}',
            [dict(cost, kind='cost'), dict(prof, kind='profile')],
            append=True)   # the bank is append-only like BENCH_SESSION
        log(f'profile: /tmp/flagship_trace written; coverage '
            f'{prof["coverage"]:.0%}, scopes '
            f'{ {s: st["share"] for s, st in prof["scopes"].items()} }, '
            f'peak {cost["peak_bytes"] / 2**30:.2f} GiB '
            f'-> PROFILE_SESSION.jsonl')

    def stage_perf_gate():
        """The enforcement pass (scripts/perf_gate.py): this session's
        banked records vs the committed PERF_BUDGETS.json. A breach
        fails the stage — regressions exit the session non-zero instead
        of waiting for a human to read the summary."""
        import perf_gate
        root = os.path.dirname(here)
        paths = [p for p in (
            os.path.join(root, name) for name in
            ('BENCH_SESSION.jsonl', 'BLOCK_AB.jsonl', 'WIDTH_TABLE.jsonl',
             'PROFILE_SESSION.jsonl')) if os.path.exists(p)]
        rc = perf_gate.main(paths)
        log(f'perf_gate: rc={rc}')
        if rc:
            raise RuntimeError(f'perf gate flagged a regression (rc={rc})')

    stages = [
        ('smoke', 'kernel_smoke (Mosaic lowering + numerics)',
         stage_kernel_smoke, True),
        ('bench', 'flagship bench', make_bench_stage(fast=False), True),
        ('bench_fast',
         'flagship bench (fast: shared radial + fuse_basis + bf16)',
         make_bench_stage(fast=True), True),
        ('bench_cb16',
         'flagship bench (fast + conv_bf16: bf16-stored equivariant '
         'operands — the round-5 A/B arm)',
         make_bench_stage(fast=True, cb16=True), True),
        ('bench_cb16_cons',
         'flagship bench (conservative + conv_bf16: the plain kernel '
         'streams the biggest V2 tensor, so the bandwidth win peaks here)',
         make_bench_stage(fast=False, cb16=True), True),
        ('baselines', 'baseline configs', stage_baselines, True),
        ('convergence', 'flagship 50-step convergence rows',
         stage_convergence, True),
        ('probe', 'knob/width/batch probe (edge_chunks x dim x batch)',
         stage_probe, True),
        ('batched', 'batched flagship record (best batch from probe)',
         stage_batched_record, True),
        ('block_ab',
         'conservative (512,16) vs (512,8) same-session block A/B',
         stage_block_ab, True),
        ('tune', 'end-to-end kernel autotune (shape-keyed table)',
         stage_kernel_tune, True),
        ('checks', 'tpu_checks', stage_tpu_checks, True),
        ('profile', 'flagship profile + per-scope attribution',
         stage_profile, False),
        ('obs_summary', 'session summary (observability.report)',
         stage_obs_summary, False),
        ('perf_gate', 'perf-regression gate (PERF_BUDGETS.json)',
         stage_perf_gate, True),
    ]
    # SE3_TPU_SESSION_STAGES=smoke,bench,bench_fast,baselines runs a
    # focused session (e.g. an A/B after a perf commit) without redoing
    # the already-banked probe/tune/checks sweeps
    only = os.environ.get('SE3_TPU_SESSION_STAGES')
    if only:
        keep = {s.strip() for s in only.split(',') if s.strip()}
        unknown = keep - {key for key, *_ in stages}
        if unknown:
            log(f'WARNING: unknown stage keys ignored: {sorted(unknown)}')
        stages = [s for s in stages if s[0] in keep]
        if keep and not stages:
            # every requested key was a typo: running zero stages and
            # exiting 0 would report success for a session that did
            # nothing (ADVICE r4 #2)
            log('ERROR: stage filter matched no stages — aborting')
            return 2
        log(f'stage filter: {[key for key, *_ in stages]}')
    # closures (stage_baselines) consult this to avoid duplicating work
    # another active stage owns
    active_stage_keys = {key for key, *_ in stages}
    stages = [(title, fn, fatal) for _key, title, fn, fatal in stages]
    for title, fn, fatal in stages:
        if not run_stage(title, fn, fatal=fatal):
            return 3

    log(f'session done ({"FAILED" if failed[0] else "ok"}), releasing chip')
    return 2 if failed[0] else 0


if __name__ == '__main__':
    sys.exit(main())
