"""One-shot patient TPU session: wait for the chip, validate, benchmark.

The axon tunnel is single-client and wedges when a claim-holding process
is killed. So this script NEVER times itself out: if the chip is busy or
wedged it blocks harmlessly at backend init (a blocked waiter holds no
claim) and proceeds the moment the lease frees up. Once it has the chip
it runs the full on-chip suite in ONE process — tpu_checks (equivariance
at f32/bf16, fused Pallas kernel numerics + speedup) and then the
flagship benchmark — and exits cleanly so the chip is released.

Usage: python scripts/tpu_session.py [logfile]
"""
import datetime
import os
import sys
import traceback

LOG = sys.argv[1] if len(sys.argv) > 1 else '/tmp/tpu_session.log'


def log(msg):
    stamp = datetime.datetime.utcnow().strftime('%H:%M:%S')
    line = f'[{stamp}] {msg}'
    print(line, flush=True)
    with open(LOG, 'a') as f:
        f.write(line + '\n')


def main():
    log(f'pid={os.getpid()} waiting for TPU (blocking, no timeout)...')
    import jax
    devs = jax.devices()
    log(f'devices: {devs}')
    if jax.default_backend() != 'tpu':
        log('backend is not tpu — aborting (nothing to validate)')
        return 1

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))  # repo root (bench, package)
    sys.path.insert(0, here)                   # scripts/ (tpu_checks)

    failed = False

    log('--- tpu_checks ---')
    try:
        import tpu_checks as tc
        tc.main()
        log('tpu_checks: completed')
    except Exception:
        failed = True
        log('tpu_checks FAILED:\n' + traceback.format_exc())

    log('--- flagship bench ---')
    try:
        import bench
        bench.main('tpu')
        log('bench: completed')
    except Exception:
        failed = True
        log('bench FAILED:\n' + traceback.format_exc())

    log(f'session done ({"FAILED" if failed else "ok"}), releasing chip')
    return 2 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
