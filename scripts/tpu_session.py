"""One-shot patient TPU session: wait for the chip, validate, benchmark.

The axon tunnel is single-client and wedges when a claim-holding process
is killed. So this script NEVER times itself out: if the chip is busy or
wedged it blocks harmlessly at backend init (a blocked waiter holds no
claim) and proceeds the moment the lease frees up. Once it has the chip
it runs the full on-chip suite in ONE process — the kernel_smoke canary,
the flagship benchmark (the round's key deliverable, so it runs before
the longer checks in case the tunnel dies mid-session), tpu_checks
(equivariance at f32/bf16, fused Pallas kernel numerics + speedup),
stage timings, baseline configs, profile — and exits cleanly so the
chip is released.

Usage: python scripts/tpu_session.py [logfile]
"""
import datetime
import os
import sys
import traceback

LOG = sys.argv[1] if len(sys.argv) > 1 else '/tmp/tpu_session.log'


def log(msg):
    stamp = datetime.datetime.utcnow().strftime('%H:%M:%S')
    line = f'[{stamp}] {msg}'
    print(line, flush=True)
    with open(LOG, 'a') as f:
        f.write(line + '\n')


def main():
    log(f'pid={os.getpid()} waiting for TPU (blocking, no timeout)...')
    import jax
    try:
        devs = jax.devices()
    except RuntimeError as e:
        # the tunnel can also FAIL init outright (not just block) while
        # recovering; that state is retryable only from a fresh process
        # (jax caches the failed backend) — exit 3 so a supervisor can
        # relaunch us (scripts/tpu_session_loop.sh retries on rc=3)
        log(f'backend unavailable (retryable): {e}')
        return 3
    log(f'devices: {devs}')
    if jax.default_backend() != 'tpu':
        # jax can also fall back to CPU silently when the tunnel's plugin
        # fails init — that's the same retryable condition as the
        # RuntimeError above, not a terminal config error
        log('backend is not tpu (tunnel down? retryable) — exiting 3')
        return 3

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))  # repo root (bench, package)
    sys.path.insert(0, here)                   # scripts/ (tpu_checks)

    # persist compiles across session relaunches: the tunnel can die
    # mid-session and every recompile over it costs minutes
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    log(f'compilation cache: {enable_compilation_cache()}')

    failed = False
    tunnel_died = [False]

    def note_failure(tb: str):
        # a mid-session tunnel death (the chip lease is gone, compiles
        # fail UNAVAILABLE / broken pipe) is RETRYABLE from a fresh
        # process — exit 3 so the session loop relaunches, instead of
        # rc=2 which ends the loop with stages uncollected
        low = tb.lower()
        if any(sig in low for sig in ('unavailable', 'broken pipe',
                                      'network error', 'connection refused',
                                      'remote_compile')):
            tunnel_died[0] = True

    log('--- kernel_smoke (Mosaic lowering + numerics) ---')
    try:
        import kernel_smoke
        rc = kernel_smoke.main()
        if rc != 0:
            failed = True
            log('kernel_smoke: FAILURES (continuing to gather data)')
        else:
            log('kernel_smoke: all pass')
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('kernel_smoke FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    import bench

    def save_bench(rec):
        # persist to the repo so the numbers survive a tunnel death in a
        # later stage. JSONL append: a crash mid-write can only lose the
        # line being written, never earlier sessions' records — and a
        # save problem must not mark a completed bench as failed
        try:
            import json
            path = os.path.join(os.path.dirname(here),
                                'BENCH_SESSION.jsonl')
            with open(path, 'a') as f:
                f.write(json.dumps(rec) + '\n')
        except Exception as e:
            log(f'save_bench warning (bench itself succeeded): {e}')

    log('--- flagship bench ---')
    try:
        rec = bench.main('tpu', fast=False)
        log(f'bench: {rec}')
        save_bench(rec)
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('bench FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- flagship bench (fast: shared radial + fuse_basis + bf16) ---')
    try:
        rec = bench.main('tpu', fast=True)
        log(f'bench fast: {rec}')
        save_bench(rec)
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('bench fast FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- tpu_checks ---')
    try:
        import tpu_checks as tc
        tc.main()
        log('tpu_checks: completed')
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('tpu_checks FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- stage timings (flagship bench config) ---')
    try:
        import stage_timings
        rep = stage_timings.main([])
        log(f'stage_timings: {rep["stage_ms"]}')
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('stage_timings FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- baseline configs ---')
    try:
        import run_baselines
        out_path = os.path.join(os.path.dirname(here), 'BASELINES_TPU.json')
        run_baselines.main(['--steps', '5', '--out', out_path])
        log(f'run_baselines: completed ({out_path})')
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('run_baselines FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- knob/width probe (edge_chunks x dim) ---')
    try:
        import tpu_probe
        tpu_probe.main(['--steps', '3'])
        log('tpu_probe: completed (PROBE_TPU.jsonl)')
    except Exception:
        failed = True
        tb = traceback.format_exc()
        note_failure(tb)
        log('tpu_probe FAILED:\n' + tb)

    if tunnel_died[0]:
        log('tunnel died; abandoning remaining stages (retryable)')
        return 3

    log('--- flagship profile ---')
    try:
        import numpy as np
        import jax.numpy as jnp
        from se3_transformer_tpu.training.recipes import flagship
        module = flagship()
        rng = np.random.RandomState(0)
        feats = jnp.asarray(rng.normal(size=(1, 1024, 64)), jnp.float32)
        coors = jnp.asarray(rng.normal(size=(1, 1024, 3)) * 3, jnp.float32)
        mask = jnp.ones((1, 1024), bool)
        params = jax.jit(module.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        fwd = jax.jit(lambda p, c: module.apply(
            {'params': p}, feats, c, mask=mask, return_type=1))
        jax.block_until_ready(fwd(params, coors))  # compile
        from se3_transformer_tpu.utils.observability import profile_trace
        with profile_trace('/tmp/flagship_trace'):
            jax.block_until_ready(fwd(params, coors))
        log('profile: /tmp/flagship_trace written')
    except Exception:
        log('profile FAILED (non-fatal):\n' + traceback.format_exc())

    if tunnel_died[0]:
        log('session lost the tunnel mid-way, releasing chip (retryable)')
        return 3
    log(f'session done ({"FAILED" if failed else "ok"}), releasing chip')
    return 2 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
