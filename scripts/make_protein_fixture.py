"""Generate tests/fixtures/mini_sidechainnet.pkl — a miniature protein
dataset in the EXACT sidechainnet pickle layout the converter consumes
(training/sidechainnet.py; reference trains on the real thing via
`scn.load`, denoise.py:40-76).

No experimental data ships in this offline environment, so the fixture
is HONEST SYNTHETIC GEOMETRY on REAL SEQUENCES: genuine protein
sequences (ubiquitin, insulin B chain, Trp-cage TC5b, villin HP36) with
backbone atoms placed by NeRF internal-coordinate chain extension using
ideal Engh–Huber bond lengths/angles and per-residue (phi, psi) drawn
from each protein's approximate secondary-structure pattern. The result
has realistic bond geometry, chain connectivity, compact helical/
extended segments, 14-atom frames (N, CA, C, O real; sidechain slots
zero-padded exactly like sidechainnet does for missing atoms), and
'-'-masked unresolved residues with zeroed coordinates (ubiquitin's
flexible C-terminal tail) — everything the converter's code paths need
from real data.

Deterministic: running this script reproduces the committed pickle
byte-for-byte (protocol pinned, no randomness).
"""
import os
import pickle

import numpy as np

# Engh & Huber ideal backbone internal coordinates (Å, degrees)
B_N_CA, B_CA_C, B_C_N, B_C_O = 1.458, 1.525, 1.329, 1.231
A_N_CA_C, A_CA_C_N, A_C_N_CA, A_CA_C_O = 111.2, 116.2, 121.7, 120.8

# (phi, psi) by secondary-structure letter
SS_ANGLES = {'H': (-57.0, -47.0),    # alpha helix
             'E': (-135.0, 135.0),   # beta strand
             'C': (-80.0, 150.0)}    # coil / PPII-ish

# real sequences + approximate secondary-structure strings (same length)
PROTEINS = {
    # ubiquitin (human, 76 aa; beta-grasp fold approximated by its
    # strand/helix segments); the 4-residue LRGG tail is flexible and
    # marked unresolved ('-') as it often is in crystal structures
    'ubiquitin': (
        'MQIFVKTLTGKTITLEVEPSDTIENVKAKIQDKEGIPPDQQRLIFAGKQLEDGRTLSDYNIQKE'
        'STLHLVLRLRGG',
        'EEEEEEECCCCEEEEEECCCCCHHHHHHHHHHHHHCCCCCCEEEEECCCCCCHHHCCCCCEEEE'
        'EECCEEEECCCC',
        4),
    # insulin B chain (human, 30 aa): central helix, extended ends
    'insulin_b': ('FVNQHLCGSHLVEALYLVCGERGFFYTPKT',
                  'CCCCCHHHHHHHHHHHHHHCCCEECCCCCC',
                  0),
    # Trp-cage TC5b (designed 20-aa miniprotein, mostly helical)
    'trp_cage': ('NLYIQWLKDGGPSSGRPPPS',
                 'HHHHHHHHHCCCCCCCCCCC',
                 0),
    # villin headpiece HP36 (36 aa, three short helices)
    'villin_hp36': ('MLSDEDFKAVFGMTRSAFANLPLWKQQNLKKEKGLF',
                    'CCCHHHHHHHHCCCHHHHHCCCCHHHHHHHHHHHCC',
                    0),
}

ATOMS_PER_RESIDUE = 14


def place_atom(a, b, c, bond, angle_deg, torsion_deg):
    """NeRF: position D with |CD| = bond, angle(B,C,D) = angle and
    torsion(A,B,C,D) = torsion."""
    ang, tor = np.deg2rad(angle_deg), np.deg2rad(torsion_deg)
    bc = c - b
    bc = bc / np.linalg.norm(bc)
    n = np.cross(b - a, bc)
    n = n / np.linalg.norm(n)
    m = np.cross(n, bc)
    d = np.array([-bond * np.cos(ang),
                  bond * np.sin(ang) * np.cos(tor),
                  bond * np.sin(ang) * np.sin(tor)])
    return c + d[0] * bc + d[1] * m + d[2] * n


def build_backbone(ss: str) -> np.ndarray:
    """[L, 14, 3] frames: N, CA, C, O placed; sidechain slots zero."""
    L = len(ss)
    phi_psi = [SS_ANGLES[s] for s in ss]
    out = np.zeros((L, ATOMS_PER_RESIDUE, 3))
    # seed residue: N at origin, CA on x, C in the xy plane
    out[0, 0] = (0.0, 0.0, 0.0)
    out[0, 1] = (B_N_CA, 0.0, 0.0)
    ang = np.deg2rad(A_N_CA_C)
    out[0, 2] = out[0, 1] + B_CA_C * np.array(
        [-np.cos(ang), np.sin(ang), 0.0])
    for i in range(1, L):
        n_prev, ca_prev, c_prev = out[i - 1, 0], out[i - 1, 1], out[i - 1, 2]
        psi_prev = phi_psi[i - 1][1]
        n_i = place_atom(n_prev, ca_prev, c_prev, B_C_N, A_CA_C_N, psi_prev)
        ca_i = place_atom(ca_prev, c_prev, n_i, B_N_CA, A_C_N_CA, 180.0)
        c_i = place_atom(c_prev, n_i, ca_i, B_CA_C, A_N_CA_C, phi_psi[i][0])
        out[i, 0], out[i, 1], out[i, 2] = n_i, ca_i, c_i
    # carbonyl O: from (N, CA, C), torsion psi + 180 (trans to next N)
    for i in range(L):
        psi = phi_psi[i][1]
        out[i, 3] = place_atom(out[i, 0], out[i, 1], out[i, 2],
                               B_C_O, A_CA_C_O, psi + 180.0)
    return out


def build_entry(seq, ss, tail_unresolved):
    L = len(seq)
    assert len(ss) == L, (len(ss), L)
    frames = build_backbone(ss)
    msk = ['+'] * L
    for i in range(L - tail_unresolved, L):
        msk[i] = '-'
        frames[i] = 0.0  # sidechainnet zeroes unresolved residues
    return seq, frames.reshape(L * ATOMS_PER_RESIDUE, 3).astype(
        np.float32), ''.join(msk)


def main(out_path=None):
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = out_path or os.path.join(
        os.path.dirname(here), 'tests', 'fixtures', 'mini_sidechainnet.pkl')
    os.makedirs(os.path.dirname(out_path), exist_ok=True)

    def split(names):
        seqs, crds, msks = [], [], []
        for name in names:
            seq, crd, msk = build_entry(*PROTEINS[name])
            seqs.append(seq)
            crds.append(crd)
            msks.append(msk)
        return {'seq': seqs, 'crd': crds, 'msk': msks}

    data = {
        'train': split(['ubiquitin', 'trp_cage', 'villin_hp36']),
        'valid-10': split(['insulin_b']),
        'test': split(['trp_cage']),
    }
    with open(out_path, 'wb') as f:
        pickle.dump(data, f, protocol=4)
    print(f'wrote {out_path}')
    return out_path


if __name__ == '__main__':
    main()
