"""CPU gate for the streaming flash-attention path (`make flash-smoke`).

Four gates, exit non-zero on any failure:

  1. PARITY — the fuse_pairwise streaming path vs the unfused trunk on
     IDENTICAL parameters must agree within 1e-4 max-abs, for BOTH
     contraction arms (dense CG and so2 banded), under a real node mask
     (padded rows) — the fused path computes the same function, so this
     is roundoff (~1e-7 in practice). Checked through the XLA streaming
     dispatch AND the interpret-mode Pallas kernel, so the kernel body
     itself is gated in tier-1-class time on CPU.
  2. EQUIVARIANCE — the fused path's equivariance L2 must stay under
     1e-4 at num_degrees 2 and 4 (the so2 arm's higher degrees are
     gated by tests/test_flash.py and the so2 sweep).
  3. A/B RECORD — bench.flash_main's fused-vs-unfused train-step A/B
     (step ms both arms, peak HBM from the PR 6 cost ledger, fused
     equivariance) is written as a schema'd `flash` record.
  4. The Makefile target then runs `obs_report --require flash` and
     `perf_gate.py` on the stream, so the committed step-time and
     peak-HBM win budgets judge the fresh numbers.

    python scripts/flash_smoke.py [--metrics FLASH.jsonl] [--steps 6]
"""
import argparse
import json
import os
import sys
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

PARITY_TOL = 1e-4
EQ_TOL = 1e-4


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='streaming flash-attention parity + equivariance + '
                    'A/B record gate')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid flash stream here')
    ap.add_argument('--steps', type=int, default=6)
    args = ap.parse_args(argv)

    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.validation import equivariance_l2

    enable_compilation_cache()
    ok = True
    rng = np.random.RandomState(0)
    n, dim, k = 24, 8, 6
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    # padded batch: the trailing rows are mask=False — parity must hold
    # on the real rows (the left-padded [global, null, self, neighbors]
    # slot order and the masked-slot semantics are exercised together)
    mask = jnp.asarray(np.arange(n) < n - 5)[None]

    kw = dict(dim=dim, depth=1, num_degrees=3, output_degrees=2,
              reduce_dim_out=True, attend_self=True, use_null_kv=True,
              num_neighbors=k, heads=2, dim_head=4,
              shared_radial_hidden=True)
    for backend in ('dense', 'so2'):
        unf = SE3TransformerModule(conv_backend=backend, **kw)
        fus = SE3TransformerModule(conv_backend=backend,
                                   fuse_pairwise=True, **kw)
        params = jax.jit(fus.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        ref = unf.apply({'params': params}, feats, coors, mask=mask,
                        return_type=1)
        for label, mod in (
                (f'{backend}-arm stream', fus),
                (f'{backend}-arm pallas-interpret',
                 SE3TransformerModule(conv_backend=backend,
                                      fuse_pairwise=True,
                                      flash_interpret=True, **kw))):
            out = mod.apply({'params': params}, feats, coors, mask=mask,
                            return_type=1)
            diff = float(jnp.abs(out - ref).max())
            print(f'{label} parity vs unfused: {diff:.3g}')
            if not diff < PARITY_TOL:
                print(f'FAIL: {label} parity {diff} >= {PARITY_TOL}')
                ok = False

    for deg in (2, 4):
        fus = SE3TransformerModule(fuse_pairwise=True,
                                   **{**kw, 'num_degrees': deg})
        params = jax.jit(fus.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        eq = equivariance_l2(fus, params, feats, coors, mask)
        print(f'fused equivariance L2 at num_degrees={deg}: {eq:.3g}')
        if not eq < EQ_TOL:
            print(f'FAIL: fused equivariance {eq} >= {EQ_TOL} at '
                  f'num_degrees={deg}')
            ok = False

    # the A/B runs in a FRESH subprocess: the parity/equivariance stage
    # above leaves this process with a dozen compiled models' allocator
    # and thread-pool state, which measurably (and one-sidedly) taxes
    # the streaming arm's chunked windows — a clean `python bench.py
    # --flash` is both the documented entry point and the honest timer
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'bench.py'), '--flash',
         '--steps', str(args.steps)],
        capture_output=True, text=True, cwd=REPO)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(f'FAIL: bench.py --flash exited {proc.returncode}')
        return 1
    record = json.loads(proc.stdout.strip().splitlines()[-1])

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(kind='flash', label=record['metric'],
                    value=record['value'], unit=record['unit'],
                    timing=record['timing'],
                    **{key: record[key] for key in (
                        'fused_step_ms', 'unfused_step_ms',
                        'fused_vs_unfused', 'parity_l2',
                        'equivariance_l2_fused', 'peak_hbm_fused',
                        'peak_hbm_unfused', 'hbm_unfused_vs_fused',
                        'cost')})
        write_record_stream(args.metrics,
                            f'flash_smoke_{uuid.uuid4().hex[:8]}', [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    summary = dict(ok=ok,
                   fused_vs_unfused=record['fused_vs_unfused'],
                   hbm_unfused_vs_fused=record['hbm_unfused_vs_fused'],
                   equivariance_l2_fused=record['equivariance_l2_fused'])
    print(json.dumps(summary))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
