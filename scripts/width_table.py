"""Max-width-per-chip-count table: flagship memory vs (dim, devices).

VERDICT r3 next #4: BASELINE.md's tracked flagship label is
SE3Transformer(dim=512, depth=6, num_degrees=4) at 1024 nodes, but
nothing had ever instantiated dim>=128 — the multi-chip memory story was
untested theory. This harness compiles the FULL sharded training step
(sp-sharded nodes + tp-sharded radial weights + edge_chunks, the same
program dryrun_multichip validates) at the label shape n=1024/k=32 over
an N-virtual-CPU-device mesh and records XLA's per-shard memory analysis
(SPMD emits one per-device program, so temp+argument sizes ARE the
per-chip footprint estimate). Optionally executes one step at a reduced
node count to prove the label-width program actually runs end to end.

The numbers are XLA:CPU SPMD estimates — layouts/fusion differ from TPU
(measured on-chip: dim=64 needs the remat recipe to fit 16 GB, which
matches this harness's estimate within ~20%) — so the table is stated
as the scaling story, with the dim=64 single-chip point anchored by the
real-HBM measurements in docs/STATUS.md.

Usage (fresh process per device count — the virtual device count is
fixed at backend init):
    python scripts/width_table.py --devices 8 --dims 512 [--exec-dim 512]
    python scripts/width_table.py --devices 1 --dims 64 128
    python scripts/width_table.py --devices 8 --weak-scaling --ab \
        [--metrics COMM.jsonl]
    python scripts/width_table.py --devices 8 --mesh-sweep \
        [--points 2,2,2 4,1,2]
Writes crash-safe JSONL to WIDTH_TABLE.jsonl (append). --weak-scaling
rows carry a `comm` payload (collective classes/bytes + the full-width
all-gather scan of the traced HLO); --ab measures the overlapped+sparse
vs serialized+dense comm arms in one process (docs/PERF.md's table).
--mesh-sweep instead walks every (dp, sp, tp) mesh point covering the
device count through the composed-parallelism route (params+opt state
over (dp, tp), ring sp when sp>1, donation pinned through explicit
in/out shardings) and banks schema'd `mesh_sweep` records — per-axis
collective split + per-shard memory — to MESH_SWEEP.jsonl for
scripts/perf_gate.py's per-axis budgets.
"""
import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _setup(n_devices: int):
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={n_devices}'
        ).strip()
    import jax
    jax.config.update('jax_platforms', 'cpu')
    return jax


def _flagship_step(jax, mesh, dim, n, k, tp, compile_only=True):
    """Lower + compile the exact bench.py training program (flagship_fast
    recipe, denoise objective, adam) over the mesh; returns (compiled,
    compile_s, example_args)."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from se3_transformer_tpu.parallel.sharding import (
        make_sharded_train_step, shard_params,
    )
    from se3_transformer_tpu.training import recipes

    module = recipes.RECIPES['flagship_fast'](
        dim=dim, num_neighbors=k, output_degrees=2, reduce_dim_out=True)

    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coords = jnp.asarray(
        np.cumsum(rng.normal(size=(1, n, 3)), axis=1), jnp.float32)
    masks = jnp.ones((1, n), bool)

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        loss = (((noised + out) - data['coords']) ** 2).sum(-1).mean()
        return loss, dict()

    # init with abstract eval only — a real init at dim=512 would
    # EXECUTE the forward on CPU (minutes to hours); eval_shape gives the
    # param tree structure for lowering, and zeros fill it for execution
    init_shapes = jax.eval_shape(
        lambda key: module.init(key, feats, coords, mask=masks,
                                return_type=1),
        jax.random.PRNGKey(0))['params']
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), init_shapes)
    params = shard_params(params, mesh)
    optimizer = optax.adam(1e-4)
    opt_state = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(optimizer.init, params))
    opt_state = jax.tree_util.tree_map(
        lambda v: jax.device_put(v, NamedSharding(mesh, P())), opt_state)

    step = make_sharded_train_step(loss_fn, optimizer, mesh=mesh,
                                   donate=False, tensor_parallel=(tp > 1))

    node_spec = P(None, 'sp', None)
    data = dict(
        seqs=jax.device_put(feats, NamedSharding(mesh, node_spec)),
        coords=jax.device_put(coords, NamedSharding(mesh, node_spec)),
        masks=jax.device_put(masks, NamedSharding(mesh, P(None, 'sp'))))
    key = jax.random.PRNGKey(1)

    t0 = time.time()
    compiled = step.lower(params, opt_state, data, key).compile()
    compile_s = time.time() - t0
    return compiled, compile_s, (params, opt_state, data, key)


def measure_point(jax, mesh, dim, n, k, tp, execute=False):
    compiled, compile_s, args = _flagship_step(jax, mesh, dim, n, k, tp)
    rec = dict(dim=dim, n=n, k=k, compile_s=round(compile_s, 1))
    try:
        # the schema'd cost ledger (observability.costs): flops + the
        # arg/output/temp split scripts/perf_gate.py budgets; the
        # legacy row fields below derive from THE SAME ledger (one
        # memory_analysis call, one representation — they can't drift)
        from se3_transformer_tpu.observability.costs import cost_payload
        rec['cost'] = cost_payload(compiled,
                                   label=f'width,dim={dim},n={n},k={k}')
        mem = rec['cost']['memory']
        for name, legacy in (('temp_bytes', 'temp_size_mb'),
                             ('argument_bytes', 'argument_size_mb'),
                             ('output_bytes', 'output_size_mb'),
                             ('alias_bytes', 'alias_size_mb'),
                             ('generated_code_bytes',
                              'generated_code_size_mb')):
            if name in mem:
                rec[legacy] = round(mem[name] / 2**20, 1)
        # per-shard footprint estimate: live temporaries + resident
        # arguments (params+opt state+batch shard). alias'd buffers are
        # counted inside argument size already.
        rec['per_shard_total_gb'] = round(
            (mem['temp_bytes'] + mem['argument_bytes']) / 2**30, 3)
    except Exception as e:  # noqa: BLE001 - accounting is best-effort
        rec['memory_analysis_error'] = f'{type(e).__name__}: {e}'[:200]
    if execute:
        t0 = time.time()
        params, opt_state, data, key = args
        out = compiled(params, opt_state, data, key)
        jax.block_until_ready(out[2])
        rec['exec_step_s'] = round(time.time() - t0, 1)
        rec['loss_finite'] = bool(jax.numpy.isfinite(out[2]))
    return rec


def weak_scaling_point(jax, n_devices, per_device_nodes, dim, k, steps=3,
                       overlap=True, exchange=True):
    """One weak-scaling row (VERDICT r4 next #8): sp=n_devices ring-path
    training step at FIXED per-device node count, executed for wall-clock
    + XLA per-shard memory. All virtual devices share this host's cores,
    so ideal weak scaling here is wall-clock LINEAR in total nodes (not
    flat); the rows record step_s only — the overhead factor
    step_s / (sp * step_s_at_sp1) is derived downstream from the sp=1
    row (docs/PERF.md does this), and per-shard memory should stay
    ~flat (the actual weak-scaling claim).

    overlap/exchange are the PR-5 comm knobs (parallel/ring.py,
    parallel/exchange.py); `--ab` measures both settings of the pair in
    one process so the A/B shares the host. Every row carries a `comm`
    payload — collective classes + bytes and the full-width-all-gather
    scan of THIS row's traced HLO (parallel.exchange.comm_payload)."""
    import time as _time

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from se3_transformer_tpu.parallel.exchange import comm_payload
    from se3_transformer_tpu.parallel.mesh import make_mesh
    from se3_transformer_tpu.parallel.sharding import make_sharded_train_step
    from se3_transformer_tpu.training import recipes

    n = per_device_nodes * n_devices
    mesh = make_mesh(jax.devices()[:n_devices], dp=1, tp=1)
    module = recipes.RECIPES['flagship_fast'](
        dim=dim, num_neighbors=k, output_degrees=2, reduce_dim_out=True,
        depth=1, sequence_parallel='ring', mesh=mesh,
        ring_overlap=overlap, ring_exchange=exchange)

    rng = np.random.RandomState(0)
    node_spec = P(None, 'sp', None)
    feats = jax.device_put(
        jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32),
        NamedSharding(mesh, node_spec))
    coords = jax.device_put(
        jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                    jnp.float32), NamedSharding(mesh, node_spec))
    masks = jax.device_put(jnp.ones((1, n), bool),
                           NamedSharding(mesh, P(None, 'sp')))

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        return (((noised + out) - data['coords']) ** 2).sum(-1).mean(), {}

    params = jax.jit(module.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), feats, coords, mask=masks,
        return_type=1)['params']
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(loss_fn, optimizer, donate=False)
    data = dict(seqs=feats, coords=coords, masks=masks)
    key = jax.random.PRNGKey(1)

    t0 = _time.time()
    compiled = step.lower(params, opt_state, data, key).compile()
    compile_s = _time.time() - t0
    rec = dict(weak_scaling=True, devices=n_devices, sp=n_devices,
               per_device_nodes=per_device_nodes, n=n, dim=dim, k=k,
               depth=1, compile_s=round(compile_s, 1),
               host_cpus=os.cpu_count(), backend='cpu-spmd',
               overlap=overlap, exchange=exchange)
    hlo_text = None
    try:
        hlo_text = compiled.as_text()
        rec['comm'] = comm_payload(
            hlo_text, sp=n_devices, ring_steps=n_devices,
            overlap=overlap, exchange=exchange, full_width_dim=n)
    except Exception as e:  # noqa: BLE001 - accounting is best-effort
        rec['comm_error'] = f'{type(e).__name__}: {e}'[:200]
    try:
        # one ledger, one memory_analysis call; the legacy per-shard
        # fields derive from it so row and cost record cannot disagree
        from se3_transformer_tpu.observability.costs import cost_payload
        rec['cost'] = cost_payload(
            compiled, hlo_text=hlo_text,
            label=f'weak_scaling,sp={n_devices},pdn={per_device_nodes},'
                  f'overlap={overlap},exchange={exchange}')
        mem = rec['cost']['memory']
        rec['per_shard_temp_mb'] = round(mem['temp_bytes'] / 2**20, 1)
        rec['per_shard_total_gb'] = round(
            (mem['temp_bytes'] + mem['argument_bytes']) / 2**30, 3)
    except Exception as e:  # noqa: BLE001 - memory analysis best-effort
        rec['memory_analysis_error'] = f'{type(e).__name__}: {e}'[:200]
    out = compiled(params, opt_state, data, key)  # warmup
    jax.block_until_ready(out[2])
    t0 = _time.time()
    for _ in range(steps):
        key, sub = jax.random.split(key)
        out = compiled(params, opt_state, data, sub)
    jax.block_until_ready(out[2])
    rec['step_s'] = round((_time.time() - t0) / steps, 3)
    rec['loss_finite'] = bool(jax.numpy.isfinite(out[2]))
    return rec


def mesh_sweep_point(jax, dp, sp, tp, per_device_nodes, dim, k, steps=3):
    """One composed-parallelism row (ROADMAP item 4): the dp x sp x tp
    train step at FIXED per-device work (batch dp, nodes
    per_device_nodes * sp), built through the explicit-aliasing route
    (parallel.sharding.composed_state_shardings: params + opt state
    over (dp, tp), step in/out shardings pinned, donation ON — the
    exact configuration the jax-0.4.37 GSPMD donation bug kills
    without the pin) and EXECUTED for wall-clock. The row's `comm`
    block carries the per-mesh-axis collective split
    (parallel.exchange.attribute_collective_axes) the per-axis budgets
    in PERF_BUDGETS.json gate on, plus the all-gather-free proof scan;
    `cost` is the usual ledger, and per_shard_total_gb the XLA
    per-shard memory estimate."""
    import time as _time

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from se3_transformer_tpu.parallel.exchange import comm_payload
    from se3_transformer_tpu.parallel.mesh import make_mesh, mesh_shape_dict
    from se3_transformer_tpu.parallel.sharding import (
        composed_state_shardings, make_sharded_train_step,
    )
    from se3_transformer_tpu.training import recipes

    n_devices = dp * sp * tp
    b, n = dp, per_device_nodes * sp
    mesh = make_mesh(jax.devices()[:n_devices], dp=dp, sp=sp, tp=tp)
    ring = dict(sequence_parallel='ring', ring_overlap=True,
                ring_exchange=True) if sp > 1 else {}
    module = recipes.RECIPES['flagship_fast'](
        dim=dim, num_neighbors=k, output_degrees=2, reduce_dim_out=True,
        depth=1, mesh=mesh, **ring)

    rng = np.random.RandomState(0)
    node_spec = P('dp', 'sp', None)
    feats = jax.device_put(
        jnp.asarray(rng.normal(size=(b, n, dim)), jnp.float32),
        NamedSharding(mesh, node_spec))
    coords = jax.device_put(
        jnp.asarray(np.cumsum(rng.normal(size=(b, n, 3)), axis=1),
                    jnp.float32), NamedSharding(mesh, node_spec))
    masks = jax.device_put(jnp.ones((b, n), bool),
                           NamedSharding(mesh, P('dp', 'sp')))

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        return (((noised + out) - data['coords']) ** 2).sum(-1).mean(), {}

    params = jax.jit(module.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), feats, coords, mask=masks,
        return_type=1)['params']
    optimizer = optax.adam(1e-4)
    params, opt_state, shardings = composed_state_shardings(
        params, optimizer.init(params), mesh)
    step = make_sharded_train_step(loss_fn, optimizer, mesh=mesh,
                                   state_shardings=shardings)
    data = dict(seqs=feats, coords=coords, masks=masks)
    key = jax.random.PRNGKey(1)

    t0 = _time.time()
    compiled = step.lower(params, opt_state, data, key).compile()
    compile_s = _time.time() - t0
    rec = dict(dp=dp, sp=sp, tp=tp, devices=n_devices, b=b, n=n,
               per_device_nodes=per_device_nodes, dim=dim, k=k, depth=1,
               compile_s=round(compile_s, 1), host_cpus=os.cpu_count(),
               backend='cpu-spmd')
    hlo_text = compiled.as_text()
    rec['comm'] = comm_payload(
        hlo_text, sp=sp, ring_steps=sp, overlap=sp > 1, exchange=sp > 1,
        full_width_dim=n, mesh_shape=mesh_shape_dict(mesh))
    try:
        from se3_transformer_tpu.observability.costs import cost_payload
        rec['cost'] = cost_payload(
            compiled, hlo_text=hlo_text,
            label=f'mesh_sweep,dp={dp},sp={sp},tp={tp},'
                  f'pdn={per_device_nodes}')
        mem = rec['cost']['memory']
        rec['per_shard_total_gb'] = round(
            (mem['temp_bytes'] + mem['argument_bytes']) / 2**30, 3)
    except Exception as e:  # noqa: BLE001 - memory analysis best-effort
        rec['memory_analysis_error'] = f'{type(e).__name__}: {e}'[:200]
        rec['per_shard_total_gb'] = 0.0   # schema'd field; error above
        #                                   flags the degenerate value
    # donation is ON (the aliasing route under test) — rebind the
    # donated state every call or the second step reads invalidated
    # buffers
    params, opt_state, loss, _ = compiled(params, opt_state, data, key)
    jax.block_until_ready(loss)                               # warmup
    t0 = _time.time()
    for _ in range(steps):
        key, sub = jax.random.split(key)
        params, opt_state, loss, _ = compiled(params, opt_state, data,
                                              sub)
    jax.block_until_ready(loss)
    rec['step_s'] = round((_time.time() - t0) / steps, 3)
    rec['loss_finite'] = bool(jax.numpy.isfinite(loss))
    return rec


def _write_comm_stream(path, recs):
    """Schema-valid telemetry stream for the weak-scaling run: run_meta +
    one `comm` AND one `cost` record per measured arm (observability
    kinds 'comm'/'cost' — gated via obs_report --require comm,cost)."""
    from se3_transformer_tpu.observability.report import write_record_stream

    bodies = []
    for rec in recs:
        if 'comm' in rec:
            bodies.append(dict(rec['comm'], kind='comm',
                               step_s=rec.get('step_s'),
                               label=rec.get('arm')))
        if 'cost' in rec:
            bodies.append(dict(rec['cost'], kind='cost'))
    write_record_stream(path, f'weak_scaling_{os.getpid()}', bodies)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--devices', type=int, required=True)
    ap.add_argument('--dims', type=int, nargs='*', default=[],
                    help='label-shape (n=1024) compile+memory points. '
                         'CAUTION: XLA:CPU memory analysis measured ~4x '
                         'over the real TPU footprint (dim=64/8dev said '
                         '32.6 GB/shard vs <16 GB measured on one whole '
                         'chip) — treat as an upper bound only')
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--k', type=int, default=32)
    ap.add_argument('--dp', type=int, default=1)
    ap.add_argument('--tp', type=int, default=None,
                    help='tp axis size (default 2 when devices%%2==0)')
    ap.add_argument('--exec-dim', type=int, default=None,
                    help='also EXECUTE one step at this dim (reduced '
                         'nodes, see --exec-nodes)')
    ap.add_argument('--exec-nodes', type=int, default=128)
    ap.add_argument('--out', default=os.path.join(REPO, 'WIDTH_TABLE.jsonl'))
    ap.add_argument('--weak-scaling', action='store_true',
                    help='one weak-scaling row: sp=devices ring path at '
                         'fixed per-device nodes, executed (fresh process '
                         'per device count)')
    ap.add_argument('--mesh-sweep', action='store_true',
                    help='composed dp x sp x tp sweep: every (dp,sp,tp) '
                         'mesh point covering --devices (mesh.mesh_points)'
                         ', each built via the explicit-aliasing route '
                         'and executed; writes a schema-valid mesh_sweep '
                         'stream (default MESH_SWEEP.jsonl, append)')
    ap.add_argument('--points', nargs='*', default=None,
                    metavar='DP,SP,TP',
                    help='with --mesh-sweep: explicit mesh points '
                         '(e.g. 2,2,2 4,1,2) instead of the full '
                         'enumeration')
    ap.add_argument('--per-device-nodes', type=int, default=256)
    ap.add_argument('--weak-dim', type=int, default=16)
    ap.add_argument('--ab', action='store_true',
                    help='with --weak-scaling: measure BOTH comm arms in '
                         'this process — overlapped+sparse (the default '
                         'path) and serialized+dense (ring_overlap='
                         'ring_exchange=False, the pre-PR5 program) — so '
                         'the A/B shares the host and the overhead delta '
                         'is attributable to the comm discipline alone')
    ap.add_argument('--no-overlap', action='store_true',
                    help='with --weak-scaling (single-arm): serialize the '
                         'ring ppermutes')
    ap.add_argument('--no-exchange', action='store_true',
                    help='with --weak-scaling (single-arm): dense global '
                         'gathers instead of the neighbor-sparse exchange')
    ap.add_argument('--metrics', default=None,
                    help='with --weak-scaling: also write a schema-valid '
                         'telemetry stream (run_meta + one comm record '
                         'per arm) for scripts/obs_report.py '
                         '--require-comm')
    args = ap.parse_args(argv)

    jax = _setup(args.devices)

    if args.mesh_sweep:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.parallel.mesh import mesh_points
        if args.points:
            points = [tuple(int(x) for x in p.split(','))
                      for p in args.points]
            bad = [p for p in points
                   if len(p) != 3 or
                   p[0] * p[1] * p[2] != args.devices]
            assert not bad, \
                f'points {bad} do not cover {args.devices} devices'
        else:
            points = mesh_points(args.devices)
        out = args.out
        if os.path.basename(out) == 'WIDTH_TABLE.jsonl':
            out = os.path.join(os.path.dirname(out), 'MESH_SWEEP.jsonl')
        bodies = []
        for dp, sp, tp in points:
            rec = mesh_sweep_point(jax, dp, sp, tp,
                                   args.per_device_nodes, args.weak_dim,
                                   min(args.k, 8))
            print(json.dumps(rec), flush=True)
            bodies.append(dict(rec, kind='mesh_sweep'))
        write_record_stream(out, f'mesh_sweep_{os.getpid()}', bodies,
                            append=True)
        print(f'{len(bodies)} mesh_sweep records -> {out}',
              file=sys.stderr)
        return

    if args.weak_scaling:
        arms = [(True, True), (False, False)] if args.ab else \
            [(not args.no_overlap, not args.no_exchange)]
        recs = []
        for overlap, exchange in arms:
            rec = weak_scaling_point(
                jax, args.devices, args.per_device_nodes, args.weak_dim,
                min(args.k, 8), overlap=overlap, exchange=exchange)
            rec['arm'] = ('overlapped_sparse' if overlap and exchange
                          else 'serialized_dense'
                          if not (overlap or exchange) else
                          f'overlap={overlap},exchange={exchange}')
            recs.append(rec)
            print(json.dumps(rec), flush=True)
            with open(args.out, 'a') as f:
                f.write(json.dumps(rec) + '\n')
        if len(recs) == 2 and all('step_s' in r for r in recs):
            ratio = dict(weak_scaling_ab=True, devices=args.devices,
                         sp=args.devices, n=recs[0]['n'],
                         dim=args.weak_dim,
                         overlapped_sparse_step_s=recs[0]['step_s'],
                         serialized_dense_step_s=recs[1]['step_s'],
                         overlapped_vs_serialized=round(
                             recs[1]['step_s'] / recs[0]['step_s'], 3))
            print(json.dumps(ratio), flush=True)
            with open(args.out, 'a') as f:
                f.write(json.dumps(ratio) + '\n')
        if args.metrics:
            _write_comm_stream(args.metrics, recs)
        return
    from se3_transformer_tpu.parallel.mesh import make_mesh
    devices = jax.devices()[:args.devices]
    assert len(devices) >= args.devices, \
        f'only {len(devices)} devices visible'
    tp = args.tp if args.tp is not None else (
        2 if args.devices % 2 == 0 else 1)
    mesh = make_mesh(devices, dp=args.dp, tp=tp)
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    print(f'mesh: {mesh_shape}', flush=True)

    for dim in args.dims:
        rec = dict(devices=args.devices, mesh=mesh_shape, backend='cpu-spmd')
        try:
            rec.update(measure_point(jax, mesh, dim, args.nodes, args.k, tp))
        except Exception as e:  # noqa: BLE001 - keep sweeping
            rec.update(dim=dim, n=args.nodes, k=args.k,
                       error=f'{type(e).__name__}: {e}'[:300])
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')

    if args.exec_dim:
        rec = dict(devices=args.devices, mesh=mesh_shape,
                   backend='cpu-spmd', executed=True)
        try:
            rec.update(measure_point(jax, mesh, args.exec_dim,
                                     args.exec_nodes, min(args.k, 16), tp,
                                     execute=True))
        except Exception as e:  # noqa: BLE001
            rec.update(dim=args.exec_dim, n=args.exec_nodes,
                       error=f'{type(e).__name__}: {e}'[:300])
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')


if __name__ == '__main__':
    main()
