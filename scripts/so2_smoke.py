"""CPU gate for the SO(2)-reduced contraction backend (`make so2-smoke`).

Three gates, exit non-zero on any failure:

  1. PARITY — dense CG backend vs the so2 banded backend on IDENTICAL
     parameters must agree within 1e-4 max-abs at every swept degree
     where the dense arm is affordable (the backends derive from the
     same Q_J intertwiners, so this is roundoff, ~1e-7 in practice);
  2. EQUIVARIANCE — the so2 backend's equivariance L2 must stay under
     1e-4 at every swept degree (including the degrees the dense arm
     never runs — the whole point of the backend);
  3. SCHEMA + RECORD — the per-degree A/B payload from
     bench.degrees_main is written as a schema'd `so2_sweep` record
     (run_meta header, observability.schema validation). The Makefile
     target then runs `obs_report --require so2_sweep` and
     `perf_gate.py` on the stream, so the committed degree-4 win /
     throughput budgets judge the fresh numbers.

    python scripts/so2_smoke.py [--metrics SO2.jsonl]
        [--degrees 2,4] [--dense-max 4] [--steps 5]

Default degrees are 2,4 (the smoke's CPU budget); the committed
SO2_SWEEP.jsonl evidence was produced with --degrees 2,4,6 (so2-only at
degree 6 — dense degree-6 basis needs the multi-minute Q_J solves the
backend exists to avoid).
"""
import argparse
import json
import os
import sys
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

PARITY_TOL = 1e-4
EQ_TOL = 1e-4


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='so2 backend parity + equivariance + degree-sweep '
                    'record gate')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid so2_sweep stream here')
    ap.add_argument('--degrees', default='2,4')
    ap.add_argument('--dense-max', type=int, default=4)
    ap.add_argument('--steps', type=int, default=5)
    args = ap.parse_args(argv)
    degrees = [int(x) for x in args.degrees.split(',')]

    import jax
    jax.config.update('jax_platforms', 'cpu')

    import bench

    record = bench.degrees_main(degrees, dense_max=args.dense_max,
                                steps=args.steps)

    ok = True
    for d, entry in sorted(record['degrees'].items(), key=lambda kv:
                           int(kv[0])):
        eq = entry.get('equivariance_l2_so2')
        if eq is None or eq >= EQ_TOL:
            print(f'FAIL: so2 equivariance L2 {eq} >= {EQ_TOL} at '
                  f'degree {d}')
            ok = False
        parity = entry.get('parity_l2')
        if 'dense_step_ms' in entry:
            if parity is None or parity >= PARITY_TOL:
                print(f'FAIL: dense-vs-so2 parity {parity} >= '
                      f'{PARITY_TOL} at degree {d} (identical params '
                      f'must give identical outputs)')
                ok = False
            if entry.get('dense_vs_so2', 0) <= 0:
                print(f'FAIL: degenerate dense_vs_so2 at degree {d}: '
                      f'{entry.get("dense_vs_so2")!r}')
                ok = False

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(kind='so2_sweep', label=record['metric'],
                    degrees=record['degrees'],
                    value=record['value'], unit=record['unit'],
                    timing=record['timing'])
        write_record_stream(args.metrics,
                            f'so2_smoke_{uuid.uuid4().hex[:8]}', [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    summary = dict(ok=ok, degrees=record['degrees'])
    print(json.dumps(summary))
    if not ok:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
