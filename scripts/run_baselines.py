"""Train every tracked BASELINE config for N steps and record throughput.

One process, runs each recipe from training.recipes (BASELINE.json
"configs") end to end: init, jitted denoise-style train steps, finite-loss
assertion, and a throughput line per config. Writes a JSON summary.

Usage: python scripts/run_baselines.py [--steps 8] [--out BASELINES.json]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def node_counts():
    # per-config data scale: flagship gets the north-star 1024 nodes,
    # stress configs enough nodes to exercise memory, toys stay toy
    return dict(toy_denoise=96, flagship=1024, flagship_fast=1024,
                af2_refinement=256, molecular_edges=128, egnn_stress=512)


def run_config(name, module, n, steps, rng, batch=1):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    needs_adj = bool(module.attend_sparse_neighbors or module.num_adj_degrees)
    has_tokens = module.num_tokens is not None
    b = batch

    if has_tokens:
        feats = jnp.asarray(rng.randint(0, module.num_tokens, (b, n)))
    else:
        feats = jnp.asarray(rng.normal(size=(b, n, module.dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(b, n, 3)), axis=1)
                        .astype(np.float32))
    coors = coors - coors.mean(axis=1, keepdims=True)
    mask = jnp.ones((b, n), bool)
    kwargs = dict(mask=mask)
    if needs_adj:
        i = np.arange(n)
        kwargs['adj_mat'] = jnp.asarray(
            np.broadcast_to((np.abs(i[:, None] - i[None, :]) == 1), (b, n, n))
            .copy())
    if module.num_edge_tokens is not None:
        kwargs['edges'] = jnp.asarray(
            rng.randint(0, module.num_edge_tokens, (b, n, n)))

    # output convention per config: denoise-style refinement loss where
    # the model emits a single type-1 vector per node (reduce_dim_out +
    # output_degrees>=2); plain mean-square objective otherwise (scalar
    # heads / EGNN multi-channel type-1)
    if module.use_egnn:
        return_type, denoise = 1, False
    elif module.reduce_dim_out and (module.output_degrees or 0) >= 2:
        return_type, denoise = 1, True
    else:
        return_type, denoise = 0, False

    def loss_fn(params, coors, key):
        noise = jax.random.normal(key, coors.shape, coors.dtype)
        noised = coors + noise
        out = module.apply({'params': params}, feats, noised,
                           return_type=return_type, **kwargs)
        if denoise:
            return (((noised + out) - coors) ** 2).sum(-1).mean()
        return (out ** 2).mean()

    init = jax.jit(module.init, static_argnames=('return_type',))
    params = init(jax.random.PRNGKey(0), feats, coors,
                  return_type=return_type, **kwargs)['params']
    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, key):
        loss, grads = jax.value_and_grad(loss_fn)(params, coors, key)
        gnorm = optax.global_norm(grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, gnorm

    key = jax.random.PRNGKey(1)
    t_c0 = time.time()
    params, opt_state, loss, gnorm = step(params, opt_state, key)
    jax.block_until_ready(loss)
    compile_s = time.time() - t_c0

    from se3_transformer_tpu.utils.helpers import fetch_sync
    # training-sanity signal travels with EVERY row (VERDICT r4 next #4:
    # fast-but-diverging must be visible in the record): per-step losses
    # and grad norms stay on device during the timed window (no extra
    # host syncs) and are floated after the clock stops
    losses, gnorms = [], []
    t0 = time.time()
    for _ in range(steps):
        key, sub = jax.random.split(key)
        params, opt_state, loss, gnorm = step(params, opt_state, sub)
        losses.append(loss)
        gnorms.append(gnorm)
    # host-materialize inside the window (loss gates the last forward, a
    # small param leaf gates the optimizer tail): block_until_ready was
    # observed to return tens of seconds early on this runtime
    loss = float(losses[-1])
    fetch_sync(min(jax.tree_util.tree_leaves(params), key=lambda l: l.size))
    dt = time.time() - t0
    losses = [float(l) for l in losses[:-1]] + [loss]
    gnorms = [float(g) for g in gnorms]
    assert np.isfinite(loss), f'{name}: non-finite loss'
    from se3_transformer_tpu.utils.helpers import loss_trajectory_fields
    rec = dict(config=name, nodes=n, steps=steps, loss=loss,
               step_ms=round(dt / steps * 1e3, 2),
               nodes_steps_per_sec=round(b * n * steps / dt, 2),
               compile_s=round(compile_s, 1),
               **loss_trajectory_fields(losses),
               grad_norm_first=round(gnorms[0], 4),
               grad_norm_last=round(gnorms[-1], 4),
               grad_norms_finite=bool(np.isfinite(gnorms).all()))
    # provenance (ADVICE r4 #5): a re-captured row that regresses purely
    # from a different host (1-core container) or code revision must be
    # explainable from the JSON alone
    try:
        import tpu_probe
        rev = tpu_probe.package_fingerprint()
        if rev:
            rec['code_rev'] = rev
    except Exception:
        pass
    rec['host_cpus'] = os.cpu_count()
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=8)
    ap.add_argument('--configs', nargs='+', default=None)
    ap.add_argument('--flagship-dim', type=int, default=64)
    ap.add_argument('--out', type=str, default=None)
    ap.add_argument('--cpu', action='store_true',
                    help='force CPU (the axon TPU tunnel is single-client; '
                         'use this when another process holds the chip)')
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import numpy as np

    from se3_transformer_tpu.training.recipes import RECIPES
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()

    backend = jax.default_backend()
    print(f'backend: {backend}')
    counts = node_counts()
    # merge-on-write: a partial run (e.g. tunnel death after config 1)
    # must not clobber rows from configs it never reached — round 4 lost
    # the six-row on-chip table exactly that way. New rows replace
    # same-config/same-backend rows; everything else is preserved.
    prior = []
    if args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                loaded = json.load(f)
            # shape-validate: a malformed prior must degrade to "no
            # prior", not crash the write loop after config 1
            prior = [r for r in loaded if isinstance(r, dict)
                     and 'config' in r] if isinstance(loaded, list) else []
        except Exception:
            prior = []
    results = []
    names = args.configs or list(RECIPES)
    failed = []

    def merged():
        # key on (config, backend): a --cpu liveness run must never
        # replace the on-chip row for the same config
        done = {(r['config'], r.get('backend')) for r in results}
        keep = [r for r in prior
                if (r['config'], r.get('backend')) not in done]
        return keep + results
    for name in names:
        builder = RECIPES[name]
        module = builder(dim=args.flagship_dim) \
            if name.startswith('flagship') else builder()
        rng = np.random.RandomState(0)
        # one config failing (e.g. an OOM at a new width) must not lose
        # the configs already measured — record and continue
        try:
            rec = run_config(name, module, counts[name], args.steps, rng)
        except Exception as e:  # noqa: BLE001
            print(f'{name} FAILED: {type(e).__name__}: {str(e)[:300]}',
                  file=sys.stderr)
            failed.append(name)
            continue
        rec['backend'] = backend
        print(json.dumps(rec))
        results.append(rec)
        if args.out:  # write-as-you-go: survive a later config crashing
            with open(args.out, 'w') as f:
                json.dump(merged(), f, indent=1)
    if args.out and results:
        print(f'wrote {args.out}')
    if failed:
        raise RuntimeError(f'configs failed: {failed}')


if __name__ == '__main__':
    main()
