"""Per-stage wall-clock breakdown of the flagship step (VERDICT #2).

Times each pipeline stage as its own jitted program on the current
backend: neighbor selection, basis construction, one ConvSE3, one
attention block, the full forward, and the full train step (fwd+bwd+
optimizer). Stage programs re-do upstream work (a conv needs neighbors
and basis), so the isolated numbers don't sum to the full step — they
bound each stage from above and show where the time goes.

Usage: python scripts/stage_timings.py [--nodes 1024] [--dim 64]
       [--degrees 4] [--neighbors 32] [--depth 6] [--iters 10] [--cpu]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, args, iters):
    from se3_transformer_tpu.utils.helpers import fetch_sync_tail
    out = jax.block_until_ready(fn(*args))  # compile
    fetch_sync_tail(out)  # warm the gating fetch (its own tiny program)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    fetch_sync_tail(out)  # one-element host fetch gates completion
    return (time.time() - t0) / iters * 1e3  # ms


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--nodes', type=int, default=1024)
    # defaults = the flagship bench config (recipes.flagship at dim=64);
    # round-3 toy-width numbers were misleadingly conv-light
    ap.add_argument('--dim', type=int, default=64)
    ap.add_argument('--degrees', type=int, default=4)
    ap.add_argument('--neighbors', type=int, default=32)
    ap.add_argument('--depth', type=int, default=6)
    ap.add_argument('--heads', type=int, default=8)
    ap.add_argument('--iters', type=int, default=10)
    ap.add_argument('--full', action='store_true',
                    help='also time the full model forward + train step '
                         '(redundant with bench.py records; the '
                         'differentiable_coors compile repeatedly '
                         'wedged the tunnel in round 3)')
    ap.add_argument('--no-pallas', action='store_true')
    ap.add_argument('--cpu', action='store_true')
    args = ap.parse_args(argv)

    global jax
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    import optax

    from se3_transformer_tpu.basis import get_basis
    from se3_transformer_tpu.models.se3_transformer import SE3TransformerModule
    from se3_transformer_tpu.ops import AttentionBlockSE3, ConvSE3, Fiber
    from se3_transformer_tpu.ops.neighbors import (
        exclude_self_indices, remove_self, select_neighbors,
    )
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()

    b, n, k, deg, dim = 1, args.nodes, args.neighbors, args.degrees, args.dim
    pallas = False if args.no_pallas else None
    rng = np.random.RandomState(0)
    coords = jnp.asarray(np.cumsum(rng.normal(size=(b, n, 3)), axis=1),
                         jnp.float32)
    mask = jnp.ones((b, n), bool)
    report = {'backend': jax.default_backend(), 'config': vars(args),
              'stage_ms': {}}

    # --- neighbor selection (O(N^2) distance + static-K top-k), on the
    # model's self-excluded [b, n, n-1] layout (exclude_self_indices) ---
    self_excl = exclude_self_indices(n)
    idx_base = jnp.broadcast_to(self_excl[None], (b, n, n - 1))

    def neighbors_fn(coords):
        rel_pos = coords[:, :, None, :] - coords[:, None, :, :]
        rel_pos = remove_self(rel_pos, self_excl)
        return select_neighbors(rel_pos, idx_base, k, 1e5,
                                pair_mask=None, neighbor_mask=None)

    def record(stage, value):
        # print as we go: a failure in a later stage (e.g. an OOM at the
        # train step) must not lose the numbers already measured
        report['stage_ms'][stage] = round(value, 3)
        print(f'stage {stage}: {report["stage_ms"][stage]} ms', flush=True)

    nf = jax.jit(neighbors_fn)
    hood, nearest = nf(coords)
    record('neighbors', timeit(nf, (coords,), args.iters))

    # --- basis construction on the selected edges ---
    basis_fn = jax.jit(lambda rp: get_basis(rp, deg - 1))
    basis = basis_fn(hood.rel_pos)
    record('basis', timeit(basis_fn, (hood.rel_pos,), args.iters))

    # --- one ConvSE3 at trunk width ---
    fiber = Fiber.create(deg, dim)
    feats = {str(d): jnp.asarray(
        rng.normal(size=(b, n, dim, 2 * d + 1)), jnp.float32)
        for d in range(deg)}
    conv = ConvSE3(fiber, fiber, pallas=pallas, shared_radial_hidden=True)
    edge_info = (hood.indices, hood.mask, None)
    cargs = (feats, edge_info, hood.rel_dist, basis)
    cparams = jax.jit(conv.init)(jax.random.PRNGKey(0), *cargs)
    conv_fn = jax.jit(lambda p, f: conv.apply(p, f, *cargs[1:]))
    record('conv', timeit(conv_fn, (cparams, feats), args.iters))

    # --- one attention block at trunk width ---
    # dim_head matches the full model below so this stage number actually
    # upper-bounds the model's attention stage
    attn = AttentionBlockSE3(fiber=fiber, dim_head=max(8, dim // 8),
                             heads=args.heads, attend_self=True,
                             pallas=pallas,
                             shared_radial_hidden=True)
    aparams = jax.jit(attn.init)(jax.random.PRNGKey(0), *cargs)
    attn_fn = jax.jit(lambda p, f: attn.apply(p, f, *cargs[1:]))
    record('attention_block', timeit(attn_fn, (aparams, feats), args.iters))

    if not args.full:
        print(json.dumps(report))
        return report

    # --- full model forward / train step (denoise-style flagship) ---
    # reversible + edge_chunks: the flagship memory recipe — a dim-64
    # deg-4 training step at 1024 nodes OOMs 16 GB HBM without them
    # (recipes.flagship docstring)
    module = SE3TransformerModule(
        num_tokens=24, dim=dim, dim_head=max(8, dim // 8), heads=args.heads,
        depth=args.depth, attend_self=True, input_degrees=1, num_degrees=deg,
        output_degrees=2, reduce_dim_out=True, differentiable_coors=True,
        num_neighbors=k, pallas=pallas, reversible=True, edge_chunks=8,
        shared_radial_hidden=True)
    seqs = jnp.asarray(rng.randint(0, 24, (b, n)))
    params = jax.jit(module.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), seqs, coords, mask=mask,
        return_type=1)['params']
    fwd = jax.jit(lambda p, c: module.apply(
        {'params': p}, seqs, c, mask=mask, return_type=1))
    record('model_forward', timeit(fwd, (params, coords), args.iters))

    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, coords, key):
        noise = jax.random.normal(key, coords.shape, coords.dtype)
        noised = coords + noise
        out = module.apply({'params': p}, seqs, noised, mask=mask,
                           return_type=1)
        return (((noised + out) - coords) ** 2).sum(-1).mean()

    @jax.jit
    def train_step(p, opt_state, coords, key):
        loss, grads = jax.value_and_grad(loss_fn)(p, coords, key)
        updates, opt_state = opt.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    p2, o2, loss = train_step(params, opt_state, coords, key)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(args.iters):
        p2, o2, loss = train_step(p2, o2, coords, key)
    jax.block_until_ready(loss)
    record('train_step', (time.time() - t0) / args.iters * 1e3)

    print(json.dumps(report))
    return report


if __name__ == '__main__':
    main()
