"""Fast on-chip smoke test of the Pallas kernels (Mosaic lowering + numerics).

Small shapes so compiles are quick; the full validation lives in
scripts/tpu_checks.py. Exits nonzero on any failure.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def check(name, a, b, tol=1e-4):
    scale = float(jnp.abs(b).max()) + 1e-9
    rel = float(jnp.abs(a - b).max()) / scale
    ok = rel < tol
    print(f'{name}: rel={rel:.2e} [{"PASS" if ok else "FAIL"}]')
    return ok


def main():
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    print('backend:', jax.default_backend())
    rng = np.random.RandomState(0)
    ok = True

    # --- pairwise conv kernel, a few shape classes ---
    from se3_transformer_tpu.kernels.pallas_pairwise import (
        fused_pairwise_conv, fused_pairwise_conv_bwd,
    )
    # mid=128 is the production value since the bias un-folding (the
    # bias is a [S, 1] operand now, not a 129th contraction row); the
    # smoke MUST cover the in-kernel lane-broadcast add and the db3
    # lane-reduce on real Mosaic
    # (64, 100, ...) keeps one deliberately sublane-UNALIGNED mid in the
    # on-chip gate: mid_dim is user-settable and the mid % 8 != 0 padding
    # path must stay covered on real Mosaic
    for (E, mid, IF, O, P) in [(300, 128, 24, 8, 5), (64, 100, 280, 20, 7),
                               (1000, 128, 56, 8, 7)]:
        h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
        w3 = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
        b3 = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
        v2 = jnp.asarray(rng.normal(size=(E, P, IF)), jnp.float32)
        g = jnp.asarray(rng.normal(size=(E, P, O)), jnp.float32)

        with jax.default_matmul_precision('highest'):
            ref = jnp.einsum('epk,eko->epo', v2,
                             jnp.einsum('em,mko->eko', h, w3) + b3)
        out = fused_pairwise_conv(h, w3, v2, b3=b3, precision='highest')
        ok &= check(f'pairwise fwd E={E} IF={IF} O={O} P={P}', out, ref)

        def f(h, w3, b3, v2):
            r = jnp.einsum('em,mko->eko', h, w3) + b3
            return (jnp.einsum('epk,eko->epo', v2, r) * g).sum()

        with jax.default_matmul_precision('highest'):
            dh_r, dw3_r, db3_r, dv2_r = jax.grad(
                f, argnums=(0, 1, 2, 3))(h, w3, b3, v2)
        dh, dw3, dv2, db3 = fused_pairwise_conv_bwd(h, w3, v2, g, b3=b3,
                                                    precision='highest')
        ok &= check(f'pairwise bwd dh  E={E}', dh, dh_r)
        ok &= check(f'pairwise bwd dw3 E={E}', dw3, dw3_r)
        ok &= check(f'pairwise bwd dv2 E={E}', dv2, dv2_r)
        ok &= check(f'pairwise bwd db3 E={E}', db3, db3_r)

    # --- radial_bf16 operands under an fp32 context precision: Mosaic
    # rejects contract_precision<fp32> on bf16 lhs ("Bad lhs type"); the
    # kernel must force DEFAULT (bf16 multiply, f32 accumulate) ---
    E, mid, IF, O, P = 300, 128, 24, 8, 5
    h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
    w3 = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
    b3 = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(E, P, IF)), jnp.float32)
    with jax.default_matmul_precision('highest'):
        ref = jnp.einsum('epk,eko->epo', v2,
                         jnp.einsum('em,mko->eko', h, w3) + b3)
    with jax.default_matmul_precision('float32'):
        out = fused_pairwise_conv(h.astype(jnp.bfloat16),
                                  w3.astype(jnp.bfloat16), v2, b3=b3,
                                  precision='float32')
    ok &= check('pairwise fwd bf16-radial @ f32 ctx', out, ref, tol=3e-2)

    # --- basis-fused pairwise kernel (forward; bwd shares the kernels
    # gated above via the reconstruct-VJP) ---
    from se3_transformer_tpu.kernels.pallas_pairwise import (
        fused_pairwise_conv_bx,
    )
    for (E, mid, C, Q, F, O, P) in [(300, 128, 8, 3, 3, 8, 5),
                                    (64, 128, 9, 5, 3, 4, 5),
                                    (1000, 128, 8, 7, 7, 8, 7)]:
        h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
        w3 = jnp.asarray(rng.normal(size=(mid, C * F, O)), jnp.float32)
        b3 = jnp.asarray(rng.normal(size=(C * F, O)), jnp.float32)
        bas = jnp.asarray(rng.normal(size=(E, P, Q, F)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(E, C, Q)), jnp.float32)
        with jax.default_matmul_precision('highest'):
            v2 = jnp.einsum('epqf,ecq->epcf', bas, x).reshape(E, P, C * F)
            ref = jnp.einsum('epk,eko->epo', v2,
                             jnp.einsum('em,mko->eko', h, w3) + b3)
        out = fused_pairwise_conv_bx(h, w3, bas, x, b3=b3,
                                     precision='highest')
        ok &= check(f'pairwise bx fwd E={E} C={C} Q={Q} F={F}', out, ref)

        # flat-basis twin (bxf): the layout the flagship fast path now
        # feeds — same math through a [E, P*F*Q] operand (Mosaic must
        # lower the 2D-transposed bt identically)
        from se3_transformer_tpu.kernels.pallas_pairwise import (
            fused_pairwise_conv_bxf,
        )
        flat = jnp.swapaxes(bas, -1, -2).reshape(E, P * F * Q)
        outf = fused_pairwise_conv_bxf(h, w3, flat, x, (P, Q, F), b3=b3,
                                       precision='highest')
        ok &= check(f'pairwise bxf fwd E={E} C={C} Q={Q} F={F}', outf, ref)

    # --- conv_bf16 operands (bf16 STORAGE of V2 / basis / x; kernel
    # upcasts rows after the VMEM load): Mosaic must lower the bf16
    # sublane slices + converts, and the result must equal the f32
    # kernel run on quantize-then-upcast operands (same math) ---
    E, mid, IF, O, P = 300, 128, 24, 8, 5
    h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
    w3 = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
    b3 = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(E, P, IF)), jnp.float32)
    v2q = v2.astype(jnp.bfloat16)
    out = fused_pairwise_conv(h, w3, v2q, b3=b3, precision='highest')
    ref = fused_pairwise_conv(h, w3, v2q.astype(jnp.float32), b3=b3,
                              precision='highest')
    ok &= check('pairwise fwd conv_bf16(v2) vs quantized oracle', out, ref,
                tol=1e-6)
    C, Q, F = 8, 7, 7
    w3x = jnp.asarray(rng.normal(size=(mid, C * F, O)), jnp.float32)
    b3x = jnp.asarray(rng.normal(size=(C * F, O)), jnp.float32)
    basf = jnp.asarray(rng.normal(size=(E, P * F * Q)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(E, C, Q)), jnp.float32)
    bq, xq = basf.astype(jnp.bfloat16), x.astype(jnp.bfloat16)
    out = fused_pairwise_conv_bxf(h, w3x, bq, xq, (P, Q, F), b3=b3x,
                                  precision='highest')
    ref = fused_pairwise_conv_bxf(h, w3x, bq.astype(jnp.float32),
                                  xq.astype(jnp.float32), (P, Q, F),
                                  b3=b3x, precision='highest')
    ok &= check('pairwise bxf fwd conv_bf16(basis,x) vs quantized oracle',
                out, ref, tol=1e-6)

    # --- MXU one-hot gather vs jnp.take at a flagship-shaped gather:
    # the auto heuristic only fires on TPU, so CPU tests never see the
    # on-chip numerics of the matmul path ---
    from se3_transformer_tpu.utils.helpers import (
        _onehot_gather, _use_onehot_gather,
    )
    vals = jnp.asarray(rng.normal(size=(1, 1024, 64, 7)), jnp.float32)
    gidx = jnp.asarray(rng.randint(0, 1024, (1, 1024 * 33)), jnp.int32)
    if _use_onehot_gather(vals, gidx, 1):
        oh = jax.jit(_onehot_gather)(vals, gidx)
        tk = jax.jit(lambda v, i: jax.vmap(
            lambda vv, ii: jnp.take(vv, ii, axis=0))(v, i))(vals, gidx)
        ok &= check('onehot gather vs take (flagship shape)', oh, tk,
                    tol=1e-6)
    else:
        # run-everything contract: never abort the remaining canaries
        from se3_transformer_tpu.utils.helpers import is_tpu_backend
        print('onehot gather heuristic OFF at flagship shape '
              f'(backend={jax.default_backend()}) [FAIL]')
        ok &= not is_tpu_backend()

    # --- attention kernel ---
    from se3_transformer_tpu.kernels.pallas_attention import (
        attention_reference, fused_attention,
    )
    # the last two rows are FLAGSHIP-SHAPED (n=1024, J=33): round 3's
    # first session OOM'd scoped VMEM exactly there while the small
    # smoke shapes passed — the canary must cover the shapes the model
    # actually runs
    for (BH, BKV, n, J, D, masked) in [(8, 8, 100, 17, 24, True),
                                       (8, 1, 64, 33, 56, True),
                                       (4, 4, 128, 9, 8, False),
                                       (8, 8, 1024, 33, 64, True),
                                       (2, 2, 1024, 33, 8, True)]:
        q = jnp.asarray(rng.normal(size=(BH, n, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(BKV, n, J, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(BKV, n, J, D)), jnp.float32)
        B = 1
        heads = BH // B
        mask = None
        if masked:
            mask = jnp.asarray(rng.rand(B, n, J) > 0.2)
            mask = mask.at[:, :, 0].set(True)
        scale = D ** -0.5
        with jax.default_matmul_precision('highest'):
            ref = attention_reference(q, k, v, mask, scale)
        out = fused_attention(q, k, v, mask, heads, scale)
        ok &= check(f'attention BH={BH} BKV={BKV} J={J} D={D} '
                    f'mask={masked}', out, ref)

        gco = jnp.asarray(rng.normal(size=out.shape), jnp.float32)

        def f_ref(q, k, v):
            return (attention_reference(q, k, v, mask, scale) * gco).sum()

        def f_fused(q, k, v):
            return (fused_attention(q, k, v, mask, heads, scale)
                    * gco).sum()

        with jax.default_matmul_precision('highest'):
            refg = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        outg = jax.grad(f_fused, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip(('dq', 'dk', 'dv'), outg, refg):
            ok &= check(f'attention bwd {name} BH={BH} BKV={BKV} '
                        f'mask={masked}', a, b)

    print('ALL PASS' if ok else 'FAILURES')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
