"""Fleet observability smoke: request tracing + SLO aggregation.

The `make slo-smoke` gate. Runs a 2-host in-process fleet (real AOT
engines behind `HostServer` + `LocalTransport`) with SEEDED transport
faults (deterministic latency + drops on `infer`, so some requests are
forced to redispatch cross-host), streams a mixed-length request load
through a traced `FleetRouter`, and banks two schema'd records off one
run: `trace` (span trees + the completeness invariant) and `slo`
(fleet availability + merged-histogram percentiles + error-budget
burn). The fleet-level zero-lost claim is gated in-process; no `fleet`
record is banked (this run exercises no rollout/recovery, and one
would shadow the chaos smoke's record under the perf gate's
last-matching-record semantics).

Exits non-zero when any of the load-bearing claims fails:

  * any request resolves neither answered nor structured-failed
    (zero-lost, fleet-wide);
  * any orphan span, or completeness_total < 1.0 — every answered OR
    structured-failed request must yield exactly one single-root span
    tree;
  * redispatch_hops != the fleet's cross_host_retries counter (the
    trace record must RECONCILE with the counters, not approximate
    them);
  * no multi-host trace (the seeded drops force redispatch — a
    redispatched request must show spans from >= 2 hosts);
  * fleet availability under the floor, or zero answered requests;
  * the stream fails schema validation.

`--inject-regression` proves the gate can fire: after the (healthy)
run, the tracer's fleet-side `attempt` spans are discarded — the
broken-instrumentation simulation: every host-recorded span loses its
parent, the trace record reports orphans and completeness < 1.0, and
this script must exit 1 (the Makefile inverts it).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='2-host traced fleet smoke: trace + slo records')
    ap.add_argument('--requests', type=int, default=40)
    ap.add_argument('--buckets', default='4,8')
    ap.add_argument('--batch-size', type=int, default=2)
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--timeout-s', type=float, default=20.0)
    ap.add_argument('--metrics', default='/tmp/slo_smoke.jsonl')
    ap.add_argument('--out', default=None,
                    help='also write the summary JSON here')
    ap.add_argument('--inject-regression', action='store_true',
                    help='discard the fleet-side attempt spans after '
                         'the run (broken instrumentation): the trace '
                         'gates MUST fire and this script exits 1')
    return ap.parse_args(argv)


def build_hosts(args, buckets):
    """Two in-process hosts: real AOT engines, one Router +
    RouterTelemetry + HostServer each, both telemetries banking into
    ONE MetricLogger (per-host serve records interleave in the same
    stream the fleet records land in)."""
    from serve import build_module_and_params

    from se3_transformer_tpu.faults import FaultInjector
    from se3_transformer_tpu.inference import AdmissionController
    from se3_transformer_tpu.observability import (
        MetricLogger, PhaseTimer,
    )
    from se3_transformer_tpu.inference.engine import InferenceEngine
    from se3_transformer_tpu.serving import (
        HostServer, LocalTransport, ReplicaWorker, Router,
        RouterTelemetry,
    )

    cfg, module, params = build_module_and_params(args, buckets)
    logger = MetricLogger(args.metrics, run_meta=dict(
        mode='slo_smoke', hosts=2, buckets=list(buckets),
        batch_size=args.batch_size, seed=args.seed))
    injector = FaultInjector(seed=args.seed)
    # deterministic transport chaos: periodic latency plus infer drops —
    # each dropped RPC surfaces as a TransportError at the fleet tier,
    # feeds the host breaker, and forces a CROSS-HOST redispatch (the
    # multi-host-trace evidence this smoke gates on)
    injector.plan('transport', 'latency', every=9, latency_s=0.02)
    injector.plan('transport', 'drop', at=(4, 11),
                  match=dict(method='infer'))

    hosts, transports, telemetries = {}, {}, {}
    t0 = time.perf_counter()
    # BOTH engines compile before EITHER telemetry arms: compile events
    # are process-wide, so arming host 0 first would book host 1's
    # warmup compiles as post-warmup retraces on host 0's records
    engines = {hid: InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, timer=PhaseTimer()) for hid in (0, 1)}
    for hid, engine in engines.items():
        worker = ReplicaWorker(0, engine, max_wait_ms=5.0)
        admission = AdmissionController(max_len=buckets[-1])
        router = Router([worker], admission=admission, max_retries=1,
                        default_timeout_s=args.timeout_s)
        telemetry = RouterTelemetry(router, admission, logger)
        telemetry.arm(emit_cost_records=False)
        server = HostServer(router, host_id=hid, telemetry=telemetry,
                            flush_every_batches=4)
        hosts[hid] = server
        telemetries[hid] = telemetry
        transports[hid] = LocalTransport(server,
                                         fault_injector=injector)
    print(f'warmup: 2 hosts x {len(buckets)} bucket executables in '
          f'{time.perf_counter() - t0:.1f}s', flush=True)
    return hosts, transports, telemetries, logger, injector


def main(argv=None):
    args = parse_args(argv)
    import jax
    jax.config.update('jax_platforms', 'cpu')
    enable_compilation_cache()
    import numpy as np

    from se3_transformer_tpu.observability import (
        SLOAggregator, Tracer, trace_record_body,
    )
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.observability.slo import AVAILABILITY_FLOOR
    from se3_transformer_tpu.serving import FleetRouter

    buckets = tuple(int(b) for b in args.buckets.split(','))
    args.checkpoint = None
    hosts, transports, telemetries, logger, injector = \
        build_hosts(args, buckets)

    tracer = Tracer(origin='fleet')
    slo = SLOAggregator(availability_target=0.999)
    rng = np.random.RandomState(args.seed)
    pending = []
    with FleetRouter(transports, max_retries=2,
                     default_timeout_s=args.timeout_s,
                     heartbeat_every_s=0.05,
                     tracer=tracer, slo=slo) as fleet:
        for i in range(args.requests):
            n = int(rng.randint(1, buckets[-1] + 1))
            pending.append(fleet.submit(
                rng.randint(0, 24, size=n).astype(np.int32),
                rng.normal(size=(n, 3)).astype(np.float32)))
            fleet.pump()
            time.sleep(0.004)
        # settle: every submit resolves (answered or structured) and
        # the heartbeat loop keeps scraping the hosts' histograms
        deadline = time.monotonic() + args.timeout_s + 30.0
        while (any(not p.done for p in pending)
               and time.monotonic() < deadline):
            fleet.drain()
            fleet.pump()
            time.sleep(0.01)
        fleet.drain()
        scraped = fleet.scrape()    # final cumulative counters
        fleet_body = fleet.record_body(pending, label='slo_smoke')
        answered = fleet.answered
        failures = fleet.request_failures
        xretries = fleet.cross_host_retries

    for s in hosts.values():
        s.stop(drain=True)
    for t in telemetries.values():
        t.flush()

    if args.inject_regression:
        # broken-instrumentation simulation: dropping the fleet-side
        # `attempt` spans orphans every host-recorded span (their
        # parent ids vanish from the trace) — the orphan/completeness
        # gates below and the perf budgets must all fire
        with tracer._lock:
            tracer._spans = [s for s in tracer._spans
                             if s.get('name') != 'attempt']
        print('INJECTED REGRESSION: fleet-side attempt spans '
              'discarded — host spans are now orphans', flush=True)

    resolved = answered + failures
    trace_body = trace_record_body(tracer, label='slo_smoke',
                                   expected=resolved)
    slo_body = slo.record_body(fleet, label='slo_smoke')
    # no `fleet` record here: this run exercises no rollout/recovery,
    # and banking one would shadow the chaos smoke's record under the
    # perf gate's last-matching-record semantics — the fleet-level
    # claims (zero lost) are gated in-process off fleet_body below
    logger.log_record('trace', mirror=False, **trace_body)
    logger.log_record('slo', mirror=False, **slo_body)
    logger.close()

    ok = True

    def gate(cond, msg):
        nonlocal ok
        if not cond:
            print(f'FAIL: {msg}')
            ok = False

    gate(answered > 0, 'zero answered requests')
    gate(fleet_body['lost_requests'] == 0,
         f'{fleet_body["lost_requests"]} lost request(s) — resolved '
         f'neither answered nor structured')
    gate(trace_body['orphan_spans'] == 0,
         f'{trace_body["orphan_spans"]} orphan span(s)')
    gate(trace_body['completeness_total'] >= 1.0,
         f'trace completeness {trace_body["completeness_total"]} < 1.0 '
         f'({trace_body["complete_trees"]}/{trace_body["traces"]} '
         f'complete over {resolved} resolved)')
    gate(trace_body['redispatch_hops'] == xretries,
         f'redispatch_hops {trace_body["redispatch_hops"]} != '
         f'cross_host_retries {xretries} — the trace record does not '
         f'reconcile with the fleet counters')
    gate(trace_body['multi_host_traces'] >= 1,
         'no multi-host trace — the seeded drops must force at least '
         'one cross-host redispatch with spans from both hosts')
    gate(isinstance(slo_body['availability'], (int, float))
         and slo_body['availability'] >= AVAILABILITY_FLOOR,
         f'fleet availability {slo_body["availability"]} under the '
         f'{AVAILABILITY_FLOOR} floor')
    gate(slo_body['hosts'] == 2 and scraped == 2,
         f'SLO aggregator saw {slo_body["hosts"]} host(s), final '
         f'scrape hit {scraped} — both hosts must report')
    gate(any(v.get('count') for v in slo_body['buckets'].values()),
         'merged histograms are empty — no host shipped latency '
         'counts')

    try:
        validate_stream(args.metrics)
        print(f'schema: {args.metrics} validated clean')
    except SchemaError as e:
        gate(False, f'schema violation: {e}')

    summary = dict(
        answered=answered, request_failures=failures,
        cross_host_retries=xretries,
        injections=injector.snapshot()['injections_total'],
        trace={k: trace_body[k] for k in (
            'traces', 'complete_trees', 'orphan_spans',
            'multi_host_traces', 'redispatch_hops',
            'completeness_total')},
        availability=slo_body['availability'],
        buckets=slo_body['buckets'],
        ok=ok,
    )
    print(json.dumps(summary, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(summary, f, indent=2)
    if ok:
        print(f'SLO SMOKE PASS: {answered} answered, {xretries} '
              f'cross-host redispatches all traced, availability '
              f'{slo_body["availability"]}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
