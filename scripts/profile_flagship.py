"""Trace the bench-identical flagship TRAIN step (fast path by default).

The session's stage_profile traces the conservative flagship FORWARD;
this script traces the full training step of the exact program bench.py
times — fast/conservative, optional remat policy and edge_chunks — so
trace_summary.py can attribute the step's wall clock op by op.

    python scripts/profile_flagship.py [--conservative] [--remat POLICY]
        [--chunks N] [--steps 2] [--out /tmp/flagship_fast_trace]

Single-client tunnel rules apply: run only when no other process holds
the chip.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default='/tmp/flagship_fast_trace')
    ap.add_argument('--conservative', action='store_true')
    ap.add_argument('--remat', default=None,
                    help="remat_policy override (e.g. save_conv_outputs)")
    ap.add_argument('--chunks', type=int, default=None,
                    help='edge_chunks override (0 = unchunked)')
    ap.add_argument('--steps', type=int, default=2)
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--cpu', action='store_true')
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    from _flagship_common import build_flagship_step
    from se3_transformer_tpu.utils.helpers import fetch_sync
    from se3_transformer_tpu.utils.observability import profile_trace

    step, params, opt_state, data, key, module = build_flagship_step(
        fast=not args.conservative, remat=args.remat, chunks=args.chunks,
        nodes=args.nodes)
    name = 'flagship' if args.conservative else 'flagship_fast'

    t0 = time.time()
    params, opt_state, loss, _ = step(params, opt_state, data, key)
    fetch_sync(loss)  # block_until_ready returns early on this runtime
    print(f'compile+first step: {time.time() - t0:.1f} s '
          f'({name}, remat={args.remat}, chunks={args.chunks})')

    with profile_trace(args.out):
        for _ in range(args.steps):
            key, sub = jax.random.split(key)
            params, opt_state, loss, _ = step(params, opt_state, data, sub)
        # the trace window must not close before the steps have run
        fetch_sync(loss)
    print(f'trace written to {args.out}; summarize with '
          f'scripts/trace_summary.py --dir {args.out}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
