"""Trace the bench-identical flagship TRAIN step (fast path by default).

The session's stage_profile traces the conservative flagship FORWARD;
this script traces the full training step of the exact program bench.py
times — fast/conservative, optional remat policy and edge_chunks — so
trace_summary.py can attribute the step's wall clock op by op.

    python scripts/profile_flagship.py [--conservative] [--remat POLICY]
        [--chunks N] [--steps 2] [--out /tmp/flagship_fast_trace]

Single-client tunnel rules apply: run only when no other process holds
the chip.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default='/tmp/flagship_fast_trace')
    ap.add_argument('--conservative', action='store_true')
    ap.add_argument('--remat', default=None,
                    help="remat_policy override (e.g. save_conv_outputs)")
    ap.add_argument('--chunks', type=int, default=None,
                    help='edge_chunks override (0 = unchunked)')
    ap.add_argument('--steps', type=int, default=2)
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--cpu', action='store_true')
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    import optax

    from se3_transformer_tpu.parallel.sharding import make_sharded_train_step
    from se3_transformer_tpu.training import recipes
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.observability import profile_trace

    enable_compilation_cache()

    name = 'flagship' if args.conservative else 'flagship_fast'
    overrides = dict(output_degrees=2, reduce_dim_out=True)
    if args.remat:
        overrides['remat_policy'] = args.remat
    if args.chunks is not None:
        overrides['edge_chunks'] = args.chunks or None
    module = recipes.RECIPES[name](dim=64, **overrides)

    n = args.nodes
    rng = np.random.RandomState(0)
    seqs = jnp.asarray(rng.normal(size=(1, n, 64)), jnp.float32)
    coords = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                         jnp.float32)
    coords = coords - coords.mean(axis=1, keepdims=True)
    masks = jnp.ones((1, n), bool)

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        return (((noised + out) - data['coords']) ** 2).sum(-1).mean(), {}

    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    params = init_fn(jax.random.PRNGKey(0), seqs, coords, mask=masks,
                     return_type=1)['params']
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(loss_fn, optimizer)
    data = dict(seqs=seqs, coords=coords, masks=masks)
    key = jax.random.PRNGKey(1)

    t0 = time.time()
    params, opt_state, loss, _ = step(params, opt_state, data, key)
    jax.block_until_ready(loss)
    print(f'compile+first step: {time.time() - t0:.1f} s '
          f'({name}, remat={args.remat}, chunks={args.chunks})')

    with profile_trace(args.out):
        for _ in range(args.steps):
            key, sub = jax.random.split(key)
            params, opt_state, loss, _ = step(params, opt_state, data, sub)
        jax.block_until_ready(loss)
    print(f'trace written to {args.out}; summarize with '
          f'scripts/trace_summary.py --dir {args.out}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
