"""Train-chaos smoke: the self-healing training loop under fire.

Usage:
    python scripts/train_chaos_smoke.py [--steps 24] [--window 4]
        [--nodes 32] [--accum 2] [--seed 0] [--pipelined/--no-pipelined]
        [--metrics TRAIN_CHAOS.jsonl] [--out SUMMARY.json]
        [--weaken none|norollback] [--workdir DIR]

The serving-side `make chaos-smoke` proves replicas heal; this gate
proves the TRAINING loop does (docs/ROBUSTNESS.md "Training fault
domain"). Four arms, three of them subprocesses so the kill is a real
SIGTERM against a real process:

  1. CONTROL    — the same config runs `--steps` guarded steps with NO
     faults and banks its final params (the parity oracle).
  2. CHAOS      — a seeded injector poisons one step's batch with NaN
     (`step_batch` nan plan: a genuine non-finite loss walks the real
     jitted step), sleeps on a periodic `step_dispatch` latency plan,
     and kills the EMERGENCY writer (`emergency_save` exception plan).
     The guard must detect the NaN window off the telemetry
     accumulator, roll back to the last good checkpoint, and replay;
     mid-run the parent sends SIGTERM and the process must exit with
     the resumable rc (75) — with its emergency save dead, the restart
     falls back to the last periodic checkpoint.
  3. RESUME     — a fresh process restores (fallback-aware), survives a
     SECOND injected NaN (at= indices are per-process call counts, so
     replay after its rollback is clean), finishes, and banks the
     cumulative `guard` record (counters carry over the kill through
     the guardian sidecar).
  4. (--weaken norollback) — detection with the ROLLBACK NULLED: the
     NaN window trips but nothing restores, the run ends on NaN params,
     and this script MUST exit rc==1 (the diverged gate fires rather
     than decorates). `make train-chaos-smoke` asserts the rc pair.

Exit is non-zero unless ALL of:
  * the chaos arm exited with the RESUMABLE rc after the SIGTERM;
  * final params of the resumed run are BIT-EXACT equal to the control
    arm's (rollback + per-step-derived batches/rngs replay the exact
    trajectory a never-faulted run walks);
  * >= 1 rollback was OBSERVED (cumulative guard record) and
    injections_total >= 1 with diverged == false;
  * zero post-warmup recompiles in the resumed process (its summary
    record's retrace_warnings_total — restore must not change shapes);
  * the telemetry stream (flush/pipeline/guard/summary) is
    schema-valid.
"""
import argparse
import atexit
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESUMABLE_RC = 75


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='seeded fault injection over the self-healing '
                    'training loop (CPU)')
    ap.add_argument('--steps', type=int, default=24)
    ap.add_argument('--window', type=int, default=4,
                    help='guard window = telemetry flush interval')
    ap.add_argument('--nodes', type=int, default=32)
    ap.add_argument('--accum', type=int, default=2)
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--nan-at', type=int, default=6,
                    help='chaos arm: poison the Nth built batch')
    ap.add_argument('--resume-nan-at', type=int, default=4,
                    help='resume arm: poison its Nth built batch')
    ap.add_argument('--kill-after-step', type=int, default=None,
                    help='SIGTERM once the chaos arm reports this step '
                         '(default: steps // 2)')
    ap.add_argument('--pipelined', dest='pipelined', action='store_true',
                    default=True,
                    help='guarded loop over the producer/prefetch data '
                         'path (default)')
    ap.add_argument('--no-pipelined', dest='pipelined',
                    action='store_false')
    ap.add_argument('--metrics', type=str, default=None)
    ap.add_argument('--out', type=str, default=None)
    ap.add_argument('--workdir', type=str, default=None,
                    help='checkpoint/params scratch dir (default: a '
                         'fresh temp dir, removed after)')
    ap.add_argument('--weaken', choices=('none', 'norollback'),
                    default='none',
                    help="'norollback': the guard detects but never "
                         'restores — the diverged gate MUST fire '
                         '(rc 1), proving it is live')
    ap.add_argument('--worker', choices=('control', 'chaos', 'resume'),
                    default=None, help=argparse.SUPPRESS)
    ap.add_argument('--progress-file', type=str, default=None,
                    help=argparse.SUPPRESS)
    return ap.parse_args(argv)


# --------------------------------------------------------------------- #
# worker arms (run as subprocesses so SIGTERM/exit codes are real)
# --------------------------------------------------------------------- #
def _build_trainer(args):
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from se3_transformer_tpu.training import DenoiseConfig, DenoiseTrainer
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    cfg = DenoiseConfig(num_nodes=args.nodes, batch_size=1,
                        num_degrees=2, max_sparse_neighbors=4,
                        accum_steps=args.accum, seed=args.seed,
                        telemetry=True, flush_every=args.window,
                        pipeline=args.pipelined,
                        donate_batch=args.pipelined)
    return DenoiseTrainer(cfg)


def _dump_params(trainer, path):
    import jax
    import numpy as np
    leaves, _ = jax.tree_util.tree_flatten(trainer.params)
    np.savez(path, *[np.asarray(l) for l in leaves])


def worker_main(args):
    import numpy as np  # noqa: F401 - jax platform pinned in builder

    from se3_transformer_tpu.faults import FaultInjector
    from se3_transformer_tpu.observability import MetricLogger
    from se3_transformer_tpu.training.guardian import (
        GuardConfig, StepGuard, TrainingFailed, resume_trainer,
    )
    from se3_transformer_tpu.training.checkpoint import CheckpointManager

    trainer = _build_trainer(args)
    ckpt_dir = os.path.join(args.workdir, 'ckpt')
    params_path = os.path.join(args.workdir, f'params_{args.worker}.npz')

    inj = None
    if args.worker != 'control':
        inj = FaultInjector(seed=args.seed)
        nan_at = (args.nan_at if args.worker == 'chaos'
                  else args.resume_nan_at)
        # at= counts BUILT batches in this process — builds are strictly
        # ordered on the producer thread, so the poisoned step is
        # deterministic; replay after the rollback fires calls past the
        # plan, so the replayed window is clean (parity holds)
        inj.plan('step_batch', 'nan', at=(nan_at,))
        inj.plan('step_dispatch', 'latency', every=9, latency_s=0.005)
        if args.worker == 'chaos':
            # the EMERGENCY writer dies too: the preemption exit must
            # still be resumable, falling back to the last periodic
            # checkpoint
            inj.plan('emergency_save', 'exception', at=(1,))

    guard = StepGuard(GuardConfig(
        rollback=(args.weaken != 'norollback'), restart_budget=4))
    mgr = CheckpointManager(ckpt_dir, max_to_keep=3)
    restart = args.worker == 'resume'
    if restart:
        restored = resume_trainer(trainer, mgr)
        print(f'resume worker: restored step {restored} '
              f'(last_restored_step={mgr.last_restored_step})')

    progress = None
    if args.progress_file:
        def progress(step):  # noqa: E306
            tmp = args.progress_file + '.tmp'
            with open(tmp, 'w') as f:
                f.write(str(step))
            os.replace(tmp, args.progress_file)

    run_meta = dict(mode='train_chaos_smoke', arm=args.worker,
                    weaken=args.weaken, pipelined=args.pipelined,
                    steps=args.steps, window=args.window, seed=args.seed)
    logger = (MetricLogger(args.metrics, run_meta=run_meta)
              if args.worker != 'control' else None)
    try:
        result = trainer.train_guarded(
            args.steps, mgr, guard=guard, injector=inj,
            metric_logger=logger, restart=restart, step_hook=progress)
    except TrainingFailed as e:
        print(f'TRAINING FAILED (structured): {e.to_record()}')
        return 1
    finally:
        if logger is not None:
            logger.close()
        mgr.close(raise_on_timeout=False)
    if not result.preempted:
        _dump_params(trainer, params_path)
    print(f'{args.worker} arm: steps={result.steps} '
          f'preempted={result.preempted} diverged={result.diverged} '
          f'counters={result.counters}')
    return result.exit_code


# --------------------------------------------------------------------- #
# the orchestrator
# --------------------------------------------------------------------- #
def _spawn(args, worker, progress_file=None):
    cmd = [sys.executable, os.path.abspath(__file__),
           '--worker', worker, '--workdir', args.workdir,
           '--steps', str(args.steps), '--window', str(args.window),
           '--nodes', str(args.nodes), '--accum', str(args.accum),
           '--seed', str(args.seed), '--nan-at', str(args.nan_at),
           '--resume-nan-at', str(args.resume_nan_at),
           '--weaken', args.weaken]
    cmd.append('--pipelined' if args.pipelined else '--no-pipelined')
    if args.metrics and worker != 'control':
        cmd += ['--metrics', args.metrics]
    if progress_file:
        cmd += ['--progress-file', progress_file]
    return subprocess.Popen(cmd)


def _read_progress(path):
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _load_leaves(path):
    import numpy as np
    with np.load(path) as z:
        return [z[k] for k in z.files]


def main(argv=None):
    args = parse_args(argv)
    if args.worker:
        assert args.workdir, '--worker requires --workdir'
        return worker_main(args)

    if args.workdir is None:
        args.workdir = tempfile.mkdtemp(prefix='train_chaos_')
        atexit.register(shutil.rmtree, args.workdir, ignore_errors=True)
    os.makedirs(args.workdir, exist_ok=True)
    kill_after = (args.kill_after_step if args.kill_after_step is not None
                  else args.steps // 2)
    ok = True

    if args.weaken == 'norollback':
        # THE WEAKENED ARM: detection without response. One process, no
        # kill — the NaN window trips, nothing restores, and the
        # diverged gate must exit this script with rc 1.
        print('WEAKENED GATE ARM: rollback is nulled (this run must '
              'exit 1)')
        p = _spawn(args, 'chaos')
        rc = p.wait()
        print(f'weakened arm rc={rc} (1 = the diverged gate FIRED; '
              f'anything else means the gate is decoration)')
        return rc

    # ---- arm 1: control (the parity oracle) -------------------------- #
    t0 = time.perf_counter()
    rc = _spawn(args, 'control').wait()
    if rc != 0:
        print(f'FAIL: control arm exited {rc}')
        return 2
    print(f'control arm done in {time.perf_counter() - t0:.1f}s')

    # the control arm checkpoints too — the chaos arm must start from
    # scratch, so reset the checkpoint dir between arms
    shutil.rmtree(os.path.join(args.workdir, 'ckpt'), ignore_errors=True)

    # ---- arm 2: chaos + a real SIGTERM ------------------------------- #
    progress_file = os.path.join(args.workdir, 'progress')
    p = _spawn(args, 'chaos', progress_file=progress_file)
    deadline = time.time() + 300
    while time.time() < deadline and p.poll() is None:
        if _read_progress(progress_file) >= kill_after:
            break
        time.sleep(0.05)
    if p.poll() is not None:
        print(f'FAIL: chaos arm exited early (rc={p.returncode}) — '
              f'never reached the kill step {kill_after}')
        return 2
    print(f'SIGTERM -> chaos arm at step '
          f'>= {_read_progress(progress_file)}')
    p.send_signal(signal.SIGTERM)
    rc = p.wait(timeout=120)
    if rc != RESUMABLE_RC:
        print(f'FAIL: chaos arm exited rc={rc} after SIGTERM — expected '
              f'the RESUMABLE rc {RESUMABLE_RC}')
        ok = False

    # ---- arm 3: resume to completion --------------------------------- #
    rc = _spawn(args, 'resume').wait()
    if rc != 0:
        print(f'FAIL: resume arm exited {rc}')
        ok = False

    # ---- gates ------------------------------------------------------- #
    report = dict(ok=False, weaken=args.weaken, steps=args.steps,
                  kill_after_step=kill_after, chaos_rc=RESUMABLE_RC)
    control = os.path.join(args.workdir, 'params_control.npz')
    resumed = os.path.join(args.workdir, 'params_resume.npz')
    max_abs = None
    if not (os.path.exists(control) and os.path.exists(resumed)):
        print('FAIL: an arm produced no final params dump')
        ok = False
    else:
        import numpy as np
        a, b = _load_leaves(control), _load_leaves(resumed)
        if len(a) != len(b):
            print(f'FAIL: param tree sizes differ ({len(a)} vs {len(b)})')
            ok = False
        else:
            max_abs = max(float(np.max(np.abs(x - y))) if x.size else 0.0
                          for x, y in zip(a, b))
            if max_abs != 0.0:
                print(f'FAIL: resumed params differ from control '
                      f'(max abs {max_abs:.3e}) — the kill-and-resume '
                      f'trajectory is NOT the unfaulted one')
                ok = False
            else:
                print(f'parity ok: {len(a)} param leaves bit-exact vs '
                      f'the uninterrupted control arm')
    report['final_param_max_abs_diff'] = max_abs

    guard_rec = summary_rec = None
    if args.metrics and os.path.exists(args.metrics):
        from se3_transformer_tpu.observability.schema import (
            SchemaError, validate_stream,
        )
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False
        recs = [json.loads(l) for l in open(args.metrics) if l.strip()]
        guards = [r for r in recs if r.get('kind') == 'guard']
        guard_rec = guards[-1] if guards else None
        run_ids = [r['run_id'] for r in recs if r.get('kind') == 'run_meta']
        resume_id = run_ids[-1] if run_ids else None
        summaries = [r for r in recs if r.get('kind') == 'summary'
                     and r.get('run_id') == resume_id]
        summary_rec = summaries[-1] if summaries else None
    if guard_rec is None:
        print('FAIL: no guard record banked')
        ok = False
    else:
        if guard_rec.get('rollbacks', 0) < 1:
            print(f'FAIL: {guard_rec.get("rollbacks")} rollbacks — the '
                  f'NaN trip was never OBSERVED paying down')
            ok = False
        if not guard_rec.get('injections_total'):
            print('FAIL: zero injections in the final guard record')
            ok = False
        if guard_rec.get('diverged') is not False:
            print(f'FAIL: diverged={guard_rec.get("diverged")!r}')
            ok = False
        if guard_rec.get('restarts', 0) < 1 or \
                guard_rec.get('preemptions', 0) < 1:
            print(f'FAIL: restarts={guard_rec.get("restarts")} / '
                  f'preemptions={guard_rec.get("preemptions")} — the '
                  f'kill never registered in the cumulative counters')
            ok = False
    if summary_rec is None:
        print('FAIL: the resumed run banked no summary record')
        ok = False
    elif summary_rec.get('retrace_warnings_total', 0) != 0:
        print(f'FAIL: {summary_rec["retrace_warnings_total"]} '
              f'post-warmup retraces in the resumed run — restore must '
              f'not change compiled shapes')
        ok = False

    report.update(ok=ok, guard=guard_rec,
                  resume_retrace_warnings=(summary_rec or {}).get(
                      'retrace_warnings_total'))
    print(json.dumps(report, indent=2, default=str))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2, default=str)
        print(f'report -> {args.out}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
