"""Render telemetry / bench JSONL streams into one summary JSON.

Usage:
    python scripts/obs_report.py STREAM.jsonl [MORE.jsonl ...]
        [--validate] [--out SUMMARY.json] [--anchor FLOAT]
        [--code-rev REV] [--require kind[,kind...]]

--require gates the stream on record kinds (pipeline / comm / tune /
cost / profile / serve / ... / assembly / mesh_sweep), each with its
load-bearing check; the old --require-pipeline/--require-comm/
--require-tune flags are aliases.

Input species are auto-detected per record:
  * bench records ({"metric", "value", "unit", ...} — BENCH_SESSION.jsonl,
    BLOCK_AB.jsonl, BENCH_r0N.json lines): grouped by metric label with
    best-of-session selection, best single timing window, and one-sided
    outlier flagging — the machine version of the round-close summary.
  * telemetry streams (kind=run_meta/step/flush/summary records from a
    `denoise.py --telemetry` run): reduced to a bench-shaped record
    (metric/value/unit/vs_baseline/step_ms/loss trajectory) with
    per-phase p50/p95 and the retrace-warning count.

--validate additionally gates telemetry streams on the record schema
(observability.schema) and exits non-zero on violation — `make
obs-smoke` runs exactly that. Never initializes a device backend (no
jax.devices()/default_backend() call anywhere on this path), so it
works while the TPU tunnel is wedged.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.observability.report import (  # noqa: E402
    load_jsonl, summarize,
)
from se3_transformer_tpu.observability.schema import (  # noqa: E402
    SchemaError, validate_stream,
)


def _gate_pipeline(records):
    pipes = [r for r in records if r.get('kind') == 'pipeline']
    if not pipes:
        print('PIPELINE GATE: no pipeline records in the stream '
              '(was the run started with --pipelined?)', file=sys.stderr)
        return False
    last = pipes[-1].get('prefetch', {})
    hits, stalls = last.get('hits', 0), last.get('stalls', 0)
    if not hits:
        print(f'PIPELINE GATE: 100% prefetch stalls ({stalls} stalls, '
              f'0 hits) — the producer never got ahead of the device',
              file=sys.stderr)
        return False
    print(f'pipeline gate ok: {hits} hits / {stalls} stalls, '
          f'verdict {pipes[-1].get("verdict")}', file=sys.stderr)
    return True


def _gate_comm(records):
    comms = [r for r in records if r.get('kind') == 'comm']
    if not comms:
        print('COMM GATE: no comm records in the stream (was the run '
              'traced with the exchange instrumented?)', file=sys.stderr)
        return False
    ex_arms = [r for r in comms if r.get('exchange')]
    if not ex_arms:
        print('COMM GATE: no exchange-enabled comm record — the '
              'sparse path was never traced', file=sys.stderr)
        return False
    dirty = [r for r in ex_arms if not r.get('all_gather_free')]
    if dirty:
        shapes = [s for r in dirty
                  for s in r.get('full_width_all_gathers', [])]
        print(f'COMM GATE: {len(dirty)} exchange-enabled program(s) '
              f'still carry full-width all-gathers: {shapes}',
              file=sys.stderr)
        return False
    print(f'comm gate ok: {len(comms)} comm records, '
          f'{len(ex_arms)} exchange arms, all all-gather-free',
          file=sys.stderr)
    return True


def _gate_tune(records):
    tunes = [r for r in records if r.get('kind') == 'tune']
    if not tunes:
        print('TUNE GATE: no tune records in the stream (was '
              'scripts/tune_kernels.py run?)', file=sys.stderr)
        return False
    promoted = [r for r in tunes if r.get('verdict') == 'promoted']
    consulted = [r for r in tunes if r.get('verdict') == 'consulted']
    if not promoted:
        print('TUNE GATE: no candidate was promoted', file=sys.stderr)
        return False
    if not consulted:
        print('TUNE GATE: no consulted verdict — the promoted entry '
              'was never proven to steer a subsequent pick',
              file=sys.stderr)
        return False
    print(f'tune gate ok: {len(tunes)} tune records, '
          f'{len(promoted)} promoted, {len(consulted)} consulted',
          file=sys.stderr)
    return True


def _gate_cost(records):
    costs = [r for r in records if r.get('kind') == 'cost']
    if not costs:
        print('COST GATE: no cost records in the stream (was the run '
              'ledgered — bench cost payload, engine warmup, '
              '--cost-record?)', file=sys.stderr)
        return False
    empty = [r for r in costs if not r.get('peak_bytes')]
    if empty:
        labels = [r.get('label') for r in empty]
        print(f'COST GATE: {len(empty)} cost record(s) with zero peak '
              f'memory — the ledger measured nothing: {labels}',
              file=sys.stderr)
        return False
    unavailable = [r.get('label') for r in costs
                   if r.get('source') == 'unavailable']
    if unavailable:
        print(f'COST GATE: source=unavailable for {unavailable} — '
              f'neither cost_analysis nor the HLO fallback produced '
              f'numbers', file=sys.stderr)
        return False
    print(f'cost gate ok: {len(costs)} cost records, peak '
          f'{max(r["peak_bytes"] for r in costs) / 2**20:.1f} MiB max',
          file=sys.stderr)
    return True


def _gate_profile(records):
    profs = [r for r in records if r.get('kind') == 'profile']
    if not profs:
        print('PROFILE GATE: no profile records in the stream (was a '
              'trace captured and attributed — make profile-smoke?)',
              file=sys.stderr)
        return False
    dead = [r.get('label') for r in profs
            if not r.get('device_time_ms') or not r.get('scopes')]
    if dead:
        print(f'PROFILE GATE: profile record(s) with no device time or '
              f'no scopes: {dead} — the trace attributed nothing',
              file=sys.stderr)
        return False
    worst = min(r.get('coverage', 0) for r in profs)
    print(f'profile gate ok: {len(profs)} profile records, worst '
          f'coverage {worst:.0%} (the >=80% bar is enforced where the '
          f'trace is captured: scripts/profile_smoke.py)',
          file=sys.stderr)
    return True


def _gate_serve(records):
    serves = [r for r in records if r.get('kind') == 'serve']
    if not serves:
        print('SERVE GATE: no serve records in the stream (was the run '
              'served through ServeTelemetry/RouterTelemetry?)',
              file=sys.stderr)
        return False
    # counters are cumulative, so the last record carries the verdict
    last = serves[-1]
    answered = (last.get('requests') or {}).get('served') or 0
    if not answered:
        print('SERVE GATE: zero answered requests in the final serve '
              'record — the stream proves nothing was served',
              file=sys.stderr)
        return False
    timed = [(b, st) for r in serves
             for b, st in (r.get('buckets') or {}).items()]
    if not timed:
        print('SERVE GATE: no per-bucket latency section in any serve '
              'record — the SLO surface is empty', file=sys.stderr)
        return False
    broken = [b for b, st in timed
              if not isinstance(st, dict)
              or any(st.get(k) is None
                     for k in ('count', 'p50_ms', 'p95_ms', 'p99_ms'))]
    if broken:
        print(f'SERVE GATE: bucket(s) {sorted(set(broken))} missing or '
              f'null latency percentiles (count/p50/p95/p99 are the '
              f'SLO surface)', file=sys.stderr)
        return False
    extras = ''
    if 'continuous_admissions' in last:
        extras = (f", {last['continuous_admissions']} continuous "
                  f"admissions, {len(last.get('replicas') or {})} "
                  f"replicas, {(last.get('swaps') or {}).get('count', 0)} "
                  f"swap events")
    print(f'serve gate ok: {len(serves)} serve records, {answered} '
          f'answered rows, {len(timed)} timed bucket windows{extras}',
          file=sys.stderr)
    return True


def _gate_fault(records):
    faults = [r for r in records if r.get('kind') == 'fault']
    if not faults:
        print('FAULT GATE: no fault records in the stream (was the run '
              'chaos-exercised — scripts/chaos_smoke.py?)',
              file=sys.stderr)
        return False
    last = faults[-1]
    if not last.get('injections_total'):
        print('FAULT GATE: zero injections in the final fault record — '
              'a fault record that exercised nothing proves nothing',
              file=sys.stderr)
        return False
    lost = last.get('lost_requests')
    if lost != 0:
        print(f'FAULT GATE: lost_requests={lost!r} — every submit must '
              f'resolve answered-or-structured-error under injected '
              f'faults (zero-lost contract)', file=sys.stderr)
        return False
    print(f"fault gate ok: {len(faults)} fault records, "
          f"{last['injections_total']} injections, "
          f"{last.get('recoveries', 0)} quarantine recoveries, "
          f"{last.get('retries', 0)} retries / "
          f"{last.get('timeouts', 0)} timeouts / "
          f"{last.get('request_failures', 0)} structured failures, "
          f"0 lost", file=sys.stderr)
    return True


def _gate_guard(records):
    guards = [r for r in records if r.get('kind') == 'guard']
    if not guards:
        print('GUARD GATE: no guard records in the stream (was the run '
              'trained through the guardian — train_guarded / '
              'scripts/train_chaos_smoke.py?)', file=sys.stderr)
        return False
    last = guards[-1]
    if not last.get('injections_total'):
        print('GUARD GATE: zero injections in the final guard record — '
              'a guard record that exercised nothing proves nothing',
              file=sys.stderr)
        return False
    if last.get('diverged') is not False:
        print(f'GUARD GATE: diverged={last.get("diverged")!r} — the '
              f'guarded run must end on finite, policy-clean '
              f'parameters (rollback paid every trip down)',
              file=sys.stderr)
        return False
    print(f"guard gate ok: {len(guards)} guard records, "
          f"{last['injections_total']} injections, "
          f"{last.get('trips', 0)} trips / "
          f"{last.get('rollbacks', 0)} rollbacks / "
          f"{last.get('restarts', 0)} restarts / "
          f"{last.get('preemptions', 0)} preemptions, not diverged",
          file=sys.stderr)
    return True


def _gate_fleet(records):
    fleets = [r for r in records if r.get('kind') == 'fleet']
    if not fleets:
        print('FLEET GATE: no fleet records in the stream (was the run '
              'served through a FleetRouter — '
              'scripts/fleet_chaos_smoke.py / serve.py --fleet?)',
              file=sys.stderr)
        return False
    last = fleets[-1]
    if not last.get('host_transitions'):
        print('FLEET GATE: empty host_transitions log in the final '
              'fleet record — a fleet record where no host breaker '
              'ever moved proves nothing was exercised',
              file=sys.stderr)
        return False
    lost = last.get('lost_requests')
    if lost != 0:
        print(f'FLEET GATE: lost_requests={lost!r} — every submit must '
              f'resolve answered-or-structured-error FLEET-WIDE across '
              f'host deaths, redispatches and rollouts (zero-lost '
              f'contract)', file=sys.stderr)
        return False
    print(f"fleet gate ok: {len(fleets)} fleet records, "
          f"{len(last.get('hosts') or {})} hosts, "
          f"{len(last['host_transitions'])} host transitions / "
          f"{last.get('recoveries', 0)} recoveries, "
          f"{last.get('cross_host_retries', 0)} cross-host retries, "
          f"{(last.get('rollouts') or {}).get('count', 0)} rollout "
          f"events / {last.get('rollbacks', 0)} rollbacks, 0 lost",
          file=sys.stderr)
    return True


def _gate_so2_sweep(records):
    sweeps = [r for r in records if r.get('kind') == 'so2_sweep']
    if not sweeps:
        print('SO2 GATE: no so2_sweep records in the stream (was '
              'scripts/so2_smoke.py / bench.py --degrees run?)',
              file=sys.stderr)
        return False
    last = sweeps[-1]
    degrees = last.get('degrees') or {}
    bad_eq = [d for d, e in degrees.items()
              if not isinstance(e.get('equivariance_l2_so2'),
                                (int, float))
              or e['equivariance_l2_so2'] >= 1e-4]
    if bad_eq:
        print(f'SO2 GATE: so2 equivariance L2 >= 1e-4 (or missing) at '
              f'degree(s) {sorted(bad_eq)} — the reduced contraction '
              f'broke equivariance', file=sys.stderr)
        return False
    ab = {d: e['dense_vs_so2'] for d, e in degrees.items()
          if 'dense_vs_so2' in e}
    if not ab:
        print('SO2 GATE: no degree carries a dense arm — the sweep '
              'proves equivariance but no A/B (the perf budgets need '
              'dense_vs_so2)', file=sys.stderr)
        return False
    print(f'so2 gate ok: degrees {sorted(degrees)}, dense_vs_so2 '
          f'{ab}, worst eq '
          f'{max(e["equivariance_l2_so2"] for e in degrees.values()):.2e}'
          f' (the win itself is enforced by scripts/perf_gate.py)',
          file=sys.stderr)
    return True


def _gate_v2_sweep(records):
    sweeps = [r for r in records if r.get('kind') == 'v2_sweep']
    if not sweeps:
        print('V2 GATE: no v2_sweep records in the stream (was '
              'scripts/v2_smoke.py / bench.py --v2-degrees run?)',
              file=sys.stderr)
        return False
    last = sweeps[-1]
    degrees = last.get('degrees') or {}
    bad_eq = [d for d, e in degrees.items()
              if not isinstance(e.get('equivariance_l2_v2'),
                                (int, float))
              or e['equivariance_l2_v2'] >= 1e-4]
    if bad_eq:
        print(f'V2 GATE: v2 equivariance L2 >= 1e-4 (or missing) at '
              f'degree(s) {sorted(bad_eq)} — the eSCN-direct family '
              f'broke equivariance', file=sys.stderr)
        return False
    ab = {d: e['so2_vs_v2'] for d, e in degrees.items()
          if 'so2_vs_v2' in e}
    if not ab:
        print('V2 GATE: no degree carries the v1+so2 baseline arm — '
              'the sweep proves equivariance but no family A/B (the '
              'perf budgets need so2_vs_v2)', file=sys.stderr)
        return False
    print(f'v2 gate ok: degrees {sorted(degrees)}, so2_vs_v2 '
          f'{ab}, worst eq '
          f'{max(e["equivariance_l2_v2"] for e in degrees.values()):.2e}'
          f' (the win itself is enforced by scripts/perf_gate.py)',
          file=sys.stderr)
    return True


def _gate_flash(records):
    recs = [r for r in records if r.get('kind') == 'flash']
    if not recs:
        print('FLASH GATE: no flash records in the stream (was '
              'scripts/flash_smoke.py / bench.py --flash run?)',
              file=sys.stderr)
        return False
    last = recs[-1]
    eq = last.get('equivariance_l2_fused')
    if not isinstance(eq, (int, float)) or eq >= 1e-4:
        print(f'FLASH GATE: fused equivariance L2 {eq!r} >= 1e-4 (or '
              f'missing) — the streaming kernel broke equivariance',
              file=sys.stderr)
        return False
    ratios = {k: last.get(k) for k in ('fused_vs_unfused',
                                       'hbm_unfused_vs_fused')}
    if any(not isinstance(v, (int, float)) or v <= 0
           for v in ratios.values()):
        print(f'FLASH GATE: degenerate A/B ratios {ratios} — the record '
              f'proves no fused-vs-unfused comparison', file=sys.stderr)
        return False
    print(f'flash gate ok: {len(recs)} flash records, step ratio '
          f'{ratios["fused_vs_unfused"]}, peak-HBM ratio '
          f'{ratios["hbm_unfused_vs_fused"]}, eq {eq:.2e} (the wins '
          f'themselves are enforced by scripts/perf_gate.py)',
          file=sys.stderr)
    return True


def _gate_quant_ab(records):
    recs = [r for r in records if r.get('kind') == 'quant_ab']
    if not recs:
        print('QUANT GATE: no quant_ab records in the stream (was '
              'scripts/quant_smoke.py / bench.py --quant run?)',
              file=sys.stderr)
        return False
    last = recs[-1]
    parity = last.get('parity_max_abs')
    if not isinstance(parity, (int, float)) or parity >= 1e-4:
        print(f'QUANT GATE: implementation parity {parity!r} >= 1e-4 '
              f'(or missing) — the quantized serving path (fused '
              f'dequant epilogues / kernels / padding) added error '
              f'beyond quantization itself', file=sys.stderr)
        return False
    eq = last.get('equivariance_l2')
    if not isinstance(eq, (int, float)) or eq >= 1e-4:
        print(f'QUANT GATE: quantized equivariance L2 {eq!r} >= 1e-4 '
              f'(or missing) — weight-only quantization must preserve '
              f'equivariance', file=sys.stderr)
        return False
    ratio = last.get('argument_bytes_ratio')
    if not isinstance(ratio, (int, float)) or ratio <= 0:
        print(f'QUANT GATE: degenerate argument_bytes_ratio {ratio!r} — '
              f'the record proves no memory claim', file=sys.stderr)
        return False
    print(f"quant gate ok: {len(recs)} quant_ab records, mix "
          f"{last.get('mix')!r}, argument-bytes ratio {ratio}, impl "
          f"parity {parity:.2e}, quant error "
          f"{last.get('quant_error_max_abs')}, eq {eq:.2e} (the ratio "
          f"ceiling itself is enforced by scripts/perf_gate.py)",
          file=sys.stderr)
    return True


def _gate_trace(records):
    recs = [r for r in records if r.get('kind') == 'trace']
    if not recs:
        print('TRACE GATE: no trace records in the stream (was '
              'scripts/slo_smoke.py / fleet_chaos_smoke.py run?)',
              file=sys.stderr)
        return False
    last = recs[-1]
    if not last.get('complete_trees'):
        print(f'TRACE GATE: zero complete span trees (traces='
              f'{last.get("traces")}) — no request produced a '
              f'single-root tree', file=sys.stderr)
        return False
    if last.get('orphan_spans'):
        print(f'TRACE GATE: {last["orphan_spans"]} orphan span(s) — '
              f'spans whose parent never appears in their trace '
              f'(instrumentation lost part of a request\'s story)',
              file=sys.stderr)
        return False
    print(f'trace gate ok: {last.get("complete_trees")}/'
          f'{last.get("traces")} complete trees, zero orphans, '
          f'{last.get("multi_host_traces")} multi-host trace(s), '
          f'{last.get("redispatch_hops")} redispatch hop(s) '
          f'(completeness_total itself is enforced by '
          f'scripts/perf_gate.py)', file=sys.stderr)
    return True


def _gate_slo(records):
    recs = [r for r in records if r.get('kind') == 'slo']
    if not recs:
        print('SLO GATE: no slo records in the stream (was '
              'scripts/slo_smoke.py run?)', file=sys.stderr)
        return False
    last = recs[-1]
    if not last.get('answered'):
        print('SLO GATE: zero answered requests — the record proves '
              'no served traffic', file=sys.stderr)
        return False
    avail = last.get('availability')
    if not isinstance(avail, (int, float)):
        print(f'SLO GATE: availability {avail!r} is not numeric',
              file=sys.stderr)
        return False
    print(f'slo gate ok: {last.get("hosts")} host(s), availability '
          f'{avail}, {last.get("answered")} answered, buckets '
          f'{sorted(last.get("buckets") or {})} (the availability '
          f'floor itself is enforced by scripts/perf_gate.py)',
          file=sys.stderr)
    return True


def _gate_assembly(records):
    recs = [r for r in records if r.get('kind') == 'assembly']
    if not recs:
        print('ASSEMBLY GATE: no assembly records in the stream (was '
              'scripts/assembly_smoke.py run?)', file=sys.stderr)
        return False
    last = recs[-1]
    if not last.get('bucket_served'):
        print('ASSEMBLY GATE: zero rows served through the engine '
              'bucket — the record proves nothing about serving',
              file=sys.stderr)
        return False
    if last.get('post_warmup_compiles'):
        print(f'ASSEMBLY GATE: {last["post_warmup_compiles"]} '
              f'post-warmup compile(s) — the AOT bucket executable '
              f'was not actually reused', file=sys.stderr)
        return False
    parity = last.get('parity_linf')
    if not isinstance(parity, (int, float)) or parity >= 1e-4:
        print(f'ASSEMBLY GATE: global-vs-materialized parity '
              f'{parity!r} >= 1e-4 (or missing) — the streaming arm '
              f'diverged from the all-pairs reference', file=sys.stderr)
        return False
    ratio = last.get('hbm_materialized_vs_global')
    if not isinstance(ratio, (int, float)) or ratio <= 0:
        print(f'ASSEMBLY GATE: degenerate hbm_materialized_vs_global '
              f'{ratio!r} — the record proves no memory claim',
              file=sys.stderr)
        return False
    print(f'assembly gate ok: {len(recs)} assembly records, '
          f'n={last.get("n")} served via bucket {last.get("bucket")} '
          f'({last.get("bucket_served")} rows, zero post-warmup '
          f'compiles), parity {parity:.2e}, eq '
          f'{last.get("equivariance_l2")}, materialized/global HBM '
          f'{ratio} (the >=3x floor and the equivariance ceiling are '
          f'enforced by scripts/perf_gate.py)', file=sys.stderr)
    return True


def _gate_mesh_sweep(records):
    recs = [r for r in records if r.get('kind') == 'mesh_sweep']
    if not recs:
        print('MESH GATE: no mesh_sweep records in the stream (was '
              'scripts/width_table.py --mesh-sweep run?)', file=sys.stderr)
        return False
    # latest row per (dp, sp, tp) point: EVERY mesh point must hold the
    # composed contract, not just the final one swept
    latest = {}
    for r in recs:
        latest[(r.get('dp'), r.get('sp'), r.get('tp'))] = r
    bad = []
    for point, r in sorted(latest.items()):
        comm = r.get('comm') or {}
        if not r.get('loss_finite'):
            bad.append(f'{point}: non-finite loss')
        elif not comm.get('all_gather_free'):
            bad.append(f'{point}: full-width all-gathers '
                       f'{comm.get("full_width_all_gathers")}')
        elif not comm.get('axis_collectives', {}) and (
                r.get('sp', 1) > 1 or r.get('dp', 1) > 1
                or r.get('tp', 1) > 1):
            bad.append(f'{point}: empty axis_collectives on a '
                       f'multi-axis mesh — nothing to gate per axis')
    if bad:
        print(f'MESH GATE: {len(bad)}/{len(latest)} mesh points '
              f'breach the composed contract: ' + '; '.join(bad),
              file=sys.stderr)
        return False
    pts = ' '.join(f'({d},{s},{t})' for d, s, t in sorted(latest))
    print(f'mesh gate ok: {len(recs)} mesh_sweep records over '
          f'{len(latest)} points {pts} — all loss-finite and '
          f'all-gather-free with per-axis attribution (byte ceilings '
          f'are enforced by scripts/perf_gate.py)', file=sys.stderr)
    return True


def _gate_transport(records):
    recs = [r for r in records if r.get('kind') == 'transport']
    if not recs:
        print('TRANSPORT GATE: no transport records in the stream '
              '(was scripts/transport_loadgen.py run?)', file=sys.stderr)
        return False
    r = recs[-1]
    arms = r.get('arms') or {}
    bad = []
    for name in ('legacy', 'binary'):
        arm = arms.get(name) or {}
        if not arm.get('requests'):
            bad.append(f'{name} arm served no requests — the A/B '
                       f'compares nothing')
        elif arm.get('errors'):
            bad.append(f'{name} arm had {arm["errors"]} errors on a '
                       f'fault-free workload')
    tstats = r.get('transport') or {}
    if tstats.get('frame_errors'):
        bad.append(f'{tstats["frame_errors"]} frame errors on a '
                   f'clean wire — the framing is corrupting data')
    if tstats.get('reconnects'):
        bad.append(f'{tstats["reconnects"]} reconnects with no host '
                   f'restart — connections are not persisting')
    if (tstats.get('peak_in_flight') or 0) < 2:
        bad.append(f'binary peak_in_flight='
                   f'{tstats.get("peak_in_flight")} — nothing ever '
                   f'multiplexed, the pooled arm degenerated to '
                   f'serial calls')
    if bad:
        print(f'TRANSPORT GATE: ' + '; '.join(bad), file=sys.stderr)
        return False
    print(f'transport gate ok: binary {r.get("qps_binary_vs_legacy")}x '
          f'qps vs legacy, p99 ratio {r.get("p99_binary_vs_legacy")}, '
          f'wire-bytes ratio {r.get("wire_bytes_binary_vs_legacy")}, '
          f'peak in-flight {tstats.get("peak_in_flight")}, zero frame '
          f'errors (the numeric floors/ceilings are enforced by '
          f'scripts/perf_gate.py)', file=sys.stderr)
    return True


_REQUIRE_GATES = dict(pipeline=_gate_pipeline, comm=_gate_comm,
                      tune=_gate_tune, cost=_gate_cost,
                      profile=_gate_profile, serve=_gate_serve,
                      so2_sweep=_gate_so2_sweep,
                      v2_sweep=_gate_v2_sweep, flash=_gate_flash,
                      fault=_gate_fault, guard=_gate_guard,
                      fleet=_gate_fleet, quant_ab=_gate_quant_ab,
                      trace=_gate_trace, slo=_gate_slo,
                      assembly=_gate_assembly,
                      mesh_sweep=_gate_mesh_sweep,
                      transport=_gate_transport)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='aggregate telemetry/bench JSONL into one summary')
    ap.add_argument('paths', nargs='+', help='JSONL stream(s)')
    ap.add_argument('--validate', action='store_true',
                    help='gate telemetry streams on the record schema '
                         '(exit 1 on violation)')
    ap.add_argument('--out', default=None,
                    help='also write the summary JSON to this path')
    ap.add_argument('--anchor', type=float, default=None,
                    help='vs_baseline anchor for telemetry throughput')
    ap.add_argument('--code-rev', default=None,
                    help='only summarize bench records with this code_rev')
    ap.add_argument('--require', default=None, metavar='KIND[,KIND...]',
                    help='gate the stream on record kinds: '
                         f'{sorted(_REQUIRE_GATES)}. Each kind runs its '
                         'load-bearing check (pipeline: >=1 prefetch '
                         'hit; comm: every exchange arm all-gather-'
                         'free; tune: a promotion that is consulted; '
                         'cost: every program ledgers nonzero peak '
                         'memory; profile: per-scope attribution '
                         'present with its coverage figure; serve: '
                         'per-bucket latency percentiles present and '
                         'a nonzero answered count; fault: injections '
                         'present and zero lost requests; guard: '
                         'injections present and diverged == false; '
                         'fleet: host-breaker transitions present and '
                         'zero lost requests fleet-wide; trace: at '
                         'least one complete span tree and zero '
                         'orphan spans; slo: nonzero answered and a '
                         'numeric availability; assembly: rows served '
                         'through an engine bucket with zero '
                         'post-warmup compiles and sub-1e-4 parity) '
                         'and exits non-zero on failure')
    # legacy aliases for the unified --require flag (kept: Makefiles and
    # session scripts in the wild still pass them)
    ap.add_argument('--require-tune', action='store_true',
                    help='alias for --require tune')
    ap.add_argument('--require-comm', action='store_true',
                    help='alias for --require comm')
    ap.add_argument('--require-pipeline', action='store_true',
                    help='alias for --require pipeline')
    args = ap.parse_args(argv)

    required = {k.strip() for k in (args.require or '').split(',')
                if k.strip()}
    for kind, legacy_on in (('tune', args.require_tune),
                            ('comm', args.require_comm),
                            ('pipeline', args.require_pipeline)):
        if legacy_on:
            required.add(kind)
    unknown = required - set(_REQUIRE_GATES)
    if unknown:
        print(f'unknown --require kinds {sorted(unknown)} '
              f'(known: {sorted(_REQUIRE_GATES)})', file=sys.stderr)
        return 2

    records = []
    for path in args.paths:
        recs = load_jsonl(path)
        if args.validate and any(r.get('kind') == 'run_meta'
                                 for r in recs):
            try:
                info = validate_stream(path)
            except SchemaError as e:
                print(f'{path}: SCHEMA VIOLATION: {e}', file=sys.stderr)
                return 1
            print(f'{path}: schema ok ({info["records"]} records, '
                  f'kinds {info["kinds"]})', file=sys.stderr)
        records += recs

    if not records:
        print('no records found', file=sys.stderr)
        return 1

    for kind in sorted(required):
        if not _REQUIRE_GATES[kind](records):
            return 1

    summary = summarize(records, anchor=args.anchor,
                        code_rev=args.code_rev)
    text = json.dumps(summary, indent=1)
    print(text)
    if args.out:
        with open(args.out, 'w') as f:
            f.write(text + '\n')
    return 0


if __name__ == '__main__':
    sys.exit(main())
