"""Render telemetry / bench JSONL streams into one summary JSON.

Usage:
    python scripts/obs_report.py STREAM.jsonl [MORE.jsonl ...]
        [--validate] [--out SUMMARY.json] [--anchor FLOAT]
        [--code-rev REV]

Input species are auto-detected per record:
  * bench records ({"metric", "value", "unit", ...} — BENCH_SESSION.jsonl,
    BLOCK_AB.jsonl, BENCH_r0N.json lines): grouped by metric label with
    best-of-session selection, best single timing window, and one-sided
    outlier flagging — the machine version of the round-close summary.
  * telemetry streams (kind=run_meta/step/flush/summary records from a
    `denoise.py --telemetry` run): reduced to a bench-shaped record
    (metric/value/unit/vs_baseline/step_ms/loss trajectory) with
    per-phase p50/p95 and the retrace-warning count.

--validate additionally gates telemetry streams on the record schema
(observability.schema) and exits non-zero on violation — `make
obs-smoke` runs exactly that. Never initializes a device backend (no
jax.devices()/default_backend() call anywhere on this path), so it
works while the TPU tunnel is wedged.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.observability.report import (  # noqa: E402
    load_jsonl, summarize,
)
from se3_transformer_tpu.observability.schema import (  # noqa: E402
    SchemaError, validate_stream,
)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='aggregate telemetry/bench JSONL into one summary')
    ap.add_argument('paths', nargs='+', help='JSONL stream(s)')
    ap.add_argument('--validate', action='store_true',
                    help='gate telemetry streams on the record schema '
                         '(exit 1 on violation)')
    ap.add_argument('--out', default=None,
                    help='also write the summary JSON to this path')
    ap.add_argument('--anchor', type=float, default=None,
                    help='vs_baseline anchor for telemetry throughput')
    ap.add_argument('--code-rev', default=None,
                    help='only summarize bench records with this code_rev')
    ap.add_argument('--require-tune', action='store_true',
                    help='gate a kernel-tuning run (make tune-smoke): '
                         'exit non-zero unless the stream carries at '
                         'least one `tune` record, at least one '
                         'promotion, and a `consulted` verdict proving '
                         'the promoted entry steered the next pick')
    ap.add_argument('--require-comm', action='store_true',
                    help='gate a sequence-parallel run (make ring-smoke): '
                         'exit non-zero unless the stream carries at '
                         'least one `comm` record with exchange=true, '
                         'and every such record proves the traced '
                         'program free of full-width all-gathers')
    ap.add_argument('--require-pipeline', action='store_true',
                    help='gate a pipelined run: exit non-zero unless the '
                         'stream carries at least one `pipeline` record '
                         'whose final cumulative counters show at least '
                         'one prefetch hit (a 100%% stall rate means the '
                         'pipeline never overlapped anything)')
    args = ap.parse_args(argv)

    records = []
    for path in args.paths:
        recs = load_jsonl(path)
        if args.validate and any(r.get('kind') == 'run_meta'
                                 for r in recs):
            try:
                info = validate_stream(path)
            except SchemaError as e:
                print(f'{path}: SCHEMA VIOLATION: {e}', file=sys.stderr)
                return 1
            print(f'{path}: schema ok ({info["records"]} records, '
                  f'kinds {info["kinds"]})', file=sys.stderr)
        records += recs

    if not records:
        print('no records found', file=sys.stderr)
        return 1

    if args.require_pipeline:
        pipes = [r for r in records if r.get('kind') == 'pipeline']
        if not pipes:
            print('PIPELINE GATE: no pipeline records in the stream '
                  '(was the run started with --pipelined?)',
                  file=sys.stderr)
            return 1
        last = pipes[-1].get('prefetch', {})
        hits, stalls = last.get('hits', 0), last.get('stalls', 0)
        if not hits:
            print(f'PIPELINE GATE: 100% prefetch stalls ({stalls} stalls, '
                  f'0 hits) — the producer never got ahead of the device',
                  file=sys.stderr)
            return 1
        print(f'pipeline gate ok: {hits} hits / {stalls} stalls, '
              f'verdict {pipes[-1].get("verdict")}', file=sys.stderr)

    if args.require_comm:
        comms = [r for r in records if r.get('kind') == 'comm']
        if not comms:
            print('COMM GATE: no comm records in the stream (was the run '
                  'traced with the exchange instrumented?)',
                  file=sys.stderr)
            return 1
        ex_arms = [r for r in comms if r.get('exchange')]
        if not ex_arms:
            print('COMM GATE: no exchange-enabled comm record — the '
                  'sparse path was never traced', file=sys.stderr)
            return 1
        dirty = [r for r in ex_arms if not r.get('all_gather_free')]
        if dirty:
            shapes = [s for r in dirty
                      for s in r.get('full_width_all_gathers', [])]
            print(f'COMM GATE: {len(dirty)} exchange-enabled program(s) '
                  f'still carry full-width all-gathers: {shapes}',
                  file=sys.stderr)
            return 1
        print(f'comm gate ok: {len(comms)} comm records, '
              f'{len(ex_arms)} exchange arms, all all-gather-free',
              file=sys.stderr)

    if args.require_tune:
        tunes = [r for r in records if r.get('kind') == 'tune']
        if not tunes:
            print('TUNE GATE: no tune records in the stream (was '
                  'scripts/tune_kernels.py run?)', file=sys.stderr)
            return 1
        promoted = [r for r in tunes if r.get('verdict') == 'promoted']
        consulted = [r for r in tunes if r.get('verdict') == 'consulted']
        if not promoted:
            print('TUNE GATE: no candidate was promoted', file=sys.stderr)
            return 1
        if not consulted:
            print('TUNE GATE: no consulted verdict — the promoted entry '
                  'was never proven to steer a subsequent pick',
                  file=sys.stderr)
            return 1
        print(f'tune gate ok: {len(tunes)} tune records, '
              f'{len(promoted)} promoted, {len(consulted)} consulted',
              file=sys.stderr)

    summary = summarize(records, anchor=args.anchor,
                        code_rev=args.code_rev)
    text = json.dumps(summary, indent=1)
    print(text)
    if args.out:
        with open(args.out, 'w') as f:
            f.write(text + '\n')
    return 0


if __name__ == '__main__':
    sys.exit(main())
