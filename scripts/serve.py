"""Serve a mixed-length request stream through the inference subsystem.

Usage:
    python scripts/serve.py [--requests N] [--oversize K]
        [--buckets 12,24] [--batch-size 2] [--max-wait-ms 5]
        [--max-queue-depth 64] [--bf16] [--checkpoint DIR] [--cpu]
        [--metrics SERVE.jsonl] [--out SUMMARY.json] [--seed S]
        [--replicas N] [--swap-at K]
        [--fleet N | --host --port P --host-id K]

Startup: restore params (params-only — optimizer state never
materializes) or init a toy model, AOT-compile one executable per
bucket, arm the compile-event watchdog. Serve loop: admit -> enqueue ->
micro-batch (flush on full or deadline) -> answer. Close: a
SESSION_SUMMARY-style report.

This doubles as the `make serve-smoke` gate, exiting non-zero when
  * the telemetry stream fails schema validation, or
  * any post-warmup compile event fired (the AOT contract: a
    mixed-length stream over precompiled buckets must compile NOTHING),
  * or an in-range request failed to produce a result.

`--replicas N` (N > 1) switches to the multi-replica continuous-
batching router (se3_transformer_tpu.serving): N replica workers, each
owning its own AOT engine, least-outstanding dispatch, requests
admitted into in-flight bucket slots (deadline only as a fallback),
and — with `--swap-at K` — one rolling weight swap after the K-th
request (fresh seeded params; zero recompiles, zero dropped requests).
This is the `make serve-multi-smoke` gate; on top of the single-replica
gates it also exits non-zero when
  * no request was ever admitted into an in-flight slot
    (continuous_admissions == 0 — the router degenerated to flush
    barriers), or
  * the rolling swap did not complete across every replica.

Every serving mode installs a SIGTERM/SIGINT handler in the
`PreemptionGuard` idiom (set a flag, nothing else): a preempted serve
loop stops admitting, drains what it already accepted, flushes the
final telemetry records, and exits 0 — a mid-serve SIGTERM must never
lose the telemetry bank (tests/test_fleet.py pins it with a real
signal).

`--host` runs this process as one FLEET HOST: the replicas/router stack
above, exposed on a TCP port through `serving.transport.serve_socket` +
`serving.fleet.HostServer` (methods: ping / stats / infer / swap /
drain). It prints `FLEET HOST READY host=K port=P` once the AOT warmup
finished and the socket listens, then parks until SIGTERM (graceful
drain + final records + a host `fault` record, exit 0). `--poison-step
S` is the chaos hook: after a swap RPC restores step S, every
subsequent dispatch fails deterministically until a swap restores a
different step — the fault-injected canary of `make serve-fleet-smoke`.

`--fleet N` (N > 1) runs the CROSS-HOST front-end: spawn N `--host`
worker processes, route the request stream through a
`serving.fleet.FleetRouter` (host-level breakers, cross-host
redispatch, deadline propagation), bank the schema'd `fleet` record,
and SIGTERM the workers on the way out (each must exit 0). Exits
non-zero when any in-range submit resolves unanswered, any request is
lost, the stream fails schema validation, or a worker exits non-zero.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='bucketed AOT serving over a mixed-length stream')
    ap.add_argument('--requests', type=int, default=8,
                    help='in-range requests, lengths cycling across '
                         'buckets (mixed-length by construction)')
    ap.add_argument('--oversize', type=int, default=1,
                    help='extra requests longer than the largest bucket '
                         '(must be rejected, never compiled)')
    ap.add_argument('--buckets', type=str, default='12,24')
    ap.add_argument('--batch-size', type=int, default=2)
    ap.add_argument('--max-wait-ms', type=float, default=5.0)
    ap.add_argument('--max-queue-depth', type=int, default=64)
    ap.add_argument('--flush-every', type=int, default=2,
                    help='emit a serve record every N dispatched batches')
    ap.add_argument('--bf16', action='store_true',
                    help='bf16 activation path (coords cast in, f32 out)')
    ap.add_argument('--precision', type=str, default=None,
                    help='weight-precision mix (quant.rules: fp32 / '
                         'bf16 / int8_mix / fp8_mix). Params quantize '
                         'at restore time — the fp32 tree never lands '
                         'on device. With --replicas N, a comma list '
                         'builds a HETEROGENEOUS fleet (cycled across '
                         'replicas, e.g. "fp32,int8_mix"); rolling '
                         'swaps re-quantize per replica at its own mix '
                         '(zero drops, zero recompiles)')
    ap.add_argument('--checkpoint', type=str, default=None,
                    help='CheckpointManager directory; params-only '
                         'restore (optimizer state is never read)')
    ap.add_argument('--metrics', type=str, default=None,
                    help='JSONL telemetry stream (serve records)')
    ap.add_argument('--out', type=str, default=None,
                    help='write the summary report JSON here')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--cpu', action='store_true',
                    help='force the CPU backend')
    ap.add_argument('--replicas', type=int, default=1,
                    help='>1 routes through the multi-replica '
                         'continuous-batching router '
                         '(se3_transformer_tpu.serving)')
    ap.add_argument('--swap-at', type=int, default=None,
                    help='multi-replica only: after this many submitted '
                         'requests, hot-swap fresh weights with a '
                         'rolling drain (zero recompiles, zero drops)')
    ap.add_argument('--async-dispatch', action='store_true',
                    help='multi-replica only: per-replica thread-pool '
                         'dispatch — replica executions overlap instead '
                         'of serializing through the submit loop '
                         '(serving.ReplicaWorker async_dispatch)')
    ap.add_argument('--timeout-s', type=float, default=None,
                    help='multi-replica only: per-request deadline '
                         '(submitted_at + timeout); expired requests '
                         'shed before dispatch and resolve with a '
                         'structured RequestFailed("deadline")')
    ap.add_argument('--max-retries', type=int, default=1,
                    help='multi-replica only: redispatches of a failed '
                         "batch's requests onto sibling replicas before "
                         'a structured RequestFailed("retries_'
                         'exhausted")')
    ap.add_argument('--pace-ms', type=float, default=0.0,
                    help='sleep this long between submitted requests '
                         '(stream pacing — gives probes/deadlines/'
                         'signals real time to land mid-serve)')
    # ---- cross-host fleet tier (serving.fleet) ---------------------- #
    ap.add_argument('--fleet', type=int, default=1,
                    help='>1 spawns N --host worker processes and '
                         'routes through the cross-host FleetRouter '
                         '(host-level breakers, cross-host redispatch, '
                         'schema\'d fleet record)')
    ap.add_argument('--host', action='store_true', dest='host_mode',
                    help='run as ONE fleet host: serve the replicas/'
                         'router stack on a TCP port (serving.fleet.'
                         'HostServer) until SIGTERM')
    ap.add_argument('--host-id', type=int, default=0,
                    help='--host only: this host\'s id in the fleet')
    ap.add_argument('--port', type=int, default=0,
                    help='--host only: TCP port (0 = OS-assigned; the '
                         'READY line names the bound port)')
    ap.add_argument('--transport', choices=('binary', 'legacy'),
                    default='binary',
                    help='fleet wire: "binary" (persistent pooled '
                         'connections, correlation-id multiplexing, '
                         'raw numpy array frames — the default) or '
                         '"legacy" (connect-per-call newline-JSON '
                         'escape hatch)')
    ap.add_argument('--checkpoint-step', type=int, default=None,
                    help='with --checkpoint: restore this step instead '
                         'of the latest (the fleet smoke starts hosts '
                         'on the OLD weights while the rollout target '
                         'sits at a later step)')
    ap.add_argument('--poison-step', type=int, default=None,
                    help='--host only (chaos hook): after a swap RPC '
                         'restores this step, every dispatch fails '
                         'deterministically until a different step is '
                         'restored — the fault-injected canary arm of '
                         'make serve-fleet-smoke')
    return ap.parse_args(argv)


# the toy serving model's vocab size — ONE constant shared by the
# module builder and every request-stream generator (a fleet front-end
# sampling out-of-vocab ids would silently gather wrong embeddings)
TOY_NUM_TOKENS = 24


def precision_mixes(args):
    """The per-replica precision list: None -> fp32 everywhere; a
    single mix applies to every replica; a comma list cycles."""
    if not args.precision:
        return [None] * max(args.replicas, 1)
    mixes = [m.strip() or None for m in args.precision.split(',')]
    if args.replicas <= 1 and len(mixes) > 1:
        raise SystemExit('--precision got a comma list but --replicas '
                         'is 1 — heterogeneous mixes need a fleet')
    return [mixes[i % len(mixes)] for i in range(max(args.replicas, 1))]


def build_module_and_params(args, buckets, seed=None):
    """Toy module + params (checkpoint restore or seeded init) — shared
    by the single-replica and router paths."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.native.loader import chain_adjacency
    from se3_transformer_tpu.training.denoise import DenoiseConfig

    seed = args.seed if seed is None else seed
    cfg = DenoiseConfig(num_tokens=TOY_NUM_TOKENS, dim=8, dim_head=8,
                        heads=2, depth=2, num_degrees=2,
                        max_sparse_neighbors=4)
    module = cfg.build_module()
    rng = np.random.RandomState(seed)
    if args.checkpoint:
        from se3_transformer_tpu.training.checkpoint import CheckpointManager
        step = getattr(args, 'checkpoint_step', None)
        params = CheckpointManager(args.checkpoint).restore_params(step)
        print(f'restored params-only from {args.checkpoint}'
              f'{f" @ step {step}" if step is not None else ""}')
    else:
        L = buckets[0]
        params = module.init(
            jax.random.PRNGKey(seed),
            jnp.asarray(rng.randint(0, cfg.num_tokens, size=(1, L))),
            jnp.asarray(rng.normal(size=(1, L, 3)).astype(np.float32)),
            mask=jnp.ones((1, L), bool),
            adj_mat=jnp.asarray(chain_adjacency(L)),
            return_type=1)['params']
        print(f'no --checkpoint: initialized fresh params (seed {seed})')
    return cfg, module, params


def request_lengths(args, buckets, max_len, rng):
    """Mixed-length stream: in-range lengths cycling across buckets,
    plus the oversize (must-reject) tail, shuffled."""
    lows = [1] + [b + 1 for b in buckets[:-1]]
    lengths = [int(rng.randint(lows[i % len(buckets)],
                               buckets[i % len(buckets)] + 1))
               for i in range(args.requests)]
    lengths += [max_len + int(rng.randint(1, 32))
                for _ in range(args.oversize)]
    rng.shuffle(lengths)
    return lengths


def main(argv=None):
    args = parse_args(argv)
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    enable_compilation_cache()
    if args.host_mode:
        return serve_host(args)
    if args.fleet > 1:
        return serve_fleet(args)
    if args.replicas > 1:
        return serve_multi(args)
    import numpy as np

    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine, MicroBatcher,
        RequestRejected, ServeTelemetry,
    )
    from se3_transformer_tpu.observability import MetricLogger
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    import jax.numpy as jnp

    buckets = tuple(int(b) for b in args.buckets.split(','))
    cfg, module, params = build_module_and_params(args, buckets)

    # ---- startup: AOT-compile every bucket, then arm the watchdog ---- #
    t0 = time.perf_counter()
    engine = InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, precision=precision_mixes(args)[0],
        activation_dtype=jnp.bfloat16 if args.bf16 else None)
    print(f'warmup: compiled {len(engine.executables)} bucket '
          f'executables in {time.perf_counter() - t0:.1f}s '
          f'({engine.compile_seconds}, precision '
          f'{engine.precision_name})')

    admission = AdmissionController(max_len=engine.max_len,
                                    max_queue_depth=args.max_queue_depth)
    batcher = MicroBatcher(engine.run, buckets=engine.buckets,
                           batch_size=args.batch_size,
                           max_wait_ms=args.max_wait_ms,
                           admission=admission)
    logger = MetricLogger(args.metrics, run_meta=dict(
        mode='serve', buckets=list(buckets), batch_size=args.batch_size,
        dtype=engine.dtype_name, precision=engine.precision_name))
    telemetry = ServeTelemetry(engine, batcher, admission, logger)
    telemetry.arm()

    # ---- the request stream: lengths cycle across buckets ----------- #
    from se3_transformer_tpu.training.guardian import PreemptionGuard

    rng = np.random.RandomState(args.seed)
    lengths = request_lengths(args, engine.buckets, engine.max_len, rng)

    pending, flushed_at, interrupted = [], 0, None
    with PreemptionGuard() as guard:
        for length in lengths:
            if guard.stop_requested:
                # graceful preemption: stop admitting, drain what we
                # accepted, flush the bank — a mid-serve SIGTERM must
                # not lose the telemetry stream
                interrupted = guard.signame
                print(f'{interrupted}: graceful shutdown — draining '
                      f'{batcher.queue_depth} queued requests, flushing '
                      f'telemetry', flush=True)
                break
            tokens = rng.randint(0, cfg.num_tokens, size=length)
            coords = rng.normal(size=(length, 3)).astype(np.float32)
            try:
                pending.append(batcher.submit(tokens, coords))
            except RequestRejected as e:
                print(f'rejected: {e.code} {e.detail}')
                logger.log_record('step', mirror=False, step=len(pending),
                                  rejected=e.to_record())
            batcher.pump()
            if args.pace_ms:
                time.sleep(args.pace_ms / 1e3)
            if batcher.batches_dispatched - flushed_at >= args.flush_every:
                telemetry.flush()
                flushed_at = batcher.batches_dispatched
        # deadline-drain the stragglers, then close the stream (the
        # drain still runs under the guard: a SECOND signal just sets
        # the already-set flag instead of killing the drain)
        while batcher.queue_depth:
            wait = batcher.next_deadline()
            if wait:
                time.sleep(wait)
            batcher.pump()
    telemetry.flush()
    summary = telemetry.close()
    logger.close()

    # ---- gates + report --------------------------------------------- #
    ok = True
    unanswered = [p.request_id for p in pending if not p.ok]
    if unanswered:
        print(f'FAIL: {len(unanswered)} admitted requests unanswered')
        ok = False
    if telemetry.post_warmup_compiles:
        print(f'FAIL: {telemetry.post_warmup_compiles} compile events '
              f'after warmup — the AOT bucket contract is broken')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        interrupted=interrupted,
        requests=dict(total=len(lengths), answered=len(pending) -
                      len(unanswered), **admission.snapshot()),
        batches=batcher.batches_dispatched,
        post_warmup_compiles=telemetry.post_warmup_compiles,
        compile_seconds=engine.stats()['compile_seconds'],
        # memory-per-bucket off the warmup cost ledger (the full
        # schema'd cost records are in the --metrics stream)
        peak_hbm_by_bucket=engine.stats()['peak_hbm_by_bucket'],
        latency_by_bucket={
            k: {p: v[p] for p in
                ('count', 'p50_ms', 'p95_ms', 'p99_ms', 'max_ms')}
            for k, v in summary['timing'].items()
            if k.startswith('bucket_')},
        request_latency_ms=summary['metrics']['request_latency_ms'],
        batch_fill=summary['metrics'].get('batch_fill'),
    )
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2)
        print(f'report -> {args.out}')
    return 0 if ok else 1


def serve_multi(args):
    """Multi-replica continuous-batching path (`--replicas N`)."""
    import numpy as np

    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine, RequestRejected,
    )
    from se3_transformer_tpu.observability import MetricLogger, PhaseTimer
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        ReplicaWorker, Router, RouterTelemetry,
    )
    import jax.numpy as jnp

    buckets = tuple(int(b) for b in args.buckets.split(','))
    cfg, module, params = build_module_and_params(args, buckets)

    # ---- startup: N replicas, ONE shared PhaseTimer (the aggregate
    # per-bucket SLO surface), every bucket AOT-compiled per replica --- #
    t0 = time.perf_counter()
    timer = PhaseTimer()
    mixes = precision_mixes(args)
    engines = [InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, timer=timer, precision=mixes[i],
        activation_dtype=jnp.bfloat16 if args.bf16 else None)
        for i in range(args.replicas)]
    print(f'warmup: {args.replicas} replicas x '
          f'{len(engines[0].executables)} bucket executables in '
          f'{time.perf_counter() - t0:.1f}s (precision mixes '
          f'{[e.precision_name for e in engines]})')

    workers = [ReplicaWorker(i, e, max_wait_ms=args.max_wait_ms,
                             async_dispatch=args.async_dispatch)
               for i, e in enumerate(engines)]
    admission = AdmissionController(max_len=buckets[-1],
                                    max_queue_depth=args.max_queue_depth)
    # the router is a context manager: its dispatch executors shut down
    # when the block exits, ON ERROR PATHS TOO — a crashed serve loop
    # must not leak replica threads
    with Router(workers, admission=admission,
                max_retries=args.max_retries,
                default_timeout_s=args.timeout_s) as router:
        # materialize the swap weights BEFORE arming the compile
        # watchdog: a real rolling reload restores numpy leaves off the
        # async-checkpoint path (zero compiles); the smoke's stand-in —
        # a fresh seeded init — compiles eager init programs, which
        # must land in the warmup window, not against the AOT contract
        swap_params = None
        if args.swap_at is not None:
            _, _, swap_params = build_module_and_params(
                args, buckets, seed=args.seed + 1)
        logger = MetricLogger(args.metrics, run_meta=dict(
            mode='serve_multi', replicas=args.replicas,
            buckets=list(buckets), batch_size=args.batch_size,
            dtype=engines[0].dtype_name,
            precision_mixes=[e.precision_name for e in engines]))
        telemetry = RouterTelemetry(router, admission, logger)
        telemetry.arm()

        # ---- the request stream, with one mid-run rolling swap ------ #
        from se3_transformer_tpu.training.guardian import PreemptionGuard

        rng = np.random.RandomState(args.seed)
        lengths = request_lengths(args, buckets, router.max_len, rng)

        pending, flushed_at, swapped, interrupted = [], 0, False, None
        with PreemptionGuard() as guard:
            for i, length in enumerate(lengths):
                if guard.stop_requested:
                    # graceful preemption: stop admitting, let the
                    # router drain below — the bank must survive
                    interrupted = guard.signame
                    print(f'{interrupted}: graceful shutdown — '
                          f'draining {router.queue_depth} queued '
                          f'requests, flushing telemetry', flush=True)
                    break
                if args.swap_at is not None and i == args.swap_at \
                        and not swapped:
                    # same shapes, new values: the swap must compile
                    # NOTHING and drop NOTHING (the gates below prove
                    # both)
                    events = router.swap_weights(
                        swap_params, tag=f'seed_{args.seed + 1}')
                    swapped = True
                    print(f'rolling weight swap after request {i}: '
                          f'{len(events)} replicas swapped, '
                          f'{sum(e["drained_batches"] for e in events)} '
                          f'partial batches drained')
                tokens = rng.randint(0, cfg.num_tokens, size=length)
                coords = rng.normal(size=(length, 3)).astype(np.float32)
                try:
                    pending.append(router.submit(tokens, coords))
                except RequestRejected as e:
                    print(f'rejected: {e.code} {e.detail}')
                    logger.log_record('step', mirror=False,
                                      step=len(pending),
                                      rejected=e.to_record())
                router.pump()
                if args.pace_ms:
                    time.sleep(args.pace_ms / 1e3)
                if router.batches_dispatched - flushed_at >= \
                        args.flush_every:
                    telemetry.flush()
                    flushed_at = router.batches_dispatched
            # deadline-drain the stragglers, then close the stream
            while router.queue_depth:
                wait = router.next_deadline()
                if wait:
                    time.sleep(wait)
                elif args.async_dispatch:
                    # async mode: queue_depth includes executor-inflight
                    # rows that no deadline governs — yield, don't spin
                    time.sleep(0.001)
                router.pump()
    # __exit__ barriered on any async dispatches and shut the
    # executors down (no-op for synchronous replicas)
    telemetry.flush()
    summary = telemetry.close()
    logger.close()

    # ---- gates + report --------------------------------------------- #
    ok = True
    unanswered = [p.request_id for p in pending if not p.ok]
    if unanswered:
        print(f'FAIL: {len(unanswered)} admitted requests unanswered '
              f'(the rolling swap must drop NOTHING)')
        ok = False
    if telemetry.post_warmup_compiles:
        print(f'FAIL: {telemetry.post_warmup_compiles} compile events '
              f'after warmup — a weight swap or mixed-length stream '
              f'broke the AOT contract')
        ok = False
    if not router.continuous_admissions and not interrupted:
        # an interrupted run may have been preempted before any slot
        # ever held two requests — graceful preemption must exit 0
        print('FAIL: zero continuous admissions — no request ever '
              'joined an in-flight bucket slot, the router degenerated '
              'to flush barriers')
        ok = False
    if args.swap_at is not None and not interrupted and \
            len(router.swap_events) != args.replicas:
        # an interrupted run may have been preempted before swap_at —
        # a graceful shutdown is not a failed swap
        print(f'FAIL: rolling swap incomplete: '
              f'{len(router.swap_events)} swap events for '
              f'{args.replicas} replicas')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        interrupted=interrupted,
        replicas=args.replicas,
        precision_mixes=[e.precision_name for e in engines],
        requests=dict(total=len(lengths), answered=len(pending) -
                      len(unanswered), **admission.snapshot()),
        batches=router.batches_dispatched,
        continuous_admissions=router.continuous_admissions,
        deadline_flushes=router.deadline_flushes,
        swaps=dict(count=len(router.swap_events),
                   events=router.swap_events),
        post_warmup_compiles=telemetry.post_warmup_compiles,
        per_replica={str(w.id): w.snapshot() for w in router.workers},
        latency_by_bucket={
            k: {p: v[p] for p in
                ('count', 'p50_ms', 'p95_ms', 'p99_ms', 'max_ms')}
            for k, v in summary['timing'].items()
            if k.startswith('bucket_')},
        request_latency_ms=summary['metrics']['request_latency_ms'],
    )
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2)
        print(f'report -> {args.out}')
    return 0 if ok else 1


def serve_host(args):
    """One fleet host (`--host`): the serve_multi stack behind a TCP
    RPC surface, parked until SIGTERM (graceful drain + final records,
    exit 0)."""
    import jax.numpy as jnp

    from se3_transformer_tpu.faults import FaultInjector
    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine,
    )
    from se3_transformer_tpu.observability import MetricLogger, PhaseTimer
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        HostServer, ReplicaWorker, Router, RouterTelemetry, serve_binary,
        serve_socket,
    )
    from se3_transformer_tpu.training.guardian import PreemptionGuard

    buckets = tuple(int(b) for b in args.buckets.split(','))
    cfg, module, params = build_module_and_params(args, buckets)

    t0 = time.perf_counter()
    timer = PhaseTimer()
    mixes = precision_mixes(args)
    injector = FaultInjector(seed=args.seed)
    engines = [InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, timer=timer, precision=mixes[i],
        activation_dtype=jnp.bfloat16 if args.bf16 else None)
        for i in range(max(args.replicas, 1))]
    print(f'host {args.host_id}: warmup {len(engines)} replicas x '
          f'{len(engines[0].executables)} bucket executables in '
          f'{time.perf_counter() - t0:.1f}s', flush=True)
    workers = [ReplicaWorker(i, e, max_wait_ms=args.max_wait_ms,
                             async_dispatch=args.async_dispatch,
                             fault_injector=injector)
               for i, e in enumerate(engines)]
    admission = AdmissionController(max_len=buckets[-1],
                                    max_queue_depth=args.max_queue_depth)

    ok = True
    with Router(workers, admission=admission,
                max_retries=args.max_retries,
                default_timeout_s=args.timeout_s) as router:
        logger = MetricLogger(args.metrics, run_meta=dict(
            mode='serve_host', host_id=args.host_id,
            replicas=len(engines), buckets=list(buckets),
            batch_size=args.batch_size, seed=args.seed,
            precision_mixes=[e.precision_name for e in engines]))
        telemetry = RouterTelemetry(router, admission, logger)
        telemetry.arm()

        # the chaos hook: after a swap restores --poison-step, every
        # dispatch fails deterministically (an every=1 injector plan)
        # until a DIFFERENT step is restored — "the new weights are bad
        # on this host", which the fleet's canary gate must catch
        poison_plans = []

        def on_swap(payload, events, _inj=injector):
            if args.poison_step is None:
                return
            tag = (events[0].get('tag') or '') if events else ''
            restored = tag.rsplit('@', 1)[-1]
            if restored == str(args.poison_step):
                poison_plans.append(_inj.plan(
                    'replica_dispatch', 'exception', every=1))
                print(f'host {args.host_id}: POISON ARMED — step '
                      f'{restored} restored, every dispatch now fails '
                      f'until a different step is swapped in',
                      flush=True)
            elif poison_plans:
                for p in poison_plans:
                    p.max_fires = p.fires    # exhausted: disarmed
                del poison_plans[:]
                print(f'host {args.host_id}: poison disarmed (step '
                      f'{restored} restored)', flush=True)

        host_server = HostServer(router, host_id=args.host_id,
                                 telemetry=telemetry,
                                 flush_every_batches=args.flush_every,
                                 on_swap=on_swap)
        if args.transport == 'binary':
            sock = serve_binary(host_server, port=args.port)
            # every serve record this host flushes carries the wire's
            # own counters (schema'd `transport` section)
            telemetry.transport_source = sock.transport_stats
        else:
            sock = serve_socket(host_server, port=args.port)
        print(f'FLEET HOST READY host={args.host_id} port={sock.port} '
              f'transport={args.transport}', flush=True)
        with PreemptionGuard() as guard:
            while not guard.stop_requested:
                time.sleep(0.05)
        print(f'host {args.host_id}: {guard.signame} — graceful '
              f'shutdown: close socket, drain router, flush the bank',
              flush=True)
        sock.close()
        host_server.stop(drain=True)
    # __exit__ -> close(): drained, retries settled, executors down
    telemetry.flush()
    telemetry.fault_flush(injector=injector, label=f'host_{args.host_id}')
    telemetry.close()
    logger.close()

    if telemetry.post_warmup_compiles:
        print(f'FAIL: host {args.host_id}: '
              f'{telemetry.post_warmup_compiles} post-warmup compile '
              f'events — a swap or mixed-length stream broke the AOT '
              f'contract', flush=True)
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'host {args.host_id}: schema ok '
                  f'({info["records"]} records {info["kinds"]})',
                  flush=True)
        except SchemaError as e:
            print(f'FAIL: host {args.host_id}: telemetry stream '
                  f'invalid: {e}', flush=True)
            ok = False
    print(f'host {args.host_id}: served '
          f'{sum(w.served_rows for w in router.workers)} rows in '
          f'{router.batches_dispatched} batches, '
          f'{len(router.swap_events)} swaps, '
          f'{router.request_failures} structured failures', flush=True)
    return 0 if ok else 1


# --------------------------------------------------------------------- #
# fleet-worker process management (shared with fleet_chaos_smoke)
# --------------------------------------------------------------------- #
def host_command(host_id, *, port=0, buckets='8,16', batch_size=2,
                 replicas=1, seed=0, max_wait_ms=10.0, timeout_s=None,
                 max_retries=1, max_queue_depth=None, checkpoint=None,
                 checkpoint_step=None, metrics=None, poison_step=None,
                 bf16=False, async_dispatch=False, cpu=True,
                 transport='binary'):
    """The argv for one `--host` worker process."""
    cmd = [sys.executable, os.path.abspath(__file__), '--host',
           '--host-id', str(host_id), '--port', str(port),
           '--buckets', str(buckets), '--batch-size', str(batch_size),
           '--replicas', str(replicas), '--seed', str(seed),
           '--max-wait-ms', str(max_wait_ms),
           '--max-retries', str(max_retries),
           '--transport', str(transport)]
    if cpu:
        cmd.append('--cpu')
    if bf16:
        cmd.append('--bf16')
    if async_dispatch:
        cmd.append('--async-dispatch')
    if timeout_s is not None:
        cmd += ['--timeout-s', str(timeout_s)]
    if max_queue_depth is not None:
        cmd += ['--max-queue-depth', str(max_queue_depth)]
    if checkpoint:
        cmd += ['--checkpoint', checkpoint]
    if checkpoint_step is not None:
        cmd += ['--checkpoint-step', str(checkpoint_step)]
    if metrics:
        cmd += ['--metrics', metrics]
    if poison_step is not None:
        cmd += ['--poison-step', str(poison_step)]
    return cmd


def spawn_host(host_id, **kw):
    """Start one `--host` worker (stdout piped — call
    `wait_host_ready` to block until its READY line AND keep the pipe
    drained afterwards, or the worker wedges on a full pipe)."""
    import subprocess
    return subprocess.Popen(host_command(host_id, **kw),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            bufsize=1)


def wait_host_ready(proc, timeout_s=300.0):
    """Block until the worker prints its READY line; returns
    `(port, sink)` where `sink` is the list a daemon reader thread
    keeps appending the worker's output into (started immediately, so
    the pipe can never fill and wedge the worker, AND the deadline is
    enforced even against a worker that wedges without printing — a
    blocking readline here would wait forever)."""
    import threading
    sink = []
    eof = threading.Event()

    def drain(p=proc, s=sink):
        try:
            for line in p.stdout:
                s.append(line)
        finally:
            eof.set()

    threading.Thread(target=drain, daemon=True).start()
    deadline = time.monotonic() + timeout_s
    scanned = 0
    while time.monotonic() < deadline:
        n = len(sink)
        while scanned < n:
            line = sink[scanned]
            scanned += 1
            if 'FLEET HOST READY' in line:
                port = int(line.split('port=')[1].split()[0])
                return port, sink
        if eof.is_set() and scanned >= len(sink):
            raise RuntimeError(
                f'fleet host died during warmup (rc={proc.poll()}):\n'
                + ''.join(sink[-30:]))
        time.sleep(0.05)
    raise RuntimeError('fleet host not READY within '
                       f'{timeout_s}s:\n' + ''.join(sink[-30:]))


def stop_host(proc, timeout_s=90.0):
    """Graceful stop: SIGTERM, wait, escalate to SIGKILL only on a
    wedge. Returns the exit code (0 = the graceful-shutdown contract
    held)."""
    import signal
    import subprocess
    if proc.poll() is None:
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    try:
        return proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10.0)
        return proc.returncode


def serve_fleet(args):
    """Cross-host front-end (`--fleet N`): spawn N `--host` workers,
    route the stream through a FleetRouter, bank the `fleet` record,
    SIGTERM the workers (each must exit 0)."""
    import numpy as np

    from se3_transformer_tpu.inference.admission import RequestRejected
    from se3_transformer_tpu.observability import MetricLogger
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        BinaryTransport, FleetRouter, SocketTransport,
    )
    from se3_transformer_tpu.training.guardian import PreemptionGuard

    buckets = tuple(int(b) for b in args.buckets.split(','))
    procs, sinks, ports = [], [], []
    print(f'spawning {args.fleet} fleet hosts...', flush=True)
    for i in range(args.fleet):
        procs.append(spawn_host(
            i, buckets=args.buckets, batch_size=args.batch_size,
            replicas=args.replicas, seed=args.seed,
            max_wait_ms=args.max_wait_ms, timeout_s=args.timeout_s,
            max_retries=args.max_retries,
            max_queue_depth=args.max_queue_depth,
            checkpoint=args.checkpoint,
            checkpoint_step=args.checkpoint_step, bf16=args.bf16,
            async_dispatch=args.async_dispatch, cpu=args.cpu,
            transport=args.transport))
    try:
        for p in procs:
            port, sink = wait_host_ready(p)
            ports.append(port)
            sinks.append(sink)
        print(f'fleet up: {args.fleet} hosts on ports {ports}',
              flush=True)

        if args.transport == 'binary':
            transports = {i: BinaryTransport('127.0.0.1', port)
                          for i, port in enumerate(ports)}
        else:
            transports = {i: SocketTransport('127.0.0.1', port)
                          for i, port in enumerate(ports)}
        ok = True
        rng = np.random.RandomState(args.seed)
        lengths = request_lengths(args, buckets, buckets[-1], rng)
        logger = MetricLogger(args.metrics, run_meta=dict(
            mode='serve_fleet', hosts=args.fleet, ports=ports,
            buckets=list(buckets), batch_size=args.batch_size,
            seed=args.seed))
        pending, interrupted = [], None
        with FleetRouter(transports, max_retries=args.max_retries,
                         default_timeout_s=args.timeout_s) as fleet:
            with PreemptionGuard() as guard:
                for length in lengths:
                    if guard.stop_requested:
                        interrupted = guard.signame
                        print(f'{interrupted}: graceful shutdown — '
                              f'draining the fleet, flushing the bank',
                              flush=True)
                        break
                    tokens = rng.randint(0, TOY_NUM_TOKENS, size=length)
                    coords = rng.normal(
                        size=(length, 3)).astype(np.float32)
                    try:
                        pending.append(fleet.submit(tokens, coords))
                    except RequestRejected as e:
                        print(f'rejected: {e.code} {e.detail}')
                        logger.log_record('step', mirror=False,
                                          step=len(pending),
                                          rejected=e.to_record())
                    fleet.pump()
                    if args.pace_ms:
                        time.sleep(args.pace_ms / 1e3)
                fleet.drain()
            body = fleet.record_body(pending, label='serve_fleet')
            logger.log_record('fleet', mirror=False, **body)
        logger.close()
        for t in transports.values():
            if hasattr(t, 'close'):
                t.close()    # joins the binary arm's reader threads

        lost = [p.request_id for p in pending if not p.done]
        # a host-side RequestRejected (oversize before the first bucket
        # scrape landed) is a structured outcome, not a lost answer
        unanswered = [p.request_id for p in pending
                      if not p.ok
                      and not isinstance(p.error, RequestRejected)]
        if lost:
            print(f'FAIL: {len(lost)} requests LOST fleet-wide')
            ok = False
        if unanswered:
            print(f'FAIL: {len(unanswered)} in-range requests resolved '
                  f'unanswered (healthy fleet must answer everything)')
            ok = False
        if args.metrics:
            try:
                info = validate_stream(args.metrics)
                print(f'schema ok: {info["records"]} records '
                      f'{info["kinds"]}')
            except SchemaError as e:
                print(f'FAIL: telemetry stream invalid: {e}')
                ok = False
    finally:
        rcs = [stop_host(p) for p in procs]
    print(f'fleet hosts stopped: rcs {rcs}')
    if any(rc != 0 for rc in rcs):
        print('FAIL: a fleet host exited non-zero on graceful SIGTERM')
        ok = False

    report = dict(ok=ok, interrupted=interrupted, hosts=args.fleet,
                  host_rcs=rcs,
                  requests=dict(total=len(lengths),
                                submitted=len(pending),
                                answered=len(pending) - len(unanswered),
                                lost=len(lost)),
                  fleet=dict(answered=body['answered'],
                             cross_host_retries=body['cross_host_retries'],
                             request_failures=body['request_failures'],
                             heartbeats=body['heartbeats']))
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2)
        print(f'report -> {args.out}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
